"""Notebook-controller load test with spawn->ready timing capture.

Capability parity with the reference load harness
(reference notebook-controller/loadtest/start_notebooks.py:1-30, which
applies N templated Notebook+PVC CRs via kubectl and captures no timing),
extended per SURVEY.md SS6: the reference publishes no performance numbers,
so this harness *establishes* the spawn->ready baseline — per-notebook
latency from CR creation to status.readyReplicas == spec replicas, with
p50/p90/max summary printed as one JSON line.

Two modes:

- ``kubectl``: template Notebook + PVC manifests (TPU-flavoured: the CR
  carries ``spec.tpu``) and apply/delete them against a real cluster,
  optionally polling readiness for the timing capture.
- ``simulate``: run the real notebook controller (Python watch loop +
  native core) against the in-memory API server with a fake kubelet that
  marks pods ready after a configurable latency. This exercises the full
  reconcile pipeline in-process — the scale tier of the test ladder
  (SURVEY.md SS4 tier 8) with actual latency numbers, no cluster needed.
- ``processes``: the control-plane path with REAL process boundaries and
  the REAL wire protocol: a dev apiserver served over HTTP
  (kubeflow_tpu.k8s.httpd), the notebook controller as a separate OS
  process (python -m kubeflow_tpu notebook-controller) watching over
  chunked HTTP streams, and the fake kubelet talking through the
  production ApiClient. Only the kubelet/scheduler is simulated — the
  latency measured is the platform's own contribution to spawn->ready.

Usage:
  python -m loadtest.start_notebooks -l 50 --mode simulate
  python -m loadtest.start_notebooks -l 20 --mode processes
  python -m loadtest.start_notebooks -l 10 -n kubeflow --mode kubectl
  python -m loadtest.start_notebooks -l 10 -n kubeflow -p delete
"""

from __future__ import annotations

import argparse
import copy
import json
import queue
import subprocess
import sys
import threading
import time
import traceback
from pathlib import Path

import yaml

# Direct script execution (`python loadtest/start_notebooks.py`) from
# anywhere: the repo root carries the kubeflow_tpu package.
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from kubeflow_tpu.k8s.fake import Conflict, NotFound  # noqa: E402

HERE = Path(__file__).resolve().parent


def load_templates() -> tuple[dict, dict]:
    notebook = yaml.safe_load((HERE / "notebook_template.yaml").read_text())
    pvc = yaml.safe_load((HERE / "pvc_template.yaml").read_text())
    return notebook, pvc


def render_notebook(template: dict, index: int, namespace: str) -> dict:
    """Per-index rename of the notebook CR and its PVC claim (reference
    write_notebook_config, loadtest/start_notebooks.py)."""
    nb = copy.deepcopy(template)
    nb["metadata"]["name"] = f"jupyter-test-{index}"
    nb["metadata"]["namespace"] = namespace
    spec = nb["spec"]["template"]["spec"]
    spec["containers"][0]["name"] = f"notebook-{index}"
    for vol in spec.get("volumes", []):
        if "persistentVolumeClaim" in vol:
            vol["persistentVolumeClaim"]["claimName"] = f"test-vol-{index}"
    return nb


def render_pvc(template: dict, index: int, namespace: str) -> dict:
    pvc = copy.deepcopy(template)
    pvc["metadata"]["name"] = f"test-vol-{index}"
    pvc["metadata"]["namespace"] = namespace
    return pvc


def percentile(sorted_values: list[float], q: float) -> float:
    """Linear-interpolated percentile, q in [0, 1]."""
    if not sorted_values:
        return 0.0
    if len(sorted_values) == 1:
        return sorted_values[0]
    pos = q * (len(sorted_values) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_values) - 1)
    frac = pos - lo
    return sorted_values[lo] * (1 - frac) + sorted_values[hi] * frac


def summarize(latencies: dict[str, float], mode: str) -> dict:
    values = sorted(latencies.values())
    return {
        "metric": "notebook_spawn_to_ready_seconds",
        "mode": mode,
        "count": len(values),
        "p50": round(percentile(values, 0.50), 4),
        "p90": round(percentile(values, 0.90), 4),
        "max": round(max(values), 4) if values else 0.0,
    }


def _histogram_p99(families, name: str) -> float | None:
    """p99 upper bound from a parsed Prometheus histogram: the
    smallest ``le`` whose cumulative count covers 99% of the total,
    summed across label sets (the ROADMAP item-3 soak gates on this
    exact read-back, so the harness computes it the way a scraper
    would — from the exposition, not in-process state)."""
    buckets: dict[float, float] = {}
    total = 0.0
    for family in families:
        if family.name != name:
            continue
        for sample in family.samples:
            if sample.name.endswith("_bucket"):
                try:
                    le = float(sample.labels.get("le", "inf"))
                except ValueError:
                    continue
                buckets[le] = buckets.get(le, 0.0) + sample.value
            elif sample.name.endswith("_count"):
                total += sample.value
    if total <= 0:
        return None
    for le in sorted(buckets):
        if buckets[le] >= 0.99 * total:
            return le
    return None


def control_plane_summary(server, slo_engine, mode: str) -> dict:
    """The churn-measurability line (the bridge to the ROADMAP item-3
    soak): reconcile p99 and queue-wait p99 read back from the
    manager's ``/metrics`` exposition, and the firing/active alert
    counts from ``/fleet`` — one JSON object per run, so a soak
    trajectory is a grep away."""
    import urllib.request

    from prometheus_client.parser import text_string_to_metric_families

    if slo_engine is not None:
        slo_engine.tick()
    base = f"http://127.0.0.1:{server.port}"
    with urllib.request.urlopen(f"{base}/metrics", timeout=10) as resp:
        families = list(
            text_string_to_metric_families(resp.read().decode())
        )
    with urllib.request.urlopen(f"{base}/fleet", timeout=10) as resp:
        fleet = json.loads(resp.read())
    alerts = fleet.get("alerts") or []
    return {
        "metric": "control_plane_churn",
        "mode": mode,
        "reconcile_p99_s": _histogram_p99(
            families, "controller_reconcile_duration_seconds"),
        "queue_wait_p99_s": _histogram_p99(
            families, "workqueue_queue_duration_seconds"),
        "alerts_firing": sum(
            1 for a in alerts if a.get("state") == "firing"),
        "alerts_active": len(alerts),
        "namespaces": len(fleet.get("namespaces") or {}),
    }


# ---------------------------------------------------------------------------
# kubectl mode (real cluster)
# ---------------------------------------------------------------------------


def kubectl_io(obj: dict, operation: str, namespace: str) -> None:
    cmd = ["kubectl", operation, "-n", namespace]
    if operation == "delete":
        cmd.append("--ignore-not-found")
    cmd += ["-f", "-"]
    proc = subprocess.run(
        cmd, input=yaml.dump(obj).encode(), capture_output=True
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"kubectl {operation} failed for "
            f"{obj['kind']}/{obj['metadata']['name']}: "
            f"{proc.stderr.decode().strip()}"
        )


def ready_notebooks_kubectl(namespace: str) -> set[str]:
    """One ``kubectl get notebooks -o json`` per poll pass (a per-notebook
    exec would bias the latencies this harness exists to measure). A CR with
    no status yet simply doesn't count as ready. Errors are tolerated — a
    transient apiserver failure should not abort the measurement."""
    proc = subprocess.run(
        ["kubectl", "get", "notebooks", "-n", namespace, "-o", "json"],
        capture_output=True,
    )
    if proc.returncode != 0:
        print(
            f"kubectl get notebooks: {proc.stderr.decode().strip()}",
            file=sys.stderr,
        )
        return set()
    ready = set()
    for item in json.loads(proc.stdout.decode()).get("items", []):
        want = max((item["spec"].get("tpu") or {}).get("replicas", 1), 1)
        if (item.get("status") or {}).get("readyReplicas", 0) >= want:
            ready.add(item["metadata"]["name"])
    return ready


def run_kubectl(args: argparse.Namespace) -> dict | None:
    nb_tmpl, pvc_tmpl = load_templates()
    created_at: dict[str, float] = {}
    for i in range(args.num_notebooks):
        nb = render_notebook(nb_tmpl, i, args.namespace)
        pvc = render_pvc(pvc_tmpl, i, args.namespace)
        print(f"kubectl {args.operation} notebook/{nb['metadata']['name']} ...")
        if args.operation == "delete":
            # Notebook first: kubectl delete waits by default, and the
            # pvc-protection finalizer holds a PVC that a live notebook
            # pod still mounts.
            kubectl_io(nb, args.operation, args.namespace)
            kubectl_io(pvc, args.operation, args.namespace)
        else:
            kubectl_io(pvc, args.operation, args.namespace)
            kubectl_io(nb, args.operation, args.namespace)
        created_at[nb["metadata"]["name"]] = time.monotonic()
    if args.operation != "apply" or not args.wait:
        return None
    latencies: dict[str, float] = {}
    deadline = time.monotonic() + args.timeout
    while len(latencies) < len(created_at) and time.monotonic() < deadline:
        now = time.monotonic()
        for name in ready_notebooks_kubectl(args.namespace):
            if name in created_at and name not in latencies:
                latencies[name] = now - created_at[name]
        time.sleep(args.poll_interval)
    return summarize(latencies, "kubectl")


# ---------------------------------------------------------------------------
# simulate mode (in-process controller + fake kubelet)
# ---------------------------------------------------------------------------


class FakeKubelet:
    """Plays the kubelet's role against the API server: for every
    StatefulSet it sees, after ``pod_latency`` seconds it creates the
    replica pods with Ready conditions and marks the StatefulSet ready —
    the signal the controller's status mirror consumes.

    Watch-driven, like the real kubelet: STS arrive over a watch stream
    (fake queue or production ApiClient chunked watch — same duck type)
    instead of a full LIST per tick. A list-per-tick kubelet was the
    harness's own quadratic term at N=200: every poll re-serialised
    every STS spec in the cluster."""

    def __init__(self, api, pod_latency: float = 0.0):
        self.api = api
        self.pod_latency = pod_latency
        self._started: dict[tuple[str, str], float] = {}
        self._pending: dict[tuple[str, str], dict] = {}
        self._done: set[tuple[str, str]] = set()
        # Informer semantics (list + watch): subscribe FIRST, then seed
        # from a full list — STS that predate this kubelet must still
        # come up, and an event arriving between the two is absorbed by
        # the idempotent pending dict.
        self._watch = api.watch("apps/v1", "StatefulSet")
        for sts in api.list("apps/v1", "StatefulSet"):
            key = (sts["metadata"]["namespace"], sts["metadata"]["name"])
            self._pending[key] = sts

    def step(self, now: float) -> int:
        while True:
            try:
                ev = self._watch.get_nowait()
            except queue.Empty:
                break
            key = (ev.object["metadata"]["namespace"],
                   ev.object["metadata"]["name"])
            if ev.type == "DELETED":
                self._pending.pop(key, None)
                self._started.pop(key, None)
                self._done.discard(key)
            elif ev.type in ("ADDED", "MODIFIED"):
                if key not in self._done:
                    self._pending[key] = ev.object
        changed = 0
        for key, sts in list(self._pending.items()):
            meta = sts["metadata"]
            self._started.setdefault(key, now)
            if now - self._started[key] < self.pod_latency:
                continue
            try:
                changed += self._make_ready(key, meta, sts)
            except NotFound:
                # STS vanished between the watch event and now; a
                # DELETED event will (or did) clean up. Never let one
                # stale entry starve the rest of the pending set.
                self._pending.pop(key, None)
        return changed

    def _make_ready(self, key, meta, sts) -> int:
        replicas = sts["spec"].get("replicas", 1)
        for ordinal in range(replicas):
            self.api.apply(
                {
                    "apiVersion": "v1",
                    "kind": "Pod",
                    "metadata": {
                        "name": f"{meta['name']}-{ordinal}",
                        "namespace": meta["namespace"],
                        "labels": dict(
                            (
                                sts["spec"].get("template", {}).get("metadata")
                                or {}
                            ).get("labels", {})
                        ),
                    },
                    "status": {
                        "phase": "Running",
                        "containerStatuses": [
                            {"state": {"running": {"startedAt": "1970-01-01T00:00:00Z"}}}
                        ],
                        "conditions": [{"type": "Ready", "status": "True"}],
                    },
                }
            )
        fresh = self.api.get(
            "apps/v1", "StatefulSet", meta["name"], meta["namespace"]
        )
        fresh.setdefault("status", {})["readyReplicas"] = replicas
        self.api.update(fresh)
        self._done.add(key)
        del self._pending[key]
        return 1


def _measure_spawn_ready(
    api,
    kubelet: FakeKubelet,
    num_notebooks: int,
    namespace: str,
    timeout: float,
    poll_sleep: float,
) -> dict[str, float]:
    """Shared measurement core for simulate/processes: run the fake
    kubelet on a thread, create N notebook+PVC pairs, record readiness
    (status.readyReplicas >= wanted replicas) from the Notebook watch
    stream, return latencies.

    Watch-driven on both sides (kubelet and readiness): a poll loop
    listing every Notebook per tick was itself a quadratic load source
    at N=200 — the harness must not be the bottleneck it measures."""
    nb_tmpl, pvc_tmpl = load_templates()
    created_at: dict[str, float] = {}
    latencies: dict[str, float] = {}
    stop = threading.Event()
    logged_errors: set[str] = set()
    lock = threading.Lock()
    # Ready can be observed before the create() caller records its
    # timestamp (the watch thread races the create loop); park those.
    ready_at: dict[str, float] = {}

    def kubelet_loop():
        while not stop.is_set():
            try:
                kubelet.step(time.monotonic())
            except Conflict:
                # Racing the controller's own STS update: the STS stays
                # pending and is retried next tick.
                pass
            except Exception:
                # A real bug must not kill the thread (readiness would
                # stall to timeout) but must also not be silent.
                err = traceback.format_exc()
                if err not in logged_errors:
                    logged_errors.add(err)
                    print(f"fake kubelet error:\n{err}", file=sys.stderr)
            time.sleep(poll_sleep)

    nb_watch = api.watch("kubeflow.org/v1beta1", "Notebook")

    def readiness_loop():
        while not stop.is_set():
            try:
                ev = nb_watch.get(timeout=0.1)
            except queue.Empty:
                continue
            try:
                obj = ev.object
                if ev.type not in ("ADDED", "MODIFIED"):
                    continue
                if obj["metadata"].get("namespace") != namespace:
                    continue
                name = obj["metadata"]["name"]
                want = max(
                    (obj.get("spec", {}).get("tpu") or {})
                    .get("replicas", 1), 1,
                )
                if (obj.get("status") or {}).get("readyReplicas", 0) >= want:
                    with lock:
                        if name not in ready_at:
                            ready_at[name] = time.monotonic()
            except Exception:
                # Same contract as kubelet_loop: a malformed event must
                # not kill the thread (the run would stall to timeout)
                # but must not be silent either.
                err = traceback.format_exc()
                if err not in logged_errors:
                    logged_errors.add(err)
                    print(f"readiness watcher error:\n{err}",
                          file=sys.stderr)

    kubelet_thread = threading.Thread(target=kubelet_loop, daemon=True)
    ready_thread = threading.Thread(target=readiness_loop, daemon=True)
    kubelet_thread.start()
    ready_thread.start()
    try:
        for i in range(num_notebooks):
            nb = render_notebook(nb_tmpl, i, namespace)
            api.create(render_pvc(pvc_tmpl, i, namespace))
            api.create(nb)
            created_at[nb["metadata"]["name"]] = time.monotonic()
        deadline = time.monotonic() + timeout
        while len(latencies) < num_notebooks and time.monotonic() < deadline:
            with lock:
                for name, t_ready in ready_at.items():
                    if name in created_at and name not in latencies:
                        latencies[name] = max(0.0, t_ready - created_at[name])
            if len(latencies) < num_notebooks:
                time.sleep(0.05)
    finally:
        stop.set()
        kubelet_thread.join(timeout=1)
        ready_thread.join(timeout=1)
    return latencies


def run_simulate(
    num_notebooks: int,
    namespace: str = "kubeflow",
    pod_latency: float = 0.0,
    timeout: float = 60.0,
) -> dict:
    """Simulate-mode run, instrumented: the controller runs with the
    manager's metrics registry + default SLO engine behind a live
    ManagerServer, and the summary carries a ``control_plane`` block
    (reconcile p99 / queue-wait p99 / alert counts) read back from
    ``/metrics`` + ``/fleet`` — the measurability bridge to the
    ROADMAP item-3 churn soak."""
    from kubeflow_tpu.controllers.manager import make_default_slo_engine
    from kubeflow_tpu.controllers.metrics import (
        ControllerMetrics,
        ManagerServer,
    )
    from kubeflow_tpu.controllers.notebook import make_notebook_controller
    from kubeflow_tpu.k8s import FakeApiServer

    api = FakeApiServer()
    prom = ControllerMetrics(api)
    controller = make_notebook_controller(api, prom=prom)
    slo_engine = make_default_slo_engine(prom, api)
    controller.tick_hooks.append(slo_engine.tick)
    prom.watch_controllers([controller])
    server = ManagerServer(prom, slo=slo_engine, fleet_api=api)
    server.start()
    kubelet = FakeKubelet(api, pod_latency=pod_latency)
    controller_thread = controller.start()
    try:
        latencies = _measure_spawn_ready(
            api, kubelet, num_notebooks, namespace, timeout,
            poll_sleep=0.002,
        )
        control_plane = control_plane_summary(server, slo_engine,
                                              "simulate")
    finally:
        controller.stop()
        controller_thread.join(timeout=1)
        server.stop()
    summary = summarize(latencies, "simulate")
    summary["control_plane"] = control_plane
    return summary


def run_processes(
    num_notebooks: int,
    namespace: str = "kubeflow",
    pod_latency: float = 0.0,
    timeout: float = 120.0,
) -> dict:
    """simulate-mode measurement across real process boundaries: the
    controller is an OS process connected over HTTP; the harness and
    fake kubelet use the production ApiClient."""
    import os
    import signal
    import subprocess

    from kubeflow_tpu.k8s.client import ApiClient, KubeConfig
    from kubeflow_tpu.k8s.httpd import FakeApiHttpServer

    server = FakeApiHttpServer().start()
    env = {
        **os.environ,
        "KFT_APISERVER": server.url,
        "METRICS_PORT": "0",
        "JAX_PLATFORMS": "cpu",
    }
    env.pop("KFT_FAKE_API", None)
    controller = subprocess.Popen(
        [sys.executable, "-m", "kubeflow_tpu", "notebook-controller"],
        env=env,
        cwd=str(HERE.parent),
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    # Drain controller output on a thread: an undrained PIPE would
    # deadlock a chatty controller once the OS buffer fills, and its
    # log is the only diagnostic when a run fails.
    controller_log: list[str] = []
    started = threading.Event()

    def drain():
        for line in controller.stdout:
            controller_log.append(line)
            if "notebook-controller started" in line:
                started.set()

    drain_thread = threading.Thread(target=drain, daemon=True)
    drain_thread.start()

    api = ApiClient(KubeConfig(host=server.url))
    kubelet = FakeKubelet(api, pod_latency=pod_latency)
    try:
        # Readiness, not a fixed sleep: the controller logs its started
        # line after wiring watches; a dead process is caught here
        # instead of burning the whole measurement timeout.
        boot_deadline = time.monotonic() + 30.0
        while not started.is_set():
            if controller.poll() is not None:
                raise RuntimeError(
                    "controller exited before starting:\n"
                    + "".join(controller_log)
                )
            if time.monotonic() > boot_deadline:
                raise RuntimeError(
                    "controller did not report started within 30s:\n"
                    + "".join(controller_log)
                )
            time.sleep(0.05)
        latencies = _measure_spawn_ready(
            api, kubelet, num_notebooks, namespace, timeout,
            poll_sleep=0.01,
        )
    finally:
        controller.send_signal(signal.SIGTERM)
        try:
            controller.wait(timeout=10)
        except subprocess.TimeoutExpired:
            controller.kill()
        drain_thread.join(timeout=2)
        api.close()
        server.close()
    summary = summarize(latencies, "processes")
    if len(latencies) < num_notebooks:
        print("controller log tail:\n" + "".join(controller_log[-50:]),
              file=sys.stderr)
    return summary


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        description="Load test the notebook controller, capturing "
        "spawn->ready latency percentiles."
    )
    parser.add_argument(
        "-l", "--load", dest="num_notebooks", type=int, default=3,
        help="Number of notebooks to spawn. (Default: %(default)s)",
    )
    parser.add_argument(
        "-n", "--namespace", default="kubeflow",
        help="Namespace for the workload. (Default: %(default)s)",
    )
    parser.add_argument(
        "-p", "--operation", choices=["apply", "delete"], default="apply",
        help="kubectl operation. (Default: %(default)s)",
    )
    parser.add_argument(
        "--mode", choices=["kubectl", "simulate", "processes"],
        default="kubectl",
        help="Real cluster via kubectl, in-process controller simulation, "
        "or real process boundaries over the HTTP wire (processes).",
    )
    parser.add_argument(
        "--wait", action="store_true",
        help="kubectl mode: poll readiness and print the latency summary.",
    )
    parser.add_argument(
        "--pod-latency", type=float, default=0.0,
        help="simulate mode: seconds the fake kubelet waits before pods go "
        "Ready.",
    )
    parser.add_argument("--timeout", type=float, default=600.0)
    parser.add_argument("--poll-interval", type=float, default=2.0)
    return parser


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.mode == "simulate":
        summary = run_simulate(
            args.num_notebooks,
            namespace=args.namespace,
            pod_latency=args.pod_latency,
            timeout=args.timeout,
        )
    elif args.mode == "processes":
        summary = run_processes(
            args.num_notebooks,
            namespace=args.namespace,
            pod_latency=args.pod_latency,
            timeout=args.timeout,
        )
    else:
        summary = run_kubectl(args)
    if summary is not None:
        # The control-plane block prints as its OWN JSON line so soak
        # tooling greps one metric per line (same discipline as
        # serve_qps's summary line).
        control_plane = summary.pop("control_plane", None)
        print(json.dumps(summary))
        if control_plane is not None:
            print(json.dumps(control_plane))
            summary["control_plane"] = control_plane
        if summary["count"] < args.num_notebooks:
            print(
                f"WARNING: only {summary['count']}/{args.num_notebooks} "
                "notebooks became ready before the timeout",
                file=sys.stderr,
            )
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
