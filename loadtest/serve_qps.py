"""Closed-loop QPS load harness for the inference gateway.

The serving analogue of start_notebooks.py: N closed-loop clients
(each issues the next request the moment its stream completes) drive
``POST /v1/generate`` and capture the serving SLO numbers the
platform optimises for — time-to-first-token (arrival of the first
SSE data frame), steady-state per-request inter-token latency
(``itl_p50_s``/``itl_p99_s``: pooled gaps between consecutive data
frames after each stream's first token — the decode hot path, where
the fused-kernel/speculative wins land) with the per-stream decode
rate ``decode_tokens_per_s_per_stream`` (= pooled gap count / pooled
gap seconds), and end-to-end stream time — plus aggregate tokens/sec;
the summary prints as one JSON line with p50/p99. After the run the
gateway's own burn-rate verdict is read back from ``/v1/status`` and
attached as ``slo`` (per-objective fast/slow burn + alert state), so
a load run that pushed TTFT or inter-token latency past its objective
reports the judgement next to the numbers that caused it.

Modes:

- ``--url http://host:port`` — drive an already-running gateway (a
  deployed InferenceService endpoint).
- default — start an in-process gateway on a small CPU model and
  drive it over real HTTP sockets: the full wire path (admission,
  SSE framing, shedding) with no cluster needed.
- ``--smoke`` — the tier-1 fast preset of the in-process mode (tiny
  model, handful of requests); tests/test_inference.py runs it.

429 responses are honoured closed-loop: the client waits the served
``Retry-After`` and retries the same request (counted in ``shed``).

Usage:
  python -m loadtest.serve_qps --clients 8 --requests 64
  python -m loadtest.serve_qps --url http://llm.team-a.svc:8800
  python -m loadtest.serve_qps --smoke
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from kubeflow_tpu.obs import perfwatch  # noqa: E402
from loadtest.start_notebooks import percentile  # noqa: E402


def stream_one(url: str, prompt: list[int], max_new: int,
               timeout: float) -> dict:
    """One greedy /v1/generate stream; returns ttft_s/total_s/tokens/
    shed counts. Retries through 429 + Retry-After (closed-loop
    clients honour shedding; that IS the protocol under test)."""
    data = json.dumps({"prompt": prompt,
                       "max_new_tokens": max_new}).encode()
    shed = 0
    while True:
        started = time.monotonic()
        req = urllib.request.Request(
            url + "/v1/generate", data=data,
            headers={"Content-Type": "application/json"})
        try:
            response = urllib.request.urlopen(req, timeout=timeout)
        except urllib.error.HTTPError as exc:
            if exc.code == 429:
                shed += 1
                time.sleep(float(exc.headers.get("Retry-After", "1")))
                continue
            raise
        ttft = None
        tokens = 0
        done = None
        last_token_at = None
        gaps: list[float] = []
        with response:
            event = None
            for raw in response:
                line = raw.decode().rstrip("\n")
                if line.startswith("event: "):
                    event = line[len("event: "):]
                elif line.startswith("data: "):
                    payload = json.loads(line[len("data: "):])
                    if event == "done":
                        done = payload
                        break
                    now = time.monotonic()
                    if ttft is None:
                        ttft = now - started
                    else:
                        # Steady-state inter-token latency: the gap
                        # between consecutive data frames AFTER the
                        # first token (prefill lives in TTFT).
                        gaps.append(now - last_token_at)
                    last_token_at = now
                    tokens += 1
                elif not line:
                    event = None
        return {
            "ttft_s": ttft if ttft is not None else float("nan"),
            "total_s": time.monotonic() - started,
            "tokens": tokens,
            "itl_s": gaps,
            "shed": shed,
            "cache_hit": bool(done and done.get("cache_hit")),
        }


def run_load(url: str, prompts: list[list[int]], clients: int,
             total_requests: int, max_new: int,
             timeout: float) -> dict:
    """Closed loop: ``clients`` threads pull request indices off one
    counter until ``total_requests`` streams completed."""
    lock = threading.Lock()
    state = {"next": 0}
    results: list[dict] = []
    errors: list[str] = []

    def worker():
        while True:
            with lock:
                index = state["next"]
                if index >= total_requests:
                    return
                state["next"] = index + 1
            prompt = prompts[index % len(prompts)]
            try:
                out = stream_one(url, prompt, max_new, timeout)
            # analysis: allow[py-broad-except] — recorded in the summary
            except Exception as exc:
                with lock:
                    errors.append(f"request {index}: {exc}")
                # Don't hammer a failing endpoint at closed-loop
                # speed: pause a beat before taking the next index.
                time.sleep(0.1)
                continue
            with lock:
                results.append(out)

    started = time.monotonic()
    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(clients)]
    for thread in threads:
        thread.start()
    for thread in threads:
        thread.join()
    wall = time.monotonic() - started
    ttfts = sorted(r["ttft_s"] for r in results
                   if r["ttft_s"] == r["ttft_s"])  # NaN-free
    totals = sorted(r["total_s"] for r in results)
    tokens = sum(r["tokens"] for r in results)
    # Steady-state decode numbers: pooled per-request inter-token
    # gaps (every gap after each stream's first token). The kernel
    # wins PR 8 chases live exactly here — TTFT is prefill-bound, the
    # gaps are the decode hot path.
    gaps = sorted(g for r in results for g in r["itl_s"])
    decode_tok_s = (round(len(gaps) / sum(gaps), 2)
                    if gaps and sum(gaps) > 0 else 0.0)
    summary = {
        "metric": "inference_gateway_load",
        "count": len(results),
        "errors": errors,
        "wall_s": round(wall, 4),
        "qps": round(len(results) / wall, 3) if wall else 0.0,
        "tokens_per_s": round(tokens / wall, 2) if wall else 0.0,
        "ttft_p50_s": round(percentile(ttfts, 0.50), 4),
        "ttft_p99_s": round(percentile(ttfts, 0.99), 4),
        "itl_p50_s": round(percentile(gaps, 0.50), 5),
        "itl_p99_s": round(percentile(gaps, 0.99), 5),
        "decode_tokens_per_s_per_stream": decode_tok_s,
        "total_p50_s": round(percentile(totals, 0.50), 4),
        "total_p99_s": round(percentile(totals, 0.99), 4),
        "shed": sum(r["shed"] for r in results),
        "cache_hits": sum(1 for r in results if r["cache_hit"]),
    }
    # Gateway SLOs join the perf trajectory through the SAME schema
    # kernel sections use: each completed stream's steady-state decode
    # rate is one trial, banded by the multi-trial protocol, so the
    # ledger/verdict engine reads `serve[decode]` exactly like a
    # `decode[*]` bench section.
    stream_rates = [
        len(r["itl_s"]) / sum(r["itl_s"])
        for r in results
        if r["itl_s"] and sum(r["itl_s"]) > 0
    ]
    if stream_rates:
        summary["perfwatch_record"] = perfwatch.make_record(
            "serve[decode]",
            "gateway_decode_tokens_per_s_per_stream",
            "tokens/sec/stream",
            perfwatch.Measurement.from_values(stream_rates),
            extra={key: summary[key] for key in (
                "qps", "ttft_p50_s", "ttft_p99_s", "itl_p50_s",
                "itl_p99_s", "decode_tokens_per_s_per_stream",
                "shed", "cache_hits",
            )},
        )
    return summary


def fetch_status(url: str, timeout: float) -> dict | None:
    """One ``/v1/status`` read after the load (the call also ticks the
    gateway's SLO engine, so the run's own observations are what gets
    judged). Best-effort: a dead endpoint returns None — the load
    numbers still print."""
    try:
        with urllib.request.urlopen(url + "/v1/status",
                                    timeout=timeout) as response:
            return json.loads(response.read().decode())
    # analysis: allow[py-broad-except] — optional read-back, None is the answer
    except Exception:
        return None


def condense_slo(doc: dict | None) -> dict | None:
    """The status doc's SLO block condensed to one row per objective
    (fast/slow burn + alert state); None on an older gateway."""
    slo = (doc or {}).get("slo")
    if not isinstance(slo, dict):
        return None
    return {
        name: {
            "burn": row.get("burn", {}),
            "states": row.get("states", {}),
        }
        for name, row in (slo.get("objectives") or {}).items()
    }


def fetch_slo_status(url: str, timeout: float) -> dict | None:
    """Back-compat shim: condensed SLO block straight off the wire."""
    return condense_slo(fetch_status(url, timeout))


def cycle_profile(doc: dict | None) -> dict | None:
    """The engine's cycle-phase digest from the status doc (PR 10):
    ``{phase: {p50_s, p99_s, n}}`` for admit / prefill / decode (+
    verify / commit in speculative mode) — bench trajectory captures
    *which phase* regressed, not just end-to-end TTFT/ITL."""
    profile = (doc or {}).get("profile")
    if isinstance(profile, dict) and profile:
        return profile
    return None


def profiler_overhead(profile: dict | None) -> dict | None:
    """Measured profiler cost against the decode hot path: the mean
    seconds ONE phase record costs on this host (clock pair + locked
    digest append + scope accumulate, measured with the profiler both
    on and exercising — :func:`obs.profile.measure_overhead_s`) times
    the records a working cycle makes (one per phase + the activation
    scope), as a fraction of the measured decode-phase p50. The
    acceptance budget is <2%; the smoke test asserts it. Valid only
    when the gateway runs on this host (the in-process mode) — the
    caller skips it for remote ``--url`` targets."""
    if not profile or "decode" not in profile:
        return None
    decode_p50 = float(profile["decode"].get("p50_s") or 0.0)
    if decode_p50 <= 0:
        return None
    from kubeflow_tpu.obs.profile import measure_overhead_s

    per_record = measure_overhead_s()
    records_per_cycle = len(profile) + 1  # + the activation scope
    return {
        "per_record_s": round(per_record, 9),
        "records_per_cycle": records_per_cycle,
        "decode_p50_s": decode_p50,
        "frac_of_decode": round(
            per_record * records_per_cycle / decode_p50, 6),
    }


def start_local_gateway(vocab: int, prompt_len: int, max_batch: int,
                        max_pending: int):
    """In-process tiny-model gateway on a real socket (imports jax
    lazily so --url mode stays light)."""
    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.models import LMConfig, build_lm, create_lm_state
    from kubeflow_tpu.serving.engine import StreamingBatcher
    from kubeflow_tpu.serving.gateway import InferenceGateway

    cfg = LMConfig(vocab=vocab, layers=2, dim=64, heads=4, kv_heads=2,
                   dtype=jnp.bfloat16)
    model = build_lm(cfg, use_flash=False)
    params = create_lm_state(model, jax.random.key(0),
                             (1, prompt_len)).params
    engine = StreamingBatcher(
        cfg, params, max_batch=max_batch,
        max_len=max(64, 4 * prompt_len), max_pending=max_pending)
    return InferenceGateway(engine, port=0).start()


def build_prompts(count: int, prompt_len: int, vocab: int,
                  seed: int) -> list[list[int]]:
    """Distinct prompts plus one shared-prefix pair so a load run also
    exercises the prefix cache."""
    import random

    rng = random.Random(seed)
    prompts = [
        [rng.randrange(1, vocab) for _ in range(prompt_len)]
        for _ in range(count)
    ]
    if count >= 2:
        prompts[1] = prompts[0] + [rng.randrange(1, vocab)]
    return prompts


def main(argv=None) -> dict:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--url", default=None,
                        help="target gateway (default: in-process)")
    parser.add_argument("--clients", type=int, default=4)
    parser.add_argument("--requests", type=int, default=32)
    parser.add_argument("--max-new", type=int, default=16)
    parser.add_argument("--prompt-len", type=int, default=12)
    parser.add_argument("--prompts", type=int, default=8,
                        help="distinct prompt count")
    parser.add_argument("--timeout", type=float, default=120.0)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--smoke", action="store_true",
                        help="tier-1 preset: tiny everything")
    args = parser.parse_args(argv)
    if args.smoke:
        args.clients, args.requests = 2, 6
        args.max_new, args.prompt_len, args.prompts = 6, 8, 3
    vocab = 128
    prompts = build_prompts(args.prompts, args.prompt_len, vocab,
                            args.seed)
    gateway = None
    url = args.url
    if url is None:
        gateway = start_local_gateway(
            vocab, args.prompt_len, max_batch=4,
            max_pending=max(64, args.requests))
        url = f"http://127.0.0.1:{gateway.port}"
    try:
        summary = run_load(url, prompts, args.clients, args.requests,
                           args.max_new, args.timeout)
        # Read the burn-rate verdict AND the cycle-phase digest AFTER
        # the load: the status call also ticks the gateway's SLO
        # engine, so the run's own TTFT and inter-token observations
        # are what gets judged.
        status_doc = fetch_status(url, args.timeout)
        summary["slo"] = condense_slo(status_doc)
        summary["cycle_profile"] = cycle_profile(status_doc)
        # Only meaningful for the in-process gateway: measure_overhead_s
        # runs on THIS host, so against a remote --url target the
        # fraction would mix client-side record cost with server-side
        # decode time — a number describing neither machine.
        summary["profiler_overhead"] = (
            profiler_overhead(summary["cycle_profile"])
            if gateway is not None else None)
    finally:
        if gateway is not None:
            gateway.stop()
    print(json.dumps(summary))
    return summary


if __name__ == "__main__":
    main()
