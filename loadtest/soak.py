"""Fleet-scale control-plane soak: 10k CRs of churn against the
sharded control plane, chaos-gated (the ROADMAP item-3 acceptance arc).

One seeded, replay-deterministic timeline drives the REAL notebook and
inference controllers — two manager replicas behind per-shard leases
(:class:`~kubeflow_tpu.controllers.leader.ShardedElector` +
``ShardGate``), informer caches, priority-laned workqueues, batched
status writes, and the slice-pool scheduler — through four phases:

1. **Flood**: ``--crs`` Notebooks/InferenceServices (mixed slice
   shapes, priorities, namespaces; one namespace TPU-quota'd) arrive
   over the first 30% of ticks into a pool sized to ~60% of demand,
   so the scheduler's gang-admission scan runs at fleet cardinality
   with a deep queue.
2. **Churn**: seeded create/update/delete/suspend/touch/preempt ops
   every tick, plus a capacity dip-and-regrow. Deletes ride the
   workqueue's fast lane; preempt arrivals (priority 100) drive the
   checkpoint drain; suspends/touches drive scale-to-zero and
   resurrect at scale.
3. **Mid-soak lease revocation**: a shard lease is forcibly rewritten
   to a foreign holder — the owner must step down (stop popping, drain
   in-flight), and after expiry a replica with spare quota re-acquires
   and resyncs the shard before reconciling it.
4. **Chaos matrix** (the PR-2 schedule against the SHARDED
   configuration): conflict storm, 5xx burst, full blackout, and
   watch drop/dup/reorder/compaction — then informer ``recover()``
   (the 410 re-list path) and ``run_to_convergence``.

Gates: reconcile-duration and queue-wait burn-rate SLOs (PR 9) judged
per replica — the flight recorder dumps on any breach — must be green
in steady state; ZERO dual-leader reconciles (every reconcile is
checked against the live shard-lease holder); zero orphaned CRs after
convergence; scheduler incremental bookkeeping audits clean; and
``replay_digest`` is byte-identical across runs.

Determinism (the game-day constraints): every clock is the scenario
clock; controllers and caches talk through a chaos proxy whose fault
windows are op-indexed and EMPTY until the chaos phase; scenario ops
(the "user" plane) and lease reads go to the plain store. Real-time
quantities — reconcile durations, queue waits, SLO burn — are
measured and gated but deliberately EXCLUDED from the digest, as are
chaos-phase injection counts (retry interleaving shifts which call a
fault hits, never the converged state the digest covers).

Usage::

  python -m loadtest.soak --crs 10000 --ticks 240 --shards 4
"""

from __future__ import annotations

import argparse
import hashlib
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from kubeflow_tpu.chaos import (  # noqa: E402
    ChaosApiServer,
    Clock,
    PreemptionInjector,
    StatefulSetPodSimulator,
    WorldBuilder,
)
from kubeflow_tpu.chaos.harness import (  # noqa: E402
    clamp_backoff,
    run_to_convergence,
)
from kubeflow_tpu.controllers.inference import (  # noqa: E402
    INFERENCE_API,
    make_inference_controller,
)
from kubeflow_tpu.controllers.leader import (  # noqa: E402
    LEASE_API,
    ShardedElector,
    shard_of,
)
from kubeflow_tpu.controllers.manager import (  # noqa: E402
    make_default_slo_engine,
)
from kubeflow_tpu.controllers.metrics import ControllerMetrics  # noqa: E402
from kubeflow_tpu.controllers.notebook import (  # noqa: E402
    NOTEBOOK_API,
    make_notebook_controller,
)
from kubeflow_tpu.controllers.runtime import (  # noqa: E402
    InformerCache,
    ShardGate,
    StatusBatcher,
)
from kubeflow_tpu.controllers.time_utils import rfc3339  # noqa: E402
from kubeflow_tpu.k8s.fake import FakeApiServer, NotFound  # noqa: E402
from kubeflow_tpu.obs.recorder import FlightRecorder  # noqa: E402
from kubeflow_tpu.scheduler import (  # noqa: E402
    PRIORITY_KEY,
    SlicePoolScheduler,
)

LEASE_NAME = "soak"
REVOKER = "chaos-revoker"

# (topology, chips) mix: mostly single-host slices with a tail of
# bigger gangs, so admission mixes trivial and chunky demands.
TOPOLOGIES = [("1x1", 1)] * 6 + [("2x2", 4)] * 3 + [("2x4", 8)]
PRIORITIES = (0, 0, 0, 0, 0, 0, 5, 5, 10, 10)


def _notebook(ns: str, name: str, topology: str, priority: int) -> dict:
    return {
        "apiVersion": NOTEBOOK_API,
        "kind": "Notebook",
        "metadata": {
            "name": name, "namespace": ns,
            "annotations": {PRIORITY_KEY: str(priority)},
        },
        "spec": {
            "tpu": {"accelerator": "v5e", "topology": topology},
            "template": {"spec": {"containers": [
                {"name": "notebook", "image": "jupyter-jax-tpu"},
            ]}},
        },
    }


def _inference(ns: str, name: str, topology: str, priority: int) -> dict:
    return {
        "apiVersion": INFERENCE_API,
        "kind": "InferenceService",
        "metadata": {
            "name": name, "namespace": ns,
            "annotations": {PRIORITY_KEY: str(priority)},
        },
        "spec": {
            "modelDir": "/models/prod",
            "tpu": {"accelerator": "v5e", "topology": topology},
        },
    }


class _RecordingReconciler:
    """Wraps a reconciler to assert the dual-leader exclusion
    invariant on EVERY reconcile: the replica performing it must be
    the live holder of the key's shard lease."""

    def __init__(self, inner, soak, identity: str):
        self.inner = inner
        self.soak = soak
        self.identity = identity

    def reconcile(self, req):
        self.soak.record_reconcile(self.identity, req)
        return self.inner.reconcile(req)


class _Replica:
    """One manager replica: its shard elector/gate, informer cache,
    status batcher, metrics registry, SLO engine + flight recorder,
    and the two workload controllers."""

    def __init__(self, soak: "Soak", index: int):
        self.identity = f"manager-{index}"
        self.gate = ShardGate(soak.shards)
        self.prom = ControllerMetrics()
        self.cache = InformerCache(soak.handle)
        self.batcher = StatusBatcher(soak.handle)
        self.recorder = FlightRecorder(
            capacity=4096, dump_dir=soak.dump_dir,
            min_dump_interval_s=600.0, clock=soak.clk,
            name=f"soak-{soak.seed}-{self.identity}",
        )
        nb = make_notebook_controller(
            soak.handle, prom=self.prom, clock=soak.clk,
            scheduler=soak.scheduler, cache=self.cache,
            status_batcher=self.batcher, shard_gate=self.gate,
            **soak.notebook_kwargs(),
        )
        inf = make_inference_controller(
            soak.handle, prom=self.prom, scheduler=soak.scheduler,
            clock=soak.clk, cache=self.cache,
            status_batcher=self.batcher, shard_gate=self.gate,
            **soak.inference_kwargs(),
        )
        self.controllers = [nb, inf]
        for ctrl in self.controllers:
            ctrl.recorder = self.recorder
            ctrl.reconciler = _RecordingReconciler(
                ctrl.reconciler, soak, self.identity
            )
        self.slo = make_default_slo_engine(
            self.prom, soak.handle, clock=soak.clk,
            recorder=self.recorder,
        )
        # Leases live on the PLAIN store: the chaos matrix targets the
        # controller plane; a blacked-out lease plane would dethrone
        # every replica at once, which is a different experiment.
        self.elector = ShardedElector(
            soak.api, LEASE_NAME, self.identity, soak.shards,
            lease_duration_s=2.0 * soak.tick_s,
            clock=soak.clk, gate=self.gate,
        )


class Soak:
    FLOOD_END = 0.30     # arrivals stop; pure churn begins
    DIP_AT = 0.45        # capacity dips to 80%...
    REGROW_AT = 0.65     # ...and returns
    REVOKE_AT = 0.55     # a shard lease is forcibly rewritten

    def __init__(self, seed: int = 11, crs: int = 10000,
                 ticks: int = 240, tick_s: float = 30.0,
                 shards: int = 4, replicas: int = 2,
                 namespaces: int = 8, chaos: bool = True,
                 pod_plane: bool = False, dump_dir: str = "."):
        self.seed = int(seed)
        self.crs = int(crs)
        self.ticks = int(ticks)
        self.tick_s = float(tick_s)
        self.shards = max(1, int(shards))
        self.replica_count = max(1, int(replicas))
        self.namespaces = max(1, int(namespaces))
        self.chaos_enabled = bool(chaos)
        self.dump_dir = dump_dir
        self.clk = Clock(0.0)

        # Pool sized to ~60% of expected demand (avg 2.6 chips/CR), so
        # a deep queue forms; the quota'd namespace binds sooner.
        avg_chips = sum(c for _, c in TOPOLOGIES) / len(TOPOLOGIES)
        self.capacity = max(32, int(self.crs * avg_chips * 0.6))
        self.world = self._build_world()
        self.schedule = self.world.schedule
        # Tenant churn draws from the world's own derived stream, so
        # composing more tracks (the fleet storm) never shifts a churn
        # instant. (This moved the draws off random.Random(seed) — the
        # soak digest was re-baselined for it; see tests/test_world.py.)
        self.rng = self.world.stream("tenants")
        self._mix = self.world.tenant_mixes["churn"]
        self._thresholds = self._mix.thresholds()
        self.api = FakeApiServer()
        # Controllers/caches reach the store through the chaos proxy;
        # its schedule holds NO fault windows until the chaos phase,
        # so the deterministic phases see a clean passthrough while op
        # counts accrue for the later window placement.
        self.handle = ChaosApiServer(self.api, self.schedule,
                                     sleep=lambda s: None)
        # Opt-in pod plane: the statefulset/kubelet simulator rides
        # the soak tick (its indexed scan keeps the pass O(pods), not
        # O(pods x statefulsets)), and correlated-domain weather gets
        # real pod casualties. Off by default — the base soak judges
        # the CR plane only, and its digest predates the pod plane.
        self.pod_plane = bool(pod_plane)
        self.sim = None
        self.injector = None
        if self.pod_plane:
            self.sim = StatefulSetPodSimulator(
                self.api, recreate_on_template_change=True,
                gc_orphans=True)
            self.injector = PreemptionInjector(self.api,
                                               sleep=lambda s: None)
        self.scheduler = SlicePoolScheduler(
            capacity_fn=lambda: self.world.capacity_at(self.clk()),
            api=self.handle,
            clock=self.clk,
            aging_s=3600.0,
            drain_grace_s=4.0 * self.tick_s,
            enabled=True,
        )
        self.api.create({
            "apiVersion": "v1", "kind": "ResourceQuota",
            "metadata": {"name": "kf-resource-quota",
                         "namespace": "ns-0"},
            "spec": {"hard": {
                "google.com/tpu": str(max(8, self.capacity // 10)),
            }},
        })
        self.replicas = [_Replica(self, i)
                         for i in range(self.replica_count)]

        self.flood_end = max(1, int(self.FLOOD_END * self.ticks))
        self.revoke_tick = int(self.REVOKE_AT * self.ticks)
        self.ops_per_tick = max(1, self.crs // 200)
        self.per_flood_tick = -(-self.crs // self.flood_end)  # ceil
        self.tick_budget = max(500, (5 * self.crs) // max(1, self.ticks))

        self.nb_counter = 0
        self.inf_counter = 0
        self.created = 0
        self.deleted = 0
        self.alive_nb: list[tuple[str, str]] = []
        # Bounded by the seeded script's create budget.
        # analysis: allow[py-unbounded-deque]
        self.alive_inf: list[tuple[str, str]] = []
        self.suspend_targets: list[tuple[str, str]] = []
        # Seeded-script artifacts, all replay-covered by the digest.
        # analysis: allow[py-unbounded-deque]
        self.op_log: list[list] = []
        # analysis: allow[py-unbounded-deque]
        self.timeline: list[list] = []
        # analysis: allow[py-unbounded-deque]
        self.dual_violations: list[tuple] = []
        self.reconcile_counts = {r.identity: 0 for r in self.replicas}

    # ---- composition hooks (FleetStorm overrides) ------------------------
    def _build_world(self):
        return self._build_world_builder().build()

    def _build_world_builder(self) -> WorldBuilder:
        """The soak's declarative timeline: capacity weather (dip +
        symmetric restore) and the churn tenant mix. Subclasses
        compose more tracks onto the returned builder — per-track
        streams guarantee these instants never shift."""
        return (
            WorldBuilder(self.seed, self.ticks, self.tick_s)
            .capacity(0.0, self.capacity)
            .capacity(self.DIP_AT, int(self.capacity * 0.8),
                      jitter_s=self.tick_s)
            .capacity_restore(self.REGROW_AT, jitter_s=self.tick_s)
            .tenants(
                "churn",
                namespaces=tuple(f"ns-{i}"
                                 for i in range(self.namespaces)),
                topologies=TOPOLOGIES,
                priorities=PRIORITIES,
                weights={"create": 0.15, "delete": 0.13,
                         "suspend": 0.10, "touch": 0.06,
                         "preempt": 0.06},
            )
        )

    def notebook_kwargs(self) -> dict:
        """Extra kwargs for every replica's notebook controller."""
        return {}

    def inference_kwargs(self) -> dict:
        return {}

    # ---- invariants ------------------------------------------------------
    def _shard_lease_name(self, shard: int) -> str:
        return (LEASE_NAME if self.shards == 1
                else f"{LEASE_NAME}-shard-{shard}")

    def lease_holder(self, shard: int) -> str | None:
        try:
            lease = self.api.get(LEASE_API, "Lease",
                                 self._shard_lease_name(shard),
                                 "kubeflow")
        except NotFound:
            return None
        return (lease.get("spec") or {}).get("holderIdentity") or None

    def record_reconcile(self, identity: str, req) -> None:
        self.reconcile_counts[identity] += 1
        shard = shard_of(req.namespace, req.name, self.shards)
        holder = self.lease_holder(shard)
        if holder != identity:
            self.dual_violations.append(
                (identity, holder, shard,
                 f"{req.namespace}/{req.name}")
            )

    # ---- the scripted world ---------------------------------------------
    def _create(self, tick: int) -> None:
        mix = self._mix
        ns = mix.namespaces[self.rng.randrange(len(mix.namespaces))]
        topology, _chips = mix.topologies[
            self.rng.randrange(len(mix.topologies))]
        priority = mix.priorities[
            self.rng.randrange(len(mix.priorities))]
        self.created += 1
        if self.created % 40 == 0:
            name = f"inf-{self.inf_counter:05d}"
            self.inf_counter += 1
            self.api.create(_inference(ns, name, topology, priority))
            self.alive_inf.append((ns, name))
            self.op_log.append([tick, "create-inf", ns, name,
                                topology, priority])
        else:
            name = f"nb-{self.nb_counter:05d}"
            self.nb_counter += 1
            self.api.create(_notebook(ns, name, topology, priority))
            self.alive_nb.append((ns, name))
            self.op_log.append([tick, "create-nb", ns, name,
                                topology, priority])

    def _churn(self, tick: int) -> None:
        for _ in range(self.ops_per_tick):
            roll = self.rng.random()
            op = "update"
            for kind, threshold in self._thresholds:
                if roll < threshold:
                    op = kind
                    break
            if op == "create":
                self._create(tick)
            elif op == "delete" and self.alive_nb:
                i = self.rng.randrange(len(self.alive_nb))
                ns, name = self.alive_nb[i]
                self.alive_nb[i] = self.alive_nb[-1]
                self.alive_nb.pop()
                try:
                    self.api.delete(NOTEBOOK_API, "Notebook", name, ns)
                except NotFound:
                    pass
                self.deleted += 1
                self.op_log.append([tick, "delete-nb", ns, name])
            elif op == "suspend" and self.alive_nb:
                ns, name = self.alive_nb[
                    self.rng.randrange(len(self.alive_nb))]
                started = self.scheduler.mark_reclaimable(
                    "Notebook", ns, name, now=self.clk())
                if started:
                    self.suspend_targets.append((ns, name))
                self.op_log.append(
                    [tick, "suspend", ns, name, int(started)])
            elif op == "touch" and self.suspend_targets:
                i = self.rng.randrange(len(self.suspend_targets))
                ns, name = self.suspend_targets[i]
                woke = self.scheduler.touch("Notebook", ns, name,
                                            now=self.clk())
                if woke:
                    self.suspend_targets.pop(i)
                self.op_log.append([tick, "touch", ns, name, int(woke)])
            elif op == "preempt":
                # Priority-100 arrival: preempts through the drain.
                mix = self._mix
                ns = mix.namespaces[
                    self.rng.randrange(len(mix.namespaces))]
                name = f"nb-{self.nb_counter:05d}"
                self.nb_counter += 1
                self.api.create(_notebook(ns, name, "2x4", 100))
                self.alive_nb.append((ns, name))
                self.op_log.append([tick, "preempt-arrival", ns, name])
            elif op == "update" and self.alive_nb:
                ns, name = self.alive_nb[
                    self.rng.randrange(len(self.alive_nb))]
                try:
                    self.api.patch_merge(
                        NOTEBOOK_API, "Notebook", name,
                        {"metadata": {"annotations": {
                            "soak.kubeflow-tpu.org/gen": str(tick),
                        }}},
                        ns,
                    )
                except NotFound:
                    pass
                self.op_log.append([tick, "update", ns, name])

    def _revoke(self, tick: int) -> None:
        """Forcibly rewrite the highest shard's lease to a foreign
        holder: the owner must step down on observation; a replica
        with spare quota re-acquires after expiry and resyncs."""
        shard = self.shards - 1
        name = self._shard_lease_name(shard)
        try:
            lease = self.api.get(LEASE_API, "Lease", name, "kubeflow")
        except NotFound:
            return
        victim = (lease.get("spec") or {}).get("holderIdentity")
        lease["spec"]["holderIdentity"] = REVOKER
        lease["spec"]["renewTime"] = rfc3339(int(self.clk()))
        self.api.update(lease)
        self.op_log.append([tick, "revoke-lease", shard, victim])

    # ---- drive -----------------------------------------------------------
    def _run_controllers(self, budget: int | None = None) -> int:
        worked = 0
        for replica in self.replicas:
            for ctrl in replica.controllers:
                worked += ctrl.run_once(
                    max_iterations=budget or self.tick_budget)
        return worked

    def _elector_rounds(self) -> None:
        for replica in self.replicas:
            replica.elector.try_acquire_or_renew()

    def _sample(self, tick: int) -> None:
        pool = self.scheduler.pool_snapshot()
        self.timeline.append([
            tick,
            self.created,
            self.deleted,
            pool["used_chips"],
            pool["queued"],
            pool["suspended"],
            [sorted(r.elector.owned()) for r in self.replicas],
            [sum(len(c.queue) for c in r.controllers)
             for r in self.replicas],
        ])

    def _world_ops(self, tick: int, now: float) -> None:
        """The user-plane script for one tick: flood, then churn, plus
        the one-shot lease revocation. Subclasses layer extra arrival
        tracks here (each on its own world stream)."""
        if tick < self.flood_end:
            for _ in range(self.per_flood_tick):
                if self.created < self.crs:
                    self._create(tick)
        else:
            self._churn(tick)
        if tick == self.revoke_tick:
            self._revoke(tick)

    def _post_slo(self, tick: int, now: float) -> None:
        """Hook after the per-replica SLO tick (the fleet storm's
        autopilot/observability plane rides here)."""

    def _tick(self, tick: int) -> None:
        now = self.clk.advance(self.tick_s)
        self._world_ops(tick, now)
        if self.sim is not None:
            self.world.apply_domains(now, self.injector, self.sim)
            self.injector.apply_capacity(self.world, now, self.sim)
            self.sim.step()
        self._elector_rounds()
        self._run_controllers()
        self.scheduler.tick(now)
        for replica in self.replicas:
            replica.slo.tick(now)
        self._post_slo(tick, now)
        if tick % 5 == 0 or tick == self.ticks - 1:
            self._sample(tick)

    def _cooldown(self) -> None:
        """Fast-forward the scenario clock past the slowest burn
        window plus its clear hysteresis (6h + 30m), SLO-ticking along
        the way: "steady state" then means any flood-era burn has had
        every chance to resolve — an alert still firing afterwards is
        a genuine steady-state breach, not leftover history."""
        horizon_s = 21600.0 + 1800.0
        for _ in range(int(horizon_s / self.tick_s) + 1):
            now = self.clk.advance(self.tick_s)
            self._elector_rounds()  # leases stay fresh while we wait
            for replica in self.replicas:
                replica.slo.tick(now)
            self._cooldown_tick(now)
        self.scheduler.tick(self.clk())

    def _cooldown_tick(self, now: float) -> None:
        """Hook per cooldown round: extra SLO planes (the storm's
        gateway / availability engines) tick here so THEIR burn
        windows also get the full resolve horizon."""

    def _drain_tick(self, now: float) -> None:
        """Hook per drain round (same purpose as _cooldown_tick)."""

    def _drain(self, max_rounds: int = 300) -> int:
        """Post-churn settle: advance ticks (drain deadlines must be
        able to expire) until no controller has work left."""
        for round_no in range(max_rounds):
            self.clk.advance(self.tick_s)
            self._elector_rounds()
            worked = self._run_controllers(budget=self.tick_budget * 4)
            self.scheduler.tick(self.clk())
            self._drain_tick(self.clk())
            pending = sum(
                len(ctrl.queue)
                for replica in self.replicas
                for ctrl in replica.controllers
            )
            if worked == 0 and pending == 0:
                return round_no + 1
        raise AssertionError(
            f"soak did not settle within {max_rounds} drain rounds"
        )

    # ---- chaos matrix (sharded configuration) ----------------------------
    def _chaos(self) -> dict:
        base = self.handle.ops_total
        storm = 800
        self.schedule.conflict_storm(base, base + storm, rate=0.25)
        self.schedule.errors(base + storm, base + storm + 400,
                             rate=0.3, status=503)
        self.schedule.blackout(base + storm + 400, base + storm + 520)
        self.schedule.watch_faults(drop=0.05, dup=0.05, reorder=0.05,
                                   compact=0.3, max_compactions=2)
        all_ctrls = [ctrl for replica in self.replicas
                     for ctrl in replica.controllers]
        for ctrl in all_ctrls:
            clamp_backoff(ctrl)
        # Push the op counter through the storm windows with bounded
        # rounds; retries inside shift which CALL a fault hits, never
        # the converged state asserted below.
        for _ in range(30):
            for ctrl in all_ctrls:
                ctrl.resync()
                ctrl.run_once(max_iterations=500)
            if self.handle.ops_total >= base + storm + 520:
                break
        # Symmetric repair on both fault planes: stream damage off,
        # API windows closed at the current op (history kept), then
        # informer watch-resume repair (the 410 / compaction re-list
        # path) and provable convergence.
        self.schedule.clear_watch_faults()
        self.schedule.clear_api_faults(at_op=self.handle.ops_total)
        relists = sum(r.cache.recover() for r in self.replicas)
        rounds = run_to_convergence(
            all_ctrls, max_rounds=600,
            # Every resync re-enqueues the whole keyspace: the
            # per-round budget must cover it or the queue never
            # drains at fleet cardinality.
            run_once_iterations=self.crs + 200,
        )
        return {
            "injected": dict(self.handle.injected),
            "cache_relists": relists,
            "convergence_rounds": rounds,
        }

    # ---- asserts / summary ----------------------------------------------
    def _orphans(self) -> dict:
        """Zero-orphan audit: every CR has its same-name StatefulSet
        owned by its uid; every owned child has a live owner."""
        problems: list[str] = []
        live_uids = {}
        for api_version, kind, pairs in (
            (NOTEBOOK_API, "Notebook", self.alive_nb),
            (INFERENCE_API, "InferenceService", self.alive_inf),
        ):
            for obj in self.api.list(api_version, kind):
                meta = obj["metadata"]
                live_uids[meta["uid"]] = (
                    f"{kind}/{meta.get('namespace')}/{meta['name']}"
                )
            for ns, name in pairs:
                try:
                    cr = self.api.get(api_version, kind, name, ns)
                except NotFound:
                    problems.append(f"{kind} {ns}/{name} vanished")
                    continue
                try:
                    sts = self.api.get("apps/v1", "StatefulSet", name,
                                       ns)
                except NotFound:
                    problems.append(
                        f"{kind} {ns}/{name} has no StatefulSet")
                    continue
                refs = (sts["metadata"].get("ownerReferences")) or []
                if not any(r.get("uid") == cr["metadata"]["uid"]
                           for r in refs):
                    problems.append(
                        f"StatefulSet {ns}/{name} not owned by its CR")
        for child_kind in ("StatefulSet",):
            for sts in self.api.list("apps/v1", child_kind):
                refs = (sts["metadata"].get("ownerReferences")) or []
                for ref in refs:
                    if ref.get("uid") and ref["uid"] not in live_uids:
                        problems.append(
                            f"{child_kind} "
                            f"{sts['metadata'].get('namespace')}/"
                            f"{sts['metadata']['name']} orphaned"
                        )
        return {"count": len(problems), "sample": problems[:10]}

    # Server-assigned identity, wall-clock stamps, and event-mirror
    # blocks (status.warningEvents embeds Events — whose CreateFailed
    # membership depends on which exact call a chaos fault hit).
    _SCRUB_KEYS = frozenset((
        "uid", "resourceVersion", "creationTimestamp",
        "warningEvents", "firstTimestamp", "lastTimestamp",
    ))
    # Annotation keys whose *values* embed server-assigned identity the
    # recursive key scrub cannot see: observed-mesh is a JSON string of
    # pod-name -> pod uid, so with the pod plane on it would smuggle
    # uuid4 output past _SCRUB_KEYS and break byte-identical replay.
    _SCRUB_KEY_SUFFIXES = ("/observed-mesh",)

    def _scrub(self, obj):
        if isinstance(obj, dict):
            return {
                k: self._scrub(v) for k, v in obj.items()
                if k not in self._SCRUB_KEYS
                and not k.endswith(self._SCRUB_KEY_SUFFIXES)
            }
        if isinstance(obj, list):
            return [self._scrub(v) for v in obj]
        return obj

    def _store_fingerprint(self) -> str:
        """Digest of the converged world: every stored object except
        Events (fault-retry dependent counts) and Leases (election
        timing), scrubbed of server-assigned identity."""
        doc = {}
        for api_version, kind in (
            (NOTEBOOK_API, "Notebook"),
            (INFERENCE_API, "InferenceService"),
            ("apps/v1", "StatefulSet"),
            ("v1", "Service"),
        ):
            doc[kind] = [self._scrub(o)
                         for o in self.api.list(api_version, kind)]
        return hashlib.sha256(
            json.dumps(doc, sort_keys=True).encode()
        ).hexdigest()

    def _slo_block(self) -> dict:
        gating = {"reconcile-duration", "queue-wait"}
        per_replica = {}
        green = True
        for replica in self.replicas:
            replica.slo.tick(self.clk())
            firing = sorted(
                f"{a['slo']}/{a['speed']}"
                for a in replica.slo.alerts.active()
                if a.get("state") == "firing"
            )
            if any(f.split("/")[0] in gating for f in firing):
                green = False
            queue_wait = None
            for ctrl in replica.controllers:
                snap = ctrl.queue.latency_snapshot()
                if queue_wait is None or (snap["p99"] or 0) > queue_wait:
                    queue_wait = snap["p99"]
            per_replica[replica.identity] = {
                "firing": firing,
                "queue_wait_p99_s": queue_wait,
                "reconciles": self.reconcile_counts[replica.identity],
                "flight_dumps": replica.recorder.dumps_total,
            }
        return {"steady_state_green": green, "replicas": per_replica}

    def _drive(self) -> None:
        """The main loop (a hook: the fleet storm wraps these ticks in
        the real ``run_with_checkpointing`` so its cadence consult
        sees the live alert state)."""
        for tick in range(self.ticks):
            self._tick(tick)

    def _digest_extras(self) -> dict:
        """Extra replay-covered payload keys (subclass hook)."""
        return {}

    def _summary_extras(self) -> dict:
        """Extra summary keys, merged last (subclass hook)."""
        return {}

    def run(self) -> dict:
        self._drive()
        drain_rounds = self._drain()
        self._cooldown()
        slo = self._slo_block()  # judged BEFORE chaos: steady state
        chaos = self._chaos() if self.chaos_enabled else None
        orphans = self._orphans()
        audit = self.scheduler.audit()
        fingerprint = self._store_fingerprint()
        ownership = [sorted(r.elector.owned()) for r in self.replicas]
        cache_stats = {r.identity: r.cache.stats()
                       for r in self.replicas}
        digest_payload = {
            "ops": self.op_log,
            "timeline": self.timeline,
            "counters": self.scheduler.metrics.counters(),
            "pool": self.scheduler.pool_snapshot(),
            "fingerprint": fingerprint,
            "ownership": ownership,
            "violations": len(self.dual_violations),
            "orphans": orphans["count"],
        }
        digest_payload.update(self._digest_extras())
        digest = hashlib.sha256(
            json.dumps(digest_payload, sort_keys=True).encode()
        ).hexdigest()
        summary = {
            "kind": "soak",
            "seed": self.seed,
            "crs": self.crs,
            "ticks": self.ticks,
            "tick_s": self.tick_s,
            "shards": self.shards,
            "replicas": self.replica_count,
            "capacity_chips": self.capacity,
            "created": self.created,
            "deleted": self.deleted,
            "drain_rounds": drain_rounds,
            "counters": self.scheduler.metrics.counters(),
            "pool": self.scheduler.pool_snapshot(),
            "slo": slo,
            "chaos": chaos,
            "dual_leader_reconciles": len(self.dual_violations),
            "dual_leader_sample": self.dual_violations[:5],
            "lease_revocations": sum(
                1 for op in self.op_log if op[1] == "revoke-lease"),
            "orphans": orphans,
            "scheduler_audit": audit,
            "ownership": ownership,
            "reconciles": dict(self.reconcile_counts),
            "cache": cache_stats,
            "store_fingerprint": fingerprint,
            "replay_digest": digest,
        }
        summary.update(self._summary_extras())
        return summary


def run_soak(**kwargs) -> dict:
    return Soak(**kwargs).run()


def problems_in(summary: dict) -> list[str]:
    """The acceptance checklist the CLI gates on (shared with the
    test suite so both judge one contract)."""
    problems = []
    if summary["dual_leader_reconciles"]:
        problems.append(
            f"dual-leader reconciles: {summary['dual_leader_sample']}")
    if summary["orphans"]["count"]:
        problems.append(f"orphaned CRs: {summary['orphans']['sample']}")
    if summary["scheduler_audit"]:
        problems.append(
            f"scheduler bookkeeping drift: {summary['scheduler_audit']}")
    if not summary["slo"]["steady_state_green"]:
        problems.append("reconcile/queue-wait SLO firing in steady state")
    if summary["created"] < summary["crs"]:
        problems.append("flood never reached the CR target")
    if summary["counters"]["admissions_total"] < 1:
        problems.append("nothing ever admitted")
    if summary["counters"]["preemptions_total"] < 1 \
            and summary["crs"] >= 50:
        problems.append("no preemption recorded")
    if summary["lease_revocations"] < 1:
        problems.append("the mid-soak lease revocation never fired")
    if summary["chaos"] is not None:
        injected = summary["chaos"]["injected"]
        for kind in ("conflict", "error", "blackout"):
            if injected.get(kind, 0) < 1:
                problems.append(f"chaos {kind} never fired")
        if injected.get("watch_compacted", 0) < 1:
            problems.append("watch compaction never fired")
    shards_owned = {s for owned in summary["ownership"] for s in owned}
    if len(shards_owned) != summary["shards"]:
        problems.append(
            f"not every shard owned at end: {summary['ownership']}")
    return problems


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Replay-deterministic fleet-scale control-plane "
        "soak: sharded managers, informer caches, scheduler-gated "
        "churn, chaos matrix.")
    parser.add_argument("--seed", type=int, default=11)
    parser.add_argument("--crs", type=int, default=10000)
    parser.add_argument("--ticks", type=int, default=240)
    parser.add_argument("--tick-s", type=float, default=30.0)
    parser.add_argument("--shards", type=int, default=4)
    parser.add_argument("--replicas", type=int, default=2)
    parser.add_argument("--namespaces", type=int, default=8)
    parser.add_argument("--no-chaos", action="store_true")
    parser.add_argument("--dump-dir", default=".")
    args = parser.parse_args(argv)
    summary = run_soak(
        seed=args.seed, crs=args.crs, ticks=args.ticks,
        tick_s=args.tick_s, shards=args.shards,
        replicas=args.replicas, namespaces=args.namespaces,
        chaos=not args.no_chaos, dump_dir=args.dump_dir,
    )
    compact = {k: v for k, v in summary.items()
               if k not in ("cache",)}
    print(json.dumps(compact))
    problems = problems_in(summary)
    if problems:
        print("SOAK FAILED: " + "; ".join(problems), file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
