"""Game day: a seeded, replay-deterministic compressed fleet timeline.

The scenario-diversity proof that the five instrument layers compose
into a self-regulating system (ROADMAP item 5): one compressed "day"
of fleet weather — traffic waves, queue pressure, a capacity
shrink/regrow, an apiserver blackout — is driven through the chaos
harness and the fake apiserver on an injected clock, and every
autopilot actuator must close its loop:

- the **gateway admission** actuator tightens ``max_pending`` /
  ``prefill_per_cycle`` while the TTFT burn is critical and restores
  them on resolve;
- the **inference scale** actuator walks ``spec.replicas`` up under
  sustained occupancy + backlog and back down when idle (the
  StatefulSet follows, via the real inference controller);
- the **checkpoint cadence** actuator tightens the save interval
  through ``run_with_checkpointing``'s agreed-token consult while the
  blackout alert fires (the scenario's training loop takes visibly
  denser saves during the incident);
- the **elastic promotion** gate defers the notebook's probe while the
  capacity timeline says the spec shape cannot fit, then opens when
  capacity regrows (the slice degrades v5e-16 → v5e-8 and climbs
  back).

Every actuation lands as a structured event + the
``autopilot_actions_total`` counter + a span + a flight-recorder
snapshot; every alert that fires during the timeline must reach
``resolved`` by the end; and the whole run is a pure function of
(seed, parameters) — ``replay_digest`` is byte-identical across
replays (asserted by tests/test_autopilot.py).

Determinism notes: controllers talk to the PLAIN fake apiserver (a
chaos proxy in the reconcile path would park keys on real-time
backoff, coupling the scenario to wall clock); the chaos proxy carries
the *availability plane* — a fixed number of probe ops per tick, so
the blackout window in op counts maps exactly onto scenario time, the
same construction the PR-9 acceptance scenario uses. Capacity weather
is scenario-time native (``FaultSchedule.capacity``).

Usage::

  python -m loadtest.game_day --seed 7 --hours 24
"""

from __future__ import annotations

import argparse
import hashlib
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from kubeflow_tpu.autopilot import (  # noqa: E402
    ActuationGuard,
    Autopilot,
    CheckpointCadenceActuator,
    ElasticPromotionGate,
    GatewayAdmissionActuator,
    InferenceScaleActuator,
)
from kubeflow_tpu.chaos import (  # noqa: E402
    ChaosApiServer,
    Clock,
    PreemptionInjector,
    StatefulSetPodSimulator,
    WorldBuilder,
)
from kubeflow_tpu.controllers.inference import (  # noqa: E402
    INFERENCE_API,
    make_inference_controller,
)
from kubeflow_tpu.controllers.manager import (  # noqa: E402
    make_default_slo_engine,
)
from kubeflow_tpu.controllers.metrics import ControllerMetrics  # noqa: E402
from kubeflow_tpu.controllers.notebook import (  # noqa: E402
    NOTEBOOK_API,
    make_notebook_controller,
)
from kubeflow_tpu.k8s.core import ApiError  # noqa: E402
from kubeflow_tpu.k8s.fake import FakeApiServer  # noqa: E402
from kubeflow_tpu.obs.recorder import FlightRecorder  # noqa: E402
from kubeflow_tpu.obs.trace import Tracer  # noqa: E402

# Elastic annotation keys (the ladder opt-in the scenario notebook
# carries).
from kubeflow_tpu.controllers.elastic import (  # noqa: E402
    ELASTIC_GRACE_KEY,
    ELASTIC_LADDER_KEY,
    ELASTIC_PROMOTE_AFTER_KEY,
    ELASTIC_SHAPE_KEY,
)


class StubServingEngine:
    """The gateway engine's autopilot-facing surface, scripted by the
    timeline: admission knobs the actuator mutates, occupancy/queue
    signals the scale actuator reads. The control loop under test is
    alert → actuator → knob/CR — decode itself is PR 6–8's proven
    territory and stays out of the scenario's inner loop."""

    def __init__(self, max_pending: int = 64,
                 prefill_per_cycle: int = 4, slots_total: int = 8):
        self.max_pending = max_pending
        self.prefill_per_cycle = prefill_per_cycle
        self.slots_total = slots_total
        self.occupancy = 0
        self.queue_depth = 0

    def pending(self) -> int:
        return self.queue_depth


class GameDayCheckpointManager:
    """Minimal manager for the scenario's training loop: counts saves
    with their scenario timestamps (the cadence assertion's raw data).
    Single-process — the SPMD discipline is pinned by the train-loop
    unit tests, not re-proven here."""

    process_count = 1

    def __init__(self, clock):
        self._clock = clock
        self.fingerprint: dict = {}
        # analysis: allow[py-unbounded-deque] — bounded by the scenario's save count
        self.saves: list[tuple[int, float]] = []

    def restore_latest_valid(self, state, placements=None):
        return None

    def save_async(self, step, state):
        self.saves.append((int(step), self._clock()))

    def save(self, step, state):
        self.saves.append((int(step), self._clock()))

    def wait(self):
        pass


def _notebook(ns: str, name: str) -> dict:
    return {
        "apiVersion": NOTEBOOK_API,
        "kind": "Notebook",
        "metadata": {
            "name": name, "namespace": ns,
            "annotations": {
                ELASTIC_LADDER_KEY: "auto",
                ELASTIC_GRACE_KEY: "300",
                ELASTIC_PROMOTE_AFTER_KEY: "1800",
            },
        },
        "spec": {
            "tpu": {"accelerator": "v5e", "topology": "4x4"},
            "template": {"spec": {"containers": [
                {"name": "notebook", "image": "jupyter-jax-tpu"},
            ]}},
        },
    }


def _inference_service(ns: str, name: str) -> dict:
    # No spec.tpu: a CPU gateway pool, so spec.replicas drives the
    # StatefulSet directly and the scale actuation is visible end to
    # end (on a TPU slice the annotation records the intent instead).
    return {
        "apiVersion": INFERENCE_API,
        "kind": "InferenceService",
        "metadata": {"name": name, "namespace": ns},
        "spec": {"modelDir": "/models/dev", "replicas": 1},
    }


class GameDay:
    """One scripted day. All phase boundaries are fractions of the run
    so ``hours`` compresses the same arc; the SLO windows (5m/1h fast,
    30m/6h slow) are real, so every phase is sized to let its alerts
    fire AND resolve inside the timeline."""

    OPS_PER_TICK = 4

    # Phase boundaries as fractions of the total tick count.
    WAVE = (0.05, 0.08)          # TTFT melts: admission must tighten
    PRESSURE = (0.15, 0.24)      # full slots + backlog: scale up
    IDLE = (0.24, 0.38)          # empty: scale back down
    SHRINK_AT = 0.40             # capacity 16 -> 8: degrade + gate
    REGROW_AT = 0.55             # capacity back: gate opens, promote
    BLACKOUT = (0.60, 0.65)      # apiserver dark: cadence tightens

    def __init__(self, seed: int = 7, hours: float = 24.0,
                 tick_s: float = 60.0, dump_dir: str = "."):
        self.seed = int(seed)
        self.hours = float(hours)
        self.tick_s = float(tick_s)
        self.total_ticks = int(round(self.hours * 3600.0 / self.tick_s))
        self.clk = Clock(0.0)
        self.namespace = "fleet"

        # --- the world ----------------------------------------------------
        # One declarative timeline on the shared builder: traffic,
        # availability (probe-plane blackout) and capacity weather are
        # separate tracks, so composing more weather onto this arc can
        # never shift these instants (chaos/world.py's contract).
        self.world = (
            WorldBuilder(self.seed, self.total_ticks, self.tick_s)
            .traffic("wave", *self.WAVE, ttft_s=30.0, itl_s=0.02)
            .traffic("pressure", *self.PRESSURE,
                     occupancy="full", queue_depth=6)
            .api_blackout(*self.BLACKOUT,
                          ops_per_tick=self.OPS_PER_TICK)
            .capacity(0.0, 16)
            .capacity(self.SHRINK_AT, 8, jitter_s=30.0)
            .capacity(self.REGROW_AT, 16, jitter_s=30.0)
            .build()
        )
        self.schedule = self.world.schedule
        self.api = FakeApiServer()
        self.proxy = ChaosApiServer(self.api, self.world.probe_schedule,
                                    sleep=lambda s: None)
        self.sim = StatefulSetPodSimulator(
            self.api, recreate_on_template_change=True)
        self.injector = PreemptionInjector(self.api,
                                           sleep=lambda s: None)

        # --- observability ------------------------------------------------
        self.tracer = Tracer(
            sample_rate=1.0,
            ring_capacity=max(4096, self.total_ticks),
            clock=self.clk)
        # Ring sized to the scenario: span/flight consistency checks
        # compare against the action counter, so nothing may evict.
        self.recorder = FlightRecorder(
            capacity=max(4096, self.total_ticks),
            dump_dir=dump_dir, min_dump_interval_s=300.0,
            clock=self.clk, name=f"gameday-{self.seed}")
        self.prom = ControllerMetrics()
        self.manager_slo = make_default_slo_engine(
            self.prom, self.proxy, clock=self.clk,
            recorder=self.recorder)

        from kubeflow_tpu.serving.gateway import (
            GatewayMetrics,
            make_gateway_slo_engine,
        )

        self.engine = StubServingEngine()
        self.gw_metrics = GatewayMetrics(self.engine)
        self.gateway_slo = make_gateway_slo_engine(
            self.gw_metrics, clock=self.clk, recorder=self.recorder)

        # --- the autopilot ------------------------------------------------
        # history_limit sized to the scenario so the event log the
        # digest covers never silently drops (events_emitted is the
        # unbounded consistency counter regardless).
        self.autopilot = Autopilot(
            clock=self.clk, tracer=self.tracer,
            recorder=self.recorder, enabled=True,
            history_limit=max(4096, self.total_ticks))
        self.admission = self.autopilot.register(GatewayAdmissionActuator(
            self.engine,
            guard=ActuationGuard(min_interval_s=300.0, clock=self.clk),
        ))
        self.scale = self.autopilot.register(InferenceScaleActuator(
            self.api, self.namespace, "gateway",
            status_fn=self._gateway_status,
            guard=ActuationGuard(min_interval_s=900.0, clock=self.clk),
            min_replicas=1, max_replicas=3, hold_s=600.0,
            clock=self.clk,
        ))
        self.cadence = self.autopilot.register(CheckpointCadenceActuator(
            capacity_fn=lambda: self.injector.capacity_chips,
            guard=ActuationGuard(min_interval_s=300.0, clock=self.clk),
        ))
        self.gate = self.autopilot.register(ElasticPromotionGate(
            capacity_fn=lambda: self.injector.capacity_chips,
            guard=ActuationGuard(min_interval_s=1200.0, clock=self.clk),
            clock=self.clk,
        ))
        self.autopilot.attach(self.manager_slo)
        self.autopilot.attach(self.gateway_slo)

        # --- control plane ------------------------------------------------
        self.nb_ctrl = make_notebook_controller(
            self.api, prom=self.prom, clock=self.clk,
            promotion_gate=self.gate)
        self.inf_ctrl = make_inference_controller(self.api,
                                                  prom=self.prom)
        self.api.create(_notebook(self.namespace, "trainer"))
        self.api.create(_inference_service(self.namespace, "gateway"))

        # --- data plane (training sim) ------------------------------------
        self.ckpt = GameDayCheckpointManager(self.clk)
        self.max_replicas_seen = 1
        self.min_max_pending_seen = self.engine.max_pending
        # analysis: allow[py-unbounded-deque] — bounded by the scenario's reshape count
        self.shapes_seen: list[str | None] = []

    # ------------------------------------------------------------------
    def _gateway_status(self) -> dict:
        return {
            "pending": self.engine.pending(),
            "slots": {"active": self.engine.occupancy,
                      "total": self.engine.slots_total},
        }

    def _traffic(self, tick: int) -> None:
        """The world's traffic track onto the gateway's live metrics —
        the same histograms the TTFT/ITL objectives judge."""
        active = self.world.traffic_active(tick)
        wave = next((p for p in active if p.ttft_s is not None), None)
        for _ in range(wave.observations if wave else 10):
            self.gw_metrics.ttft.observe(wave.ttft_s if wave else 0.08)
            self.gw_metrics.itl.observe(
                wave.itl_s if wave and wave.itl_s else 0.02)
        pressure = next(
            (p for p in active if p.occupancy == "full"), None)
        if pressure is not None:
            self.engine.occupancy = self.engine.slots_total
            self.engine.queue_depth = pressure.queue_depth
        else:
            self.engine.occupancy = 1
            self.engine.queue_depth = 0

    def _availability_ops(self, tick: int) -> None:
        """A fixed probe-op budget per tick through the chaos proxy:
        the availability plane the apiserver objective judges. Op
        counts advance deterministically, so the blackout window in
        ops maps exactly onto scenario ticks."""
        for _ in range(self.OPS_PER_TICK):
            try:
                self.proxy.list(NOTEBOOK_API, "Notebook")
            except ApiError:
                pass  # the blackout the scenario is about

    def _sample(self) -> None:
        self.min_max_pending_seen = min(self.min_max_pending_seen,
                                        self.engine.max_pending)
        try:
            svc = self.api.get(INFERENCE_API, "InferenceService",
                               "gateway", self.namespace)
            replicas = int((svc.get("spec") or {}).get("replicas") or 1)
            self.max_replicas_seen = max(self.max_replicas_seen,
                                         replicas)
        # analysis: allow[py-broad-except] — game-day harness: actuator faults are the scenario, recorded not raised
        except Exception:
            pass  # mid-delete read; next tick samples again
        try:
            nb = self.api.get(NOTEBOOK_API, "Notebook", "trainer",
                              self.namespace)
            shape = (nb["metadata"].get("annotations") or {}).get(
                ELASTIC_SHAPE_KEY)
            if not self.shapes_seen or self.shapes_seen[-1] != shape:
                self.shapes_seen.append(shape)
        # analysis: allow[py-broad-except] — game-day harness: actuator faults are the scenario, recorded not raised
        except Exception:
            pass

    def _ticks(self):
        """The world IS the batch iterator: each ``next()`` advances
        one scenario tick — chaos weather, controllers, SLO engines,
        autopilot — then yields one training batch, so the real
        ``run_with_checkpointing`` drives the whole scenario and its
        cadence consult sees the live alert state."""
        for tick in range(self.total_ticks):
            now = self.clk.advance(self.tick_s)
            self._traffic(tick)
            self._availability_ops(tick)
            self.injector.apply_capacity(self.world, now, self.sim)
            self.sim.step()
            for ctrl in (self.nb_ctrl, self.inf_ctrl):
                # Periodic resync: elastic timers (grace/promote) and
                # the scale actuator's patches must be observed even
                # when no watch event fires this tick.
                ctrl.resync()
                ctrl.run_once()
            self.manager_slo.tick(now)
            self.gateway_slo.tick(now)
            self.autopilot.tick(now)
            self._sample()
            yield {"x": [0.0]}

    # ------------------------------------------------------------------
    def run(self) -> dict:
        from kubeflow_tpu.models.train import run_with_checkpointing

        state = {"step": 0}

        def step_fn(state, batch):
            return dict(state, step=state["step"] + 1), {}

        state, report = run_with_checkpointing(
            step_fn, state, self._ticks(), self.ckpt,
            save_every_s=3600.0,
            cadence_signal=self.cadence.factor,
            install_signal_handler=False,
            clock=self.clk,
        )
        return self._summarize(report)

    # ------------------------------------------------------------------
    def _alert_ledger(self) -> tuple[list, list]:
        """(transition history, unresolved) across both engines. An
        alert counts as resolved when its firing has a later
        ``resolved`` transition AND it is not active at the end."""
        transitions = []
        unresolved = []
        for engine_name, engine in (("manager", self.manager_slo),
                                    ("gateway", self.gateway_slo)):
            history = list(engine.alerts.history)
            for t in history:
                transitions.append({
                    "engine": engine_name, "slo": t["slo"],
                    "speed": t["speed"], "from": t["from"],
                    "to": t["to"], "at": t["at"],
                })
            fired = {(t["slo"], t["speed"]) for t in history
                     if t["to"] == "firing"}
            resolved = {(t["slo"], t["speed"]) for t in history
                        if t["to"] == "resolved"}
            still_active = {(a["slo"], a["speed"])
                            for a in engine.alerts.active()}
            for key in sorted((fired - resolved) | still_active):
                unresolved.append(
                    {"engine": engine_name, "slo": key[0],
                     "speed": key[1]})
        return transitions, unresolved

    def _save_intervals(self) -> dict:
        times = [at for _step, at in self.ckpt.saves]
        intervals = [b - a for a, b in zip(times, times[1:])]
        b0 = self.BLACKOUT[0] * self.total_ticks * self.tick_s
        b1 = (self.BLACKOUT[1] * self.total_ticks * self.tick_s
              + 3600.0)
        incident = [b - a for a, b in zip(times, times[1:])
                    if b0 <= b <= b1]
        return {
            "total": len(times),
            "min_interval_s": round(min(intervals), 3) if intervals
            else None,
            "min_incident_interval_s": round(min(incident), 3)
            if incident else None,
        }

    def _summarize(self, report) -> dict:
        transitions, unresolved = self._alert_ledger()
        events = list(self.autopilot.events)
        counts = self.autopilot.counts()
        fired_actuators = sorted({
            e["actuator"] for e in events if e["outcome"] != "error"
        })
        # Metric ↔ event-log consistency: the counter is derived from
        # the same emit pipeline, so the sums must match exactly.
        counter_total = sum(self.autopilot.actions_total.values())
        spans = sum(1 for s in self.tracer.ring.spans()
                    if s.get("name") == "autopilot action")
        flight_actions = sum(
            1 for s in self.recorder.snapshots()
            if s.get("kind") == "autopilot_action")
        digest_payload = {
            "events": [{k: v for k, v in e.items()} for e in events],
            "transitions": transitions,
            "counts": counts,
            "saves": [[s, round(at, 3)]
                      for s, at in self.ckpt.saves],
            "shapes": self.shapes_seen,
        }
        digest = hashlib.sha256(
            json.dumps(digest_payload, sort_keys=True).encode()
        ).hexdigest()
        try:
            svc = self.api.get(INFERENCE_API, "InferenceService",
                               "gateway", self.namespace)
            final_replicas = int(
                (svc.get("spec") or {}).get("replicas") or 1)
        # analysis: allow[py-broad-except] — game-day harness: best-effort teardown
        except Exception:
            final_replicas = None
        return {
            "kind": "game_day",
            "seed": self.seed,
            "hours": self.hours,
            "tick_s": self.tick_s,
            "ticks": self.total_ticks,
            "final_step": report.final_step,
            "actuators_fired": fired_actuators,
            "actions": counts,
            "actions_total": counter_total,
            # Counter-to-counter (the bounded deque is only a view).
            "events_total": self.autopilot.events_emitted,
            "events_logged": len(events),
            "spans_total": spans,
            "flight_actions": flight_actions,
            "flight_dumps": self.recorder.dumps_total,
            "alerts_fired": sorted({
                f"{t['engine']}:{t['slo']}/{t['speed']}"
                for t in transitions if t["to"] == "firing"
            }),
            "alerts_unresolved": unresolved,
            "transitions": transitions,
            "events": events,
            "saves": self._save_intervals(),
            "admission": {
                "initial_max_pending": 64,
                "min_max_pending": self.min_max_pending_seen,
                "final_max_pending": self.engine.max_pending,
            },
            "scale": {
                "max_replicas_seen": self.max_replicas_seen,
                "final_replicas": final_replicas,
            },
            "elastic": {
                "shapes": self.shapes_seen,
                "gate_vetoes": self.gate.vetoes,
                "gate_allows": self.gate.allows,
            },
            "replay_digest": digest,
        }


def run_game_day(seed: int = 7, hours: float = 24.0,
                 tick_s: float = 60.0, dump_dir: str = ".") -> dict:
    return GameDay(seed=seed, hours=hours, tick_s=tick_s,
                   dump_dir=dump_dir).run()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Replay-deterministic game-day fleet timeline "
        "asserting the autopilot closes every loop.")
    parser.add_argument("--seed", type=int, default=7)
    parser.add_argument("--hours", type=float, default=24.0)
    parser.add_argument("--tick-s", type=float, default=60.0)
    parser.add_argument("--dump-dir", default=".")
    args = parser.parse_args(argv)
    summary = run_game_day(seed=args.seed, hours=args.hours,
                           tick_s=args.tick_s, dump_dir=args.dump_dir)
    compact = {k: v for k, v in summary.items()
               if k not in ("events", "transitions")}
    print(json.dumps(compact))
    problems = []
    expected = {"gateway-admission", "inference-scale",
                "checkpoint-cadence", "elastic-promotion"}
    missing = expected - set(summary["actuators_fired"])
    if missing:
        problems.append(f"actuators never fired: {sorted(missing)}")
    if summary["alerts_unresolved"]:
        problems.append(
            f"alerts unresolved: {summary['alerts_unresolved']}")
    if summary["actions_total"] != summary["events_total"]:
        problems.append("counter/event-log mismatch")
    if problems:
        print("GAME DAY FAILED: " + "; ".join(problems),
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
