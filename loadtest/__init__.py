"""Load-test harness for the notebook controller (SURVEY.md §2 #23, §6)."""
