"""Two-tenant slice-pool contention: a seeded, replay-deterministic
scheduler scenario (the PR-12 acceptance arc, game_day.py's sibling).

One compressed timeline on an injected clock drives the real notebook,
inference and culling controllers, the capacity-aware pod simulator
and the ``PreemptionInjector`` capacity timeline against the
:class:`~kubeflow_tpu.scheduler.SlicePoolScheduler`, and proves every
scheduler promise end to end:

- **gang admission**: ``team-a/train-lo`` (v5e-16, priority 0) and
  ``team-a/idle-nb`` (v5e-4, priority 5) admit whole-slice into a
  24-chip pool; the REAL ``run_with_checkpointing`` drives train-lo's
  training loop (the world IS the batch iterator, the game-day
  construction).
- **priority preemption through the SIGTERM grace path**:
  ``team-b/serve-hi`` (v5e-8 InferenceService, priority 10) arrives
  into a full pool; the scheduler drains train-lo — the reconciler
  stamps ``preempt-requested``, the scenario delivers the actual
  SIGTERM (``signal.raise_signal``), the loop's final synchronous
  checkpoint stamps the checkpoint-step annotation, the drain acks on
  that advance, the StatefulSet scales to zero and serve-hi admits.
  At most one cadence of steps is lost and the later resume is
  bit-identical to an uninterrupted run (asserted).
- **quota refusal**: ``team-b/greedy`` (second v5e-8) is refused by
  team-b's 8-chip ``google.com/tpu`` ResourceQuota — Queued with the
  quota reason, never blocking other tenants.
- **idle reclamation + scale-to-zero + first-touch resurrect**: the
  culling controller's idle verdict (kernel probe empty, duty-cycle
  probe not busy) marks idle-nb reclaimable; it drains, parks as
  ``Suspended`` with its checkpoint step recorded, its chips fund the
  pool, and a scripted first touch resurrects it through the resume
  handshake.
- **cost is charged**: queue wait and suspension land on per-workload
  GoodputMeters as ``queued``/``suspended`` downtime and in the
  ``scheduler_admission_wait_seconds`` histogram.

``replay_digest`` is byte-identical across runs of the same (seed,
parameters): every clock is the scenario clock, the capacity timeline
is the seeded ``FaultSchedule``, and controllers talk to the plain
fake apiserver (the game-day determinism constraints).

Usage::

  python -m loadtest.contention --seed 11 --ticks 240
"""

from __future__ import annotations

import argparse
import copy
import hashlib
import json
import signal
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from kubeflow_tpu.chaos import (  # noqa: E402
    Clock,
    PreemptionInjector,
    StatefulSetPodSimulator,
    WorldBuilder,
)
from kubeflow_tpu.controllers.culling import (  # noqa: E402
    CullingOptions,
    make_culling_controller,
)
from kubeflow_tpu.controllers.inference import (  # noqa: E402
    INFERENCE_API,
    make_inference_controller,
)
from kubeflow_tpu.controllers.metrics import (  # noqa: E402
    ControllerMetrics,
    ManagerServer,
)
from kubeflow_tpu.controllers.notebook import (  # noqa: E402
    CHECKPOINT_STEP_KEY,
    NOTEBOOK_API,
    RESUME_EXPECTED_KEY,
    make_notebook_controller,
)
from kubeflow_tpu.k8s.fake import FakeApiServer, NotFound  # noqa: E402
from kubeflow_tpu.obs import GoodputMeter  # noqa: E402
from kubeflow_tpu.scheduler import (  # noqa: E402
    PRIORITY_KEY,
    SlicePoolScheduler,
)


class InMemoryCheckpointManager:
    """Deterministic manager for the scenario's training loop: commits
    are deep copies keyed by step (restore is bit-exact by
    construction, so the scenario's bit-identity assertion tests the
    SCHEDULER's resume path, not serialization), and every commit
    stamps the checkpoint-step annotation on the owning CR — the
    in-image reporter's hop, and the drain-ack signal."""

    process_count = 1

    def __init__(self, api, namespace: str, name: str, clock):
        self.api = api
        self.namespace = namespace
        self.name = name
        self._clock = clock
        self.fingerprint: dict = {}
        self.store: dict[int, dict] = {}
        # Bounded by the scenario's step budget (the digest's raw
        # data).  # analysis: allow[py-unbounded-deque]
        self.saves: list[tuple[int, float]] = []

    def _commit(self, step, state) -> None:
        step = int(step)
        self.store[step] = copy.deepcopy(state)
        self.saves.append((step, self._clock()))
        try:
            self.api.patch_merge(
                NOTEBOOK_API, "Notebook", self.name,
                {"metadata": {"annotations": {
                    CHECKPOINT_STEP_KEY: str(step),
                }}},
                self.namespace,
            )
        except NotFound:
            pass  # CR deleted mid-save: nothing to stamp

    def save_async(self, step, state) -> None:
        self._commit(step, state)

    def save(self, step, state) -> None:
        self._commit(step, state)

    def wait(self) -> None:
        pass

    def restore_latest_valid(self, like, placements=None):
        if not self.store:
            return None
        step = max(self.store)
        return copy.deepcopy(self.store[step]), step


def train_step(state, batch):
    """Deterministic integer-arithmetic step: resume divergence of any
    kind shows up as an exact mismatch against the uninterrupted
    reference run."""
    step = state["step"] + 1
    return {"step": step, "acc": state["acc"] + step * step}, {}


def reference_state(steps: int) -> dict:
    state = {"step": 0, "acc": 0}
    for _ in range(steps):
        state, _ = train_step(state, None)
    return state


def _notebook(ns: str, name: str, topology: str, priority: int,
              extra_annotations: dict | None = None) -> dict:
    return {
        "apiVersion": NOTEBOOK_API,
        "kind": "Notebook",
        "metadata": {
            "name": name, "namespace": ns,
            "annotations": {
                PRIORITY_KEY: str(priority),
                **(extra_annotations or {}),
            },
        },
        "spec": {
            "tpu": {"accelerator": "v5e", "topology": topology},
            "template": {"spec": {"containers": [
                {"name": "notebook", "image": "jupyter-jax-tpu"},
            ]}},
        },
    }


def _inference(ns: str, name: str, topology: str, priority: int) -> dict:
    return {
        "apiVersion": INFERENCE_API,
        "kind": "InferenceService",
        "metadata": {
            "name": name, "namespace": ns,
            "annotations": {PRIORITY_KEY: str(priority)},
        },
        "spec": {
            "modelDir": "/models/prod",
            "tpu": {"accelerator": "v5e", "topology": topology},
        },
    }


class Contention:
    """One scripted contention day. Tick fractions script the arc so
    ``ticks`` compresses the same story."""

    SERVE_ARRIVES = 0.10   # serve-hi lands: preemption of train-lo
    GREEDY_ARRIVES = 0.20  # second team-b slice: quota refusal
    REGROW_AT = 0.40       # capacity 24 -> 32: train-lo re-admits
    TOUCH_AT = 0.80        # first touch resurrects idle-nb

    def __init__(self, seed: int = 11, ticks: int = 240,
                 tick_s: float = 30.0):
        self.seed = int(seed)
        self.total_ticks = int(ticks)
        self.tick_s = float(tick_s)
        self.clk = Clock(0.0)
        self.tick_index = 0

        # Declarative timeline on the shared builder: capacity weather
        # plus the scripted tenant arrivals/touch (the tenant track).
        self.world = (
            WorldBuilder(self.seed, self.total_ticks, self.tick_s)
            .capacity(0.0, 24)
            .capacity(self.REGROW_AT, 32, jitter_s=self.tick_s)
            .arrival(self.SERVE_ARRIVES, "inference", "team-b",
                     "serve-hi", topology="2x4", priority=10)
            .arrival(self.GREEDY_ARRIVES, "inference", "team-b",
                     "greedy", topology="2x4", priority=10)
            .arrival(self.TOUCH_AT, "touch", "team-a", "idle-nb")
            .build()
        )
        self.schedule = self.world.schedule
        self.api = FakeApiServer()
        self.sim = StatefulSetPodSimulator(
            self.api, recreate_on_template_change=True)
        self.injector = PreemptionInjector(self.api,
                                           sleep=lambda s: None)

        self.meters: dict[tuple[str, str, str], GoodputMeter] = {}
        self.scheduler = SlicePoolScheduler(
            capacity_fn=lambda: self.world.capacity_at(self.clk()),
            api=self.api,
            clock=self.clk,
            aging_s=3600.0,
            drain_grace_s=4 * self.tick_s,
            enabled=True,
            charge_downtime=self._charge,
        )

        self.prom = ControllerMetrics()
        self.nb_ctrl = make_notebook_controller(
            self.api, prom=self.prom, clock=self.clk,
            scheduler=self.scheduler)
        self.inf_ctrl = make_inference_controller(
            self.api, prom=self.prom, scheduler=self.scheduler,
            clock=self.clk)
        self.touched = False
        self.cull_ctrl = make_culling_controller(
            self.api,
            # Every notebook's kernels read idle; train-lo is protected
            # by the duty-cycle busy veto (it is training), and a
            # touched idle-nb reads busy again (the user attached) —
            # exactly the reclaim discipline.
            kernel_probe=lambda ns, name: [],
            options=CullingOptions(
                enabled=True,
                cull_idle_time_min=max(
                    1, int(0.5 * self.total_ticks * self.tick_s / 60)),
                idleness_check_period_min=1,
            ),
            tpu_busy_probe=lambda ns, name: (
                name == "train-lo"
                or (name == "idle-nb" and self.touched)
            ),
            clock=self.clk,
            prom=self.prom,
            scheduler=self.scheduler,
        )

        # Tenants: team-b holds an 8-chip TPU quota (the Profile
        # controller's ResourceQuota shape).
        self.api.create({
            "apiVersion": "v1", "kind": "ResourceQuota",
            "metadata": {"name": "kf-resource-quota",
                         "namespace": "team-b"},
            "spec": {"hard": {"google.com/tpu": "8"}},
        })
        self.api.create(_notebook("team-a", "train-lo", "4x4", 0))
        self.api.create(_notebook(
            "team-a", "idle-nb", "2x2", 5,
            extra_annotations={CHECKPOINT_STEP_KEY: "7"},
        ))
        # The scheduler's first-HTTP-touch surface: the scenario's
        # resurrect goes through the real ManagerServer POST /touch
        # route (what a JWA details page or gateway front door hits),
        # not a scripted scheduler call. The hop is synchronous and
        # the scheduler runs on the scenario clock, so the digest
        # stays replay-deterministic.
        self.server = ManagerServer(
            self.prom, enable_debug=True, scheduler=self.scheduler,
        )
        self.server.start()
        self.ckpt = InMemoryCheckpointManager(
            self.api, "team-a", "train-lo", self.clk)
        self.sigterm_sent = False
        # Change-gated, bounded by the scenario's tick count.
        # analysis: allow[py-unbounded-deque]
        self.phase_timeline: list[list] = []
        self._last_phases: tuple | None = None

    # ------------------------------------------------------------------
    def _http_touch(self, namespace: str, name: str) -> dict:
        """The first user touch, over the wire: POST /touch on the
        live manager server (debug-gated route; the scheduler side is
        :meth:`SlicePoolScheduler.touch`)."""
        import urllib.request

        request = urllib.request.Request(
            f"http://127.0.0.1:{self.server.port}"
            f"/touch/{namespace}/{name}",
            data=b"", method="POST",
        )
        with urllib.request.urlopen(request, timeout=10) as resp:
            return json.loads(resp.read())

    def _charge(self, kind: str, namespace: str, name: str,
                downtime_kind: str, seconds: float) -> None:
        meter = self.meters.setdefault(
            (kind, namespace, name), GoodputMeter(clock=self.clk,
                                                  epoch_clock=self.clk))
        meter.record_downtime(downtime_kind, seconds)

    def _phase_of(self, api_version: str, kind: str, ns: str,
                  name: str) -> str | None:
        try:
            obj = self.api.get(api_version, kind, name, ns)
        except NotFound:
            return None
        return (obj.get("status") or {}).get("phase")

    def _annotations(self, ns: str, name: str) -> dict:
        try:
            obj = self.api.get(NOTEBOOK_API, "Notebook", name, ns)
        except NotFound:
            return {}
        return (obj.get("metadata") or {}).get("annotations") or {}

    def _sample(self) -> None:
        phases = (
            self.tick_index,
            self._phase_of(NOTEBOOK_API, "Notebook", "team-a",
                           "train-lo"),
            self._phase_of(NOTEBOOK_API, "Notebook", "team-a",
                           "idle-nb"),
            self._phase_of(INFERENCE_API, "InferenceService", "team-b",
                           "serve-hi"),
            self._phase_of(INFERENCE_API, "InferenceService", "team-b",
                           "greedy"),
            self.scheduler.pool_snapshot()["used_chips"],
        )
        if self._last_phases is None or phases[1:] != self._last_phases:
            self._last_phases = phases[1:]
            self.phase_timeline.append(list(phases))

    def _tick(self) -> None:
        now = self.clk.advance(self.tick_s)
        for arrival in self.world.arrivals_at(self.tick_index):
            if arrival.kind == "inference":
                self.api.create(_inference(
                    arrival.namespace, arrival.name, arrival.topology,
                    arrival.priority))
            elif arrival.kind == "touch":
                self.touched = True
                self._http_touch(arrival.namespace, arrival.name)
        self.injector.apply_capacity(self.world, now, self.sim)
        self.sim.step()
        for ctrl in (self.nb_ctrl, self.inf_ctrl, self.cull_ctrl):
            ctrl.resync()
            ctrl.run_once()
        self._sample()
        self.tick_index += 1

    def _ticks_until(self, fraction: float):
        limit = int(fraction * self.total_ticks)
        while self.tick_index < limit:
            self._tick()

    # ------------------------------------------------------------------
    def _segment1_batches(self):
        """The world up to (and through) the preemption: each batch
        advances one scenario tick; the preempt-requested annotation
        becomes the real SIGTERM the grace path is built for."""
        from kubeflow_tpu.scheduler import PREEMPT_REQUESTED_KEY

        while self.tick_index < self.total_ticks:
            self._tick()
            anns = self._annotations("team-a", "train-lo")
            if (PREEMPT_REQUESTED_KEY in anns
                    and not self.sigterm_sent):
                self.sigterm_sent = True
                signal.raise_signal(signal.SIGTERM)
            yield {"x": [1.0]}

    def _segment2_batches(self, count: int):
        for _ in range(count):
            if self.tick_index < self.total_ticks:
                self._tick()
            yield {"x": [1.0]}

    def run(self) -> dict:
        from kubeflow_tpu.models.train import run_with_checkpointing

        try:
            cadence = 5
            state1, report1 = run_with_checkpointing(
                train_step, {"step": 0, "acc": 0},
                self._segment1_batches(), self.ckpt,
                save_every_steps=cadence,
                install_signal_handler=True,
                clock=self.clk,
            )
            # Drain ack -> scale to zero -> serve-hi admits; then
            # capacity regrows and train-lo re-admits.
            self._ticks_until(self.REGROW_AT + 0.05)
            segment2_steps = max(10, int(0.2 * self.total_ticks))
            state2, report2 = run_with_checkpointing(
                train_step, {"step": 0, "acc": 0},
                self._segment2_batches(segment2_steps), self.ckpt,
                save_every_steps=cadence,
                install_signal_handler=False,
                clock=self.clk,
            )
            while self.tick_index < self.total_ticks:
                self._tick()
            return self._summarize(cadence, report1, report2, state2)
        finally:
            self.server.stop()

    # ------------------------------------------------------------------
    def _summarize(self, cadence, report1, report2, state2) -> dict:
        steps_lost = report1.final_step - (report2.resumed_from_step
                                           or 0)
        reference = reference_state(report2.final_step)
        goodput = {
            f"{k[0]}/{k[1]}/{k[2]}": {
                "downtime_s": {
                    kind: round(s, 3)
                    for kind, s in sorted(
                        meter.summary()["downtime_s"].items())
                },
            }
            for k, meter in sorted(self.meters.items())
        }
        wait_snap = self.scheduler.metrics.admission_wait.snapshot()
        resume_expected = self._annotations(
            "team-a", "idle-nb").get(RESUME_EXPECTED_KEY)
        digest_payload = {
            "phases": self.phase_timeline,
            "saves": [[s, round(at, 3)] for s, at in self.ckpt.saves],
            "counters": self.scheduler.metrics.counters(),
            "goodput": goodput,
            "wait": {"count": wait_snap["count"],
                     "sum": round(wait_snap["sum"], 3)},
            "resume": [report1.final_step, report2.resumed_from_step,
                       report2.final_step],
        }
        digest = hashlib.sha256(
            json.dumps(digest_payload, sort_keys=True).encode()
        ).hexdigest()
        return {
            "kind": "contention",
            "seed": self.seed,
            "ticks": self.total_ticks,
            "tick_s": self.tick_s,
            "counters": self.scheduler.metrics.counters(),
            "preemption": {
                "victim_final_step": report1.final_step,
                "victim_preempted": report1.preempted,
                "resumed_from_step": report2.resumed_from_step,
                "steps_lost": steps_lost,
                "cadence": cadence,
                "bit_identical": state2 == reference,
            },
            "reclaim": {
                "idle_suspended": any(
                    row[2] == "Suspended" for row in self.phase_timeline
                ),
                "idle_resurrected": self._phase_of(
                    NOTEBOOK_API, "Notebook", "team-a", "idle-nb"
                ) not in ("Suspended", "Queued"),
                "resume_expected_step": resume_expected,
            },
            "quota": {
                "greedy_phase": self._phase_of(
                    INFERENCE_API, "InferenceService", "team-b",
                    "greedy"),
                "greedy_reason": (
                    (self.api.get(INFERENCE_API, "InferenceService",
                                  "greedy", "team-b")
                     .get("status") or {}).get("schedulingReason")
                ),
            },
            "goodput": goodput,
            "queue_wait": {
                "count": wait_snap["count"],
                "p99_s": self.scheduler.metrics.admission_wait
                             .quantile(0.99),
            },
            "pool": self.scheduler.pool_snapshot(),
            "phases": self.phase_timeline,
            "replay_digest": digest,
        }


def run_contention(seed: int = 11, ticks: int = 240,
                   tick_s: float = 30.0) -> dict:
    return Contention(seed=seed, ticks=ticks, tick_s=tick_s).run()


def problems_in(summary: dict) -> list[str]:
    """The acceptance checklist the CLI gates on (shared with the test
    suite so both judge one contract)."""
    problems = []
    pre = summary["preemption"]
    if not pre["victim_preempted"]:
        problems.append("victim never took the SIGTERM grace path")
    if pre["resumed_from_step"] is None:
        problems.append("victim never resumed from a checkpoint")
    elif pre["steps_lost"] > pre["cadence"]:
        problems.append(
            f"lost {pre['steps_lost']} steps > cadence "
            f"{pre['cadence']}")
    if not pre["bit_identical"]:
        problems.append("resumed run diverged from the uninterrupted "
                        "reference")
    if summary["counters"]["preemptions_total"] < 1:
        problems.append("no preemption recorded")
    if summary["counters"]["reclaims_total"] < 1:
        problems.append("idle slice never reclaimed")
    if not summary["reclaim"]["idle_suspended"]:
        problems.append("idle-nb never surfaced Suspended")
    if not summary["reclaim"]["idle_resurrected"]:
        problems.append("idle-nb never resurrected after touch")
    if summary["quota"]["greedy_phase"] != "Queued":
        problems.append("quota refusal did not queue the greedy slice")
    if "quota" not in (summary["quota"]["greedy_reason"] or ""):
        problems.append("quota reason missing from status")
    meters = summary["goodput"]
    queued_kinds = [m for m in meters.values()
                    if "queued" in m["downtime_s"]]
    suspended_kinds = [m for m in meters.values()
                       if "suspended" in m["downtime_s"]]
    if not queued_kinds:
        problems.append("no queued downtime charged to goodput")
    if not suspended_kinds:
        problems.append("no suspended downtime charged to goodput")
    if summary["queue_wait"]["count"] < 1:
        problems.append("admission wait histogram is empty")
    return problems


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Replay-deterministic two-tenant slice-pool "
        "contention scenario asserting the scheduler's promises.")
    parser.add_argument("--seed", type=int, default=11)
    parser.add_argument("--ticks", type=int, default=240)
    parser.add_argument("--tick-s", type=float, default=30.0)
    args = parser.parse_args(argv)
    summary = run_contention(seed=args.seed, ticks=args.ticks,
                             tick_s=args.tick_s)
    compact = {k: v for k, v in summary.items() if k != "phases"}
    print(json.dumps(compact))
    problems = problems_in(summary)
    if problems:
        print("CONTENTION FAILED: " + "; ".join(problems),
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
