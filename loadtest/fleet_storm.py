"""Fleet storm: the game-day actuation timeline UNDER the sharded
10k-CR soak — one world, every plane at once (ROADMAP item 5's "under,
not next to" composition).

The :class:`~loadtest.soak.Soak` provides the substrate: sharded
manager replicas behind per-shard leases, informer caches, batched
status writes, the slice-pool scheduler, seeded flood + churn, a
mid-soak lease revocation, capacity dip-and-restore. This harness
composes the game day's weather ON TOP via the shared
:class:`~kubeflow_tpu.chaos.world.WorldBuilder` — each track on its
own derived stream, so none of the soak's instants move (the
`tests/test_world.py` isolation contract):

- **traffic**: a prompt-length-abuse TTFT wave (64k-token prompts
  against chunked-prefill admission — the gateway actuator must
  tighten ``max_pending``/``prefill_per_cycle`` and restore on
  resolve) and a full-slots backlog phase (the scale actuator walks
  ``spec.replicas`` up and back down through the REAL sharded
  inference controller).
- **correlated domains**: mid-storm whole-rack loss — every worker
  bound in the rack taints + dies in one instant, multi-host slices
  partial-fail together, the rack's chips leave the merged capacity
  view until the scripted repair. The elastic trainer degrades its
  slice and climbs back only when the promotion gate's per-slice
  capacity view says the rack is back.
- **api faults**: an apiserver blackout on the probe plane (fixed
  probe-op budget per tick, the game-day construction) driving the
  availability burn that tightens checkpoint cadence.
- **adversarial tenants**: a quota-gaming mix hammering the quota'd
  namespace with gang arrivals that must be *refused with a quota
  reason*, not admitted and not wedged.

Gates are the union of both parents plus the composition's own: the
soak checklist (zero dual-leader reconciles, zero orphans, clean
scheduler audit, steady-state burn SLOs green), all four autopilot
actuators fired, every fired alert resolved, admission tightened AND
restored, the rack loss observed with pod casualties, at least one
quota refusal standing, and ``replay_digest`` byte-identical across
runs of the same (seed, parameters).

Usage::

  python -m loadtest.fleet_storm --crs 10000 --ticks 300 --tick-s 60
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from kubeflow_tpu.autopilot import (  # noqa: E402
    ActuationGuard,
    Autopilot,
    CheckpointCadenceActuator,
    ElasticPromotionGate,
    GatewayAdmissionActuator,
    InferenceScaleActuator,
)
from kubeflow_tpu.chaos import (  # noqa: E402
    ChaosApiServer,
    PreemptionInjector,
    StatefulSetPodSimulator,
)
from kubeflow_tpu.controllers.elastic import (  # noqa: E402
    ELASTIC_GRACE_KEY,
    ELASTIC_LADDER_KEY,
    ELASTIC_PROMOTE_AFTER_KEY,
    ELASTIC_SHAPE_KEY,
)
from kubeflow_tpu.controllers.inference import INFERENCE_API  # noqa: E402
from kubeflow_tpu.controllers.manager import (  # noqa: E402
    make_default_slo_engine,
)
from kubeflow_tpu.controllers.metrics import ControllerMetrics  # noqa: E402
from kubeflow_tpu.controllers.notebook import NOTEBOOK_API  # noqa: E402
from kubeflow_tpu.k8s.core import ApiError  # noqa: E402
from kubeflow_tpu.obs.recorder import FlightRecorder  # noqa: E402
from kubeflow_tpu.obs.trace import Tracer  # noqa: E402
from kubeflow_tpu.scheduler import PRIORITY_KEY  # noqa: E402

from loadtest.game_day import (  # noqa: E402
    GameDayCheckpointManager,
    StubServingEngine,
)
from loadtest.soak import Soak, _notebook, problems_in  # noqa: E402

TRAINER_NS = "fleet"


def _trainer(ns: str, name: str) -> dict:
    nb = _notebook(ns, name, "4x4", 1000)
    nb["metadata"]["annotations"].update({
        ELASTIC_LADDER_KEY: "auto",
        ELASTIC_GRACE_KEY: "300",
        ELASTIC_PROMOTE_AFTER_KEY: "1200",
    })
    return nb


def _gateway(ns: str, name: str) -> dict:
    # CPU gateway pool (no spec.tpu): spec.replicas drives the
    # StatefulSet directly, so the scale actuation is visible end to
    # end through the sharded inference controller.
    return {
        "apiVersion": INFERENCE_API,
        "kind": "InferenceService",
        "metadata": {"name": name, "namespace": ns,
                     "annotations": {PRIORITY_KEY: "1000"}},
        "spec": {"modelDir": "/models/prod", "replicas": 1},
    }


class FleetStorm(Soak):
    """The composed scenario. Phase fractions interleave with the
    soak's own (FLOOD_END 0.30, DIP 0.45, REVOKE 0.55, REGROW 0.65)
    so every weather system is live while churn runs."""

    OPS_PER_TICK = 4     # availability-probe budget per tick
    DOMAINS = 4          # racks; worker k of every slice on rack k%4

    WAVE = (0.05, 0.08)          # prompt-abuse TTFT melt
    PRESSURE = (0.15, 0.24)      # full slots + backlog: scale up
    RACK_LOSS_AT = 0.40          # whole-rack correlated failure
    RACK_REPAIR_AT = 0.60        # the rack returns
    BLACKOUT = (0.62, 0.66)      # probe-plane apiserver outage

    _gate = None  # created lazily: replicas build during super().__init__

    def __init__(self, seed: int = 11, crs: int = 10000,
                 ticks: int = 300, tick_s: float = 60.0,
                 shards: int = 4, replicas: int = 2,
                 namespaces: int = 8, pod_plane: bool = True,
                 dump_dir: str = "."):
        super().__init__(seed=seed, crs=crs, ticks=ticks, tick_s=tick_s,
                         shards=shards, replicas=replicas,
                         namespaces=namespaces, chaos=False,
                         pod_plane=pod_plane, dump_dir=dump_dir)

        # --- observability + autopilot (the game-day plane) --------------
        self.tracer = Tracer(sample_rate=1.0,
                             ring_capacity=max(4096, self.ticks),
                             clock=self.clk)
        self.storm_recorder = FlightRecorder(
            capacity=max(4096, self.ticks), dump_dir=dump_dir,
            min_dump_interval_s=600.0, clock=self.clk,
            name=f"storm-{self.seed}")
        from kubeflow_tpu.serving.gateway import (
            GatewayMetrics,
            make_gateway_slo_engine,
        )
        self.engine = StubServingEngine()
        self.gw_metrics = GatewayMetrics(self.engine)
        self.gateway_slo = make_gateway_slo_engine(
            self.gw_metrics, clock=self.clk,
            recorder=self.storm_recorder)
        # The availability plane: a fixed probe-op budget per tick
        # through the world's probe schedule — op-indexed blackout
        # windows map exactly onto scenario ticks, and the controller
        # plane (self.handle) never parks on its backoff.
        self.avail_proxy = ChaosApiServer(
            self.api, self.world.probe_schedule, sleep=lambda s: None)
        self.avail_slo = make_default_slo_engine(
            ControllerMetrics(), self.avail_proxy, clock=self.clk,
            recorder=self.storm_recorder)

        self.autopilot = Autopilot(
            clock=self.clk, tracer=self.tracer,
            recorder=self.storm_recorder, enabled=True,
            history_limit=max(4096, self.ticks))
        self.admission = self.autopilot.register(GatewayAdmissionActuator(
            self.engine,
            guard=ActuationGuard(min_interval_s=300.0, clock=self.clk),
        ))
        self.scale = self.autopilot.register(InferenceScaleActuator(
            self.api, TRAINER_NS, "gateway",
            status_fn=self._gateway_status,
            guard=ActuationGuard(min_interval_s=900.0, clock=self.clk),
            min_replicas=1, max_replicas=3, hold_s=600.0,
            clock=self.clk,
        ))
        self.cadence = self.autopilot.register(CheckpointCadenceActuator(
            capacity_fn=lambda: self.world.capacity_at(self.clk()),
            guard=ActuationGuard(min_interval_s=300.0, clock=self.clk),
        ))
        self.autopilot.register(self._ensure_gate())
        self.autopilot.attach(self.gateway_slo)
        self.autopilot.attach(self.avail_slo)
        for replica in self.replicas:
            self.autopilot.attach(replica.slo)

        # --- the composed workloads ---------------------------------------
        self.api.create(_trainer(TRAINER_NS, "trainer"))
        self.api.create(_gateway(TRAINER_NS, "gateway"))
        self.ckpt = GameDayCheckpointManager(self.clk)
        self.train_report = None

        self.gamer_counter = 0
        # Bounded by the seeded arrival script.
        # analysis: allow[py-unbounded-deque]
        self.gamers: list[tuple[str, str]] = []
        self.max_replicas_seen = 1
        self.min_max_pending_seen = self.engine.max_pending
        # analysis: allow[py-unbounded-deque] — bounded by reshape count
        self.shapes_seen: list[str | None] = []
        self._settle_round = 0

    # ---- world (the soak's tracks + the storm's) -------------------------
    def _build_world(self):
        builder = (
            super()._build_world_builder()
            .traffic("prompt-abuse", *self.WAVE, ttft_s=30.0,
                     itl_s=0.02, prompt_len=65536)
            .traffic("pressure", *self.PRESSURE,
                     occupancy="full", queue_depth=6)
            .api_blackout(*self.BLACKOUT,
                          ops_per_tick=self.OPS_PER_TICK)
            .tenants(
                "quota-gamer",
                namespaces=("ns-0",),
                topologies=(("2x4", 8),),
                priorities=(10,),
                weights={"create": 1.0},
            )
            # Rack 3: the trainer's 4x4 loses worker-3 (the slice
            # partial-fails) while the v5e-8 rung's hosts 0-1 stay
            # reachable — so the degraded shape RUNS, its promote
            # probe lands inside the outage, and the gate must veto
            # promotion back into the missing rack.
            .domains(self.DOMAINS)
            .domain_loss(self.RACK_LOSS_AT, domain=3,
                         chips=max(8, self.capacity // 4),
                         jitter_s=self.tick_s)
            .domain_repair(self.RACK_REPAIR_AT, domain=3,
                           jitter_s=self.tick_s)
        )
        return builder.build()

    def _ensure_gate(self):
        if self._gate is None:
            # Per-slice capacity view: the trainer's 4x4 slice (16
            # chips on 4 hosts) partial-fails under a rack loss even
            # while the fleet pool has headroom.
            self._gate = ElasticPromotionGate(
                capacity_fn=lambda: self.world.slice_capacity(16, 4),
                guard=ActuationGuard(min_interval_s=1200.0,
                                     clock=self.clk),
                clock=self.clk,
            )
        return self._gate

    def notebook_kwargs(self) -> dict:
        return {"promotion_gate": self._ensure_gate()}

    # ---- per-tick planes -------------------------------------------------
    def _gateway_status(self) -> dict:
        return {
            "pending": self.engine.pending(),
            "slots": {"active": self.engine.occupancy,
                      "total": self.engine.slots_total},
        }

    def _traffic(self, tick: int) -> None:
        active = self.world.traffic_active(tick)
        wave = next((p for p in active if p.ttft_s is not None), None)
        for _ in range(wave.observations if wave else 10):
            self.gw_metrics.ttft.observe(wave.ttft_s if wave else 0.08)
            self.gw_metrics.itl.observe(
                wave.itl_s if wave and wave.itl_s else 0.02)
        pressure = next(
            (p for p in active if p.occupancy == "full"), None)
        if pressure is not None:
            self.engine.occupancy = self.engine.slots_total
            self.engine.queue_depth = pressure.queue_depth
        else:
            self.engine.occupancy = 1
            self.engine.queue_depth = 0

    def _availability_ops(self, tick: int) -> None:
        for _ in range(self.OPS_PER_TICK):
            try:
                self.avail_proxy.list(NOTEBOOK_API, "Notebook")
            except ApiError:
                pass  # the blackout the availability SLO judges

    def _quota_gamers(self, tick: int) -> None:
        """The adversarial mix: gang arrivals into the quota'd
        namespace, ~one per five churn ticks, from the track's own
        stream (composing it shifted no churn instant)."""
        if tick < self.flood_end:
            return
        rng = self.world.stream("quota-gamer")
        if rng.random() >= 0.2:
            return
        mix = self.world.tenant_mixes["quota-gamer"]
        ns = mix.namespaces[0]
        topology, _chips = mix.topologies[0]
        name = f"gamer-{self.gamer_counter:04d}"
        self.gamer_counter += 1
        self.api.create(_notebook(ns, name, topology,
                                  mix.priorities[0]))
        self.gamers.append((ns, name))
        self.op_log.append([tick, "quota-gamer", ns, name])

    def _world_ops(self, tick: int, now: float) -> None:
        super()._world_ops(tick, now)
        self._quota_gamers(tick)
        self._traffic(tick)
        self._availability_ops(tick)
        if tick % 5 == 0:
            # Periodic resync: elastic timers (grace/promote) and the
            # scale actuator's patches must be observed even when no
            # watch event fires this tick.
            for replica in self.replicas:
                for ctrl in replica.controllers:
                    ctrl.resync()

    def _post_slo(self, tick: int, now: float) -> None:
        self.gateway_slo.tick(now)
        self.avail_slo.tick(now)
        self.autopilot.tick(now)
        self._storm_sample()

    def _storm_sample(self) -> None:
        self.min_max_pending_seen = min(self.min_max_pending_seen,
                                        self.engine.max_pending)
        try:
            svc = self.api.get(INFERENCE_API, "InferenceService",
                               "gateway", TRAINER_NS)
            replicas = int((svc.get("spec") or {}).get("replicas") or 1)
            self.max_replicas_seen = max(self.max_replicas_seen,
                                         replicas)
            nb = self.api.get(NOTEBOOK_API, "Notebook", "trainer",
                              TRAINER_NS)
            shape = (nb["metadata"].get("annotations") or {}).get(
                ELASTIC_SHAPE_KEY)
            if not self.shapes_seen or self.shapes_seen[-1] != shape:
                self.shapes_seen.append(shape)
        # analysis: allow[py-broad-except] — storm harness: mid-delete reads resample next tick
        except Exception:
            pass

    def _settle_tick(self, now: float) -> None:
        """Shared drain/cooldown plane: the storm's SLO engines and
        autopilot keep ticking (restores and scale-downs land), and
        every few rounds the controllers resync so elastic promote
        timers are observed."""
        self.gateway_slo.tick(now)
        self.avail_slo.tick(now)
        self.autopilot.tick(now)
        if self.sim is not None:
            self.world.apply_domains(now, self.injector, self.sim)
            self.sim.step()
        self._settle_round += 1
        if self._settle_round % 5 == 0:
            for replica in self.replicas:
                for ctrl in replica.controllers:
                    ctrl.resync()
                    ctrl.run_once(max_iterations=self.tick_budget)

    def _drain_tick(self, now: float) -> None:
        self._settle_tick(now)

    def _cooldown_tick(self, now: float) -> None:
        self._settle_tick(now)

    # ---- drive: the world IS the batch iterator --------------------------
    def _batches(self):
        for tick in range(self.ticks):
            self._tick(tick)
            yield {"x": [0.0]}

    def _drive(self) -> None:
        from kubeflow_tpu.models.train import run_with_checkpointing

        def step_fn(state, batch):
            return dict(state, step=state["step"] + 1), {}

        _state, self.train_report = run_with_checkpointing(
            step_fn, {"step": 0}, self._batches(), self.ckpt,
            save_every_s=3600.0,
            cadence_signal=self.cadence.factor,
            install_signal_handler=False,
            clock=self.clk,
        )

    # ---- alert ledger across every engine --------------------------------
    def _engines(self):
        yield "gateway", self.gateway_slo
        yield "availability", self.avail_slo
        for replica in self.replicas:
            yield replica.identity, replica.slo

    def _alert_ledger(self) -> tuple[list, list]:
        transitions = []
        unresolved = []
        for engine_name, engine in self._engines():
            history = list(engine.alerts.history)
            for t in history:
                transitions.append({
                    "engine": engine_name, "slo": t["slo"],
                    "speed": t["speed"], "from": t["from"],
                    "to": t["to"], "at": t["at"],
                })
            fired = {(t["slo"], t["speed"]) for t in history
                     if t["to"] == "firing"}
            resolved = {(t["slo"], t["speed"]) for t in history
                        if t["to"] == "resolved"}
            still_active = {(a["slo"], a["speed"])
                            for a in engine.alerts.active()}
            for key in sorted((fired - resolved) | still_active):
                unresolved.append({"engine": engine_name,
                                   "slo": key[0], "speed": key[1]})
        return transitions, unresolved

    def _quota_refusals(self) -> int:
        refused = 0
        for ns, name in self.gamers:
            try:
                nb = self.api.get(NOTEBOOK_API, "Notebook", name, ns)
            except Exception:  # analysis: allow[py-broad-except] — churn may have raced a delete
                continue
            reason = ((nb.get("status") or {})
                      .get("schedulingReason") or "")
            if "quota" in reason.lower():
                refused += 1
        return refused

    # ---- summary / digest extras -----------------------------------------
    def _digest_extras(self) -> dict:
        transitions, _ = self._alert_ledger()
        return {
            "world": self.world.manifest(),
            "autopilot_events": [dict(e) for e in self.autopilot.events],
            "autopilot_counts": self.autopilot.counts(),
            "alert_transitions": transitions,
            "saves": [[s, round(at, 3)] for s, at in self.ckpt.saves],
            "shapes": self.shapes_seen,
            "domain_log": self.world.domain_log,
        }

    def _summary_extras(self) -> dict:
        transitions, unresolved = self._alert_ledger()
        events = list(self.autopilot.events)
        fired_actuators = sorted({
            e["actuator"] for e in events if e["outcome"] != "error"
        })
        return {
            "kind": "fleet_storm",
            "final_step": (self.train_report.final_step
                           if self.train_report else 0),
            "actuators_fired": fired_actuators,
            "actions_total": sum(
                self.autopilot.actions_total.values()),
            "events_total": self.autopilot.events_emitted,
            "alerts_fired": sorted({
                f"{t['engine']}:{t['slo']}/{t['speed']}"
                for t in transitions if t["to"] == "firing"
            }),
            "alerts_unresolved": unresolved,
            "saves": {"total": len(self.ckpt.saves)},
            "admission": {
                "initial_max_pending": 64,
                "min_max_pending": self.min_max_pending_seen,
                "final_max_pending": self.engine.max_pending,
            },
            "scale": {"max_replicas_seen": self.max_replicas_seen},
            "elastic": {
                "shapes": self.shapes_seen,
                "gate_vetoes": self._gate.vetoes,
                "gate_allows": self._gate.allows,
            },
            "domain_log": self.world.domain_log,
            "pod_plane": self.pod_plane,
            "pods": ({"created": self.sim.created_total,
                      "deleted": self.sim.deleted_total,
                      "pending": self.sim.pending_total}
                     if self.sim is not None else None),
            "quota": {"gamers": len(self.gamers),
                      "refused": self._quota_refusals()},
        }


def run_fleet_storm(**kwargs) -> dict:
    return FleetStorm(**kwargs).run()


def storm_problems_in(summary: dict) -> list[str]:
    """The composed acceptance checklist: the soak's own gates plus
    the actuation/weather gates."""
    problems = problems_in(summary)
    expected = {"gateway-admission", "inference-scale",
                "checkpoint-cadence", "elastic-promotion"}
    missing = expected - set(summary["actuators_fired"])
    if missing:
        problems.append(f"actuators never fired: {sorted(missing)}")
    if summary["alerts_unresolved"]:
        problems.append(
            f"alerts unresolved: {summary['alerts_unresolved']}")
    if summary["actions_total"] != summary["events_total"]:
        problems.append("autopilot counter/event-log mismatch")
    admission = summary["admission"]
    if admission["min_max_pending"] >= admission["initial_max_pending"]:
        problems.append("gateway admission never tightened")
    if admission["final_max_pending"] != admission["initial_max_pending"]:
        problems.append("gateway admission never restored")
    kinds = [d["kind"] for d in summary["domain_log"]]
    if "domain_loss" not in kinds or "domain_repair" not in kinds:
        problems.append("the rack loss/repair arc never fired")
    if summary["pod_plane"]:
        losses = [d for d in summary["domain_log"]
                  if d["kind"] == "domain_loss"]
        if not any(d["pods"] for d in losses):
            problems.append("rack loss killed no pods")
        shapes = summary["elastic"]["shapes"]
        if not any(s for s in shapes):
            problems.append("the trainer never degraded its slice")
        if shapes and shapes[-1] is not None:
            problems.append(
                f"the trainer never promoted back: {shapes}")
    if summary["quota"]["refused"] < 1:
        problems.append("no quota-gaming arrival was refused")
    return problems


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Game-day actuation under the sharded fleet soak: "
        "one composed world — traffic, rack loss, blackout, "
        "adversarial tenants — every gate at once.")
    parser.add_argument("--seed", type=int, default=11)
    parser.add_argument("--crs", type=int, default=10000)
    parser.add_argument("--ticks", type=int, default=300)
    parser.add_argument("--tick-s", type=float, default=60.0)
    parser.add_argument("--shards", type=int, default=4)
    parser.add_argument("--replicas", type=int, default=2)
    parser.add_argument("--namespaces", type=int, default=8)
    parser.add_argument("--no-pod-plane", action="store_true")
    parser.add_argument("--dump-dir", default=".")
    args = parser.parse_args(argv)
    summary = run_fleet_storm(
        seed=args.seed, crs=args.crs, ticks=args.ticks,
        tick_s=args.tick_s, shards=args.shards,
        replicas=args.replicas, namespaces=args.namespaces,
        pod_plane=not args.no_pod_plane, dump_dir=args.dump_dir,
    )
    compact = {k: v for k, v in summary.items()
               if k not in ("cache", "ops", "timeline")}
    print(json.dumps(compact, default=str))
    problems = storm_problems_in(summary)
    if problems:
        print("FLEET STORM FAILED: " + "; ".join(problems),
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
