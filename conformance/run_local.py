"""In-process conformance: the cluster jobs' checks, run against the
in-memory stack.

The reference conformance harness (reference conformance/1.7/Makefile)
only runs in a live cluster. This runner executes the same certification
scenario — profile materialisation, TPU notebook spawn to ready, PodDefault
TPU-env injection — against the real controllers + native core + fake
apiserver, so `make -C conformance/1.0 local` (and CI) can certify a build
with no cluster. Each check returns a (name, passed, detail) tuple; the
process exits non-zero if any check fails.
"""

from __future__ import annotations

import sys
from pathlib import Path

import yaml

REPO = Path(__file__).resolve().parent.parent
# profile.yaml (the Profile) + setup.yaml (namespaced SA/RoleBinding,
# applied after the namespace exists in the cluster flow).
SETUP_DOCS = [
    REPO / "conformance" / "1.0" / "profile.yaml",
    REPO / "conformance" / "1.0" / "setup.yaml",
]


def check_profile(api, docs) -> tuple[str, bool, str]:
    """setup.yaml's Profile → namespace + SAs + owner binding + quota
    (the platform side of reference conformance setup)."""
    from kubeflow_tpu.controllers.profile import make_profile_controller

    profile = next(d for d in docs if d["kind"] == "Profile")
    ctrl = make_profile_controller(api)
    api.create(profile)
    ctrl.run_once()
    ns = profile["metadata"]["name"]
    try:
        api.get("v1", "Namespace", ns)
        api.get("v1", "ServiceAccount", "default-editor", ns)
        api.get("rbac.authorization.k8s.io/v1", "RoleBinding", "namespaceAdmin", ns)
        quota = api.get("v1", "ResourceQuota", "kf-resource-quota", ns)
    # analysis: allow[py-broad-except] — conformance runner: a probe failure IS the recorded result
    except Exception as e:  # NotFound
        return ("profile-conformance", False, str(e))
    hard = quota["spec"]["hard"]
    if hard.get("google.com/tpu") != "4":
        return ("profile-conformance", False, f"TPU quota missing: {hard}")
    return ("profile-conformance", True, f"namespace {ns} materialised")


def check_notebook(api, namespace: str) -> tuple[str, bool, str]:
    """TPU Notebook CR → ready STS with google.com/tpu limits + GKE
    topology selectors (the notebook-conformance.yaml job's check)."""
    from kubeflow_tpu.controllers.notebook import make_notebook_controller
    from loadtest.start_notebooks import FakeKubelet
    import time

    ctrl = make_notebook_controller(api)
    api.create(
        {
            "apiVersion": "kubeflow.org/v1beta1",
            "kind": "Notebook",
            "metadata": {"name": "conformance-nb", "namespace": namespace},
            "spec": {
                "tpu": {"accelerator": "v5e", "topology": "4x4", "replicas": 4},
                "template": {
                    "spec": {
                        "containers": [
                            {
                                "name": "conformance-nb",
                                "image": "ghcr.io/kubeflow-tpu/jupyter-jax-tpu:latest",
                            }
                        ]
                    }
                },
            },
        }
    )
    ctrl.run_once()
    kubelet = FakeKubelet(api)
    kubelet.step(time.monotonic())
    ctrl.run_once()
    sts = api.get("apps/v1", "StatefulSet", "conformance-nb", namespace)
    tmpl = sts["spec"]["template"]["spec"]
    limits = tmpl["containers"][0].get("resources", {}).get("limits", {})
    selectors = tmpl.get("nodeSelector", {})
    nb = api.get("kubeflow.org/v1beta1", "Notebook", "conformance-nb", namespace)
    env_names = {
        e["name"] for e in tmpl["containers"][0].get("env", [])
    }
    checks = {
        "replicas=4": sts["spec"]["replicas"] == 4,
        "tpu-limit": limits.get("google.com/tpu") == "4",
        "gke-topology": selectors.get("cloud.google.com/gke-tpu-topology") == "4x4",
        "worker-id-env": "TPU_WORKER_ID" in env_names,
        "coordinator-env": "KFT_COORDINATOR_ADDRESS" in env_names,
        "ready": nb.get("status", {}).get("readyReplicas", 0) == 4,
    }
    failed = [k for k, ok in checks.items() if not ok]
    if failed:
        return ("notebook-conformance", False, f"failed: {failed}")
    return ("notebook-conformance", True, "v5e-16 notebook spawned to ready")


def check_poddefault(api, namespace: str) -> tuple[str, bool, str]:
    """A pod created in the profile namespace gets the TPU distributed env
    injected (the tpu-conformance.yaml job relies on this)."""
    from kubeflow_tpu.webhook.server import register_with_fake, tpu_env_poddefault

    register_with_fake(api)
    api.create(tpu_env_poddefault(namespace))
    api.create(
        {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": "tpu-workload",
                "namespace": namespace,
                "labels": {"tpu-env": "true"},
            },
            "spec": {"containers": [{"name": "main", "image": "x"}]},
        }
    )
    pod = api.get("v1", "Pod", "tpu-workload", namespace)
    env = {
        e["name"]: e.get("value")
        for c in pod["spec"]["containers"]
        for e in c.get("env", [])
    }
    tolerations = pod["spec"].get("tolerations", [])
    if env.get("JAX_PLATFORMS") != "tpu,cpu":
        return ("poddefault-conformance", False, f"env injected: {env}")
    if not any(t.get("key") == "google.com/tpu" for t in tolerations):
        return ("poddefault-conformance", False, "TPU toleration not injected")
    return ("poddefault-conformance", True, "TPU env + toleration injected")


def _load_docs() -> list[dict]:
    return [
        d
        for path in SETUP_DOCS
        for d in yaml.safe_load_all(path.read_text())
        if d
    ]


def _wait_for(fn, timeout: float = 30.0, interval: float = 0.1):
    """Poll ``fn`` until it returns without raising; returns its value.
    Re-raises the last error on timeout."""
    import time

    deadline = time.monotonic() + timeout
    while True:
        try:
            return fn()
        except Exception:
            if time.monotonic() >= deadline:
                raise
            time.sleep(interval)


def processes_main() -> int:
    """The same certification against REAL process boundaries: dev
    apiserver over HTTP, profile/notebook controllers and the admission
    webhook as OS processes (the deployed topology, minus kubelet) —
    the closest a machine without a cluster gets to the cluster flow.
    """
    import os
    import signal
    import subprocess
    import tempfile
    import threading
    import time

    from kubeflow_tpu.k8s.client import ApiClient, KubeConfig
    from kubeflow_tpu.k8s.httpd import FakeApiHttpServer
    from kubeflow_tpu.webhook.server import register_remote_webhook
    from loadtest.start_notebooks import FakeKubelet

    docs = _load_docs()
    profile = next(d for d in docs if d["kind"] == "Profile")
    ns = profile["metadata"]["name"]

    server = FakeApiHttpServer().start()
    env = {
        **os.environ,
        "KFT_APISERVER": server.url,
        "METRICS_PORT": "0",
        "JAX_PLATFORMS": "cpu",
        "PYTHONUNBUFFERED": "1",
    }
    env.pop("KFT_FAKE_API", None)

    certdir = tempfile.mkdtemp(prefix="kft-conformance-")
    cert = os.path.join(certdir, "tls.crt")
    key = os.path.join(certdir, "tls.key")
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", key, "-out", cert, "-days", "1",
         "-subj", "/CN=127.0.0.1", "-addext", "subjectAltName=IP:127.0.0.1"],
        check=True, capture_output=True,
    )
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        webhook_port = s.getsockname()[1]

    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "kubeflow_tpu", component],
            env={**env, **extra}, cwd=str(REPO),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for component, extra in [
            ("profile-controller", {}),
            ("notebook-controller", {}),
            ("admission-webhook", {"WEBHOOK_PORT": str(webhook_port),
                                   "CERT_FILE": cert, "KEY_FILE": key}),
        ]
    ]
    logs = [[] for _ in procs]
    for i, proc in enumerate(procs):
        threading.Thread(
            target=lambda p=proc, buf=logs[i]: buf.extend(p.stdout),
            daemon=True,
        ).start()

    api = ApiClient(KubeConfig(host=server.url))
    results = []
    kubelet_stop = threading.Event()
    try:
        # Wire the apiserver -> webhook-process admission path (what the
        # MutatingWebhookConfiguration does in a cluster).
        import ssl as ssl_mod
        import urllib.request

        ctx = ssl_mod.create_default_context(cafile=cert)

        def webhook_up():
            with urllib.request.urlopen(
                f"https://127.0.0.1:{webhook_port}/healthz",
                timeout=2, context=ctx,
            ):
                return True

        _wait_for(webhook_up, timeout=30.0)
        register_remote_webhook(
            server.fake, f"https://127.0.0.1:{webhook_port}/apply-poddefault",
            cafile=cert,
        )

        # ---- profile-conformance ----
        api.create(profile)

        def profile_ready():
            api.get("v1", "Namespace", ns)
            api.get("v1", "ServiceAccount", "default-editor", ns)
            api.get("rbac.authorization.k8s.io/v1", "RoleBinding",
                    "namespaceAdmin", ns)
            return api.get("v1", "ResourceQuota", "kf-resource-quota", ns)

        try:
            quota = _wait_for(profile_ready)
            hard = quota["spec"]["hard"]
            ok = hard.get("google.com/tpu") == "4"
            results.append((
                "profile-conformance", ok,
                f"namespace {ns} materialised by the controller process"
                if ok else f"TPU quota missing: {hard}",
            ))
        # analysis: allow[py-broad-except] — conformance runner: a probe failure IS the recorded result
        except Exception as exc:
            results.append(("profile-conformance", False, str(exc)))

        # ---- notebook-conformance ----
        kubelet = FakeKubelet(api)
        kubelet_errors: set[str] = set()

        def kubelet_loop():
            import traceback

            while not kubelet_stop.is_set():
                try:
                    kubelet.step(time.monotonic())
                # analysis: allow[py-broad-except] — conformance runner: a probe failure IS the recorded result
                except Exception:
                    # Keep ticking, but a broken kubelet must be
                    # diagnosable (first traceback per distinct error).
                    err = traceback.format_exc()
                    if err not in kubelet_errors:
                        kubelet_errors.add(err)
                        print(f"fake kubelet error:\n{err}",
                              file=sys.stderr)
                time.sleep(0.05)

        threading.Thread(target=kubelet_loop, daemon=True).start()
        api.create({
            "apiVersion": "kubeflow.org/v1beta1",
            "kind": "Notebook",
            "metadata": {"name": "conformance-nb", "namespace": ns},
            "spec": {
                "tpu": {"accelerator": "v5e", "topology": "4x4",
                        "replicas": 4},
                "template": {"spec": {"containers": [{
                    "name": "conformance-nb",
                    "image": "ghcr.io/kubeflow-tpu/jupyter-jax-tpu:latest",
                }]}},
            },
        })

        def notebook_ready():
            nb = api.get("kubeflow.org/v1beta1", "Notebook",
                         "conformance-nb", ns)
            assert nb.get("status", {}).get("readyReplicas", 0) == 4, (
                nb.get("status")
            )
            return nb

        try:
            _wait_for(notebook_ready, timeout=60.0)
            sts = api.get("apps/v1", "StatefulSet", "conformance-nb", ns)
            tmpl = sts["spec"]["template"]["spec"]
            limits = tmpl["containers"][0].get("resources", {}).get(
                "limits", {})
            env_names = {e["name"]
                         for e in tmpl["containers"][0].get("env", [])}
            checks = {
                "replicas=4": sts["spec"]["replicas"] == 4,
                "tpu-limit": limits.get("google.com/tpu") == "4",
                "gke-topology": tmpl.get("nodeSelector", {}).get(
                    "cloud.google.com/gke-tpu-topology") == "4x4",
                "worker-id-env": "TPU_WORKER_ID" in env_names,
                "coordinator-env": "KFT_COORDINATOR_ADDRESS" in env_names,
            }
            failed = [k for k, ok in checks.items() if not ok]
            results.append((
                "notebook-conformance", not failed,
                "v5e-16 notebook spawned to ready across processes"
                if not failed else f"failed: {failed}",
            ))
        # analysis: allow[py-broad-except] — conformance runner: a probe failure IS the recorded result
        except Exception as exc:
            results.append(("notebook-conformance", False, str(exc)))

        # ---- poddefault-conformance (through the webhook PROCESS) ----
        from kubeflow_tpu.webhook.server import tpu_env_poddefault

        try:
            api.create(tpu_env_poddefault(ns))
            api.create({
                "apiVersion": "v1", "kind": "Pod",
                "metadata": {"name": "tpu-workload", "namespace": ns,
                             "labels": {"tpu-env": "true"}},
                "spec": {"containers": [{"name": "main", "image": "x"}]},
            })
            pod = api.get("v1", "Pod", "tpu-workload", ns)
            env_map = {
                e["name"]: e.get("value")
                for c in pod["spec"]["containers"]
                for e in c.get("env", [])
            }
            tolerations = pod["spec"].get("tolerations", [])
            ok = env_map.get("JAX_PLATFORMS") == "tpu,cpu" and any(
                t.get("key") == "google.com/tpu" for t in tolerations
            )
            results.append((
                "poddefault-conformance", ok,
                "TPU env + toleration injected over HTTPS by the webhook "
                "process" if ok else
                f"injection incomplete: env={env_map}",
            ))
        # analysis: allow[py-broad-except] — conformance runner: a probe failure IS the recorded result
        except Exception as exc:
            results.append(("poddefault-conformance", False, str(exc)))
    finally:
        kubelet_stop.set()
        for proc in procs:
            proc.send_signal(signal.SIGTERM)
        for proc in procs:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
        api.close()
        server.close()

    ok = True
    for name, passed, detail in results:
        print(f"{'PASS' if passed else 'FAIL'} {name}: {detail}")
        ok = ok and passed
    if not ok:
        for i, buf in enumerate(logs):
            tail = "".join(buf[-30:])
            if tail:
                print(f"--- process {i} log tail ---\n{tail}",
                      file=sys.stderr)
    return 0 if ok else 1


def main(argv: list[str] | None = None) -> int:
    import argparse

    from kubeflow_tpu.k8s import FakeApiServer

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--mode", choices=["local", "processes"], default="local",
        help="local: in-process stack; processes: dev apiserver over "
        "HTTP + controllers/webhook as OS processes.",
    )
    args = parser.parse_args(argv)
    if args.mode == "processes":
        return processes_main()

    docs = _load_docs()
    api = FakeApiServer()
    results = [check_profile(api, docs)]
    ns = next(d for d in docs if d["kind"] == "Profile")["metadata"]["name"]
    results.append(check_notebook(api, ns))
    results.append(check_poddefault(api, ns))
    ok = True
    for name, passed, detail in results:
        print(f"{'PASS' if passed else 'FAIL'} {name}: {detail}")
        ok = ok and passed
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
