"""In-process conformance: the cluster jobs' checks, run against the
in-memory stack.

The reference conformance harness (reference conformance/1.7/Makefile)
only runs in a live cluster. This runner executes the same certification
scenario — profile materialisation, TPU notebook spawn to ready, PodDefault
TPU-env injection — against the real controllers + native core + fake
apiserver, so `make -C conformance/1.0 local` (and CI) can certify a build
with no cluster. Each check returns a (name, passed, detail) tuple; the
process exits non-zero if any check fails.
"""

from __future__ import annotations

import sys
from pathlib import Path

import yaml

REPO = Path(__file__).resolve().parent.parent
# profile.yaml (the Profile) + setup.yaml (namespaced SA/RoleBinding,
# applied after the namespace exists in the cluster flow).
SETUP_DOCS = [
    REPO / "conformance" / "1.0" / "profile.yaml",
    REPO / "conformance" / "1.0" / "setup.yaml",
]


def check_profile(api, docs) -> tuple[str, bool, str]:
    """setup.yaml's Profile → namespace + SAs + owner binding + quota
    (the platform side of reference conformance setup)."""
    from kubeflow_tpu.controllers.profile import make_profile_controller

    profile = next(d for d in docs if d["kind"] == "Profile")
    ctrl = make_profile_controller(api)
    api.create(profile)
    ctrl.run_once()
    ns = profile["metadata"]["name"]
    try:
        api.get("v1", "Namespace", ns)
        api.get("v1", "ServiceAccount", "default-editor", ns)
        api.get("rbac.authorization.k8s.io/v1", "RoleBinding", "namespaceAdmin", ns)
        quota = api.get("v1", "ResourceQuota", "kf-resource-quota", ns)
    except Exception as e:  # NotFound
        return ("profile-conformance", False, str(e))
    hard = quota["spec"]["hard"]
    if hard.get("google.com/tpu") != "4":
        return ("profile-conformance", False, f"TPU quota missing: {hard}")
    return ("profile-conformance", True, f"namespace {ns} materialised")


def check_notebook(api, namespace: str) -> tuple[str, bool, str]:
    """TPU Notebook CR → ready STS with google.com/tpu limits + GKE
    topology selectors (the notebook-conformance.yaml job's check)."""
    from kubeflow_tpu.controllers.notebook import make_notebook_controller
    from loadtest.start_notebooks import FakeKubelet
    import time

    ctrl = make_notebook_controller(api)
    api.create(
        {
            "apiVersion": "kubeflow.org/v1beta1",
            "kind": "Notebook",
            "metadata": {"name": "conformance-nb", "namespace": namespace},
            "spec": {
                "tpu": {"accelerator": "v5e", "topology": "4x4", "replicas": 4},
                "template": {
                    "spec": {
                        "containers": [
                            {
                                "name": "conformance-nb",
                                "image": "ghcr.io/kubeflow-tpu/jupyter-jax-tpu:latest",
                            }
                        ]
                    }
                },
            },
        }
    )
    ctrl.run_once()
    kubelet = FakeKubelet(api)
    kubelet.step(time.monotonic())
    ctrl.run_once()
    sts = api.get("apps/v1", "StatefulSet", "conformance-nb", namespace)
    tmpl = sts["spec"]["template"]["spec"]
    limits = tmpl["containers"][0].get("resources", {}).get("limits", {})
    selectors = tmpl.get("nodeSelector", {})
    nb = api.get("kubeflow.org/v1beta1", "Notebook", "conformance-nb", namespace)
    env_names = {
        e["name"] for e in tmpl["containers"][0].get("env", [])
    }
    checks = {
        "replicas=4": sts["spec"]["replicas"] == 4,
        "tpu-limit": limits.get("google.com/tpu") == "4",
        "gke-topology": selectors.get("cloud.google.com/gke-tpu-topology") == "4x4",
        "worker-id-env": "TPU_WORKER_ID" in env_names,
        "coordinator-env": "KFT_COORDINATOR_ADDRESS" in env_names,
        "ready": nb.get("status", {}).get("readyReplicas", 0) == 4,
    }
    failed = [k for k, ok in checks.items() if not ok]
    if failed:
        return ("notebook-conformance", False, f"failed: {failed}")
    return ("notebook-conformance", True, "v5e-16 notebook spawned to ready")


def check_poddefault(api, namespace: str) -> tuple[str, bool, str]:
    """A pod created in the profile namespace gets the TPU distributed env
    injected (the tpu-conformance.yaml job relies on this)."""
    from kubeflow_tpu.webhook.server import register_with_fake, tpu_env_poddefault

    register_with_fake(api)
    api.create(tpu_env_poddefault(namespace))
    api.create(
        {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": "tpu-workload",
                "namespace": namespace,
                "labels": {"tpu-env": "true"},
            },
            "spec": {"containers": [{"name": "main", "image": "x"}]},
        }
    )
    pod = api.get("v1", "Pod", "tpu-workload", namespace)
    env = {
        e["name"]: e.get("value")
        for c in pod["spec"]["containers"]
        for e in c.get("env", [])
    }
    tolerations = pod["spec"].get("tolerations", [])
    if env.get("JAX_PLATFORMS") != "tpu,cpu":
        return ("poddefault-conformance", False, f"env injected: {env}")
    if not any(t.get("key") == "google.com/tpu" for t in tolerations):
        return ("poddefault-conformance", False, "TPU toleration not injected")
    return ("poddefault-conformance", True, "TPU env + toleration injected")


def main() -> int:
    from kubeflow_tpu.k8s import FakeApiServer

    docs = [
        d
        for path in SETUP_DOCS
        for d in yaml.safe_load_all(path.read_text())
        if d
    ]
    api = FakeApiServer()
    results = [check_profile(api, docs)]
    ns = next(d for d in docs if d["kind"] == "Profile")["metadata"]["name"]
    results.append(check_notebook(api, ns))
    results.append(check_poddefault(api, ns))
    ok = True
    for name, passed, detail in results:
        print(f"{'PASS' if passed else 'FAIL'} {name}: {detail}")
        ok = ok and passed
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
