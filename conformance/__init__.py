"""Conformance harness (SURVEY.md §2 #21): in-cluster jobs under
``conformance/1.0`` and the in-process runner in ``run_local``."""
