#!/bin/sh
# Runs against a conformance pod ($1). Waits for the done file ($2) to
# appear, then copies out the test report ($3)
# (reference conformance/1.7/report-pod.sh).

until kubectl exec "$1" -n kf-conformance -- ls "$2"
do
    sleep 30
    echo "Waiting for $1 to finish ..."
done

REPORT_PATH=/tmp/kf-conformance/$(basename "$3")
kubectl cp "kf-conformance/$1:$3" "$REPORT_PATH"

echo "Test report copied to $REPORT_PATH"
