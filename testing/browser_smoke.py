"""Wire-level run of the browser-tier scenarios (in-env artifact).

This image has no browser binary, no JS runtime, and no pip install —
the Playwright tier (`tests/e2e_frontend/`) is CI-only here by
construction. This runner is the honest in-env substitute: it serves
the SAME seeded apps the Playwright conftest builds (real werkzeug
HTTP servers, real backends, fake apiserver) and drives every spec
scenario at the wire level — shell + asset serving, list/details
payloads, form create, server-side validation, stop annotation, the
editor's dry-run→apply flow, i18n catalogs, viewer launch, fleet
cards, contributor lifecycle — asserting both HTTP responses and
resulting apiserver state. Everything the specs check except DOM
rendering and client-side JS behaviour (that half runs in CI:
`.github/workflows/frontend_e2e.yaml`).

Usage: python testing/browser_smoke.py
Exit 0 iff every scenario passed; prints one line per scenario and a
trailing JSON summary. Output is committed as
`testing/browser_smoke_r05.log`.
"""

from __future__ import annotations

import json
import re
import sys
import threading
import urllib.error
import urllib.request

from werkzeug.serving import make_server

sys.path.insert(0, ".")
from testing.browser_serve import (  # noqa: E402
    USER, seeded_dashboard_app, seeded_jwa_app, seeded_vwa_app,
)

RESULTS: list[tuple[str, str, str]] = []  # (scenario, PASS/FAIL, note)


def serve(app) -> tuple[str, object]:
    server = make_server("127.0.0.1", 0, app, threaded=True)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return f"http://127.0.0.1:{server.server_port}", server


class Client:
    """Cookie-jar HTTP client that plays the SPA's CSRF double-submit."""

    def __init__(self, base: str):
        self.base = base
        self.cookies: dict[str, str] = {}

    def request(self, method: str, path: str, body=None,
                headers: dict | None = None):
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(self.base + path, data=data,
                                     method=method)
        req.add_header("Content-Type", "application/json")
        if self.cookies:
            req.add_header("Cookie", "; ".join(
                f"{k}={v}" for k, v in self.cookies.items()))
        if method not in ("GET", "HEAD") and "XSRF-TOKEN" in self.cookies:
            req.add_header("X-XSRF-TOKEN", self.cookies["XSRF-TOKEN"])
        for k, v in (headers or {}).items():
            req.add_header(k, v)
        try:
            resp = urllib.request.urlopen(req, timeout=10)
            status, raw = resp.status, resp.read()
            set_cookies = resp.headers.get_all("Set-Cookie") or []
        except urllib.error.HTTPError as exc:
            status, raw = exc.code, exc.read()
            set_cookies = exc.headers.get_all("Set-Cookie") or []
        for sc in set_cookies:
            first = sc.split(";", 1)[0]
            if "=" in first:
                k, v = first.split("=", 1)
                self.cookies[k.strip()] = v.strip()
        return status, raw

    def get(self, path):
        return self.request("GET", path)

    def get_json(self, path):
        status, raw = self.get(path)
        return status, json.loads(raw)

    def post_json(self, path, body):
        status, raw = self.request("POST", path, body)
        return status, json.loads(raw)


def check(scenario: str, ok: bool, note: str = ""):
    RESULTS.append((scenario, "PASS" if ok else "FAIL", note))
    print(f"{'PASS' if ok else 'FAIL'}  {scenario}  {note}", flush=True)


def run_scenario(name: str, fn):
    try:
        fn()
    # analysis: allow[py-broad-except] — smoke harness: report-and-continue
    except Exception as exc:  # noqa: BLE001 — record, keep running
        check(name, False, f"exception: {type(exc).__name__}: {exc}")


# ---------------------------------------------------------------- JWA

def jwa_scenarios():
    app, api = seeded_jwa_app()
    base, server = serve(app)
    c = Client(base)

    def shell_and_assets():
        status, raw = c.get("/")
        html = raw.decode()
        ok = status == 200 and 'id="nb-table"' in html
        # Assets are referenced relative to the app root.
        srcs = re.findall(r'(?:src|href)="([^"]+\.(?:js|css))"', html)
        bad = []
        for s in srcs:
            st, _ = c.get(s if s.startswith("/") else "/" + s)
            if st != 200:
                bad.append((s, st))
        check("jwa/shell_and_assets",
              ok and srcs and not bad,
              f"{len(srcs)} assets served{', bad: ' + repr(bad) if bad else ''}")

    def list_renders_notebook_row():
        _, d = c.get_json("/api/namespaces/alice/notebooks")
        nbs = {n["name"]: n for n in d["notebooks"]}
        demo = nbs.get("demo-nb") or {}
        tpu = demo.get("tpu") or {}
        check("jwa/list_renders_notebook_row",
              "demo-nb" in nbs and tpu.get("accelerator") == "v5e"
              and tpu.get("topology") == "2x4"
              and demo.get("status", {}).get("phase") == "running",
              f"row: tpu={tpu}, phase={demo.get('status', {}).get('phase')}")

    def details_conditions_events_logs():
        _, d = c.get_json("/api/namespaces/alice/notebooks/demo-nb")
        conds = d["notebook"].get("status", {}).get("conditions", [])
        _, ev = c.get_json("/api/namespaces/alice/notebooks/demo-nb/events")
        msgs = [e.get("message", "") for e in ev["events"]]
        _, pods = c.get_json("/api/namespaces/alice/notebooks/demo-nb/pod")
        pod_names = [p["metadata"]["name"] for p in pods["pods"]]
        _, logs = c.get_json(
            "/api/namespaces/alice/notebooks/demo-nb/pod/demo-nb-0/logs")
        check("jwa/details_conditions_events_logs",
              any(cd.get("reason") == "PodsReady" for cd in conds)
              and any("StatefulSet demo-nb created" in m for m in msgs)
              and pod_names == ["demo-nb-0"]
              and any("jupyterlab listening" in ln for ln in logs["logs"])
              and any("TPU v5e" in ln for ln in logs["logs"]),
              f"conds={len(conds)} events={len(msgs)} pods={pod_names}")

    def new_notebook_form_creates_cr():
        status, d = c.post_json("/api/namespaces/alice/notebooks",
                                {"name": "from-wire"})
        cr = api.get("kubeflow.org/v1beta1", "Notebook", "from-wire",
                     "alice")
        check("jwa/new_notebook_form_creates_cr",
              status == 200 and cr["metadata"]["name"] == "from-wire",
              f"status={status}")

    def form_validation_server_side():
        s1, d1 = c.post_json("/api/namespaces/alice/notebooks",
                             {"name": "Bad Name!"})
        s2, d2 = c.post_json(
            "/api/namespaces/alice/notebooks",
            {"name": "good-wire", "cpu": "half a core"})
        bad_reached = True
        try:
            api.get("kubeflow.org/v1beta1", "Notebook", "Bad Name!",
                    "alice")
        # analysis: allow[py-broad-except] — smoke harness: report-and-continue
        except Exception:
            bad_reached = False
        check("jwa/form_validation_server_side",
              400 <= s1 < 500 and 400 <= s2 < 500 and not bad_reached,
              f"bad-name={s1}, bad-cpu={s2}")

    def csrf_required_on_mutation():
        fresh = Client(base)  # no cookie jar warm-up: no token to echo
        status, raw = fresh.request("POST",
                                    "/api/namespaces/alice/notebooks",
                                    {"name": "no-csrf"})
        reached = True
        try:
            api.get("kubeflow.org/v1beta1", "Notebook", "no-csrf", "alice")
        # analysis: allow[py-broad-except] — smoke harness: best-effort teardown
        except Exception:
            reached = False
        check("jwa/csrf_required_on_mutation",
              status == 403 and not reached, f"status={status}")

    def stop_sets_annotation():
        status, _ = c.request(
            "PATCH", "/api/namespaces/alice/notebooks/demo-nb",
            {"stopped": True})
        nb = api.get("kubeflow.org/v1beta1", "Notebook", "demo-nb", "alice")
        anns = nb["metadata"].get("annotations") or {}
        stopped = "kubeflow-resource-stopped" in anns
        c.request("PATCH", "/api/namespaces/alice/notebooks/demo-nb",
                  {"stopped": False})
        nb = api.get("kubeflow.org/v1beta1", "Notebook", "demo-nb", "alice")
        restarted = "kubeflow-resource-stopped" not in (
            nb["metadata"].get("annotations") or {})
        check("jwa/stop_sets_annotation", status == 200 and stopped
              and restarted, f"status={status}")

    def yaml_editor_dry_run_apply():
        _, d = c.get_json("/api/namespaces/alice/notebooks/demo-nb")
        res = d["notebook"]
        res["metadata"].setdefault("labels", {})["from-editor"] = "dry"
        s1, _ = c.request(
            "PUT", "/api/namespaces/alice/notebooks/demo-nb/yaml",
            {"resource": res, "dryRun": True})
        nb = api.get("kubeflow.org/v1beta1", "Notebook", "demo-nb", "alice")
        dry_persisted = (nb["metadata"].get("labels") or {}).get(
            "from-editor") == "dry"
        res["metadata"]["labels"]["from-editor"] = "edited"
        s2, _ = c.request(
            "PUT", "/api/namespaces/alice/notebooks/demo-nb/yaml",
            {"resource": res, "dryRun": False})
        nb = api.get("kubeflow.org/v1beta1", "Notebook", "demo-nb", "alice")
        applied = (nb["metadata"].get("labels") or {}).get(
            "from-editor") == "edited"
        # Identity pinning: renaming through the editor must 4xx.
        evil = dict(res, metadata=dict(res["metadata"], name="other"))
        s3, _ = c.request(
            "PUT", "/api/namespaces/alice/notebooks/demo-nb/yaml",
            {"resource": evil, "dryRun": True})
        check("jwa/yaml_editor_dry_run_apply",
              s1 == 200 and not dry_persisted and s2 == 200 and applied
              and 400 <= s3 < 500,
              f"dry={s1} (persisted={dry_persisted}) apply={s2} "
              f"rename={s3}")

    def i18n_catalogs():
        sf, fr = c.get("/lib/i18n/fr.js")
        se, es = c.get("/lib/i18n/es.js")
        check("jwa/i18n_catalogs",
              sf == 200 and "Nouveau notebook" in fr.decode()
              and se == 200 and "Nuevo notebook" in es.decode(),
              f"fr={sf} es={se}")

    for fn in (shell_and_assets, list_renders_notebook_row,
               details_conditions_events_logs,
               new_notebook_form_creates_cr, form_validation_server_side,
               csrf_required_on_mutation, stop_sets_annotation,
               yaml_editor_dry_run_apply, i18n_catalogs):
        run_scenario(f"jwa/{fn.__name__}", fn)
    server.shutdown()


# ---------------------------------------------------------------- VWA

def vwa_scenarios():
    app, api = seeded_vwa_app()
    base, server = serve(app)
    c = Client(base)

    def pvc_list_details_events():
        status, raw = c.get("/")
        html_ok = status == 200 and 'id="pvc-table"' in raw.decode()
        _, d = c.get_json("/api/namespaces/alice/pvcs")
        pvcs = {p["name"]: p for p in d["pvcs"]}
        ws = pvcs.get("workspace") or {}
        _, ev = c.get_json("/api/namespaces/alice/pvcs/workspace/events")
        msgs = [e.get("message", "") for e in ev["events"]]
        check("vwa/pvc_list_details_events",
              html_ok and ws.get("size") == "10Gi"
              and ws.get("status") == "Bound"
              and ws.get("mode") == "ReadWriteOnce"
              and any("volume bound to pv-123" in m for m in msgs),
              f"pvc={ws.get('size')}/{ws.get('status')} "
              f"events={len(msgs)}")

    def viewer_launch_creates_cr():
        status, _ = c.post_json("/api/namespaces/alice/viewers",
                                {"pvc": "workspace"})
        cr = api.get("kubeflow.org/v1alpha1", "PVCViewer", "workspace",
                     "alice")
        check("vwa/viewer_launch_creates_cr",
              status == 200 and cr["spec"]["pvc"] == "workspace",
              f"status={status}")

    for fn in (pvc_list_details_events, viewer_launch_creates_cr):
        run_scenario(f"vwa/{fn.__name__}", fn)
    server.shutdown()


# ---------------------------------------------------------- Dashboard

def dashboard_scenarios():
    app, api = seeded_dashboard_app()
    base, server = serve(app)
    c = Client(base)

    def home_fleet_activities_and_user():
        status, raw = c.get("/")
        html = raw.decode()
        _, ns = c.get_json("/api/namespaces")
        _, fleet = c.get_json("/api/metrics/tpu")
        _, acts = c.get_json("/api/activities/team-alpha")
        fleet_txt = json.dumps(fleet)
        acts_txt = json.dumps(acts)
        _, env = c.get_json("/api/workgroup/env-info")
        check("dash/home_fleet_activities_and_user",
              status == 200 and 'id="fleet-cards"' in html
              and "team-alpha" in json.dumps(ns)
              and "tpu-v5-lite-podslice" in fleet_txt
              and "StatefulSet nb created" in acts_txt
              and USER in json.dumps(env),
              f"ns+fleet+activities+user all present")

    def contributor_add_and_remove():
        s1, d1 = c.post_json("/api/workgroup/add-contributor/team-alpha",
                             {"contributor": "bob@example.org"})

        def bob_bindings():
            return [
                rb for rb in api.list(
                    "rbac.authorization.k8s.io/v1", "RoleBinding",
                    namespace="team-alpha")
                if (rb["metadata"].get("annotations") or {}).get("user")
                == "bob@example.org"
            ]

        added = "bob@example.org" in d1.get("contributors", []) \
            and bool(bob_bindings())
        s2, raw2 = c.request(
            "DELETE", "/api/workgroup/remove-contributor/team-alpha",
            {"contributor": "bob@example.org"})
        d2 = json.loads(raw2)
        removed = "bob@example.org" not in d2.get("contributors", []) \
            and not bob_bindings()
        check("dash/contributor_add_and_remove",
              s1 == 200 and added and s2 == 200 and removed,
              f"add={s1} remove={s2}")

    def i18n_shell_marks():
        status, raw = c.get("/")
        html = raw.decode()
        check("dash/i18n_shell_marks",
              status == 200 and "data-i18n" in html,
              "shell carries data-i18n marks (catalog render is "
              "client-side: CI tier)")

    for fn in (home_fleet_activities_and_user, contributor_add_and_remove,
               i18n_shell_marks):
        run_scenario(f"dash/{fn.__name__}", fn)
    server.shutdown()


def main() -> int:
    jwa_scenarios()
    vwa_scenarios()
    dashboard_scenarios()
    passed = sum(1 for _, st, _ in RESULTS if st == "PASS")
    failed = len(RESULTS) - passed
    print(json.dumps({"tier": "browser-wire", "scenarios": len(RESULTS),
                      "passed": passed, "failed": failed}))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
