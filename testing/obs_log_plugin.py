"""Pytest plugin for the observability gate (obs_gate.sh): every record
emitted by a ``kubeflow_tpu.*`` logger during the run must render as a
valid structured JSON object with the schema core (ts/level/logger/msg)
— i.e. telemetry flows through the structured formatter, not ad-hoc
formats that log shippers cannot index.

Loaded with ``pytest -p obs_log_plugin`` (PYTHONPATH=testing). Failures
are appended to the file named by ``KFT_OBS_LOG_REPORT`` (one line per
offending record); the gate script fails the build when that file is
non-empty. Reporting via a file keeps the plugin inert under plain
pytest runs — it observes, the gate enforces.
"""

from __future__ import annotations

import json
import logging
import os

from kubeflow_tpu.obs.logging import SCHEMA_KEYS, JsonLogFormatter

_violations: list[str] = []


class _SchemaCheckHandler(logging.Handler):
    def __init__(self):
        super().__init__(level=logging.DEBUG)
        self._formatter = JsonLogFormatter()

    def emit(self, record: logging.LogRecord) -> None:
        try:
            doc = json.loads(self._formatter.format(record))
            missing = [k for k in SCHEMA_KEYS if k not in doc]
            if missing:
                raise ValueError(f"missing schema keys {missing}")
        except Exception as exc:  # analysis: allow[py-broad-except]
            # The whole point of this handler is to RECORD formatter
            # failures, never to raise from inside logging.
            _violations.append(
                f"{record.name} ({record.pathname}:{record.lineno}): "
                f"unstructured record: {exc}"
            )


def pytest_configure(config):
    logging.getLogger("kubeflow_tpu").addHandler(_SchemaCheckHandler())


def pytest_sessionfinish(session, exitstatus):
    report = os.environ.get("KFT_OBS_LOG_REPORT")
    if not report:
        return
    if _violations:
        with open(report, "a", encoding="utf-8") as fh:
            for line in _violations:
                fh.write(line + "\n")
