"""Same-process LM train A/B: dense logits + optax CE vs the chunked
fused cross-entropy head (ops/cross_entropy.py). Run on the real chip:

    python -u testing/ab_ce.py

Prints one JSON line per (batch, seq) config with both paths'
tokens/s and the fused/dense speedup. Same-process comparison only
(BASELINE.md variance note).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import bench  # noqa: E402

CONFIGS = [
    ("b4-s2048", dict(batch=4, seq=2048, steps=10, warmup=4)),
    ("b1-s8192", dict(batch=1, seq=8192, steps=5, warmup=2)),
    ("b1-s32768", dict(batch=1, seq=32768, steps=3, warmup=1)),
]


def measure(loss_impl, batch, seq, steps, warmup):
    from kubeflow_tpu.models import (
        LMConfig,
        build_lm,
        create_lm_state,
        make_lm_train_step,
    )

    cfg = LMConfig(
        vocab=32768, layers=8, dim=1024, heads=8, dtype=jnp.bfloat16,
        loss_impl=loss_impl,
    )
    model = build_lm(cfg)
    state = create_lm_state(model, jax.random.key(0), (1, seq))
    step = make_lm_train_step(cfg=cfg)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(
        rng.integers(0, cfg.vocab, size=(batch, seq)), jnp.int32
    )
    state, meas = bench.run_timed(step, state, {"tokens": tokens},
                                  warmup, steps)
    return (batch * seq * steps / meas.median,
            1000 * meas.median / steps)


def main():
    for name, kw in CONFIGS:
        row = {"config": name}
        for impl in ("dense", "fused"):
            try:
                tok_s, step_ms = measure(impl, **kw)
                row[impl] = {"tokens_s": round(tok_s, 1),
                             "step_ms": round(step_ms, 2)}
            # analysis: allow[py-broad-except] — A/B harness: a candidate crash is a recorded verdict
            except Exception as exc:  # OOM at 32k dense is plausible
                row[impl] = {"error": str(exc)[:200]}
        if "tokens_s" in row.get("dense", {}) and \
                "tokens_s" in row.get("fused", {}):
            row["fused_speedup"] = round(
                row["fused"]["tokens_s"] / row["dense"]["tokens_s"], 4
            )
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
