"""Hour-scale soak over LIVE components (SURVEY §5 failure recovery).

The chaos tier (tests/test_chaos.py) proves each failure mode once;
this driver proves the system holds up under SUSTAINED load: a dev
apiserver over the real wire protocol, two notebook-controller OS
processes with leader election and culling enabled, and a live kernel
fixture, driven through continuous spawn → cull → restart →
gang-restart cycles with periodic leader kills (SIGKILL + respawn) and
lease deletions for the configured duration (default 1 hour).

What it asserts at the end (and per cycle in the JSONL log):

- convergence: every cycle's spawn/cull/restart/gang sequence completes
  within its timeout, across every induced failure;
- bounded memory: controller RSS in the final cycles must not exceed
  1.5x the post-warmup level (leak detection over wall-clock, which the
  reference inherits from controller-runtime maturity and this runtime
  must demonstrate);
- bounded events: deterministic-name event recording must AGGREGATE
  (bump counts on stable names), so the apiserver's event count stays
  bounded while cycles repeat over the same object names.

Usage:
    python -m testing.soak --duration 3600 --log testing/soak_r04.log

The pytest suite smoke-runs 2 cycles of this exact driver
(tests/test_soak.py) so the soak logic itself cannot rot; the hour run
is launched out-of-band and its log committed under testing/.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from kubeflow_tpu.k8s.core import NotFound  # noqa: E402
from kubeflow_tpu.k8s.httpd import FakeApiHttpServer  # noqa: E402
from tests.test_chaos import _KernelServer  # noqa: E402
from tests.test_entrypoints import (  # noqa: E402
    free_port,
    spawn,
    terminate,
    wait_http,
)

NB_API = "kubeflow.org/v1beta1"


def rss_kb(pid: int) -> int:
    try:
        with open(f"/proc/{pid}/status") as fh:
            for line in fh:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1])
    except OSError:
        pass
    return 0


def _wait(predicate, timeout: float, what: str):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        got = predicate()
        if got is not None:
            return got
        time.sleep(0.2)
    raise AssertionError(f"timeout waiting for {what}")


class Soak:
    """One live stack: apiserver + kernel fixture + 2 controllers."""

    IDENTITIES = ("soak-a", "soak-b")

    def __init__(self, log_path: str):
        self.server = FakeApiHttpServer().start()
        self.fake = self.server.fake
        self.kernels = _KernelServer()
        self._kernels_idle()
        self.log = open(log_path, "a", buffering=1)
        self.procs: dict[str, subprocess.Popen] = {}
        self.ports: dict[str, int] = {}
        for name in self.IDENTITIES:
            self._spawn_controller(name)
        for port in self.ports.values():
            wait_http(f"http://127.0.0.1:{port}/healthz")
        self.failed_cycles = 0
        # analysis: allow[py-unbounded-deque] — one sample per soak tick, bounded by soak duration
        self.rss_history: list[tuple[int, int]] = []

    def _spawn_controller(self, name: str):
        self.ports.setdefault(name, free_port())
        self.procs[name] = spawn("notebook-controller", self.server.url, {
            "METRICS_PORT": str(self.ports[name]),
            "LEADER_ELECT": "1",
            "POD_NAME": name,
            "ENABLE_CULLING": "1",
            "CULL_IDLE_TIME": "60",
            "IDLENESS_CHECK_PERIOD": "1",
            "KFT_KERNEL_PROBE_URL":
                f"http://127.0.0.1:{self.kernels.port}/"
                "notebook/{namespace}/{name}/api/kernels",
        })

    def _kernels_idle(self):
        """Probe fixture reports long-idle kernels: eligible to cull."""
        self.kernels.kernels = [{
            "execution_state": "idle",
            "last_activity": "2026-07-28T00:00:00Z",
        }]

    def _kernels_busy(self):
        """Probe fixture reports active kernels — notebooks stay up
        (the culler would otherwise re-cull the restarted notebook and
        cull the gang-restart slice mid-baseline)."""
        import datetime

        now = datetime.datetime.now(datetime.timezone.utc)
        self.kernels.kernels = [{
            "execution_state": "busy",
            "last_activity": now.strftime("%Y-%m-%dT%H:%M:%SZ"),
        }]

    # ----------------------------------------------------------- cycle
    def _nb(self, name: str) -> dict:
        return {
            "apiVersion": NB_API, "kind": "Notebook",
            "metadata": {"name": name, "namespace": "alice"},
            "spec": {"template": {"spec": {"containers": [
                {"name": name, "image": "jupyter-jax-tpu:latest"}
            ]}}},
        }

    def _pod(self, nb_name: str, ordinal: int = 0, extra_status=None):
        return {
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {
                "name": f"{nb_name}-{ordinal}", "namespace": "alice",
                "labels": {"notebook-name": nb_name},
            },
            "status": {"phase": "Running",
                       **(extra_status or {})},
        }

    def _cleanup(self, name: str):
        for kind, api in (("Notebook", NB_API), ("StatefulSet", "apps/v1"),
                          ("Service", "v1")):
            try:
                self.fake.delete(api, kind, name, "alice")
            except NotFound:
                pass
        for pod in self.fake.list("v1", "Pod", namespace="alice"):
            if pod["metadata"].get("labels", {}) \
                    .get("notebook-name") == name:
                try:
                    self.fake.delete("v1", "Pod",
                                     pod["metadata"]["name"], "alice")
                except NotFound:
                    pass

    def spawn_cull_restart(self, name: str):
        """Create → reconcile → cull (live kernel probe) → restart."""
        self._kernels_idle()
        self.fake.create(self._pod(name))
        self.fake.create(self._nb(name))
        _wait(lambda: self._get_or_none("apps/v1", "StatefulSet", name),
              30, f"{name} StatefulSet")

        def stopped():
            obj = self.fake.get(NB_API, "Notebook", name, "alice")
            anns = obj["metadata"].get("annotations") or {}
            return True if "kubeflow-resource-stopped" in anns else None

        _wait(stopped, 90, f"{name} culled")
        _wait(lambda: (
            self.fake.get("apps/v1", "StatefulSet", name, "alice")
            ["spec"].get("replicas") == 0 or None
        ), 30, f"{name} scaled to zero")
        # Restart: drop the stop annotation; the reconciler must scale
        # the STS back up. Kernels go busy first or the culler would
        # immediately re-cull the restarted notebook.
        self._kernels_busy()
        self.fake.patch_merge(
            NB_API, "Notebook", name,
            {"metadata": {"annotations":
                          {"kubeflow-resource-stopped": None}}},
            "alice",
        )
        _wait(lambda: (
            self.fake.get("apps/v1", "StatefulSet", name, "alice")
            ["spec"].get("replicas") == 1 or None
        ), 30, f"{name} restarted")

    def gang_restart(self, name: str):
        """Multi-host slice; rank 1 crashes; ALL pods must recycle."""
        hosts = 4  # v5e 4x4 = 16 chips = 4 hosts (smallest multi-host)
        self.fake.create({
            "apiVersion": NB_API, "kind": "Notebook",
            "metadata": {"name": name, "namespace": "alice"},
            "spec": {
                "tpu": {"accelerator": "v5e", "topology": "4x4",
                        "replicas": hosts},
                "template": {"spec": {"containers": [
                    {"name": name, "image": "img"}]}},
            },
        })
        _wait(lambda: self._get_or_none("apps/v1", "StatefulSet", name),
              30, f"{name} slice STS")
        for i in range(hosts):
            self.fake.create(self._pod(name, i, {
                "containerStatuses": [{"restartCount": 0}]}))
        want = {f"{name}-{i}": 0 for i in range(hosts)}

        def baselined():
            obj = self.fake.get(NB_API, "Notebook", name, "alice")
            ann = obj["metadata"].get("annotations") or {}
            observed = ann.get(
                "notebooks.kubeflow-tpu.org/observed-restarts")
            return True if (observed
                            and json.loads(observed) == want) else None

        _wait(baselined, 30, f"{name} restart baseline")
        self.fake.patch_merge(
            "v1", "Pod", f"{name}-1",
            {"status": {"containerStatuses": [{"restartCount": 1}]}},
            "alice",
        )

        def recycled():
            pods = [p for p in self.fake.list("v1", "Pod",
                                              namespace="alice")
                    if p["metadata"].get("labels", {})
                    .get("notebook-name") == name]
            return True if not pods else None

        _wait(recycled, 30, f"{name} gang recycle")

    def kill_leader(self):
        """SIGKILL whichever replica holds the lease, then respawn it
        under the same identity; the survivor must take over."""
        try:
            lease = self.fake.get("coordination.k8s.io/v1", "Lease",
                                  "notebook-controller", "kubeflow")
            holder = lease["spec"].get("holderIdentity")
        except NotFound:
            holder = None
        victim = holder if holder in self.procs else self.IDENTITIES[0]
        proc = self.procs[victim]
        proc.kill()
        proc.wait()
        self._spawn_controller(victim)
        wait_http(
            f"http://127.0.0.1:{self.ports[victim]}/healthz"
        )

    def flap_lease(self):
        try:
            self.fake.delete("coordination.k8s.io/v1", "Lease",
                             "notebook-controller", "kubeflow")
        except NotFound:
            pass

    def _get_or_none(self, api, kind, name):
        try:
            return self.fake.get(api, kind, name, "alice")
        except NotFound:
            return None

    def cycle(self, i: int):
        t0 = time.monotonic()
        # Names reuse a small pool so event aggregation (deterministic
        # names) is what bounds the event count, not object turnover.
        name = f"soak-nb-{i % 10}"
        record = {"cycle": i, "name": name, "ok": True}
        try:
            if i % 7 == 3:
                self.flap_lease()
                record["lease_flap"] = True
            self.spawn_cull_restart(name)
            if i % 3 == 1:
                gname = f"soak-slice-{i % 10}"
                self.gang_restart(gname)
                self._cleanup(gname)
                record["gang"] = True
            if i % 5 == 2:
                self.kill_leader()
                record["leader_kill"] = True
        # analysis: allow[py-broad-except] — soak harness: best-effort teardown
        except Exception as exc:  # log + count, keep soaking
            self.failed_cycles += 1
            record["ok"] = False
            record["error"] = f"{type(exc).__name__}: {exc}"
        finally:
            self._cleanup(name)
        record["dt_s"] = round(time.monotonic() - t0, 2)
        record["rss_kb"] = {
            name: rss_kb(proc.pid)
            for name, proc in self.procs.items()
        }
        record["events"] = len(self.fake.list("v1", "Event",
                                              namespace="alice"))
        record["objects"] = sum(
            len(self.fake.list(api, kind, namespace="alice"))
            for api, kind in (("v1", "Pod"), (NB_API, "Notebook"),
                              ("apps/v1", "StatefulSet"))
        )
        self.rss_history.append(
            (i, max(record["rss_kb"].values() or [0]))
        )
        self.log.write(json.dumps(record) + "\n")
        return record

    def run(self, duration_s: float, min_cycles: int = 2) -> dict:
        start = time.monotonic()
        i = 0
        last_events = 0
        while (time.monotonic() - start < duration_s
               or i < min_cycles):
            rec = self.cycle(i)
            last_events = rec["events"]
            i += 1
        warm = [r for c, r in self.rss_history if 2 <= c < 7]
        tail = [r for c, r in self.rss_history[-5:]]
        summary = {
            "cycles": i,
            "failed_cycles": self.failed_cycles,
            "duration_s": round(time.monotonic() - start, 1),
            "rss_warmup_kb": max(warm) if warm else None,
            "rss_tail_kb": max(tail) if tail else None,
            "events_final": last_events,
        }
        self.log.write(json.dumps({"summary": summary}) + "\n")
        return summary

    def close(self):
        for proc in self.procs.values():
            try:
                terminate(proc)
            except AssertionError:
                pass
        self.kernels.close()
        self.server.close()
        self.log.close()

    @staticmethod
    def check(summary: dict):
        """The soak's pass/fail contract."""
        assert summary["failed_cycles"] == 0, summary
        if summary["rss_warmup_kb"] and summary["cycles"] >= 12:
            assert (summary["rss_tail_kb"]
                    <= 1.5 * summary["rss_warmup_kb"]), (
                f"controller RSS grew {summary['rss_tail_kb']} kB vs "
                f"post-warmup {summary['rss_warmup_kb']} kB"
            )
        # Deterministic-name aggregation: events scale with the name
        # pool x reasons, not with cycles.
        assert summary["events_final"] <= 600, summary


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--duration", type=float, default=3600.0)
    parser.add_argument("--log", default="testing/soak_r04.log")
    args = parser.parse_args()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    soak = Soak(args.log)
    try:
        summary = soak.run(args.duration)
    finally:
        soak.close()
    print(json.dumps(summary))
    Soak.check(summary)


if __name__ == "__main__":
    main()
