"""Decompose the b1 decode step's time on the real chip: which part of
the ~(step - weight-streaming-floor) overhead belongs to what. Arms
build up from bare weight streaming to the full step, all timed as a
256-iteration lax.scan inside one dispatch (relay-floor amortised),
median of 3.

    python -u testing/ab_decode_floor.py
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from kubeflow_tpu.models import LMConfig, build_lm  # noqa: E402
from kubeflow_tpu.models.decoding import (  # noqa: E402
    KVCache,
    forward_with_cache,
)
from kubeflow_tpu.models.transformer import rms_norm, tied_head  # noqa: E402
from kubeflow_tpu.ops import apply_rope  # noqa: E402

STEPS = 256
REPS = 3


def timed(fn, *args):
    out = fn(*args)
    jax.block_until_ready(out)
    float(np.asarray(jax.device_get(jax.tree.leaves(out)[0])).ravel()[0])
    dts = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        out = fn(*args)
        float(np.asarray(
            jax.device_get(jax.tree.leaves(out)[0])
        ).ravel()[0])
        dts.append(time.perf_counter() - t0)
    return float(np.median(dts)) / STEPS * 1000  # ms/step


def main():
    cfg = LMConfig(vocab=32768, layers=8, dim=1024, heads=8, kv_heads=2,
                   dtype=jnp.bfloat16)
    model = build_lm(cfg)
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab, size=(1, 1024)),
                         jnp.int32)
    params = model.init(jax.random.key(0), prompt[:, :8])["params"]
    bf16 = lambda a: a.astype(jnp.bfloat16)
    blocks = [params[f"block_{i}"] for i in range(cfg.layers)]
    w = [
        {k: bf16(blk[k]["kernel"])
         for k in ("q_proj", "k_proj", "v_proj", "proj", "up", "down")}
        for blk in blocks
    ]
    emb = bf16(params["embed"]["embedding"])
    x0 = jnp.zeros((1, 1, cfg.dim), jnp.bfloat16)

    @jax.jit
    def arm_matmuls(w, emb, x0):
        # Bare weight streaming: the 6 block matmuls x 8 + the head.
        def step(x, _):
            for blk in w:
                q = x @ blk["q_proj"]
                k = x @ blk["k_proj"]
                v = x @ blk["v_proj"]
                x = x + q @ blk["proj"]
                h = jax.nn.gelu(x @ blk["up"])
                x = x + h @ blk["down"] + jnp.sum(k) + jnp.sum(v)
            logits = tied_head(x, emb, jnp.bfloat16)
            out = x * 0.999 + logits[..., :1, :1024] * 1e-6
            return out.astype(x.dtype), None

        x, _ = jax.lax.scan(step, x0, None, length=STEPS)
        return x

    @jax.jit
    def arm_matmuls_fused_qkv(w, emb, x0):
        def step(x, _):
            for blk in w:
                qkv = x @ jnp.concatenate(
                    [blk["q_proj"], blk["k_proj"], blk["v_proj"]],
                    axis=1,
                )
                x = x + qkv[..., :1024] @ blk["proj"]
                h = jax.nn.gelu(x @ blk["up"])
                x = x + h @ blk["down"] + jnp.sum(qkv[..., 1024:])
            logits = tied_head(x, emb, jnp.bfloat16)
            out = x * 0.999 + logits[..., :1, :1024] * 1e-6
            return out.astype(x.dtype), None

        x, _ = jax.lax.scan(step, x0, None, length=STEPS)
        return x

    @jax.jit
    def arm_norms_rope(w, emb, x0):
        # + norms and rope (no cache, no attention softmax).
        scales = [
            (blocks[i]["RMSNorm_0"]["scale"],
             blocks[i]["RMSNorm_1"]["scale"])
            for i in range(cfg.layers)
        ]

        def step(x, _):
            for blk, (s0, s1) in zip(w, scales):
                h = rms_norm(s0, x)
                q = h @ blk["q_proj"]
                k = h @ blk["k_proj"]
                qh = q.reshape(1, 1, 8, 128).transpose(0, 2, 1, 3)
                kh = k.reshape(1, 1, 2, 128).transpose(0, 2, 1, 3)
                qh = apply_rope(qh, offset=100)
                kh = apply_rope(kh, offset=100)
                v = h @ blk["v_proj"]
                x = x + qh.transpose(0, 2, 1, 3).reshape(1, 1, 1024) \
                    @ blk["proj"]
                h2 = rms_norm(s1, x)
                g = jax.nn.gelu(h2 @ blk["up"])
                x = x + g @ blk["down"] + jnp.sum(kh) + jnp.sum(v)
            logits = tied_head(rms_norm(
                params["final_norm"]["scale"], x), emb, jnp.bfloat16)
            out = x * 0.999 + logits[..., :1, :1024] * 1e-6
            return out.astype(x.dtype), None

        x, _ = jax.lax.scan(step, x0, None, length=STEPS)
        return x

    # Pallas GEMV arm: same bare-matmul chain through the PRODUCTION
    # kernel (ops/gemv.py) — the A/B must measure the code that ships,
    # not a local reimplementation whose block picker could diverge.
    from kubeflow_tpu.ops.gemv import gemv  # noqa: E402

    def pgemv(x, wmat, block_n):
        k = wmat.shape[0]
        y = gemv(x.reshape(1, k), wmat, block_n=block_n)
        return y.reshape(x.shape[:-1] + (wmat.shape[1],))

    def make_arm_pallas(block_n):
        @jax.jit
        def arm_matmuls_pallas(w, emb, x0):
            def step(x, _):
                for blk in w:
                    q = pgemv(x, blk["q_proj"], block_n)
                    k = pgemv(x, blk["k_proj"], block_n)
                    v = pgemv(x, blk["v_proj"], block_n)
                    x = x + pgemv(q.astype(jnp.bfloat16), blk["proj"],
                                  block_n)
                    h = jax.nn.gelu(pgemv(x, blk["up"], block_n))
                    x = (x + pgemv(h.astype(jnp.bfloat16), blk["down"],
                                   block_n)
                         + jnp.sum(k) + jnp.sum(v)).astype(jnp.bfloat16)
                logits = pgemv(x, emb.T, block_n)
                out = x * 0.999 + logits[..., :1, :1024] * 1e-6
                return out.astype(jnp.bfloat16), None

            x, _ = jax.lax.scan(step, x0, None, length=STEPS)
            return x

        return arm_matmuls_pallas

    results = {
        "matmuls_only_ms": timed(arm_matmuls, w, emb, x0),
        "matmuls_fused_qkv_ms": timed(arm_matmuls_fused_qkv, w, emb,
                                      x0),
        "plus_norms_rope_ms": timed(arm_norms_rope, w, emb, x0),
    }
    # 4096 is not swept: gemv's VMEM cap clamps it back to 2048.
    for bn in (512, 1024, 2048):
        results[f"matmuls_pallas_b{bn}_ms"] = timed(
            make_arm_pallas(bn), w, emb, x0)

    # Full production step at p1024 for reference, same process.
    cache0 = KVCache.init(cfg, 1, 1024 + STEPS)
    _, cache = forward_with_cache(cfg, params, prompt, cache0)
    tok = jnp.zeros((1,), jnp.int32)

    @jax.jit
    def arm_full(params, tok, cache):
        def step(carry, _):
            tok, cache = carry
            logits, cache = forward_with_cache(
                cfg, params, tok[:, None], cache
            )
            return (jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32),
                    cache), None

        (tok, cache), _ = jax.lax.scan(
            step, (tok, cache), None, length=STEPS
        )
        return tok

    results["full_step_p1024_ms"] = timed(arm_full, params, tok, cache)
    print(json.dumps({k: round(v, 4) for k, v in results.items()}),
          flush=True)


if __name__ == "__main__":
    main()
