"""Serve the three SPAs with the browser-tier fixtures for an in-env
WebView run (SURVEY §4 tier 4).

This image has no Playwright (and no pip install), so the committed
browser-tier specs (`tests/e2e_frontend/`) skip locally and run in CI
(`frontend_e2e.yaml`). To still leave an *in-env* artifact, this script
serves the same seeded apps the Playwright conftest builds — identical
fixtures, real HTTP, real backends against the fake apiserver — so an
external WebView/browser harness can drive the exact spec scenarios and
record the results (`testing/browser_run_r05.md`).

Usage: python testing/browser_serve.py  (serves until killed)
  JWA       http://127.0.0.1:7701
  VWA       http://127.0.0.1:7702
  Dashboard http://127.0.0.1:7703
"""

from __future__ import annotations

import threading

from werkzeug.serving import make_server

from kubeflow_tpu.apps.jupyter import create_app as create_jwa
from kubeflow_tpu.apps.volumes import create_app as create_vwa
from kubeflow_tpu.crud_backend import AllowAll, AuthnConfig
from kubeflow_tpu.dashboard import KfamProxy, create_app as create_dash
from kubeflow_tpu.k8s.fake import FakeApiServer
from kubeflow_tpu.kfam import create_app as create_kfam

USER = "dev@local"


def seeded_jwa_app(extra_fixtures: bool = False):
    """The browser-tier JWA: real app factory over a seeded fake
    apiserver. SINGLE SOURCE for these fixtures — the Playwright
    conftest (tests/e2e_frontend/conftest.py) imports this builder, so
    CI specs and the in-env wire smoke drive the same seeded state by
    construction. ``extra_fixtures`` adds the objects the smoke runner
    needs up front (the Playwright specs create them in-test)."""
    api = FakeApiServer()
    api.create({"apiVersion": "v1", "kind": "Namespace",
                "metadata": {"name": "alice"}})
    api.create({
        "apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
        "metadata": {"name": "demo-nb", "namespace": "alice",
                     "creationTimestamp": "2026-07-30T06:00:00Z"},
        "spec": {"tpu": {"accelerator": "v5e", "topology": "2x4"},
                 "template": {"spec": {"containers": [{
                     "name": "demo-nb",
                     "image": "ghcr.io/kubeflow-tpu/jupyter-jax-tpu:latest",
                     "resources": {"requests": {"cpu": "2",
                                                "memory": "4Gi"}},
                 }]}}},
        "status": {"readyReplicas": 1,
                   "containerState": {"running": {}},
                   "conditions": [{
                       "type": "Ready", "status": "True",
                       "reason": "PodsReady",
                       "message": "all replicas ready",
                       "lastTransitionTime": "2026-07-30T06:05:00Z"}]},
    })
    api.create({"apiVersion": "v1", "kind": "Pod",
                "metadata": {"name": "demo-nb-0", "namespace": "alice",
                             "labels": {"notebook-name": "demo-nb"}},
                "spec": {}, "status": {"phase": "Running"}})
    api.set_pod_logs("alice", "demo-nb-0",
                     "jupyterlab listening on 8888\n"
                     "TPU v5e 2x4 slice initialised\n")
    api.create({"apiVersion": "v1", "kind": "Event",
                "metadata": {"name": "demo-ev1", "namespace": "alice"},
                "involvedObject": {"kind": "Notebook", "name": "demo-nb"},
                "reason": "Created",
                "message": "StatefulSet demo-nb created",
                "type": "Normal", "count": 1,
                "lastTimestamp": "2026-07-30T06:01:00Z"})
    if extra_fixtures:
        # The humanized-time smoke scenario needs a fresh event.
        import datetime
        recent = (datetime.datetime.now(datetime.timezone.utc)
                  - datetime.timedelta(minutes=5)
                  ).strftime("%Y-%m-%dT%H:%M:%SZ")
        api.create({
            "apiVersion": "v1", "kind": "Event",
            "metadata": {"name": "demo-nb.recent", "namespace": "alice"},
            "involvedObject": {"kind": "Notebook", "name": "demo-nb"},
            "reason": "Tested", "message": "humanized", "type": "Normal",
            "count": 1, "lastTimestamp": recent,
        })
        # A second notebook so list ordering is observable.
        api.create({
            "apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
            "metadata": {"name": "aaa-nb", "namespace": "alice",
                         "creationTimestamp": "2026-07-30T07:00:00Z"},
            "spec": {"template": {"spec": {"containers": [{
                "name": "aaa-nb", "image": "img:latest"}]}}},
            "status": {"readyReplicas": 1},
        })
    return create_jwa(api, authn=AuthnConfig(dev_mode=True),
                      authorizer=AllowAll(), secure_cookies=False), api


def seeded_vwa_app():
    """Single source for the VWA browser-tier fixtures (imported by
    tests/e2e_frontend/test_vwa_browser.py)."""
    api = FakeApiServer()
    api.create({"apiVersion": "v1", "kind": "Namespace",
                "metadata": {"name": "alice"}})
    api.create({
        "apiVersion": "v1", "kind": "PersistentVolumeClaim",
        "metadata": {"name": "workspace", "namespace": "alice"},
        "spec": {"accessModes": ["ReadWriteOnce"],
                 "resources": {"requests": {"storage": "10Gi"}}},
        "status": {"phase": "Bound"},
    })
    api.create({
        "apiVersion": "v1", "kind": "Event",
        "metadata": {"name": "ev1", "namespace": "alice"},
        "involvedObject": {"kind": "PersistentVolumeClaim",
                           "name": "workspace"},
        "reason": "ProvisioningSucceeded",
        "message": "volume bound to pv-123",
        "type": "Normal", "count": 1,
        "lastTimestamp": "2026-07-30T06:00:00Z",
    })
    return create_vwa(api, authn=AuthnConfig(dev_mode=True),
                      authorizer=AllowAll(), secure_cookies=False), api


def seeded_dashboard_app():
    """Single source for the dashboard browser-tier fixtures (imported
    by tests/e2e_frontend/test_dashboard_browser.py)."""
    api = FakeApiServer()
    api.create({
        "apiVersion": "kubeflow.org/v1", "kind": "Profile",
        "metadata": {"name": "team-alpha"},
        "spec": {"owner": {"kind": "User", "name": USER}},
    })
    api.create({"apiVersion": "v1", "kind": "Namespace",
                "metadata": {"name": "team-alpha"}})
    api.create({
        "apiVersion": "v1", "kind": "Node",
        "metadata": {
            "name": "tpu-node-0",
            "labels": {
                "cloud.google.com/gke-tpu-accelerator":
                    "tpu-v5-lite-podslice",
                "cloud.google.com/gke-tpu-topology": "2x4",
            },
        },
        "status": {"allocatable": {"google.com/tpu": "4"}},
    })
    api.create({
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "nb-0", "namespace": "team-alpha"},
        "spec": {"nodeName": "tpu-node-0", "containers": [{
            "name": "nb",
            "resources": {"limits": {"google.com/tpu": "4"}},
        }]},
        "status": {"phase": "Running"},
    })
    api.create({
        "apiVersion": "v1", "kind": "Event",
        "metadata": {"name": "ev1", "namespace": "team-alpha"},
        "involvedObject": {"kind": "Notebook", "name": "nb"},
        "reason": "Created",
        "message": "StatefulSet nb created",
        "type": "Normal", "count": 1,
        "lastTimestamp": "2026-07-30T06:01:00Z",
    })
    kfam_app = create_kfam(api, secure_cookies=False)
    return create_dash(
        api, kfam=KfamProxy(kfam_app),
        authn=AuthnConfig(dev_mode=True), secure_cookies=False,
    ), api


def main():
    servers = []
    for port, (app, _api), name in [
            (7701, seeded_jwa_app(extra_fixtures=True), "JWA"),
            (7702, seeded_vwa_app(), "VWA"),
            (7703, seeded_dashboard_app(), "Dashboard")]:
        server = make_server("127.0.0.1", port, app, threaded=True)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        servers.append(server)
        print(f"{name} http://127.0.0.1:{port}", flush=True)
    print("READY", flush=True)
    threading.Event().wait()


if __name__ == "__main__":
    main()
