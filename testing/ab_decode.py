"""Same-process decode A/B across implementation arms. Same-process
comparison is the only trustworthy one on this relay — cross-process b1
decode swings 15-25% (BASELINE.md variance note). Run on the real chip:

    python -u testing/ab_decode.py [config ...]

Arms (per config, traced fresh per call so module-constant overrides
take effect):
  base          round-4 production: raw params pytree + dense read,
                plain XLA projection dots (KFT_DECODE_MM=dense)
  gemv          raw pytree + dense read + Pallas weight-streaming
                projections (ops/gemv.py; round-5 production auto)
  fused         StackedDecodeParams (fused qkv, pre-cast bf16, no scan)
                + dense read
  kernel-<B>    fused + Pallas flash-decode, cache block B
                (bf16 non-rolling configs only)

Prints one JSON line per config with decode/prefill tok/s per arm.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import bench  # noqa: E402
from kubeflow_tpu.models import decoding  # noqa: E402

CONFIGS = {
    "b1-p1024": dict(batch=1, prompt_len=1024, new_tokens=256),
    "b1-p8k": dict(batch=1, prompt_len=8192, new_tokens=128),
    "b1-p8k-w1k": dict(batch=1, prompt_len=8192, new_tokens=128,
                       window=1024),
    "b8-p8k": dict(batch=8, prompt_len=8192, new_tokens=64),
    "b8-p8k-int8": dict(batch=8, prompt_len=8192, new_tokens=64,
                        quantized=True),
    "b1-p32k": dict(batch=1, prompt_len=32768, new_tokens=64),
    # 128k-cache regime (run with KFT_BENCH_PREFILL_REPS=1 — the
    # default 8 independent 128k prefills per timed pass are pure
    # warm-up cost at this scale): ~1.07 GB bf16 cache, flash-decode
    # auto threshold well exceeded.
    "b1-p128k": dict(batch=1, prompt_len=131072, new_tokens=32),
}

KERNEL_BLOCKS = (1024, 2048, 4096)


def run_arm(kw, path, impl, block=None, mm="dense"):
    os.environ["KFT_BENCH_DECODE_PATH"] = path
    decoding.DECODE_IMPL = impl
    decoding.DECODE_MM = mm
    if block is not None:
        decoding.DECODE_KERNEL_BLOCK = block
    r = bench.bench_decode(prefill_anchor=None, decode_anchor=None,
                           **kw)
    return {
        "decode_tok_s": r["value"],
        "step_ms": r["decode_step_ms"],
        "prefill_tok_s": r["prefill_tokens_per_sec"],
    }


def main():
    names = sys.argv[1:] or list(CONFIGS)
    for name in names:
        kw = CONFIGS[name]
        row = {"config": name}
        row["base"] = run_arm(kw, "unrolled", "dense")
        row["gemv"] = run_arm(kw, "unrolled", "dense", mm="gemv")
        if not kw.get("quantized"):
            row["w8"] = run_arm(dict(kw, weight_int8=True), "unrolled",
                                "dense", mm="gemv")
        row["fused"] = run_arm(kw, "stacked", "dense")
        kernel_ok = not kw.get("quantized") and not kw.get("window")
        if kernel_ok:
            for block in KERNEL_BLOCKS:
                row[f"kernel-{block}"] = run_arm(
                    kw, "stacked", "kernel", block
                )
        best = max(
            (k for k in row if k != "config"),
            key=lambda k: row[k]["decode_tok_s"],
        )
        row["best"] = best
        row["best_speedup"] = round(
            row[best]["decode_tok_s"] / row["base"]["decode_tok_s"], 4
        )
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
