"""1f1b x sp characterisation runner (round 5): one (pp, sp, V) config
per invocation on the virtual CPU mesh — loss parity vs the sequential
reference + finite grads. The committed matrix record is
testing/matrix_1f1b_sp_r05.log; full grad parity per-leaf lives in the
permanent suite tests (tests/test_pipeline.py).

    env JAX_PLATFORMS=cpu \
        XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python testing/repro_1f1b_sp.py <pp> <sp> <virtual_stages>
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from kubeflow_tpu.models import LMConfig  # noqa: E402
from kubeflow_tpu.models.pipeline_lm import PipelinedLM  # noqa: E402
from kubeflow_tpu.models.transformer import lm_loss  # noqa: E402
from kubeflow_tpu.parallel import MeshSpec, make_mesh  # noqa: E402


def main():
    pp, sp, v = (int(a) for a in sys.argv[1:4])
    cfg = LMConfig(vocab=64, layers=pp * v, dim=32, heads=2)
    mesh = make_mesh(MeshSpec(pp=pp, sp=sp))
    model = PipelinedLM(cfg, mesh, num_microbatches=pp,
                        schedule="1f1b", virtual_stages=v)
    params = model.init(jax.random.key(0))
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(
        rng.integers(0, 64, size=(2 * pp, 16)), jnp.int32
    )
    loss = jax.jit(lambda p: lm_loss(
        model.apply({"params": p}, tokens), tokens))(params)
    ref = jax.jit(lambda p: lm_loss(
        model.sequential_apply({"params": p}, tokens), tokens))(params)
    np.testing.assert_allclose(loss, ref, rtol=1e-4)
    g = jax.jit(jax.grad(lambda p: lm_loss(
        model.apply({"params": p}, tokens), tokens)))(params)
    assert all(bool(jnp.all(jnp.isfinite(leaf)))
               for leaf in jax.tree.leaves(g))
    print(f"OK pp={pp} sp={sp} V={v} loss={float(loss):.4f}")


if __name__ == "__main__":
    main()
