#!/usr/bin/env bash
# Scenario-world gate for CI (PR 19). Four checks:
#
# 1. World tier-1 subset: tests/test_world.py fast set — derived
#    per-track streams (cross-process constants), the track-isolation
#    contract (composing a track leaves every other track's jittered
#    instants byte-identical), correlated-domain loss/repair against a
#    live pod plane (merged capacity_at, slice_capacity, rebind
#    refusal until repair), and the game-day + contention digest pins
#    proving the builder refactor replayed their exact draw order —
#    plus the py-shared-rng-stream rule fixtures in
#    tests/test_analysis.py.
#
# 2. Composition smoke: a tiny composed world must fire its domain
#    pair, merge the pool view, and leave the bare world's instants
#    untouched.
#
# 3. Analysis: chaos/ + loadtest/ hold ZERO findings under every pack
#    — including the new py-shared-rng-stream rule — and the full
#    kubeflow_tpu package stays clean.
#
# 4. RUN_SLOW=1: loadtest/fleet_storm.py --crs 10000 via the CLI (its
#    exit code gates storm_problems_in: all four actuator families
#    fired incl. the rack-veto/allow elastic arc, alerts resolved,
#    domain loss+repair with pod casualties, quota-gamers refused by
#    quota, byte-identical replay digest) and the JSON artifact is
#    asserted.
set -euo pipefail

cd "$(dirname "$0")/../.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

echo "== world gate: scenario-world tier-1 subset =="
python -m pytest -q -p no:cacheprovider -m 'not slow' \
  tests/test_world.py \
  "tests/test_analysis.py::TestSharedRngStreamRule"

echo "== world gate: composition smoke =="
python - <<'PY'
from kubeflow_tpu.chaos import WorldBuilder

def base():
    return (WorldBuilder(seed=4, ticks=20, tick_s=30.0)
            .capacity(0.0, 32)
            .domains(4)
            .domain_loss(0.3, domain=1, chips=8, jitter_s=15.0)
            .domain_repair(0.7, domain=1, jitter_s=15.0))

bare = base().build()
composed = (base()
            .traffic("wave", 0.1, 0.5, ttft_s=10.0)
            .api_blackout(0.4, 0.6, ops_per_tick=2)
            .build())
assert composed.instants()["domains"] == bare.instants()["domains"], \
    "composing traffic/api tracks moved the domain instants"
assert composed.instants()["capacity"] == bare.instants()["capacity"]

class _Sim:
    def __init__(self):
        self.lost_domains = set()
        self.domain_of = None
    def _is_bound(self, pod):
        return False

class _Injector:
    class api:
        @staticmethod
        def list(*a, **k):
            return []
    @staticmethod
    def preempt_pod(ns, name):
        return None
    @staticmethod
    def recover_node(node):
        pass
    @staticmethod
    def apply_capacity(schedule, now_s, sim):
        pass

sim = _Sim()
world = composed
assert world.capacity_at(0.0) == 32
fired = world.apply_domains(world.duration_s, _Injector, sim)
assert [f["kind"] for f in fired] == ["domain_loss", "domain_repair"]
assert world.capacity_at(world.duration_s) == 32
assert world.lost_domains() == frozenset()
print("  composed world: domain pair fired, pool view merged, "
      "instants isolated")
PY

echo "== world gate: zero analysis findings (all packs) =="
python - <<'PY'
from kubeflow_tpu.analysis import AnalysisConfig, analyze_paths

for scope in (["kubeflow_tpu/chaos", "loadtest"], ["kubeflow_tpu"]):
    findings = analyze_paths(AnalysisConfig(
        paths=scope, check_emitted=False,
    ))
    if findings:
        for f in findings:
            print(f.render())
        raise SystemExit(
            f"{len(findings)} finding(s) in {scope} under the full "
            "pack set (incl. py-shared-rng-stream)"
        )
print("  chaos/ + loadtest/ + kubeflow_tpu/: zero findings, all packs")
PY

echo "== world gate: no new Pack C pragma budget =="
if grep -rn "analysis: allow\[det-" kubeflow_tpu/chaos loadtest; then
  echo "Pack C pragmas are not allowed in chaos/ or loadtest/ — fix" \
    "the determinism hazard instead of annotating it" >&2
  exit 1
fi
echo "  zero det-* pragmas in chaos/ + loadtest/"

if [[ "${RUN_SLOW:-0}" == "1" ]]; then
  echo "== world gate: composed fleet storm (10k CRs, rack loss) =="
  artifact="${STORM_SUMMARY_JSON:-storm-summary.json}"
  python -m loadtest.fleet_storm --crs 10000 --ticks 300 \
    --dump-dir . | tee "$artifact"
  python - "$artifact" <<'PY'
import json
import sys

with open(sys.argv[1]) as fh:
    doc = json.loads(fh.read().strip().splitlines()[-1])
assert doc["kind"] == "fleet_storm", doc
assert doc["created"] >= 10000
assert doc["dual_leader_reconciles"] == 0
assert doc["orphans"]["count"] == 0
assert doc["slo"]["steady_state_green"] is True
assert doc["actuators_fired"] == [
    "checkpoint-cadence", "elastic-promotion",
    "gateway-admission", "inference-scale",
]
assert doc["alerts_unresolved"] == []
assert [e["kind"] for e in doc["domain_log"]] \
    == ["domain_loss", "domain_repair"]
assert doc["domain_log"][0]["pods"] >= 1
assert doc["elastic"]["gate_vetoes"] >= 1
assert doc["elastic"]["gate_allows"] >= 1
assert doc["quota"]["refused"] == doc["quota"]["gamers"] >= 1
assert doc["replay_digest"]
print(f"  storm artifact ok: {doc['counters']}, "
      f"elastic {doc['elastic']['shapes']}, "
      f"digest {doc['replay_digest'][:12]}…")
PY
fi

echo "world gate OK"