#!/usr/bin/env bash
# Slice-pool scheduler gate for CI (PR 12). Four checks:
#
# 1. Scheduler tier-1 subset: the full tests/test_scheduler.py fast
#    set — gang all-or-nothing admission, quota refusal, priority
#    preemption through the checkpoint drain (≤ cadence steps lost,
#    bit-identical resume), idle→suspend→first-touch-resurrect,
#    starvation freedom under aging, KFT_SCHEDULER=0 inertness, the
#    observability surfaces, the elastic demotion arm, and the fast
#    contention scenario with byte-identical replay — plus the
#    py-unbounded-queue-admission rule fixtures in
#    tests/test_analysis.py.
#
# 2. Disabled-switch smoke: KFT_SCHEDULER=0 must make
#    SlicePoolScheduler() report disabled and admit everything with
#    zero bookkeeping (the KFT_AUTOPILOT discipline; the full
#    byte-identical reconcile pin lives in the test suite).
#
# 3. Analysis: kubeflow_tpu/scheduler/ holds ZERO findings under
#    every pack — including the new py-unbounded-queue-admission rule
#    — with no pragma budget; the full kubeflow_tpu package stays
#    clean too.
#
# 4. RUN_SLOW=1: the full-size contention scenario via the CLI (its
#    own exit code gates the acceptance checklist) and the
#    goodput/queue-wait JSON artifact is asserted.
set -euo pipefail

cd "$(dirname "$0")/../.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

echo "== scheduler gate: tier-1 subset =="
python -m pytest -q -p no:cacheprovider -m 'not slow' \
  tests/test_scheduler.py \
  "tests/test_analysis.py::TestUnboundedQueueAdmissionRule"

echo "== scheduler gate: disabled switch =="
KFT_SCHEDULER=0 python - <<'PY'
from kubeflow_tpu.scheduler import SlicePoolScheduler, scheduler_enabled

assert not scheduler_enabled(), "KFT_SCHEDULER=0 must disable"
sched = SlicePoolScheduler(capacity_fn=lambda: 0)
assert not sched.enabled
verdict = sched.decide("Notebook", "ns", "nb", 16, {})
assert verdict.admitted and verdict.phase is None, \
    "disabled scheduler must admit everything"
assert sched.pool_snapshot()["admitted"] == 0, \
    "disabled scheduler must keep zero state"
print("  KFT_SCHEDULER=0: layer fully inert")
PY

echo "== scheduler gate: zero analysis findings (all packs) =="
python - <<'PY'
from kubeflow_tpu.analysis import AnalysisConfig, analyze_paths

findings = analyze_paths(AnalysisConfig(
    paths=["kubeflow_tpu/scheduler"], check_emitted=False,
))
if findings:
    for f in findings:
        print(f.render())
    raise SystemExit(
        f"{len(findings)} finding(s) in kubeflow_tpu/scheduler/ — "
        "the scheduler carries no pragma budget"
    )
whole = analyze_paths(AnalysisConfig(
    paths=["kubeflow_tpu"], check_emitted=False,
))
if whole:
    for f in whole:
        print(f.render())
    raise SystemExit(
        f"{len(whole)} finding(s) in kubeflow_tpu/ under the full "
        "pack set (incl. py-unbounded-queue-admission)"
    )
print("  kubeflow_tpu/ (incl. scheduler/): zero findings, all packs")
PY

if [[ "${RUN_SLOW:-0}" == "1" ]]; then
  echo "== scheduler gate: full contention scenario =="
  artifact="${SCHEDULER_CONTENTION_JSON:-contention-summary.json}"
  python -m loadtest.contention --seed 11 --ticks 240 \
    | tee "$artifact"
  python - "$artifact" <<'PY'
import json
import sys

with open(sys.argv[1]) as fh:
    doc = json.loads(fh.read().strip().splitlines()[-1])
assert doc["kind"] == "contention", doc
assert doc["counters"]["preemptions_total"] >= 1
assert doc["counters"]["reclaims_total"] >= 1
assert doc["counters"]["resurrects_total"] >= 1
pre = doc["preemption"]
assert pre["victim_preempted"] and pre["bit_identical"]
assert pre["steps_lost"] <= pre["cadence"]
meters = doc["goodput"]
assert any("queued" in m["downtime_s"] for m in meters.values())
assert any("suspended" in m["downtime_s"] for m in meters.values())
assert doc["queue_wait"]["count"] >= 1
assert doc["replay_digest"]
print(f"  contention artifact ok: {doc['counters']}, "
      f"queue-wait p99 {doc['queue_wait']['p99_s']}s, "
      f"digest {doc['replay_digest'][:12]}…")
PY
fi

echo "scheduler gate OK"
