#!/usr/bin/env bash
# Decode gate for CI (PR 8). Three checks:
#
# 1. Fast decode parity subset: the fused-kernel parity matrix, the
#    speculative token-identity suite, the verify-step chain tests and
#    the kernel-level extension tests (all tier-1 members, so the gate
#    holds even where CI doesn't run). RUN_SLOW=1 widens to every
#    slow-marked serving/generate case (compile-heavy gateway paths).
#
# 2. Decode bench artifact: a tiny-model timing pass over the plain,
#    fused-forced and speculative decode paths (CPU interpret — NOT a
#    perf claim, the flagship numbers come from bench.py on TPU) so
#    every CI run leaves a decode-bench.json breadcrumb proving the
#    three paths run end to end and agree token-for-token.
#
# 3. Static analysis: the decode stack (ops/ + decoding/speculative/
#    serving model files + kubeflow_tpu/serving/) must hold EVERY pack
#    at zero findings with no pragma budget — since Pack D that
#    includes the kernel launch contracts, VMEM budgets, donation
#    aliasing and int8 scale flow of the very kernels checked in
#    step 1, so a fused-path edit that breaks a contract fails here
#    even when the CPU-interpret parity subset can't see it.
set -euo pipefail

cd "$(dirname "$0")/../.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

echo "== decode gate: fused + speculative parity subset =="
FAST_TESTS=(
  tests/test_speculative.py
  "tests/test_serving.py::TestFusedDecodeParity"
  "tests/test_serving.py::TestVerifyStep"
  "tests/test_serving.py::TestSpeculativeEngine"
  "tests/test_generate.py::TestGemvResidualEpilogue"
  "tests/test_generate.py::TestQkvRopeKernel"
  "tests/test_generate.py::TestDecodeKernelExtensions"
)
if [ "${RUN_SLOW:-0}" = "1" ]; then
  # The full compile-heavy matrix: every serving/generate/speculative
  # test incl. slow-marked gateway paths.
  python -m pytest tests/test_speculative.py tests/test_serving.py \
    tests/test_generate.py \
    "tests/test_inference.py::TestSpeculativeGateway" \
    -q -p no:cacheprovider
else
  python -m pytest "${FAST_TESTS[@]}" -q -p no:cacheprovider \
    -m 'not slow'
fi

echo "== decode gate: tiny-model decode bench artifact =="
python - <<'PY'
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.models import LMConfig, build_lm, create_lm_state
from kubeflow_tpu.models import decoding
from kubeflow_tpu.models.decoding import generate
from kubeflow_tpu.models.speculative import speculative_generate

cfg = LMConfig(vocab=256, layers=2, dim=128, heads=4, kv_heads=2,
               dtype=jnp.bfloat16)
model = build_lm(cfg, use_flash=False)
params = create_lm_state(model, jax.random.key(0), (1, 16)).params
rng = np.random.default_rng(0)
base = rng.integers(0, cfg.vocab, size=16)
prompt = jnp.asarray(np.tile(base, 6)[None, :], jnp.int32)
NEW = 32


def timed(fn):
    out = fn()
    toks = np.asarray(jax.device_get(out))
    t0 = time.perf_counter()
    out = fn()
    jax.device_get(out)
    return toks, time.perf_counter() - t0


sections = {}
ref = None
prev = decoding.DECODE_FUSED
try:
    for name, mode, fn in [
        ("decode[tiny-plain]", "off",
         lambda: generate(cfg, params, prompt, NEW)),
        ("decode[tiny-fused]", "on",
         lambda: generate(cfg, params, prompt, NEW)),
        ("decode[tiny-spec]", "off",
         lambda: speculative_generate(cfg, params, prompt, NEW)),
    ]:
        decoding.DECODE_FUSED = mode
        jax.clear_caches()
        toks, dt = timed(fn)
        if ref is None:
            ref = toks
        assert (toks == ref).all(), f"{name} diverged from plain decode"
        sections[name] = {"tok_s": round(NEW / dt, 1)}
finally:
    decoding.DECODE_FUSED = prev
    jax.clear_caches()

record = {"metric": "decode_gate_tiny_bench", "backend": "cpu-interpret",
          "note": "path-agreement breadcrumb, not a perf claim",
          "sections": sections}
with open("decode-bench.json", "w") as fh:
    json.dump(record, fh, indent=1)
    fh.write("\n")
print(json.dumps(record))
PY

echo "== decode gate: analysis packs at zero findings =="
python -m kubeflow_tpu.analysis kubeflow_tpu/ops \
  kubeflow_tpu/models/decoding.py kubeflow_tpu/models/speculative.py \
  kubeflow_tpu/models/serving.py kubeflow_tpu/serving
python - <<'PY'
from kubeflow_tpu.analysis import AnalysisConfig, analyze_paths

findings = analyze_paths(AnalysisConfig(
    paths=["kubeflow_tpu/ops", "kubeflow_tpu/models/decoding.py",
           "kubeflow_tpu/models/speculative.py",
           "kubeflow_tpu/models/serving.py", "kubeflow_tpu/serving"],
    check_emitted=False,
))
# No pragma budget, no baseline: the decode stack must be spotless
# under every pack, dataflow and Pack D kernel hazards included.
if findings:
    print("\n".join(f.render() for f in findings))
    raise SystemExit(1)
# Prove the kernel pack actually ran over this tree rather than
# being silently dropped from the engine dispatch: the engine source
# must dispatch kernel_rules (the fixture-firing probe lives in
# analysis_gate.sh).
import inspect

from kubeflow_tpu.analysis import engine
assert "kernel_rules.analyze" in inspect.getsource(engine), \
    "kernel pack missing from engine dispatch"
print("  decode stack: clean under all packs (Pack D live)")
PY

echo "decode gate: OK"
