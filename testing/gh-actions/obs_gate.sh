#!/usr/bin/env bash
# Observability gate for CI (PR 3). Two checks:
#
# 1. Exposition integrity: every platform registry (controller-manager,
#    jupyter CRUD app, dashboard) must parse cleanly with
#    prometheus_client.parser — no duplicate families, no invalid
#    lines — and use only the canonical label schema
#    (kubeflow_tpu.obs.CANONICAL_LABELS).
#
# 2. Log discipline: the obs/resilience tier-1 subset runs with
#    testing/obs_log_plugin.py attached; any kubeflow_tpu.* record
#    that the structured JSON formatter cannot render with the schema
#    core (ts/level/logger/msg) fails the gate. Pairs with the
#    analyzer's py-print-in-lib rule: prints never reach loggers, so
#    the two checks together cover both escape routes.
set -euo pipefail

cd "$(dirname "$0")/../.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

echo "== obs gate: /metrics exposition integrity =="
python - <<'PY'
from prometheus_client import generate_latest
from prometheus_client.parser import text_string_to_metric_families

from kubeflow_tpu import obs
from kubeflow_tpu.apps.jupyter import create_app as create_jwa
from kubeflow_tpu.controllers.metrics import ControllerMetrics
from kubeflow_tpu.dashboard import create_app as create_dash
from kubeflow_tpu.k8s.fake import FakeApiServer

failures = []
api = FakeApiServer()
registries = {
    "controller-manager": ControllerMetrics(api=api).registry,
    "jupyter": create_jwa(api, secure_cookies=False).registry,
    "dashboard": create_dash(api, secure_cookies=False).registry,
}
for origin, registry in registries.items():
    text = generate_latest(registry).decode()
    try:
        families = list(text_string_to_metric_families(text))
    except ValueError as exc:
        failures.append(f"{origin}: exposition does not parse: {exc}")
        continue
    names = [f.name for f in families]
    for name in sorted({n for n in names if names.count(n) > 1}):
        failures.append(f"{origin}: duplicate metric family {name!r}")
    for family in families:
        for sample in family.samples:
            bad = set(sample.labels) - obs.CANONICAL_LABELS
            if bad:
                failures.append(
                    f"{origin}: {sample.name} uses non-canonical "
                    f"label(s) {sorted(bad)}"
                )
    print(f"  {origin}: {len(families)} families ok")
if failures:
    print("\n".join(failures))
    raise SystemExit(1)
PY

echo "== obs gate: structured-log discipline over tier-1 subset =="
REPORT="$(mktemp)"
rm -f "$REPORT"
KFT_OBS_LOG_REPORT="$REPORT" PYTHONPATH="testing${PYTHONPATH:+:$PYTHONPATH}" \
  python -m pytest tests/test_obs.py tests/test_resilience.py \
  -q -m 'not slow' -p obs_log_plugin

if [[ -s "$REPORT" ]]; then
  echo "unstructured log records from kubeflow_tpu.* loggers:"
  cat "$REPORT"
  rm -f "$REPORT"
  exit 1
fi
rm -f "$REPORT"
echo "obs gate: OK"
