#!/usr/bin/env bash
# Observability gate for CI (PR 3; SLO layer PR 9; profiling +
# flight recorder PR 10). Five checks:
#
# 1. Exposition integrity: every platform registry (controller-manager,
#    jupyter CRUD app, dashboard) must parse cleanly with
#    prometheus_client.parser — no duplicate families, no invalid
#    lines — and use only the canonical label schema
#    (kubeflow_tpu.obs.CANONICAL_LABELS).
#
# 2. Exemplar exposition: the manager registry rendered as OpenMetrics
#    (the format that carries exemplars) must parse with the
#    OpenMetrics parser, with no duplicate families, and a reconcile
#    observation made under a span must surface its trace id as a
#    bucket exemplar.
#
# 3. Alert-triggered black-box dump: a seeded chaos blackout must
#    deterministically produce a firing burn-rate alert AND a
#    flight-recorder JSONL artifact whose snapshots carry per-phase
#    durations, queue depth, and a trace id that resolves in the
#    tracer ring.
#
# 4. Log discipline: the obs/resilience/slo/profile tier-1 subset
#    (including ALL of tests/test_slo.py — burn-rate math, alert
#    hysteresis, exemplar round-trips, /fleet + /debug/alerts schemas,
#    the chaos blackout acceptance arc — and ALL of
#    tests/test_profile.py — digest math, recorder ring + dumps,
#    /debug/profile + /debug/flightrecord, the alert-dump acceptance)
#    runs with testing/obs_log_plugin.py attached; any kubeflow_tpu.*
#    record that the structured JSON formatter cannot render with the
#    schema core (ts/level/logger/msg) fails the gate. Pairs with the
#    analyzer's py-print-in-lib rule: prints never reach loggers, so
#    the two checks together cover both escape routes.
#
# 5. Analysis: kubeflow_tpu/obs/ holds ZERO findings under every pack
#    (no pragma budget, no baseline entries for the package —
#    including PR 10's py-unbounded-deque rule).
set -euo pipefail

cd "$(dirname "$0")/../.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

echo "== obs gate: /metrics exposition integrity =="
python - <<'PY'
from prometheus_client import generate_latest
from prometheus_client.parser import text_string_to_metric_families

from kubeflow_tpu import obs
from kubeflow_tpu.apps.jupyter import create_app as create_jwa
from kubeflow_tpu.controllers.metrics import ControllerMetrics
from kubeflow_tpu.dashboard import create_app as create_dash
from kubeflow_tpu.k8s.fake import FakeApiServer

failures = []
api = FakeApiServer()
registries = {
    "controller-manager": ControllerMetrics(api=api).registry,
    "jupyter": create_jwa(api, secure_cookies=False).registry,
    "dashboard": create_dash(api, secure_cookies=False).registry,
}
for origin, registry in registries.items():
    text = generate_latest(registry).decode()
    try:
        families = list(text_string_to_metric_families(text))
    except ValueError as exc:
        failures.append(f"{origin}: exposition does not parse: {exc}")
        continue
    names = [f.name for f in families]
    for name in sorted({n for n in names if names.count(n) > 1}):
        failures.append(f"{origin}: duplicate metric family {name!r}")
    for family in families:
        for sample in family.samples:
            bad = set(sample.labels) - obs.CANONICAL_LABELS
            if bad:
                failures.append(
                    f"{origin}: {sample.name} uses non-canonical "
                    f"label(s) {sorted(bad)}"
                )
    print(f"  {origin}: {len(families)} families ok")
if failures:
    print("\n".join(failures))
    raise SystemExit(1)
PY

echo "== obs gate: OpenMetrics exemplar exposition =="
python - <<'PY'
from prometheus_client.openmetrics.exposition import generate_latest
from prometheus_client.openmetrics.parser import (
    text_string_to_metric_families,
)

from kubeflow_tpu import obs
from kubeflow_tpu.controllers.metrics import ControllerMetrics

prom = ControllerMetrics()
tracer = obs.Tracer(sample_rate=1.0)
with tracer.span("reconcile") as span:
    prom.reconcile_duration.labels("notebook").observe(
        0.2, exemplar={"trace_id": span.context.trace_id}
    )
text = generate_latest(prom.registry).decode()
families = list(text_string_to_metric_families(text))
names = [f.name for f in families]
dupes = sorted({n for n in names if names.count(n) > 1})
if dupes:
    raise SystemExit(f"duplicate families in OpenMetrics text: {dupes}")
exemplars = [
    s.exemplar
    for f in families
    for s in f.samples
    if s.name == "controller_reconcile_duration_seconds_bucket"
    and s.exemplar
]
if not exemplars:
    raise SystemExit("reconcile histogram exposed no exemplar")
if exemplars[0].labels.get("trace_id") != span.context.trace_id:
    raise SystemExit("exemplar trace id does not match the span")
print(f"  manager: {len(families)} families ok, exemplar round-trips")
PY

echo "== obs gate: alert-triggered flight-recorder dump =="
python - <<'PY'
import json
import os
import tempfile

from kubeflow_tpu import obs
from kubeflow_tpu.chaos import ChaosApiServer, FaultSchedule
from kubeflow_tpu.controllers.manager import make_default_slo_engine
from kubeflow_tpu.controllers.metrics import ControllerMetrics
from kubeflow_tpu.controllers.notebook import make_notebook_controller
from kubeflow_tpu.k8s.core import ApiError
from kubeflow_tpu.k8s.fake import FakeApiServer
from kubeflow_tpu.obs.recorder import FlightRecorder


class Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s
        return self.t


tracer = obs.Tracer(sample_rate=1.0)
obs.set_tracer(tracer)
fake = FakeApiServer()
fake.create({
    "apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
    "metadata": {"name": "victim", "namespace": "chaos-ns"},
    "spec": {"template": {"spec": {"containers": [
        {"name": "victim", "image": "jupyter-jax-tpu"}]}}},
})
clk = Clock()
proxy = ChaosApiServer(fake, FaultSchedule(seed=5).blackout(50, 120),
                       sleep=lambda s: None)
workdir = tempfile.mkdtemp(prefix="obs-gate-flight-")
recorder = FlightRecorder(capacity=64, dump_dir=workdir, clock=clk)
prom = ControllerMetrics()
engine = make_default_slo_engine(prom, proxy, clock=clk,
                                 recorder=recorder)
ctrl = make_notebook_controller(fake, prom=prom)
ctrl.recorder = recorder
ctrl.run_once()
for _ in range(24):
    for _ in range(5):
        try:
            proxy.list("kubeflow.org/v1beta1", "Notebook")
        except ApiError:
            pass
    engine.tick(clk.advance(30.0))
assert engine.alerts.state_of("apiserver-availability", "fast") \
    == "firing", "blackout never fired the fast-burn alert"
assert recorder.dumps_total == 1, "firing transition did not dump"
path = recorder.last_dump_path
lines = [json.loads(line) for line in open(path, encoding="utf-8")]
header, *snaps = lines
assert header["kind"] == "flight_dump"
assert "apiserver-availability" in header["reason"]
reconciles = [s for s in snaps if s["kind"] == "reconcile"]
assert reconciles, "dump carries no reconcile snapshots"
ring_ids = {s["trace_id"] for s in tracer.ring.spans()}
victim = next(s for s in reconciles if s["name"] == "victim")
assert {"list", "desired-state", "patch", "status"} <= set(
    victim["phases"]), victim["phases"]
assert victim["queue_depth"] >= 0
assert victim["trace_id"] in ring_ids, "trace id not in the ring"
obs.set_tracer(None)
print(f"  blackout -> firing -> {os.path.basename(path)}: "
      f"{len(snaps)} snapshot(s), trace id resolves")
PY

echo "== obs gate: kubeflow_tpu/obs at zero analysis findings =="
python - <<'PY'
from kubeflow_tpu.analysis import AnalysisConfig, analyze_paths

findings = analyze_paths(AnalysisConfig(
    paths=["kubeflow_tpu/obs"], check_emitted=False,
))
# No pragma budget, no baseline, not even warnings: the telemetry
# layer must be spotless under every pack (including its own new
# py-unbounded-metric-labels rule).
if findings:
    print("\n".join(f.render() for f in findings))
    raise SystemExit(1)
print("  kubeflow_tpu/obs: 0 findings under all packs")
PY

echo "== obs gate: structured-log discipline over tier-1 subset =="
REPORT="$(mktemp)"
rm -f "$REPORT"
KFT_OBS_LOG_REPORT="$REPORT" PYTHONPATH="testing${PYTHONPATH:+:$PYTHONPATH}" \
  python -m pytest tests/test_obs.py tests/test_resilience.py tests/test_slo.py \
  tests/test_profile.py \
  -q -m 'not slow' -p obs_log_plugin

if [[ -s "$REPORT" ]]; then
  echo "unstructured log records from kubeflow_tpu.* loggers:"
  cat "$REPORT"
  rm -f "$REPORT"
  exit 1
fi
rm -f "$REPORT"
echo "obs gate: OK"
