#!/usr/bin/env bash
# Static-analysis gate for CI: fail the build on any new error-severity
# finding (manifest/topology agreement, PodDefault conflicts, traced-code
# and controller hazards, SPMD coherence, concurrency discipline,
# Pack C replay determinism — the static twin of the replay_digest
# gates — and Pack D accelerator hazards: Pallas launch contracts,
# buffer-donation aliasing, int8 scale flow). Intentional occurrences
# carry an inline
# `# analysis: allow[rule-id]` pragma; the accepted-findings baseline
# (.analysis-baseline.json) is EMPTY since the PR 15 audit and must
# stay empty — tests/test_analysis_self.py pins the whole tree at zero
# findings, so environments without CI enforce the same gate.
#
# A SARIF 2.1.0 document is always written (even when the gate fails)
# so CI can upload it for PR diff annotation:
#   - path: ${ANALYSIS_SARIF:-analysis-results.sarif}
#   - GitHub: upload with github/codeql-action/upload-sarif or attach
#     as a build artifact.
set -euo pipefail

cd "$(dirname "$0")/../.."

SARIF_OUT="${ANALYSIS_SARIF:-analysis-results.sarif}"

# One scan: text report for the build log, SARIF artifact on the side,
# wall-time/parse stats on stderr.
rc=0
rm -f "$SARIF_OUT"
python -m kubeflow_tpu.analysis . --sarif-out "$SARIF_OUT" --stats || rc=$?
if [ -f "$SARIF_OUT" ]; then
    echo "SARIF written to $SARIF_OUT"
else
    echo "no SARIF produced (analysis aborted before reporting)" >&2
fi

# --changed-only smoke: the sub-second pre-commit mode must keep
# working (diff seed + reverse import closure; falls back to a full
# scan when git can't answer). Scoped to vs-HEAD, so on a clean CI
# checkout it scans the empty closure and exits 0 fast.
if [ "$rc" -eq 0 ]; then
    python -m kubeflow_tpu.analysis . --changed-only --stats || rc=$?
fi

# Pack D liveness probe: a clean tree produces an empty SARIF rule
# inventory, so the zero-findings gate above can't distinguish "the
# kernels are clean" from "the pack was dropped from the dispatch".
# Scan the seeded kernel fixtures and require all nine
# accelerator-hazard rules to fire AND to land in the SARIF rules
# array the annotation tooling reads.
if [ "$rc" -eq 0 ]; then
    python - <<'PY' || rc=$?
import json

from kubeflow_tpu.analysis import AnalysisConfig, analyze_paths
from kubeflow_tpu.analysis.sarif import sarif_document

findings = analyze_paths(AnalysisConfig(
    paths=["tests/analysis_fixtures/bad/kernels"], check_emitted=False,
))
doc = sarif_document(findings, [])
fired = {r["id"] for r in doc["runs"][0]["tool"]["driver"]["rules"]}
want = {
    "krn-block-nondivisor", "krn-index-map-arity", "krn-operand-arity",
    "krn-vmem-budget", "krn-vmem-proxy-dim", "don-read-after-donate",
    "don-thread-capture", "qnt-scale-skipped", "qnt-ragged-unmasked",
}
missing = want - fired
if missing:
    print(f"Pack D probe: rules missing from SARIF: {sorted(missing)}")
    raise SystemExit(1)
print(f"Pack D probe: all {len(want)} rules fire and reach SARIF "
      f"({json.dumps(sorted(fired))})")
PY
fi

exit "$rc"
