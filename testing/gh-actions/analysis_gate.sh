#!/usr/bin/env bash
# Static-analysis gate for CI: fail the build on any new error-severity
# finding (manifest/topology agreement, PodDefault conflicts, traced-code
# and controller hazards, SPMD coherence, concurrency discipline).
# Pre-existing accepted findings live in .analysis-baseline.json;
# intentional occurrences carry an inline `# analysis: allow[rule-id]`
# pragma. The same gate runs inside tier-1 pytest as
# tests/test_analysis_self.py, so environments without CI still
# enforce it.
#
# A SARIF 2.1.0 document is always written (even when the gate fails)
# so CI can upload it for PR diff annotation:
#   - path: ${ANALYSIS_SARIF:-analysis-results.sarif}
#   - GitHub: upload with github/codeql-action/upload-sarif or attach
#     as a build artifact.
set -euo pipefail

cd "$(dirname "$0")/../.."

SARIF_OUT="${ANALYSIS_SARIF:-analysis-results.sarif}"

# One scan: text report for the build log, SARIF artifact on the side.
rc=0
rm -f "$SARIF_OUT"
python -m kubeflow_tpu.analysis . --sarif-out "$SARIF_OUT" || rc=$?
if [ -f "$SARIF_OUT" ]; then
    echo "SARIF written to $SARIF_OUT"
else
    echo "no SARIF produced (analysis aborted before reporting)" >&2
fi

exit "$rc"
