#!/usr/bin/env bash
# Static-analysis gate for CI: fail the build on any new error-severity
# finding (manifest/topology agreement, PodDefault conflicts, traced-code
# and controller hazards). Pre-existing accepted findings live in
# .analysis-baseline.json; intentional occurrences carry an inline
# `# analysis: allow[rule-id]` pragma. The same gate runs inside tier-1
# pytest as tests/test_analysis_self.py, so environments without CI
# still enforce it.
set -euo pipefail

cd "$(dirname "$0")/../.."

python -m kubeflow_tpu.analysis .
