#!/usr/bin/env bash
# Checkpoint gate for CI: the crash-consistency contract must hold —
# atomic commit under injected kill points, digest-verified fallback
# past torn/corrupt steps, retention/GC, and the end-to-end
# preempt → slice restart → resume scenario with bounded lost work.
#
# The fast subset (manager unit tests + the chaos resume scenarios)
# runs on every PR tier-1 style; RUN_SLOW=1 adds the multi-process
# jax.distributed commit-barrier matrix (real OS processes, shared
# checkpoint dir, process 0 commits the manifest).
#
# Failures are deterministic: kill points are named protocol events
# (see kubeflow_tpu/chaos/ckpt.py KILL_POINTS), not timing races —
# re-running the named test reproduces the exact torn state. See
# docs/operations.md "Checkpoint & resume".
set -euo pipefail

cd "$(dirname "$0")/../.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

if [[ "${RUN_SLOW:-0}" == "1" ]]; then
  exec python -m pytest tests/test_checkpoint.py \
    "tests/test_chaos.py::TestCheckpointResume" \
    "tests/test_chaos.py::TestPreemptionDuringBlackout" -q
fi

exec python -m pytest tests/test_checkpoint.py \
  "tests/test_chaos.py::TestCheckpointResume" \
  "tests/test_chaos.py::TestPreemptionDuringBlackout" \
  -q -m 'not slow'
