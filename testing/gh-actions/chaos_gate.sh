#!/usr/bin/env bash
# Chaos gate for CI: the seeded fault-injection suite must converge the
# controllers to the fault-free desired state. The fast subset (every
# deterministic schedule + a couple of kitchen-sink seeds) runs on every
# PR inside tier-1; RUN_SLOW=1 adds the full seed matrix and the
# process-tier outage scenarios marked `slow`.
#
# A failure prints the schedule's seed and fault windows
# (FaultSchedule.describe()); re-running the named test reproduces the
# exact fault sequence — chaos here is deterministic, never flaky-by-
# design. See docs/operations.md "Failure modes & recovery".
set -euo pipefail

cd "$(dirname "$0")/../.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

if [[ "${RUN_SLOW:-0}" == "1" ]]; then
  exec python -m pytest tests/test_chaos.py tests/test_resilience.py -q
fi

exec python -m pytest tests/test_chaos.py tests/test_resilience.py \
  -q -m 'not slow'
