#!/usr/bin/env bash
# Autopilot gate for CI (PR 11). Four checks:
#
# 1. Actuator tier-1 subset: the full tests/test_autopilot.py fast
#    set — subscription plumbing (outside-lock dispatch, exception
#    isolation), SloEngine.signal() coherence, every actuator's
#    hysteresis under flap input, the disabled==instrument-only pin,
#    and the compressed game-day arc with byte-identical replay —
#    plus the py-unbounded-actuation rule fixtures in
#    tests/test_analysis.py.
#
# 2. Disabled-switch smoke: KFT_AUTOPILOT=0 must make Autopilot()
#    report disabled and install nothing (the Python-level half of the
#    PR-10 behaviour pin; the full equality pin lives in the test
#    suite).
#
# 3. Analysis: kubeflow_tpu/autopilot/ holds ZERO findings under
#    every pack — including the new py-unbounded-actuation rule — with
#    no pragma budget; the full kubeflow_tpu package stays clean too.
#
# 4. RUN_SLOW=1: the full 24h game-day timeline via the CLI (its own
#    exit code gates: all four actuators fired, counter == event log,
#    every fired alert resolved) and the summary artifact is asserted
#    (parses as JSON, replay digest present, no unresolved alerts).
set -euo pipefail

cd "$(dirname "$0")/../.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

echo "== autopilot gate: actuator tier-1 subset =="
python -m pytest -q -p no:cacheprovider -m 'not slow' \
  tests/test_autopilot.py \
  "tests/test_analysis.py::TestUnboundedActuationRule"

echo "== autopilot gate: disabled switch =="
KFT_AUTOPILOT=0 python - <<'PY'
from kubeflow_tpu.autopilot import (
    Autopilot,
    GatewayAdmissionActuator,
    autopilot_enabled,
)
from kubeflow_tpu.obs.alerts import SloEngine

assert not autopilot_enabled(), "KFT_AUTOPILOT=0 must disable"
pilot = Autopilot()
assert not pilot.enabled
engine = SloEngine()
stub = type("E", (), {"max_pending": 64, "prefill_per_cycle": 2})()
pilot.register(GatewayAdmissionActuator(stub))
pilot.attach(engine)
assert engine.alerts._subscribers == [], \
    "disabled autopilot must install no subscription"
assert pilot.actuators() == [], \
    "disabled autopilot must drive no actuators"
print("  KFT_AUTOPILOT=0: layer fully inert")
PY

echo "== autopilot gate: zero analysis findings (all packs) =="
python - <<'PY'
from kubeflow_tpu.analysis import AnalysisConfig, analyze_paths

findings = analyze_paths(AnalysisConfig(
    paths=["kubeflow_tpu/autopilot"], check_emitted=False,
))
if findings:
    for f in findings:
        print(f.render())
    raise SystemExit(
        f"{len(findings)} finding(s) in kubeflow_tpu/autopilot/ — "
        "the actuation layer carries no pragma budget"
    )
whole = analyze_paths(AnalysisConfig(
    paths=["kubeflow_tpu"], check_emitted=False,
))
if whole:
    for f in whole:
        print(f.render())
    raise SystemExit(
        f"{len(whole)} finding(s) in kubeflow_tpu/ under the full "
        "pack set (incl. py-unbounded-actuation)"
    )
print("  kubeflow_tpu/ (incl. autopilot/): zero findings, all packs")
PY

if [[ "${RUN_SLOW:-0}" == "1" ]]; then
  echo "== autopilot gate: full 24h game-day timeline =="
  artifact="${AUTOPILOT_GAMEDAY_JSON:-game-day-summary.json}"
  tmpdir="$(mktemp -d)"
  python -m loadtest.game_day --seed 7 --hours 24 \
    --dump-dir "$tmpdir" | tee "$artifact"
  python - "$artifact" <<'PY'
import json
import sys

with open(sys.argv[1]) as fh:
    doc = json.loads(fh.read().strip().splitlines()[-1])
assert doc["kind"] == "game_day", doc
expected = {"gateway-admission", "inference-scale",
            "checkpoint-cadence", "elastic-promotion"}
assert set(doc["actuators_fired"]) == expected, doc["actuators_fired"]
assert doc["alerts_unresolved"] == [], doc["alerts_unresolved"]
assert doc["actions_total"] == doc["events_total"]
assert doc["flight_dumps"] >= 1
assert doc["replay_digest"]
print(f"  game-day artifact ok: {doc['actions_total']} actions, "
      f"{len(doc['alerts_fired'])} alerts fired+resolved, "
      f"digest {doc['replay_digest'][:12]}…")
PY
  echo "== autopilot gate: slow suite (full game-day tests) =="
  python -m pytest -q -p no:cacheprovider -m slow tests/test_autopilot.py
fi

echo "autopilot gate OK"
