#!/usr/bin/env bash
# Install kustomize (role of the reference
# testing/gh-actions/install_kustomize.sh).
set -euo pipefail

KUSTOMIZE_VERSION="${KUSTOMIZE_VERSION:-v5.4.1}"

if command -v kustomize > /dev/null; then
  exit 0
fi
curl -sL \
  "https://github.com/kubernetes-sigs/kustomize/releases/download/kustomize%2F${KUSTOMIZE_VERSION}/kustomize_${KUSTOMIZE_VERSION}_linux_amd64.tar.gz" \
  | tar xz
chmod +x kustomize
sudo mv kustomize /usr/local/bin/kustomize
