#!/usr/bin/env bash
# Perf gate for CI (PR 18). The perf observatory's contract, smoke-
# tested on CPU:
#
# 1. Protocol tests: the hand-computed band math, the seeded 20%
#    regression caught (exit 1), the within-band wobble forgiven, the
#    provenance-mismatch -> incomparable rule, ledger atomicity.
#
# 2. Tiny end-to-end round: a real (tiny) jitted workload through
#    timed_trials -> make_record -> pin -> verdict -> report, schema
#    validated at every step, plus a planted 20% slowdown that MUST
#    flip the verdict exit code. NOT a perf claim — the protocol's
#    plumbing proven end to end on every CI run.
#
# 3. Committed artifacts: BENCH_r06.json parses, every decode[*] and
#    spec section is pinned in PERF_ANCHORS.json with a band and
#    provenance, and the trajectory ledger renders with an r06 column.
#
# 4. RUN_SLOW=1 only: a real cpu-mini bench mini-round (train +
#    decode[b1]) diffed against the committed anchors — each mode runs
#    THREE times and the three per-process medians are banded as one
#    measurement (in-process trial bands are blind to cross-process
#    wobble: CPU frequency, cache layout, container neighbors).
#    Because the committed anchors are single-process pins, the live
#    diff adds a flat cross-process allowance on top of the banded
#    tolerance; regressions past the allowance exit nonzero,
#    incomparable (different host provenance) reports loudly but does
#    not gate.
#
# 5. Static analysis: the perf trees (perfwatch, bench.py, loadtest/)
#    hold every pack at zero findings, and the new
#    py-single-shot-bench rule holds with NO pragma escapes.
set -euo pipefail

cd "$(dirname "$0")/../.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

echo "== perf gate: protocol tests (bands, verdicts, atomicity) =="
python -m pytest tests/test_perfwatch.py -q -p no:cacheprovider \
  -m 'not slow'

echo "== perf gate: tiny round through the full protocol =="
python - <<'PY'
import json
import os
import tempfile

import jax
import jax.numpy as jnp

from kubeflow_tpu.obs import perfwatch

x = jnp.ones((256, 256), jnp.float32)
mul = jax.jit(lambda a: a @ a)


def thunk():
    # Long enough (~10-20 ms) that scheduler jitter averages out even
    # on a loud single-core CI box; device_get forces the chain.
    for _rep in range(50):
        out = mul(x)
    jax.device_get(out)


meas = perfwatch.timed_trials(thunk, trials=7, warmup=2)
noise = perfwatch.host_noise_sentinel(spin_samples=500, sleeps=3)
record = perfwatch.make_record(
    "gate[tiny-matmul]", "gate_tiny_matmul_s", "seconds", meas,
    noise=noise,
)
problems = perfwatch.validate_record(record)
assert problems == [], f"tiny record failed schema: {problems}"
assert record["band"]["lo"] <= record["value"] <= record["band"]["hi"]

with tempfile.TemporaryDirectory() as tmp:
    rec_path = os.path.join(tmp, "full.json")
    anchors = os.path.join(tmp, "anchors.json")
    ledger = os.path.join(tmp, "ledger.jsonl")
    with open(rec_path, "w") as fh:
        json.dump(record, fh)
    assert perfwatch.main(["pin", "--record", rec_path, "--round",
                           "gate", "--anchors", anchors]) == 0
    # The round judged against its own pins: within noise, exit 0.
    assert perfwatch.main(["verdict", "--record", rec_path,
                           "--anchors", anchors]) == 0
    # A planted slowdown MUST flip the gate: at least 20%, deeper if
    # this host's honest tolerance is wider (the deterministic 20%
    # proof is tests/test_perfwatch.py; here the protocol runs live).
    (verdict,) = perfwatch.judge_records(
        [record], perfwatch.load_anchors(anchors)
    )
    assert verdict.tolerance < 0.5, (
        f"host too noisy for the gate to mean anything "
        f"(tolerance {verdict.tolerance})"
    )
    factor = min(0.8, 1.0 - verdict.tolerance - 0.05)
    slow = dict(record)
    slow["value"] = round(record["value"] * factor, 6)
    with open(rec_path, "w") as fh:
        json.dump(slow, fh)
    rc = perfwatch.main(["verdict", "--record", rec_path,
                        "--anchors", anchors])
    assert rc == 1, (
        f"planted {100 * (1 - factor):.0f}% regression escaped the "
        f"gate (rc={rc})"
    )
    assert perfwatch.main(["ingest", "--record", rec_path, "--round",
                           "gate", "--ledger", ledger]) == 0
    assert perfwatch.main(["report", "--ledger", ledger]) == 0
print("  tiny round: protocol plumbing OK (regression gate flips)")
PY

echo "== perf gate: committed r06 artifacts =="
python - <<'PY'
import json

from kubeflow_tpu.obs import perfwatch

with open("BENCH_r06.json") as fh:
    driver = json.load(fh)
assert driver["rc"] == 0, "committed r06 round did not exit 0"
sections = driver["parsed"]["sections"]
anchors = perfwatch.load_anchors("PERF_ANCHORS.json")["anchors"]
perf_sections = sorted(
    s for s in sections if s.startswith("decode[") or "spec" in s
)
assert perf_sections, "r06 recorded no decode/spec sections"
missing = [s for s in perf_sections if s not in anchors]
assert not missing, f"sections missing from PERF_ANCHORS.json: {missing}"
for name, anchor in anchors.items():
    assert anchor.get("value"), f"anchor {name} has no value"
    assert anchor.get("band_rel") is not None, f"{name} has no band"
    prov = anchor.get("provenance") or {}
    for key in ("git_rev", "platform", "env"):
        assert key in prov, f"{name} provenance missing {key}"
entries = perfwatch.read_ledger("PERF_TRAJECTORY.jsonl")
rounds = {e.get("round") for e in entries}
assert "r06" in rounds, f"trajectory ledger has no r06 column: {rounds}"
table = perfwatch.render_trend(entries)
assert "r06" in table.splitlines()[0]
print(f"  {len(perf_sections)} decode/spec sections pinned, "
      f"ledger rounds: {sorted(r for r in rounds if r)}")
PY

if [ "${RUN_SLOW:-0}" = "1" ]; then
  echo "== perf gate: real cpu-mini round vs committed anchors =="
  GATE_TMP="$(mktemp -d)"
  trap 'rm -rf "$GATE_TMP"' EXIT
  # Three full processes per mode: measured on this class of box,
  # cpu-mini medians wobble ~20% BETWEEN processes while in-process
  # trial bands read 4-6% — one process's band under-states the real
  # variance, so the gate bands the three per-process medians instead.
  for i in 1 2 3; do
    KFT_BENCH_PRESET=cpu-mini KFT_BENCH_MODE=lm \
      python bench.py > "$GATE_TMP/train_$i.json"
    KFT_BENCH_PRESET=cpu-mini KFT_BENCH_MODE=decode \
      python bench.py > "$GATE_TMP/decode_$i.json"
  done
  python - "$GATE_TMP" <<'PY'
import json
import sys

from kubeflow_tpu.obs import perfwatch

tmp = sys.argv[1]
runs = []
for i in (1, 2, 3):
    with open(f"{tmp}/train_{i}.json") as fh:
        doc = json.load(fh)
    with open(f"{tmp}/decode_{i}.json") as fh:
        doc["extra_metrics"] = [json.load(fh)]
    by_section = {}
    for record in perfwatch.records_from_full(doc):
        problems = perfwatch.validate_record(record)
        assert problems == [], f"{record['section']}: {problems}"
        by_section[record["section"]] = record
    runs.append(by_section)

# One combined record per gated section: the three per-process medians
# banded as a fresh Measurement, stamped with the run's provenance and
# the WORST noise grade any process saw.
combined = []
for section in ("train", "decode[b1]"):
    per_run = [run[section] for run in runs if section in run]
    assert len(per_run) == len(runs), f"{section}: missing from a run"
    meas = perfwatch.Measurement.from_values(
        [r["value"] for r in per_run]
    )
    noise = max(
        (r.get("noise") or {} for r in per_run),
        key=lambda n: perfwatch.GRADES.index(n.get("grade", "loud")),
    )
    combined.append(perfwatch.make_record(
        section, per_run[0]["metric"], per_run[0]["unit"], meas,
        noise=noise, prov=per_run[0].get("provenance"),
    ))
verdicts = perfwatch.judge_records(
    combined, perfwatch.load_anchors("PERF_ANCHORS.json"),
    sections=["train", "decode[b1]"],
)
# The committed anchors are SINGLE-process pins; the live diff crosses
# a process boundary the banded tolerance never sampled. Measured on
# this box: back-to-back cpu-mini rounds land 20-30% apart (lm medians
# cluster at ~5.5k AND ~7.3k tok/s) with 4-6% in-process bands. The
# live tier therefore grants a flat cross-process allowance on top of
# the verdict tolerance and gates on what's left — a halving still
# fails loudly, a process-placement wobble does not. The tight gate is
# the smoke tier above (same process, planted slowdown MUST flip it).
ALLOWANCE = 0.30
failed = []
for verdict in verdicts:
    print("  " + verdict.render())
    if verdict.status != "regressed":
        continue
    if verdict.ratio < 1.0 - (verdict.tolerance + ALLOWANCE):
        failed.append(verdict.section)
    else:
        print(f"    ^ within the ±{ALLOWANCE:.0%} cross-process "
              "allowance — reported, not gated")
if failed:
    print(f"  GATING regression past allowance: {failed}")
raise SystemExit(1 if failed else 0)
PY
fi

echo "== perf gate: analysis packs at zero findings, no new pragmas =="
python -m kubeflow_tpu.analysis kubeflow_tpu/obs/perfwatch.py \
  bench.py loadtest
python - <<'PY'
from kubeflow_tpu.analysis import AnalysisConfig, analyze_paths
from kubeflow_tpu.analysis.findings import pragma_rules

paths = ["kubeflow_tpu/obs/perfwatch.py", "bench.py", "loadtest"]
findings = analyze_paths(AnalysisConfig(paths=paths,
                                        check_emitted=False))
if findings:
    print("\n".join(f.render() for f in findings))
    raise SystemExit(1)
# The single-shot rule holds WITHOUT escapes: the perf trees repeat
# their measurements, they don't pragma their way past the protocol.
import glob
import os

files = [p for p in paths if os.path.isfile(p)]
files += [p for pattern in ("loadtest/*.py",)
          for p in sorted(glob.glob(pattern))]
for path in files:
    with open(path) as fh:
        for lineno, line in enumerate(fh, 1):
            for rule in pragma_rules(line):
                assert rule != "py-single-shot-bench", (
                    f"{path}:{lineno} pragmas py-single-shot-bench — "
                    "repeat the measurement instead"
                )
print("  perf trees: clean under all packs, no single-shot pragmas")
PY

echo "perf gate: OK"
