#!/usr/bin/env bash
# Install Istio into the integration cluster (role of the reference
# testing/gh-actions/install_istio.sh): istioctl with the default
# profile (istiod + ingressgateway), then wait for both. The platform's
# VirtualServices/AuthorizationPolicies need the CRDs and the gateway.
set -euo pipefail

ISTIO_VERSION="${ISTIO_VERSION:-1.22.3}"

if ! command -v istioctl > /dev/null; then
  curl -L https://istio.io/downloadIstio | \
    ISTIO_VERSION="${ISTIO_VERSION}" TARGET_ARCH=x86_64 sh -
  sudo mv "istio-${ISTIO_VERSION}/bin/istioctl" /usr/local/bin/
fi

istioctl install -y --set profile=default \
  --set meshConfig.accessLogFile=/dev/stdout

kubectl -n istio-system wait deploy/istiod \
  --for=condition=Available --timeout=300s
kubectl -n istio-system wait deploy/istio-ingressgateway \
  --for=condition=Available --timeout=300s

# The mesh gateway the manifests' VirtualServices route through.
kubectl apply -f - <<'EOF'
apiVersion: networking.istio.io/v1beta1
kind: Gateway
metadata:
  name: kubeflow-gateway
  namespace: kubeflow
spec:
  selector:
    istio: ingressgateway
  servers:
    - port: {number: 80, name: http, protocol: HTTP}
      hosts: ["*"]
EOF
