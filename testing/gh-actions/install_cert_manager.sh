#!/usr/bin/env bash
# Install cert-manager (role of the reference
# testing/gh-actions/install_cert_manager.sh): the admission webhook's
# serving cert + caBundle injection come from a self-signed Issuer.
set -euo pipefail

CERT_MANAGER_VERSION="${CERT_MANAGER_VERSION:-v1.15.1}"

kubectl apply -f \
  "https://github.com/cert-manager/cert-manager/releases/download/${CERT_MANAGER_VERSION}/cert-manager.yaml"

for deploy in cert-manager cert-manager-webhook cert-manager-cainjector; do
  kubectl -n cert-manager wait "deploy/${deploy}" \
    --for=condition=Available --timeout=300s
done
