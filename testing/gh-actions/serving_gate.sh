#!/usr/bin/env bash
# Serving gate for CI (PR 6). Three checks:
#
# 1. Fast serving subset: the InferenceService controller + gateway
#    suite and the int8-KV parity tests (tier-1 members, so the gate
#    holds even where CI doesn't run).
#
# 2. Metrics schema: the gateway registry (request metrics + the
#    engine collector) must parse cleanly and use only the canonical
#    label schema (kubeflow_tpu.obs.CANONICAL_LABELS) — checked on a
#    stub engine so the schema check needs no jax/model.
#
# 3. Static analysis: kubeflow_tpu/serving/ must be at ZERO findings
#    under every pack — including the PR-5 SPMD/concurrency dataflow
#    packs, with no pragma budget: the gateway's scheduler thread and
#    swap staging are exactly what conc-unlocked-shared-write exists
#    for.
set -euo pipefail

cd "$(dirname "$0")/../.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

echo "== serving gate: serving subset (incl. slow-marked) =="
# No 'not slow' filter: the gate owns the serving tests tier-1 skips
# for time (eos/non-stream framing, MoE fallback).
python -m pytest tests/test_inference.py \
  "tests/test_serving.py::TestInt8KVCache" \
  -q -p no:cacheprovider

echo "== serving gate: gateway metrics schema =="
python - <<'PY'
from prometheus_client import generate_latest
from prometheus_client.parser import text_string_to_metric_families

from kubeflow_tpu import obs
from kubeflow_tpu.obs.metrics import BucketHistogram
from kubeflow_tpu.serving.gateway import GatewayMetrics


class StubEngine:
    """Just the surface GatewayMetrics reads — no model, no jax."""

    swaps_total = 0
    prefix_cache = None

    def __init__(self):
        self.cycle_seconds = {
            "prefill": BucketHistogram(),
            "decode": BucketHistogram(),
        }

    def pending(self):
        return 0


metrics = GatewayMetrics(StubEngine())
text = generate_latest(metrics.registry).decode()
failures = []
families = list(text_string_to_metric_families(text))
names = [f.name for f in families]
for name in sorted({n for n in names if names.count(n) > 1}):
    failures.append(f"duplicate metric family {name!r}")
for family in families:
    for sample in family.samples:
        bad = set(sample.labels) - obs.CANONICAL_LABELS
        if bad:
            failures.append(
                f"{sample.name} uses non-canonical label(s) "
                f"{sorted(bad)}"
            )
expected = {
    "inference_request_duration_seconds",
    "inference_ttft_seconds",
    "inference_tokens",
    "inference_queue_depth",
    "inference_prefix_cache",
    "inference_batch_cycle_seconds",
    "inference_shed",
    "inference_model_swap",
}
missing = expected - set(names)
if missing:
    failures.append(f"metric families missing: {sorted(missing)}")
if failures:
    print("\n".join(failures))
    raise SystemExit(1)
print(f"  gateway registry: {len(families)} families ok")
PY

echo "== serving gate: analysis packs at zero findings =="
python -m kubeflow_tpu.analysis kubeflow_tpu/serving
python - <<'PY'
from kubeflow_tpu.analysis import AnalysisConfig, analyze_paths

findings = analyze_paths(AnalysisConfig(
    paths=["kubeflow_tpu/serving"], check_emitted=False,
))
# No pragma budget, no baseline, not even warnings: serving must be
# spotless under the dataflow packs.
noisy = [f for f in findings if f.rule.startswith(("spmd-", "conc-"))]
if noisy:
    print("\n".join(f.render() for f in noisy))
    raise SystemExit(1)
print("  kubeflow_tpu/serving: clean under spmd/conc packs")
PY

echo "serving gate: OK"
