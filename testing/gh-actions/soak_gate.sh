#!/usr/bin/env bash
# Fleet-scale sharded control-plane gate for CI (PR 13). Four checks:
#
# 1. Sharding/informer tier-1 subset: tests/test_shard.py fast set —
#    per-shard lease quota + rebalance on membership change, the
#    drain-before-release handoff, the revoked-lease step-down, the
#    shard-gated controller (enqueue/pop filters + successor resync),
#    the informer cache (indexes, rv discipline, 410 re-list
#    recovery), workqueue priority lanes, batched status writes, the
#    KFT_SHARDS=1 byte-identity pin, the POST /touch resurrect
#    surface, the informer-backed capacity_fn, and the small soak
#    acceptance arc with byte-identical replay — plus the
#    py-list-in-reconcile rule fixtures in tests/test_analysis.py.
#
# 2. One-shard smoke: KFT_SHARDS unset/1 must resolve to the classic
#    single-leader manager (plain LeaderElector, no gate).
#
# 3. Analysis: the controllers package holds ZERO findings under
#    every pack — including the new py-list-in-reconcile rule — and
#    the full kubeflow_tpu package stays clean.
#
# 4. RUN_SLOW=1: loadtest/soak.py --crs 10000 via the CLI (its exit
#    code gates the acceptance checklist: SLOs green in steady state,
#    zero dual-leader reconciles, zero orphans, chaos matrix + lease
#    revocation survived, byte-identical replay digest) and the
#    SLO/churn JSON artifact is asserted — including the sharded
#    chaos subset counters.
set -euo pipefail

cd "$(dirname "$0")/../.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

echo "== soak gate: sharding/informer tier-1 subset =="
python -m pytest -q -p no:cacheprovider -m 'not slow' \
  tests/test_shard.py \
  "tests/test_analysis.py::TestListInReconcileRule"

echo "== soak gate: one-shard smoke =="
python - <<'PY'
import os

os.environ.pop("KFT_SHARDS", None)
from kubeflow_tpu.controllers.leader import ShardedElector, shard_count
from kubeflow_tpu.controllers.manager import Manager
from kubeflow_tpu.controllers.notebook import make_notebook_controller
from kubeflow_tpu.k8s.fake import FakeApiServer

assert shard_count() == 1, "unset KFT_SHARDS must mean one shard"
api = FakeApiServer()
manager = Manager(api, [make_notebook_controller(api)],
                  leader_elect=True, identity="m1", http_port=None)
assert not isinstance(manager.elector, ShardedElector)
assert manager.shard_gate is None
print("  KFT_SHARDS=1: classic single-leader manager")
PY

echo "== soak gate: zero analysis findings (all packs) =="
python - <<'PY'
from kubeflow_tpu.analysis import AnalysisConfig, analyze_paths

findings = analyze_paths(AnalysisConfig(
    paths=["kubeflow_tpu/controllers"], check_emitted=False,
))
if findings:
    for f in findings:
        print(f.render())
    raise SystemExit(
        f"{len(findings)} finding(s) in kubeflow_tpu/controllers/"
    )
whole = analyze_paths(AnalysisConfig(
    paths=["kubeflow_tpu"], check_emitted=False,
))
if whole:
    for f in whole:
        print(f.render())
    raise SystemExit(
        f"{len(whole)} finding(s) in kubeflow_tpu/ under the full "
        "pack set (incl. py-list-in-reconcile)"
    )
print("  kubeflow_tpu/ (incl. controllers/): zero findings, all packs")
PY

if [[ "${RUN_SLOW:-0}" == "1" ]]; then
  echo "== soak gate: 10k-CR soak (sharded, chaos-gated) =="
  artifact="${SOAK_SUMMARY_JSON:-soak-summary.json}"
  python -m loadtest.soak --crs 10000 --ticks 240 --shards 4 \
    --replicas 2 --dump-dir . | tee "$artifact"
  python - "$artifact" <<'PY'
import json
import sys

with open(sys.argv[1]) as fh:
    doc = json.loads(fh.read().strip().splitlines()[-1])
assert doc["kind"] == "soak", doc
assert doc["created"] >= 10000
assert doc["dual_leader_reconciles"] == 0
assert doc["orphans"]["count"] == 0
assert doc["scheduler_audit"] == {}
assert doc["slo"]["steady_state_green"] is True
assert doc["lease_revocations"] >= 1
chaos = doc["chaos"]
assert chaos["injected"]["conflict"] >= 1
assert chaos["injected"]["blackout"] >= 1
assert chaos["injected"]["watch_compacted"] >= 1
assert doc["replay_digest"]
print(f"  soak artifact ok: {doc['counters']}, "
      f"convergence {chaos['convergence_rounds']} rounds, "
      f"digest {doc['replay_digest'][:12]}…")
PY
fi

echo "soak gate OK"
