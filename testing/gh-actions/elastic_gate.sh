#!/usr/bin/env bash
# Elastic-topology gate for CI: training must survive capacity changes
# by resuming on a different slice shape, not by waiting for the exact
# original topology — and the cost must be measured, not assumed.
#
# The fast subset (tier-1 style) runs:
#   - the seeded capacity-timeline weather + capacity-aware simulator,
#   - the control-plane ladder scenario (v5e-16 → v5e-8 → v5e-16:
#     degrade after grace, StatefulSet re-emitted at the new replica
#     count/chip limits, status.phase=Resharding, promote back up),
#   - the cross-topology restore matrix (mesh→smaller, mesh→bigger,
#     dp/fsdp re-layouts, optimizer-state resharding, refusals),
#   - the data-plane scenario (resume at each shape, ≤ one checkpoint
#     cadence lost per transition, bit-identical parity against an
#     uninterrupted run, goodput ≥ the scenario target).
#
# RUN_SLOW=1 adds the 2-process jax.distributed cross-topology matrix
# (real OS processes save under one layout, restore under another).
#
# The goodput summary lands as a JSON artifact next to the BENCH files
# (override with KFT_ELASTIC_GOODPUT_JSON). Everything is seeded: a
# failure replays exactly. See docs/operations.md
# "Elastic topology & goodput".
set -euo pipefail

cd "$(dirname "$0")/../.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
export KFT_ELASTIC_GOODPUT_JSON="${KFT_ELASTIC_GOODPUT_JSON:-$PWD/GOODPUT_elastic.json}"

# The cross-topology matrix class runs in FULL here regardless of slow
# markers — the gate is its dedicated home; tier-1 keeps only the
# shrink row in-cap.
if [[ "${RUN_SLOW:-0}" == "1" ]]; then
  python -m pytest tests/test_elastic.py \
    "tests/test_checkpoint.py::TestCrossTopologyRestore" \
    "tests/test_checkpoint.py::test_multihost_cross_topology_restore_two_processes" \
    tests/test_topology.py tests/test_parallel.py -q
else
  python -m pytest "tests/test_checkpoint.py::TestCrossTopologyRestore" -q
  python -m pytest tests/test_elastic.py \
    tests/test_topology.py tests/test_parallel.py -q -m 'not slow'
fi

if [[ -f "$KFT_ELASTIC_GOODPUT_JSON" ]]; then
  echo "goodput summary artifact: $KFT_ELASTIC_GOODPUT_JSON"
  cat "$KFT_ELASTIC_GOODPUT_JSON"
else
  echo "ERROR: goodput summary artifact was not produced" >&2
  exit 1
fi
