#!/usr/bin/env bash
# Install KinD and create the integration cluster with fake-TPU worker
# labels (role of the reference testing/gh-actions/install_kind.sh +
# kind-1-25.yaml: real multi-node without a real cloud).
set -euo pipefail

KIND_VERSION="${KIND_VERSION:-v0.23.0}"
CLUSTER_NAME="${CLUSTER_NAME:-kubeflow-tpu}"

if ! command -v kind > /dev/null; then
  curl -Lo ./kind "https://kind.sigs.k8s.io/dl/${KIND_VERSION}/kind-linux-amd64"
  chmod +x ./kind
  sudo mv ./kind /usr/local/bin/kind
fi

kind create cluster --name "${CLUSTER_NAME}" \
  --config "$(dirname "$0")/kind-config.yaml" --wait 120s
kubectl cluster-info
