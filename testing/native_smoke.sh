#!/usr/bin/env bash
# Native-core smoke: drive every kft_invoke entry point through the CLI
# with representative payloads (valid + malformed). Pure native — no
# Python in the loop — so it runs unchanged under sanitizers:
#
#   make -C native CXXFLAGS="-O1 -g -std=c++17 -fPIC -Wall -Wextra \
#     -Werror -fsanitize=address,undefined -fno-sanitize-recover=all"
#   testing/native_smoke.sh
#
# (CI: the sanitize job in native_build.yaml. SURVEY §5 notes the
# reference runs no race detection/sanitizers at all; this tier is the
# TPU build's answer for the C++ core.)
set -euo pipefail

cd "$(dirname "$0")/.."
KFT=./native/build/kft

ok() {  # fn payload — must exit 0
  echo "$2" | $KFT "$1" > /dev/null || {
    echo "FAIL(ok) $1" >&2; exit 1; }
}

err() {  # fn payload — must exit nonzero (clean error, no crash)
  if echo "$2" | $KFT "$1" > /dev/null 2>&1; then
    echo "FAIL(err) $1 unexpectedly succeeded" >&2; exit 1
  fi
}

NB='{"notebook":{"apiVersion":"kubeflow.org/v1beta1","kind":"Notebook","metadata":{"name":"nb","namespace":"ns","uid":"u1"},"spec":{"tpu":{"accelerator":"v5e","topology":"4x4","replicas":4},"template":{"spec":{"containers":[{"name":"nb","image":"img"}]}}}},"options":{}}'
ok notebook_reconcile "$NB"
err notebook_reconcile '{"notebook":{"metadata":{}}}'
err notebook_reconcile '{"notebook":{"apiVersion":"kubeflow.org/v1beta1","kind":"Notebook","metadata":{"name":"nb","namespace":"ns"},"spec":{"tpu":{"accelerator":"bogus","topology":"4x4"}}}}'

ok parse_tpu_slice '{"accelerator":"v5e","topology":"4x4"}'
err parse_tpu_slice '{"accelerator":"v5e","topology":"4x4x9x9"}'

ok cull_decide '{"notebook":{"metadata":{"name":"nb","namespace":"ns","annotations":{}}},"kernels":[{"execution_state":"idle","last_activity":"2026-01-01T00:00:00Z"}],"nowIso":"2026-07-30T00:00:00Z","options":{}}'
err cull_decide '{"kernels":[]}'  # missing notebook

ok poddefault_mutate '{"pod":{"metadata":{"name":"p","namespace":"ns","labels":{"tpu-env":"true"}},"spec":{"containers":[{"name":"c","image":"i"}]}},"poddefaults":[{"metadata":{"name":"pd","namespace":"ns"},"spec":{"selector":{"matchLabels":{"tpu-env":"true"}},"env":[{"name":"X","value":"1"}]}}]}'
ok poddefault_mutate '{"pod":{"metadata":{"name":"p"},"spec":{"containers":[]}},"poddefaults":[]}'

ok profile_reconcile '{"profile":{"apiVersion":"kubeflow.org/v1","kind":"Profile","metadata":{"name":"team","uid":"u2"},"spec":{"owner":{"kind":"User","name":"a@x.io"}}},"options":{}}'
err profile_reconcile '{"profile":{"metadata":{}}}'

ok kfam_binding '{"user":"bob@x.io","namespace":"team","role":"edit","userIdHeader":"kubeflow-userid","userIdPrefix":""}'
err kfam_binding '{"user":"","namespace":"team"}'

ok tensorboard_reconcile '{"tensorboard":{"apiVersion":"tensorboard.kubeflow.org/v1alpha1","kind":"Tensorboard","metadata":{"name":"tb","namespace":"ns","uid":"u3"},"spec":{"logspath":"pvc://logs/tb"}},"options":{}}'
err tensorboard_reconcile '{"tensorboard":{"metadata":{"name":"tb","namespace":"ns"},"spec":{}}}'

ok pvcviewer_reconcile '{"viewer":{"apiVersion":"kubeflow.org/v1alpha1","kind":"PVCViewer","metadata":{"name":"v","namespace":"ns","uid":"u4"},"spec":{"pvc":"data"}},"options":{}}'
ok pvcviewer_admit '{"viewer":{"metadata":{"name":"v","namespace":"ns"},"spec":{"pvc":"data"}}}'
ok pvcviewer_admit '{"viewer":{"metadata":{"generateName":"v-"},"spec":{"pvc":"data"}},"requestNamespace":"ns"}'
# Admission rejections are expressed as result.errors (ok envelope):
admit_rejects() {
  out=$(echo "$1" | $KFT pvcviewer_admit)
  echo "$out" | grep -q '"errors":\["' || {
    echo "FAIL pvcviewer_admit accepted: $1" >&2; exit 1; }
}
admit_rejects '{"viewer":{"metadata":{"name":"v","namespace":"ns"},"spec":{}}}'
admit_rejects '{"viewer":{"metadata":{"name":"v","namespace":"ns"},"spec":{"pvc":"d","networking":{"targetPort":"str"}}}}'
admit_rejects '{"viewer":"not-an-object"}'

ok copy_owned_fields '{"kind":"StatefulSet","existing":{"apiVersion":"apps/v1","kind":"StatefulSet","metadata":{"name":"s","namespace":"ns"},"spec":{"replicas":1}},"desired":{"apiVersion":"apps/v1","kind":"StatefulSet","metadata":{"name":"s","namespace":"ns"},"spec":{"replicas":4}}}'

ok notebook_gang_restart '{"notebook":{"metadata":{"name":"nb","namespace":"ns","annotations":{}}},"pods":[{"metadata":{"name":"nb-0"},"status":{"containerStatuses":[{"restartCount":0}]}}]}'

# Malformed envelopes must error cleanly, never crash.
err notebook_reconcile 'not json at all'
err notebook_reconcile '{"unterminated": "'
err no_such_function '{}'

echo "native smoke: all entry points OK"
