"""Named device meshes and canonical shardings.

The platform spawns multi-host notebooks onto one TPU pod slice; inside the
notebook, user code builds a mesh over all chips of the slice. Axis names
are fixed platform-wide so models, optimizers, and checkpoints agree:

- ``"dp"``   — data parallel (batch dimension; gradients all-reduced)
- ``"fsdp"`` — fully-sharded data parallel (params/opt-state sharded,
               all-gathered just-in-time; rides ICI)
- ``"tp"``   — tensor parallel (hidden/heads dimension)
- ``"sp"``   — sequence/context parallel (ring attention over ICI)

A v5e-16 slice (4 hosts x 4 chips) with ``MeshSpec(dp=2, fsdp=4, tp=2)``
yields a 16-device mesh; XLA lays collectives onto the ICI torus.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("dp", "fsdp", "tp", "sp")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Logical parallelism layout mapped onto the slice's chips.

    Any axis left at 1 is inert (its collectives compile away). ``dp=-1``
    means "absorb all remaining devices into data parallelism".
    """

    dp: int = -1
    fsdp: int = 1
    tp: int = 1
    sp: int = 1

    def resolve(self, n_devices: int) -> "MeshSpec":
        fixed = self.fsdp * self.tp * self.sp
        dp = self.dp
        if dp == -1:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fsdp*tp*sp={fixed}"
                )
            dp = n_devices // fixed
        if dp * fixed != n_devices:
            raise ValueError(
                f"mesh {dp}x{self.fsdp}x{self.tp}x{self.sp} != {n_devices} devices"
            )
        return MeshSpec(dp=dp, fsdp=self.fsdp, tp=self.tp, sp=self.sp)

    @property
    def shape(self) -> tuple[int, ...]:
        return (self.dp, self.fsdp, self.tp, self.sp)


def make_mesh(
    spec: MeshSpec | None = None, devices: Sequence[jax.Device] | None = None
) -> Mesh:
    """Build a named mesh over ``devices`` (default: all addressable chips).

    Device order follows ``jax.devices()``, which JAX already orders so
    that adjacent ids are ICI neighbours on TPU; the innermost mesh axes
    therefore get the tightest interconnect (tp/sp innermost).
    """
    devices = list(devices if devices is not None else jax.devices())
    spec = (spec or MeshSpec()).resolve(len(devices))
    arr = np.asarray(devices).reshape(spec.shape)
    return Mesh(arr, AXES)


def auto_mesh(n_devices: int | None = None) -> Mesh:
    """Pure data-parallel mesh over all (or the first n) devices."""
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return make_mesh(MeshSpec(dp=len(devices)), devices)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) dim over dp+fsdp; replicate the rest."""
    return NamedSharding(mesh, P(("dp", "fsdp")))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def param_sharding(mesh: Mesh, path: tuple, leaf: jax.ShapeDtypeStruct):
    """Canonical parameter sharding: shard the largest dim that divides
    evenly over ``fsdp`` (zero-redundancy style); replicate small leaves.

    Works for any pytree path; models with explicit tp layouts override
    this per-module instead.
    """
    fsdp = mesh.shape["fsdp"]
    if fsdp == 1 or not leaf.shape or math.prod(leaf.shape) < 2**14:
        return replicated(mesh)
    dims = sorted(range(len(leaf.shape)), key=lambda d: -leaf.shape[d])
    for d in dims:
        if leaf.shape[d] % fsdp == 0:
            spec = [None] * len(leaf.shape)
            spec[d] = "fsdp"
            return NamedSharding(mesh, P(*spec))
    return replicated(mesh)
