"""Named device meshes and canonical shardings.

The platform spawns multi-host notebooks onto one TPU pod slice; inside the
notebook, user code builds a mesh over all chips of the slice. Axis names
are fixed platform-wide so models, optimizers, and checkpoints agree:

- ``"dp"``   — data parallel (batch dimension; gradients all-reduced)
- ``"pp"``   — pipeline parallel (layer stages; point-to-point ppermute
               circulation — tolerates the slowest links, so it sits
               next to dp on the outer/coarser interconnect)
- ``"fsdp"`` — fully-sharded data parallel (params/opt-state sharded,
               all-gathered just-in-time; rides ICI)
- ``"tp"``   — tensor parallel (hidden/heads dimension)
- ``"sp"``   — sequence/context parallel (ring attention over ICI)
- ``"ep"``   — expert parallel (MoE experts sharded; token dispatch
               rides ICI all-to-alls)

A v5e-16 slice (4 hosts x 4 chips) with ``MeshSpec(dp=2, fsdp=4, tp=2)``
yields a 16-device mesh; XLA lays collectives onto the ICI torus.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("dp", "pp", "fsdp", "tp", "sp", "ep")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Logical parallelism layout mapped onto the slice's chips.

    Any axis left at 1 is inert (its collectives compile away). ``dp=-1``
    means "absorb all remaining devices into data parallelism".
    """

    dp: int = -1
    pp: int = 1
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    ep: int = 1

    def resolve(self, n_devices: int) -> "MeshSpec":
        fixed = self.pp * self.fsdp * self.tp * self.sp * self.ep
        dp = self.dp
        if dp == -1:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by "
                    f"pp*fsdp*tp*sp*ep={fixed}"
                )
            dp = n_devices // fixed
        if dp * fixed != n_devices:
            raise ValueError(
                f"mesh {dp}x{self.pp}x{self.fsdp}x{self.tp}x{self.sp}"
                f"x{self.ep} != {n_devices} devices"
            )
        return MeshSpec(dp=dp, pp=self.pp, fsdp=self.fsdp, tp=self.tp,
                        sp=self.sp, ep=self.ep)

    @property
    def shape(self) -> tuple[int, ...]:
        return (self.dp, self.pp, self.fsdp, self.tp, self.sp, self.ep)

    @property
    def n_devices(self) -> int:
        return math.prod(self.shape)

    def refactor(self, n_devices: int) -> "MeshSpec":
        """Deterministically re-factor a *resolved* spec onto a
        different device count, preserving axis semantics — the data
        plane's half of elastic topology (a preemption leaves a
        smaller slice, or the queue frees a bigger one).

        Shrinking divides axes in the order **dp, then fsdp, then tp**:
        dp absorbs as much of the reduction as it can (re-dividing the
        batch is semantically free), fsdp next (params re-shard but the
        math is unchanged), tp last (kept widest the longest — tp width
        interacts with kernel layouts). Growing multiplies **dp only**:
        new capacity becomes data parallelism, so fsdp/tp shardings —
        and therefore every checkpoint leaf's layout rules — survive
        the transition. ``pp``/``sp``/``ep`` never change: pipeline
        stages, sequence splits and expert counts are model structure,
        not capacity, and silently re-factoring them would change the
        model's numerics contract.

        Raises ``ValueError`` when the spec is unresolved (``dp=-1``),
        when ``n_devices`` is not an integer multiple/divisor of the
        current size, or when a shrink cannot be absorbed by dp·fsdp·tp
        — the caller must refuse the shape, not run a broken mesh.
        """
        if self.dp == -1:
            raise ValueError("refactor() needs a resolved spec; call "
                             "resolve(n_devices) first")
        if n_devices < 1:
            raise ValueError(f"cannot refactor to {n_devices} devices")
        old = self.n_devices
        if n_devices == old:
            return self
        if n_devices > old:
            if n_devices % old:
                raise ValueError(
                    f"cannot grow {old} -> {n_devices} devices: not an "
                    "integer multiple"
                )
            return dataclasses.replace(self, dp=self.dp * (n_devices // old))
        if old % n_devices:
            raise ValueError(
                f"cannot shrink {old} -> {n_devices} devices: not an "
                "integer divisor"
            )
        factor = old // n_devices
        axes = {"dp": self.dp, "fsdp": self.fsdp, "tp": self.tp}
        for name in ("dp", "fsdp", "tp"):
            g = math.gcd(axes[name], factor)
            axes[name] //= g
            factor //= g
            if factor == 1:
                break
        if factor != 1:
            raise ValueError(
                f"cannot shrink {self} to {n_devices} devices: "
                f"dp*fsdp*tp cannot absorb a /{old // n_devices} "
                "(pp/sp/ep are fixed model structure)"
            )
        return dataclasses.replace(self, **axes)


def make_mesh(
    spec: MeshSpec | None = None, devices: Sequence[jax.Device] | None = None
) -> Mesh:
    """Build a named mesh over ``devices`` (default: all addressable chips).

    Device order follows ``jax.devices()``, which JAX already orders so
    that adjacent ids are ICI neighbours on TPU; the innermost mesh axes
    therefore get the tightest interconnect (tp/sp innermost).
    """
    devices = list(devices if devices is not None else jax.devices())
    spec = (spec or MeshSpec()).resolve(len(devices))
    arr = np.asarray(devices).reshape(spec.shape)
    return Mesh(arr, AXES)


def make_multislice_mesh(
    spec: MeshSpec | None = None,
    num_slices: int = 1,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Mesh spanning multiple TPU slices connected over DCN.

    The scaling-book recipe: only data parallelism crosses the DCN
    boundary (gradient all-reduce decomposes into a fast intra-slice
    ICI phase and one inter-slice DCN phase); fsdp/tp/sp stay within a
    slice on ICI. The dp axis is laid out slice-major so XLA can make
    that split — dp must be divisible by ``num_slices``.

    Devices are grouped by ``slice_index`` when the runtime exposes it
    (real multislice via megascale); otherwise contiguous equal chunks
    stand in (CPU test meshes).
    """
    devices = list(devices if devices is not None else jax.devices())
    if num_slices <= 1:
        return make_mesh(spec, devices)
    if len(devices) % num_slices:
        raise ValueError(
            f"{len(devices)} devices not divisible by {num_slices} slices"
        )
    spec = (spec or MeshSpec()).resolve(len(devices))
    if spec.dp % num_slices:
        raise ValueError(
            f"dp={spec.dp} must be divisible by num_slices={num_slices}: "
            "only data parallelism may cross the DCN boundary"
        )
    try:
        devices.sort(key=lambda d: (d.slice_index, d.id))
        groups: dict[int, int] = {}
        for dev in devices:
            groups[dev.slice_index] = groups.get(dev.slice_index, 0) + 1
        per_slice = len(devices) // num_slices
        if len(groups) != num_slices or set(groups.values()) != {per_slice}:
            # An uneven grouping (e.g. a subset truncated mid-slice)
            # would silently put fsdp/tp/sp collectives on DCN — the
            # exact thing this layout exists to prevent.
            raise ValueError(
                f"devices span slices {dict(sorted(groups.items()))}, need "
                f"exactly {num_slices} slices x {per_slice} devices"
            )
    except AttributeError:
        pass  # no slice topology info: keep given order, chunk evenly
    # After the slice-major sort, dp (the outermost mesh axis) enumerates
    # whole slices first, so the plain row-major reshape is the layout.
    return make_mesh(spec, devices)


def auto_mesh(n_devices: int | None = None) -> Mesh:
    """Pure data-parallel mesh over all (or the first n) devices."""
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return make_mesh(MeshSpec(dp=len(devices)), devices)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) dim over dp+fsdp; replicate the rest."""
    return NamedSharding(mesh, P(("dp", "fsdp")))


def token_sharding(mesh: Mesh) -> NamedSharding:
    """Canonical LM token layout: batch over (dp, fsdp), sequence over
    sp. The single source of truth for every LM train step (standard
    and pipelined)."""
    return NamedSharding(mesh, P(("dp", "fsdp"), "sp"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def path_key(entry) -> str:
    """String key of one tree-path entry (DictKey / GetAttrKey / …)."""
    key = getattr(entry, "key", None)
    if key is None:
        key = getattr(entry, "name", None)
    return "" if key is None else str(key)


def _tp_kernel_dim(path: tuple, tp_rules: dict | None) -> int | None:
    """Which dim of a 2D Dense kernel shards over tp, per the MODEL's
    explicit rules ({module name -> dim}). Models opt in by passing
    rules (e.g. the LM's Megatron layout, transformer.py LM_TP_RULES);
    generic models never get tp sharding by accident."""
    if not tp_rules or len(path) < 2 or path_key(path[-1]) != "kernel":
        return None
    return tp_rules.get(path_key(path[-2]))


def _is_expert_stack(path: tuple) -> bool:
    """True for MoE expert weight stacks. The contract with the model
    layer (models/transformer.py MoEFFN) is the parameter NAME: leaves
    whose final path key starts with ``experts_`` carry experts on dim 0.
    Deliberately exact-prefix on the last key only — a module merely
    named *experts* elsewhere must not trip ep sharding."""
    return bool(path) and path_key(path[-1]).startswith("experts_")


def param_sharding(
    mesh: Mesh,
    path: tuple,
    leaf: jax.ShapeDtypeStruct,
    tp_rules: dict | None = None,
    stage_axis: str | None = None,
):
    """Canonical parameter sharding: shard the largest dim that divides
    evenly over ``fsdp`` (zero-redundancy style); replicate small leaves.

    Works for any pytree path. Tensor parallelism is strictly opt-in:
    a model passes ``tp_rules`` ({module name -> kernel dim}) to place
    its projection kernels on the tp axis (the LM's Megatron layout);
    without rules the tp axis replicates params.

    ``stage_axis`` marks a depth-stacked leaf (pipeline stages): dim 0
    goes on that axis, tp_rules apply at the stack-shifted kernel dim,
    and fsdp takes the largest remaining dim — the single source of
    truth for pipelined layouts too (models/pipeline_lm.py).
    """
    if stage_axis is not None and getattr(leaf, "shape", ()):
        spec: list = [None] * len(leaf.shape)
        if leaf.shape[0] % mesh.shape[stage_axis] == 0:
            spec[0] = stage_axis
        tp = mesh.shape.get("tp", 1)
        if tp > 1:
            tp_dim = _tp_kernel_dim(path, tp_rules)
            # +1: the stage stack prepends the depth dim to the kernel.
            if tp_dim is not None and leaf.shape[tp_dim + 1] % tp == 0:
                spec[tp_dim + 1] = "tp"
        fsdp_n = mesh.shape["fsdp"]
        if fsdp_n > 1:
            for d in sorted(
                range(1, len(leaf.shape)), key=lambda d: -leaf.shape[d]
            ):
                if spec[d] is None and leaf.shape[d] % fsdp_n == 0:
                    spec[d] = "fsdp"
                    break
        return NamedSharding(mesh, P(*spec))
    # MoE expert stacks shard their leading (expert) dim over ep — the
    # dispatch einsums then lower to all-to-alls over that axis. The
    # remaining dims still get fsdp (expert weights are the largest
    # params in an MoE; replicating them across fsdp would waste exactly
    # the HBM zero-redundancy exists to save).
    ep = mesh.shape.get("ep", 1)
    if ep > 1 and _is_expert_stack(path) and leaf.shape:
        if leaf.shape[0] % ep == 0:
            spec = [None] * len(leaf.shape)
            spec[0] = "ep"
            fsdp_n = mesh.shape["fsdp"]
            if fsdp_n > 1:
                for d in sorted(
                    range(1, len(leaf.shape)), key=lambda d: -leaf.shape[d]
                ):
                    if leaf.shape[d] % fsdp_n == 0:
                        spec[d] = "fsdp"
                        break
            return NamedSharding(mesh, P(*spec))

    # Megatron-style tp for the model's declared projection kernels;
    # fsdp takes the other dim when it divides.
    tp = mesh.shape.get("tp", 1)
    if tp > 1 and len(leaf.shape) == 2:
        tp_dim = _tp_kernel_dim(path, tp_rules)
        if tp_dim is not None and leaf.shape[tp_dim] % tp == 0:
            spec = [None, None]
            spec[tp_dim] = "tp"
            other = 1 - tp_dim
            if mesh.shape["fsdp"] > 1 and (
                leaf.shape[other] % mesh.shape["fsdp"] == 0
            ):
                spec[other] = "fsdp"
            return NamedSharding(mesh, P(*spec))

    fsdp = mesh.shape["fsdp"]
    if fsdp == 1 or not leaf.shape or math.prod(leaf.shape) < 2**14:
        return replicated(mesh)
    dims = sorted(range(len(leaf.shape)), key=lambda d: -leaf.shape[d])
    for d in dims:
        if leaf.shape[d] % fsdp == 0:
            spec = [None] * len(leaf.shape)
            spec[d] = "fsdp"
            return NamedSharding(mesh, P(*spec))
    return replicated(mesh)
