"""Device-mesh parallelism for TPU slices.

Scaling is expressed the TPU-native way: a named :class:`jax.sharding.Mesh`
over the slice's chips, sharding annotations on arrays, and XLA collectives
over ICI/DCN inserted by the compiler — never hand-written NCCL/MPI calls
(the reference platform has no collective layer at all; see SURVEY.md §2.3).
"""

from kubeflow_tpu.parallel.mesh import (
    MeshSpec,
    make_mesh,
    make_multislice_mesh,
    auto_mesh,
    batch_sharding,
    token_sharding,
    replicated,
    param_sharding,
)
from kubeflow_tpu.parallel.distributed import (
    DistributedEnv,
    initialize_from_env,
    slice_env_for_rank,
)
from kubeflow_tpu.parallel.pipeline import (
    gpipe,
    interleaved_gpipe,
    interleaved_one_f_one_b,
    one_f_one_b,
    pipeline_ticks,
    stage_stack,
    stage_stack_interleaved,
)

__all__ = [
    "MeshSpec",
    "make_mesh",
    "make_multislice_mesh",
    "auto_mesh",
    "batch_sharding",
    "token_sharding",
    "replicated",
    "param_sharding",
    "gpipe",
    "interleaved_gpipe",
    "interleaved_one_f_one_b",
    "one_f_one_b",
    "pipeline_ticks",
    "stage_stack",
    "stage_stack_interleaved",
    "DistributedEnv",
    "initialize_from_env",
    "slice_env_for_rank",
]
