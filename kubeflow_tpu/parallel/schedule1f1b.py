"""Static schedule construction for INTERLEAVED 1F1B.

The plain 1F1B tables (pipeline.py `_1f1b_tables`) come from closed
formulas; with virtual stages the slot structure is irregular enough
(per-device warmup depths, chunk cycling, wrap-edge transfers) that a
closed form is easy to get subtly wrong. So the schedule here is
CONSTRUCTED by an event-driven simulator following the Megatron
discipline — per-device warmup of ``2*(P-d-1) + (V-1)*P`` forwards,
then strict 1B1F alternation with idling when the due unit's inputs
have not arrived — and then VALIDATED by an independent checker
(`check_schedule`) that re-derives every dataflow constraint from
scratch. Buffer slots for activations and cotangents are assigned by
static interval-graph colouring, so the executor performs no modular
keying at runtime: every slot of every device knows statically which
buffer entry to read or write.

Unit vocabulary: global stage s = v*P + d (chunk v lives on device
s mod P), unit (s, m) = one forward or backward of microbatch m
through stage s. Dataflow:

- F(s, m) consumes the activation produced by F(s-1, m) (ring hop
  d-1 -> d, with the wrap edge P-1 -> 0 carrying chunk boundaries);
  s = 0 reads the microbatch input directly.
- B(s, m) consumes the stored input of (s, m) (for the vjp recompute)
  and the cotangent produced by B(s+1, m) (reverse ring hop with the
  wrap edge 0 -> P-1); s = C-1 seeds from the loss cotangent.

No reference counterpart (the reference platform ships no parallelism
code; SURVEY.md §2.3).
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class Interleaved1F1B:
    """Static tables, all shaped (T, P) unless noted; -1 = not
    applicable at that slot. Buffer slots are per-device colourings
    (two devices may use the same slot id independently)."""

    num_slots: int
    num_stages: int           # P
    virtual_stages: int       # V
    num_microbatches: int     # M
    xbuf_slots: int           # Kx: activation buffer depth
    cbuf_slots: int           # Kc: cotangent buffer depth
    action: np.ndarray        # 0 idle / 1 forward / 2 backward
    unit_v: np.ndarray        # chunk index of the unit
    unit_m: np.ndarray        # microbatch index of the unit
    f_in: np.ndarray          # xbuf slot feeding an F unit (-1: xm[m])
    b_in: np.ndarray          # xbuf slot feeding a B unit (-1: xm[m])
    b_cot: np.ndarray         # cbuf slot feeding a B unit (-1: seed)
    act_store: np.ndarray     # xbuf slot for THIS slot's arriving act
    cot_store: np.ndarray     # cbuf slot for THIS slot's arriving cot


def build_schedule(num_microbatches: int, num_stages: int,
                   virtual_stages: int) -> Interleaved1F1B:
    """Simulate the Megatron interleaved-1F1B discipline into static
    tables. Requires M % P == 0 (microbatch groups tile the chunk
    cycle)."""
    M, P, V = num_microbatches, num_stages, virtual_stages
    if M % P:
        raise ValueError(f"num_microbatches={M} must divide by pp={P}")
    C = V * P

    def stage(v: int, d: int) -> int:
        return v * P + d

    # Per-device unit orders (Megatron): forwards sweep chunks within
    # each P-microbatch group; backwards sweep chunks in reverse.
    def forward_order(d):
        # The LAST global stage's re-forward is dropped: in the
        # custom_vjp backward, F units exist solely to produce the next
        # stage's input, and stage C-1 feeds nothing (the primal
        # already computed the real forward). Keeping it would read its
        # input slot after B(C-1, m) freed it.
        return [
            (v, g * P + j)
            for g in range(M // P)
            for v in range(V)
            for j in range(P)
            if stage(v, d) != C - 1
        ]

    def backward_order(d):
        return [
            (V - 1 - v, g * P + j)
            for g in range(M // P)
            for v in range(V)
            for j in range(P)
        ]

    f_units = {d: forward_order(d) for d in range(P)}
    b_units = {d: backward_order(d) for d in range(P)}
    warmup = {
        d: min(len(f_units[d]), 2 * (P - d - 1) + (V - 1) * P)
        for d in range(P)
    }

    f_done: dict[tuple[int, int], int] = {}   # (s, m) -> slot
    b_done: dict[tuple[int, int], int] = {}
    fi = {d: 0 for d in range(P)}
    bi = {d: 0 for d in range(P)}
    # After warmup the device alternates, starting with a backward.
    prefer_b = {d: True for d in range(P)}
    schedule: list[list[tuple[str, int, int] | None]] = []

    def f_runnable(d, t):
        if fi[d] >= len(f_units[d]):
            return False
        v, m = f_units[d][fi[d]]
        s = stage(v, d)
        return s == 0 or f_done.get((s - 1, m), t) < t

    def b_runnable(d, t):
        if bi[d] >= len(b_units[d]):
            return False
        v, m = b_units[d][bi[d]]
        s = stage(v, d)
        # Needs the vjp input (arrived via F(s-1, m)) and the incoming
        # cotangent (B(s+1, m)); the last stage seeds from the loss.
        if s > 0 and not f_done.get((s - 1, m), t) < t:
            return False
        if s < C - 1 and not b_done.get((s + 1, m), t) < t:
            return False
        return True

    # F + B units across ALL devices, minus the dropped last-stage
    # re-forwards (M of them).
    total_units = 2 * M * V * P - M
    scheduled = 0
    t = 0
    max_slots = 16 * (total_units + 2 * P)  # hard runaway stop
    while scheduled < total_units:
        if t > max_slots:
            raise RuntimeError(
                f"schedule did not converge (M={M}, P={P}, V={V})"
            )
        row: list[tuple[str, int, int] | None] = [None] * P
        # Decide all devices against the PRE-SLOT state so arrivals
        # within the same slot cannot be consumed early.
        for d in range(P):
            in_warmup = fi[d] < warmup[d]
            if in_warmup:
                choice = "F" if f_runnable(d, t) else None
            else:
                order = ("B", "F") if prefer_b[d] else ("F", "B")
                choice = None
                for kind in order:
                    if kind == "F" and f_runnable(d, t):
                        choice = "F"
                        break
                    if kind == "B" and b_runnable(d, t):
                        choice = "B"
                        break
            if choice == "F":
                v, m = f_units[d][fi[d]]
                row[d] = ("F", v, m)
            elif choice == "B":
                v, m = b_units[d][bi[d]]
                row[d] = ("B", v, m)
        for d in range(P):
            unit = row[d]
            if unit is None:
                continue
            kind, v, m = unit
            s = stage(v, d)
            if kind == "F":
                f_done[(s, m)] = t
                fi[d] += 1
                if fi[d] > warmup[d]:
                    prefer_b[d] = True
            else:
                b_done[(s, m)] = t
                bi[d] += 1
                prefer_b[d] = False  # alternate: next prefers F
            scheduled += 1
        schedule.append(row)
        t += 1
    T = len(schedule)

    # ---- static buffer assignment (interval-graph colouring) --------
    # Activation intervals per device: unit (s, m) with s > 0 stores
    # its input at F(s-1, m) + 1 and frees it after B(s, m).
    def colour(intervals):
        """intervals: {unit: (start, end)} -> ({unit: slot}, depth)."""
        events = sorted(
            intervals.items(), key=lambda kv: (kv[1][0], kv[1][1])
        )
        free: list[int] = []
        live: list[tuple[int, int]] = []  # (end, slot)
        assign = {}
        depth = 0
        for unit, (start, end) in events:
            live = [(e, sl) for (e, sl) in live if e >= start or (
                free.append(sl) or False)]
            if free:
                slot = free.pop()
            else:
                slot = depth
                depth += 1
            assign[unit] = slot
            live.append((end, slot))
        return assign, depth

    x_assign: dict[int, dict[tuple[int, int], int]] = {}
    c_assign: dict[int, dict[tuple[int, int], int]] = {}
    kx = kc = 0
    for d in range(P):
        xin = {}
        cin = {}
        for v in range(V):
            s = stage(v, d)
            for m in range(M):
                if s > 0:
                    xin[(s, m)] = (f_done[(s - 1, m)] + 1,
                                   b_done[(s, m)])
                if s < C - 1:
                    cin[(s, m)] = (b_done[(s + 1, m)] + 1,
                                   b_done[(s, m)])
        xa, kxd = colour(xin)
        ca, kcd = colour(cin)
        x_assign[d] = xa
        c_assign[d] = ca
        kx = max(kx, kxd)
        kc = max(kc, kcd)

    # ---- tables -----------------------------------------------------
    shape = (T, P)
    action = np.zeros(shape, np.int32)
    unit_v = np.full(shape, -1, np.int32)
    unit_m = np.full(shape, -1, np.int32)
    f_in = np.full(shape, -1, np.int32)
    b_in = np.full(shape, -1, np.int32)
    b_cot = np.full(shape, -1, np.int32)
    act_store = np.full(shape, -1, np.int32)
    cot_store = np.full(shape, -1, np.int32)

    for t_i, row in enumerate(schedule):
        for d, unit in enumerate(row):
            if unit is None:
                continue
            kind, v, m = unit
            s = stage(v, d)
            unit_v[t_i, d] = v
            unit_m[t_i, d] = m
            if kind == "F":
                action[t_i, d] = 1
                if s > 0:
                    f_in[t_i, d] = x_assign[d][(s, m)]
            else:
                action[t_i, d] = 2
                if s > 0:
                    b_in[t_i, d] = x_assign[d][(s, m)]
                if s < C - 1:
                    b_cot[t_i, d] = c_assign[d][(s, m)]

    # Arrivals: the producer ran at t-1 on the ring neighbour.
    for (s, m), t_f in f_done.items():
        if s + 1 >= C:
            continue  # last stage's output has no consumer
        d_to = (s + 1) % P
        act_store[t_f + 1, d_to] = x_assign[d_to][(s + 1, m)]
    for (s, m), t_b in b_done.items():
        if s == 0:
            continue  # dx of stage 0 feeds dxm, not the ring
        d_to = (s - 1) % P
        cot_store[t_b + 1, d_to] = c_assign[d_to][(s - 1, m)]

    return Interleaved1F1B(
        num_slots=T, num_stages=P, virtual_stages=V,
        num_microbatches=M, xbuf_slots=max(kx, 1),
        cbuf_slots=max(kc, 1),
        action=action, unit_v=unit_v, unit_m=unit_m,
        f_in=f_in, b_in=b_in, b_cot=b_cot,
        act_store=act_store, cot_store=cot_store,
    )


def check_schedule(sched: Interleaved1F1B) -> None:
    """Independent validity check: re-derives every constraint from
    the tables alone (does NOT reuse the simulator state). Raises
    AssertionError on any violation."""
    P, V, M = (sched.num_stages, sched.virtual_stages,
               sched.num_microbatches)
    C = V * P
    f_at: dict[tuple[int, int], int] = {}
    b_at: dict[tuple[int, int], int] = {}
    for t in range(sched.num_slots):
        for d in range(P):
            a = sched.action[t, d]
            if a == 0:
                continue
            v, m = int(sched.unit_v[t, d]), int(sched.unit_m[t, d])
            assert 0 <= v < V and 0 <= m < M
            s = v * P + d
            key = (s, m)
            if a == 1:
                assert key not in f_at, f"F{key} scheduled twice"
                f_at[key] = t
            else:
                assert key not in b_at, f"B{key} scheduled twice"
                b_at[key] = t
    # The last stage's re-forward is deliberately dropped (see
    # build_schedule.forward_order).
    assert len(f_at) == (C - 1) * M, "missing forwards"
    assert len(b_at) == C * M, "missing backwards"
    assert not any(s == C - 1 for (s, _m) in f_at), "waste F scheduled"
    for (s, m), t in f_at.items():
        if s > 0:
            assert f_at[(s - 1, m)] < t, f"F({s},{m}) before its input"
    for (s, m), t in b_at.items():
        # (No constraint against the unit's OWN forward: the backward
        # recomputes via vjp from the stored INPUT, so only the input
        # arrival and the incoming cotangent gate it.)
        if s < C - 1:
            assert b_at[(s + 1, m)] < t, f"B({s},{m}) before its seed"
        if s > 0:
            assert f_at[(s - 1, m)] < t, f"B({s},{m}) before its input"

    # Buffer discipline: replay the static slots and assert no live
    # entry is overwritten and every read was written. Store ownership
    # is resolved through reverse maps built once — (arrival slot,
    # dest device) -> unit — instead of scanning f_at/b_at per store,
    # which is O(units^2) overall and real time at production scale
    # (M=512 x P=16 x V=4 is ~65k units).
    xowner: dict[tuple[int, int], tuple[int, int]] = {}
    for (s, m), tf in f_at.items():
        if s + 1 < C:
            key = (tf + 1, (s + 1) % P)
            assert key not in xowner, f"two acts arrive at {key}"
            xowner[key] = (s + 1, m)
    cowner: dict[tuple[int, int], tuple[int, int]] = {}
    for (s, m), tb in b_at.items():
        if s > 0:
            key = (tb + 1, (s - 1) % P)
            assert key not in cowner, f"two cots arrive at {key}"
            cowner[key] = (s - 1, m)
    for d in range(P):
        xlive: dict[int, tuple[int, int]] = {}
        clive: dict[int, tuple[int, int]] = {}
        for t in range(sched.num_slots):
            xs = int(sched.act_store[t, d])
            if xs >= 0:
                assert xs < sched.xbuf_slots
                # Overwriting is only legal if the previous occupant
                # is dead (its B already ran strictly before t).
                if xs in xlive:
                    prev = xlive[xs]
                    assert b_at[prev] < t, (
                        f"xbuf[{xs}]@dev{d} overwritten live: {prev}"
                    )
                # Which unit does this arrival belong to?
                owner = xowner.get((t, d))
                assert owner is not None, f"orphan act store t={t} d={d}"
                xlive[xs] = owner
            cs = int(sched.cot_store[t, d])
            if cs >= 0:
                assert cs < sched.cbuf_slots
                if cs in clive:
                    prev = clive[cs]
                    assert b_at[prev] < t, (
                        f"cbuf[{cs}]@dev{d} overwritten live: {prev}"
                    )
                owner = cowner.get((t, d))
                assert owner is not None, f"orphan cot store t={t} d={d}"
                clive[cs] = owner
            a = sched.action[t, d]
            if a == 0:
                continue
            v, m = int(sched.unit_v[t, d]), int(sched.unit_m[t, d])
            s = v * P + d
            if a == 1 and s > 0:
                slot = int(sched.f_in[t, d])
                assert xlive.get(slot) == (s, m), (
                    f"F({s},{m}) reads wrong xbuf entry"
                )
            if a == 2:
                if s > 0:
                    slot = int(sched.b_in[t, d])
                    assert xlive.get(slot) == (s, m), (
                        f"B({s},{m}) reads wrong xbuf entry"
                    )
                if s < C - 1:
                    slot = int(sched.b_cot[t, d])
                    assert clive.get(slot) == (s, m), (
                        f"B({s},{m}) reads wrong cbuf entry"
                    )
