"""jax.distributed wiring from platform-injected environment.

This is the meeting point of the control plane and the compute stack. The
platform side (notebook controller + PodDefault webhook) injects these env
vars into every replica of a multi-host notebook StatefulSet:

- ``TPU_WORKER_ID``        — pod ordinal (rank), 0..N-1
- ``TPU_WORKER_HOSTNAMES`` — comma-separated stable DNS names of all
                             replicas (headless Service)
- ``KFT_COORDINATOR_ADDRESS`` — ``<name>-0.<svc>.<ns>.svc:8476`` (rank 0)
- ``KFT_NUM_PROCESSES``    — replica count (hosts in the slice)

The reference platform had no distributed backend at all (SURVEY.md §2.3,
reference notebook-controller hardcodes replicas=1 at
``controllers/notebook_controller.go:362-365``); here multi-host is
first-class: user code in the image calls :func:`initialize_from_env` once
and then sees every chip of the slice via ``jax.devices()``.
"""

from __future__ import annotations

import dataclasses
import logging
import os

log = logging.getLogger(__name__)

COORDINATOR_PORT = 8476

ENV_WORKER_ID = "TPU_WORKER_ID"
ENV_WORKER_HOSTNAMES = "TPU_WORKER_HOSTNAMES"
ENV_COORDINATOR = "KFT_COORDINATOR_ADDRESS"
ENV_NUM_PROCESSES = "KFT_NUM_PROCESSES"
# JAX-native name the image's 10-tpu-env script derives for pods booted
# WITHOUT the webhook (ordinal path); from_env falls back to it.
ENV_JAX_COORDINATOR = "JAX_COORDINATOR_ADDRESS"


@dataclasses.dataclass(frozen=True)
class DistributedEnv:
    """Parsed view of the platform-injected distributed environment."""

    process_id: int = 0
    num_processes: int = 1
    coordinator_address: str | None = None
    worker_hostnames: tuple[str, ...] = ()

    @property
    def is_multihost(self) -> bool:
        return self.num_processes > 1

    @classmethod
    def from_env(cls, env: dict[str, str] | None = None) -> "DistributedEnv":
        env = os.environ if env is None else env
        hostnames = tuple(
            h for h in env.get(ENV_WORKER_HOSTNAMES, "").split(",") if h
        )
        num = int(env.get(ENV_NUM_PROCESSES, len(hostnames) or 1))
        # Precedence: webhook-injected KFT_COORDINATOR_ADDRESS, then the
        # JAX-native name the image's 10-tpu-env script derives for pods
        # spawned WITHOUT the webhook (ordinal-derivation path), then
        # rank 0 of the hostname list.
        coord = env.get(ENV_COORDINATOR) or env.get(ENV_JAX_COORDINATOR)
        if not coord and hostnames:
            coord = f"{hostnames[0]}:{COORDINATOR_PORT}"
        return cls(
            process_id=int(env.get(ENV_WORKER_ID, 0)),
            num_processes=num,
            coordinator_address=coord,
            worker_hostnames=hostnames,
        )


def initialize_from_env(env: dict[str, str] | None = None) -> DistributedEnv:
    """Initialise ``jax.distributed`` from platform env; no-op single-host.

    Safe to call unconditionally at image startup (the jupyter-jax-tpu
    images call it from a sitecustomize hook): a single-replica notebook
    has no hostnames env and skips initialisation, so the same image runs
    single-host and multi-host (BASELINE.md "TPU_WORKER_ID=0 fallback").
    """
    denv = DistributedEnv.from_env(env)
    if not denv.is_multihost:
        log.info("single-host notebook: skipping jax.distributed")
        return denv
    import jax

    jax.distributed.initialize(
        coordinator_address=denv.coordinator_address,
        num_processes=denv.num_processes,
        process_id=denv.process_id,
    )
    log.info(
        "jax.distributed up: rank %d/%d coordinator=%s",
        denv.process_id,
        denv.num_processes,
        denv.coordinator_address,
    )
    return denv


def slice_env_for_rank(
    name: str,
    namespace: str,
    rank: int,
    num_replicas: int,
    service: str | None = None,
) -> dict[str, str]:
    """The env block the platform injects for replica ``rank``.

    Single source of truth shared by the notebook controller's
    StatefulSet generator and the PodDefault webhook tests, so the two
    injection paths can never drift apart. The default ``service`` is the
    headless per-replica Service the controller creates (``<name>-hosts``,
    native/src/notebook.cpp) — per-pod DNS only resolves under it.
    """
    service = service or f"{name}-hosts"
    hosts = ",".join(
        f"{name}-{i}.{service}.{namespace}.svc" for i in range(num_replicas)
    )
    env = {
        ENV_WORKER_ID: str(rank),
        ENV_NUM_PROCESSES: str(num_replicas),
    }
    if num_replicas > 1:
        env[ENV_WORKER_HOSTNAMES] = hosts
        env[ENV_COORDINATOR] = (
            f"{name}-0.{service}.{namespace}.svc:{COORDINATOR_PORT}"
        )
    return env
