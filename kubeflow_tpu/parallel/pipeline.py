"""Pipeline parallelism: GPipe microbatch schedule over the ``pp`` axis.

TPU-native pipelining, per the scaling-book recipe: the layer stack is
split into P identical stages whose parameters shard over the mesh's
``pp`` axis; activations circulate stage-to-stage with
``jax.lax.ppermute`` (point-to-point, so pp tolerates the coarsest
interconnect — it is laid out next to dp, and on a multislice mesh
never crosses DCN). The schedule runs inside ``jax.shard_map`` manual
ONLY over ``pp`` (``axis_names={"pp"}``): every other mesh axis (dp,
fsdp, tp, sp, ep) stays automatic, so batch sharding and Megatron tp
compose with pipelining without any code here knowing about them.

The reference platform has no pipeline/parallelism layer at all
(SURVEY.md §2.3: replicas hardcoded to 1, no collective backend); this
module is part of the first-class distributed backend the TPU build
adds on top of the injected ``jax.distributed`` world.

Schedule: plain GPipe. M microbatches flow through P stages in
M + P - 1 ticks; each tick every stage runs once (the first/last P-1
ticks carry bubbles). The backward schedule is whatever autodiff makes
of the forward scan — correct, with the standard GPipe bubble fraction
(P-1)/(M+P-1); raise ``num_microbatches`` to amortise. ``remat=True``
wraps the stage in ``jax.checkpoint`` so live activation memory is one
microbatch per tick instead of the whole scan history.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

# stage_fn(stage_params, x) -> y with y.shape == x.shape: one pipeline
# stage (e.g. a lax.scan over its slice of the layer stack).
StageFn = Callable[[jax.Array, jax.Array], jax.Array]


def pipeline_ticks(num_microbatches: int, num_stages: int) -> int:
    """Ticks for one GPipe pass: M + P - 1."""
    return num_microbatches + num_stages - 1


def gpipe(
    stage_fn: StageFn,
    mesh: Mesh,
    *,
    num_microbatches: int,
    axis: str = "pp",
    remat: bool = False,
    activation_spec: P | None = None,
    extra_manual_axes: tuple[str, ...] = (),
):
    """Wrap ``stage_fn`` into a pipelined pass over the full layer stack.

    Returns ``run(stage_params, x) -> y``:

    - ``stage_params``: pytree whose every leaf is stacked on a leading
      stage dim of size P = mesh.shape[axis] (leaf shape ``(P, ...)``).
      The leading dim shards over ``axis``; each device sees only its
      stage's slice.
    - ``x``: activations ``(B, ...)`` with B divisible by
      ``num_microbatches``. Batch may additionally be dp-sharded — dp
      stays an automatic axis and composes transparently.
    - ``y``: ``(B, ...)``, the stack's output, replicated over ``axis``
      (an explicit masked-psum broadcast from the last stage).

    ``activation_spec``/``extra_manual_axes`` compose pipelining with a
    second manual-collective dimension in the SAME region (no shard_map
    nesting): e.g. ring attention over sp inside a pipelined stage —
    pass ``activation_spec=P(None, None, "sp", None)`` for (M, mb, S, D)
    microbatches sequence-sharded over sp and
    ``extra_manual_axes=("sp",)`` so the stage's psum/ppermute over sp
    resolve. The spec indexes MICROBATCHED activations: dim 0 is the
    microbatch axis the schedule owns and must stay unsharded.

    Differentiable end-to-end: ppermute/psum have exact transposes, so
    ``jax.grad`` through the returned function yields the GPipe backward
    pass with cotangents flowing stage-to-stage in reverse.
    """
    num_stages = mesh.shape[axis]
    if remat:
        stage_fn = jax.checkpoint(stage_fn)
    act_spec = P() if activation_spec is None else activation_spec
    if act_spec and act_spec[0] is not None:
        raise ValueError(
            "activation_spec dim 0 is the microbatch axis and must be "
            f"unsharded, got {act_spec}"
        )

    @partial(
        jax.shard_map,
        mesh=mesh,
        axis_names=frozenset({axis, *extra_manual_axes}),
        in_specs=(P(axis), act_spec),
        out_specs=act_spec,
        check_vma=False,
    )
    def run_sharded(stage_params, xm):
        # Per-device view: leading stage dim is now 1 — this device's
        # stage. (M, mb, ...) microbatches are replicated over pp.
        params = jax.tree.map(lambda p: jnp.squeeze(p, 0), stage_params)
        idx = jax.lax.axis_index(axis)
        n_mb = xm.shape[0]
        # Open chain, not a ring: the last stage's output would only be
        # discarded by stage 0, so the wrap-around edge is omitted and
        # ppermute delivers zeros there — one less (mb, ...) transfer
        # per tick on the coarsest links.
        perm = [(i, i + 1) for i in range(num_stages - 1)]

        def tick(carry, t):
            state, outbuf = carry
            # Shift every stage's last output one stage forward; stage 0
            # feeds microbatch t instead (clipped re-feeds past the end
            # are bubbles that never get written out).
            recv = jax.lax.ppermute(state, axis, perm)
            x_t = jax.lax.dynamic_index_in_dim(
                xm, jnp.clip(t, 0, n_mb - 1), 0, keepdims=False
            )
            out = stage_fn(params, jnp.where(idx == 0, x_t, recv))
            # The last stage finishes microbatch t-(P-1) at tick t.
            w = t - (num_stages - 1)
            w_clip = jnp.clip(w, 0, n_mb - 1)
            keep = jax.lax.dynamic_index_in_dim(
                outbuf, w_clip, 0, keepdims=False
            )
            write = jnp.logical_and(idx == num_stages - 1, w >= 0)
            outbuf = jax.lax.dynamic_update_index_in_dim(
                outbuf, jnp.where(write, out, keep), w_clip, 0
            )
            return (out, outbuf), None

        init = (jnp.zeros_like(xm[0]), jnp.zeros_like(xm))
        ticks = jnp.arange(pipeline_ticks(n_mb, num_stages))
        (_, outbuf), _ = jax.lax.scan(tick, init, ticks)
        # Broadcast the last stage's buffer to every stage (masked psum:
        # all other stages contribute zeros).
        return jax.lax.psum(
            jnp.where(idx == num_stages - 1, outbuf, jnp.zeros_like(outbuf)),
            axis,
        )

    def run(stage_params, x):
        if x.shape[0] % num_microbatches:
            raise ValueError(
                f"batch {x.shape[0]} not divisible by "
                f"num_microbatches={num_microbatches}"
            )
        xm = x.reshape(
            num_microbatches, x.shape[0] // num_microbatches, *x.shape[1:]
        )
        ym = run_sharded(stage_params, xm)
        return ym.reshape(x.shape[0], *ym.shape[2:])

    return run


def stage_stack(params, num_stages: int):
    """Reshape a depth-stacked layer pytree ``(L, ...)`` into the stage
    layout ``(P, L/P, ...)`` gpipe shards: contiguous groups of L/P
    consecutive layers per stage (row-major reshape = stage order)."""

    def reshape(leaf):
        depth = leaf.shape[0]
        if depth % num_stages:
            raise ValueError(
                f"layer stack depth {depth} not divisible by "
                f"pp={num_stages} stages"
            )
        return leaf.reshape(num_stages, depth // num_stages, *leaf.shape[1:])

    return jax.tree.map(reshape, params)
