"""Pipeline parallelism: GPipe and 1F1B schedules over the ``pp`` axis.

TPU-native pipelining, per the scaling-book recipe: the layer stack is
split into P identical stages whose parameters shard over the mesh's
``pp`` axis; activations circulate stage-to-stage with
``jax.lax.ppermute`` (point-to-point, so pp tolerates the coarsest
interconnect — it is laid out next to dp, and on a multislice mesh
never crosses DCN). The schedule runs inside ``jax.shard_map`` manual
ONLY over ``pp`` (``axis_names={"pp"}``): every other mesh axis (dp,
fsdp, tp, sp, ep) stays automatic, so batch sharding and Megatron tp
compose with pipelining without any code here knowing about them.

The reference platform has no pipeline/parallelism layer at all
(SURVEY.md §2.3: replicas hardcoded to 1, no collective backend); this
module is part of the first-class distributed backend the TPU build
adds on top of the injected ``jax.distributed`` world.

Two schedules, one contract:

- :func:`gpipe` — plain GPipe. M microbatches flow through P stages in
  M + P - 1 ticks; the backward is whatever autodiff makes of the
  forward scan — correct, with the standard bubble fraction
  (P-1)/(M+P-1), but AD saves the per-tick carry chain, so live
  microbatch state in the backward is O(M). ``remat=True`` wraps the
  stage in ``jax.checkpoint`` so stage INTERNALS are recomputed.
- :func:`one_f_one_b` — PipeDream-flush / 1F1B. Same bubble fraction,
  but the backward is a hand-scheduled interleave (custom_vjp): each
  slot a stage runs either one forward-recompute or one backward, and
  stage inputs live in a P-slot circular buffer — O(P) live microbatch
  state regardless of M, the property that lets microbatch counts grow
  to amortise the bubble without growing memory.

Output modes (both schedules): ``output="sharded"`` (default for new
code) hands back the microbatch dim SHARDED over pp via one
``psum_scatter`` — the minimal redistribution for data that exists
only on the last stage (a masked psum would all-reduce zeros at ~2x
the link time), and everything downstream (head, loss) then runs on
M/P microbatches per stage instead of redundantly on all M.
``output="replicated"`` keeps the round-2 behavior.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

# stage_fn(stage_params, x) -> y with y.shape == x.shape: one pipeline
# stage (e.g. a lax.scan over its slice of the layer stack).
StageFn = Callable[[jax.Array, jax.Array], jax.Array]


def pipeline_ticks(num_microbatches: int, num_stages: int) -> int:
    """Ticks for one GPipe pass: M + P - 1."""
    return num_microbatches + num_stages - 1


def _validate(act_spec: P, output: str, num_microbatches: int,
              num_stages: int) -> None:
    """Shared public-contract checks for both schedules."""
    if act_spec and act_spec[0] is not None:
        raise ValueError(
            "activation_spec dim 0 is the microbatch axis and must be "
            f"unsharded, got {act_spec}"
        )
    if output not in ("replicated", "sharded"):
        raise ValueError(f"output must be replicated|sharded, got {output}")
    if output == "sharded" and num_microbatches % num_stages:
        raise ValueError(
            f"sharded output needs num_microbatches={num_microbatches} "
            f"divisible by pp={num_stages}"
        )


def _microbatched(pipeline_fn, num_microbatches: int):
    """Shared (B, ...) <-> (M, mb, ...) wrapper for both schedules.
    ``extra`` (e.g. segment ids for packed batches) microbatches the
    same way and rides NEXT TO the activations — it is indexed per
    microbatch at each stage, never circulated."""

    def run(stage_params, x, extra=None):
        if x.shape[0] % num_microbatches:
            raise ValueError(
                f"batch {x.shape[0]} not divisible by "
                f"num_microbatches={num_microbatches}"
            )
        mb = x.shape[0] // num_microbatches
        xm = x.reshape(num_microbatches, mb, *x.shape[1:])
        if extra is not None:
            em = extra.reshape(num_microbatches, mb, *extra.shape[1:])
            ym = pipeline_fn(stage_params, xm, em)
        else:
            ym = pipeline_fn(stage_params, xm)
        return ym.reshape(x.shape[0], *ym.shape[2:])

    return run


def _collective_seq(x, dep):
    """Thread a data dependency from ``dep`` into ``x`` so every op
    consuming ``x`` — in particular any collective inside the stage —
    is issued AFTER the collective that produced ``dep``, on every
    device. XLA backends without a total collective stream order (the
    CPU thunk runtime: one thread per device, independent collectives
    executed in device-divergent order) otherwise cross-block when two
    concurrently-runnable collectives get picked in different orders by
    different devices — the round-4 1f1b x virtual x sp deadlock. An
    ``optimization_barrier`` is metadata-only on backends that already
    stream-order collectives (TPU)."""
    x, _ = jax.lax.optimization_barrier((x, dep))
    return x


def _out_spec(act_spec: P, axis: str, output: str) -> P:
    """out_specs for the schedule result: microbatch dim 0 sharded over
    ``axis`` in sharded mode, act_spec otherwise."""
    if output == "sharded":
        rest = tuple(act_spec)[1:] if len(tuple(act_spec)) else ()
        return P(axis, *rest)
    return act_spec


def _forward_ticks(stage_fn, params, xm, idx, axis, num_stages, output,
                   em=None):
    """The GPipe forward schedule body, shared by both schedules (the
    1F1B primal IS the GPipe forward; only backwards differ): tick
    scan with ppermute circulation, last-stage output buffer, and the
    output-mode emission. ``em`` is the optional per-microbatch side
    input: stage ``idx`` at tick ``t`` runs microbatch ``t - idx``, so
    it is indexed, not circulated (bubble ticks read a clipped index
    whose result is discarded)."""
    n_mb = xm.shape[0]
    perm = [(i, i + 1) for i in range(num_stages - 1)]

    def tick(carry, t):
        state, outbuf = carry
        # Shift every stage's last output one stage forward; stage 0
        # feeds microbatch t instead (clipped re-feeds past the end
        # are bubbles that never get written out).
        recv = jax.lax.ppermute(state, axis, perm)
        x_t = jax.lax.dynamic_index_in_dim(
            xm, jnp.clip(t, 0, n_mb - 1), 0, keepdims=False
        )
        x_in = jnp.where(idx == 0, x_t, recv)
        if em is None:
            out = stage_fn(params, x_in)
        else:
            e_in = jax.lax.dynamic_index_in_dim(
                em, jnp.clip(t - idx, 0, n_mb - 1), 0, keepdims=False
            )
            out = stage_fn(params, x_in, e_in)
        # The last stage finishes microbatch t-(P-1) at tick t.
        w = t - (num_stages - 1)
        w_clip = jnp.clip(w, 0, n_mb - 1)
        keep = jax.lax.dynamic_index_in_dim(
            outbuf, w_clip, 0, keepdims=False
        )
        write = jnp.logical_and(idx == num_stages - 1, w >= 0)
        outbuf = jax.lax.dynamic_update_index_in_dim(
            outbuf, jnp.where(write, out, keep), w_clip, 0
        )
        return (out, outbuf), None

    init = (jnp.zeros_like(xm[0]), jnp.zeros_like(xm))
    ticks = jnp.arange(pipeline_ticks(n_mb, num_stages))
    (_, outbuf), _ = jax.lax.scan(tick, init, ticks)
    return _emit_output(outbuf, idx, num_stages, axis, output)


def _emit_output(outbuf, idx, num_stages, axis, output):
    """Deliver the last stage's (M, ...) buffer per the output mode.

    sharded: one ring reduce-scatter moves exactly the data each stage
    needs (chunk s of the microbatch dim) — wall time ~ buf*(P-1)/P on
    the ICI ring, the lower bound for a one-source redistribution.
    replicated: full masked psum broadcast (2x the link time; kept for
    callers that want the output whole on every stage)."""
    masked = jnp.where(
        idx == num_stages - 1, outbuf, jnp.zeros_like(outbuf)
    )
    if output == "sharded":
        return jax.lax.psum_scatter(
            masked, axis, scatter_dimension=0, tiled=True
        )
    return jax.lax.psum(masked, axis)


def gpipe(
    stage_fn: StageFn,
    mesh: Mesh,
    *,
    num_microbatches: int,
    axis: str = "pp",
    remat: bool = False,
    activation_spec: P | None = None,
    extra_spec: P | None = None,
    extra_manual_axes: tuple[str, ...] = (),
    output: str = "replicated",
):
    """Wrap ``stage_fn`` into a pipelined pass over the full layer stack.

    Returns ``run(stage_params, x) -> y``:

    - ``stage_params``: pytree whose every leaf is stacked on a leading
      stage dim of size P = mesh.shape[axis] (leaf shape ``(P, ...)``).
      The leading dim shards over ``axis``; each device sees only its
      stage's slice.
    - ``x``: activations ``(B, ...)`` with B divisible by
      ``num_microbatches``. Batch may additionally be dp-sharded — dp
      stays an automatic axis and composes transparently.
    - ``y``: ``(B, ...)``, the stack's output. ``output="replicated"``
      (default) hands it back whole on every stage (masked-psum
      broadcast, ~2x the link time); ``output="sharded"`` leaves the
      microbatch dim SHARDED over ``axis`` via one psum_scatter — the
      minimal redistribution — so downstream global-array code (head,
      loss) runs on M/P microbatches per stage. Requires
      num_microbatches divisible by P.

    ``activation_spec``/``extra_manual_axes`` compose pipelining with a
    second manual-collective dimension in the SAME region (no shard_map
    nesting): e.g. ring attention over sp inside a pipelined stage —
    pass ``activation_spec=P(None, None, "sp", None)`` for (M, mb, S, D)
    microbatches sequence-sharded over sp and
    ``extra_manual_axes=("sp",)`` so the stage's psum/ppermute over sp
    resolve. The spec indexes MICROBATCHED activations: dim 0 is the
    microbatch axis the schedule owns and must stay unsharded.

    ``extra_spec`` enables a per-microbatch SIDE input (``run(params, x,
    extra)``, e.g. packed-batch segment ids): microbatched like x,
    replicated over pp, indexed by each stage at the microbatch it is
    running — never circulated through the ppermute chain.

    Differentiable end-to-end: ppermute/psum have exact transposes, so
    ``jax.grad`` through the returned function yields the GPipe backward
    pass with cotangents flowing stage-to-stage in reverse.
    """
    num_stages = mesh.shape[axis]
    if remat:
        stage_fn = jax.checkpoint(stage_fn)
    act_spec = P() if activation_spec is None else activation_spec
    _validate(act_spec, output, num_microbatches, num_stages)
    has_extra = extra_spec is not None
    in_specs = (P(axis), act_spec) + ((extra_spec,) if has_extra else ())

    @partial(
        jax.shard_map,
        mesh=mesh,
        axis_names=frozenset({axis, *extra_manual_axes}),
        in_specs=in_specs,
        out_specs=_out_spec(act_spec, axis, output),
        check_vma=False,
    )
    def run_sharded(stage_params, xm, *maybe_em):
        # Per-device view: leading stage dim is now 1 — this device's
        # stage. (M, mb, ...) microbatches are replicated over pp.
        # Open chain, not a ring: the last stage's output would only be
        # discarded by stage 0, so the wrap-around edge is omitted and
        # ppermute delivers zeros there — one less (mb, ...) transfer
        # per tick on the coarsest links.
        params = jax.tree.map(lambda p: jnp.squeeze(p, 0), stage_params)
        idx = jax.lax.axis_index(axis)
        return _forward_ticks(
            stage_fn, params, xm, idx, axis, num_stages, output,
            em=maybe_em[0] if maybe_em else None,
        )

    return _microbatched(run_sharded, num_microbatches)


def _1f1b_tables(num_microbatches: int, num_stages: int):
    """Static slot tables for the PipeDream-flush schedule. Slot = one
    compute unit (one stage forward OR one stage backward). Derived
    from the canonical timing (stage s, microbatch m):

      F(s, m) = s + m            for m <= P-1-s   (warmup)
                2m + s           otherwise        (1F1B steady state)
      B(s, m) = 2P - 1 + 2m - s                   (B(P-1,m)=F(P-1,m)+1)

    Properties the implementation relies on (each checkable from the
    formulas): F and B slots are disjoint per stage; the activation for
    (s, m) is PRODUCED at F(s-1, m) and may wait until F(s, m), but
    never more than P microbatches are in flight per stage, so a P-slot
    circular buffer keyed m mod P holds every pending input; the
    cotangent for (s, m) ARRIVES exactly at B(s, m) (no buffering).

    Returns (F_tbl, B_tbl, R_tbl) of shape (T, P) with -1 = idle,
    where R_tbl[t, s] is the microbatch whose activation arrives at
    stage s in slot t (= F_tbl[t-1, s-1]), and T = 2(M + P - 1).
    """
    M, Pn = num_microbatches, num_stages
    T = 2 * (M + Pn - 1)
    F = np.full((T, Pn), -1, np.int32)
    B = np.full((T, Pn), -1, np.int32)
    for s in range(Pn):
        for m in range(M):
            tf = s + m if m <= Pn - 1 - s else 2 * m + s
            F[tf, s] = m
            B[2 * Pn - 1 + 2 * m - s, s] = m
    R = np.full((T, Pn), -1, np.int32)
    R[1:, 1:] = F[:-1, :-1]
    # The uniform tick computes ONE unit per slot, so the schedule must
    # never put an F and a B on the same (slot, stage) — true of 1F1B
    # by construction (F and B slots have opposite parity per stage);
    # pinned here because silently dropping one would be a wrong-grads
    # bug, not a crash. (numpy domain: the caller may be tracing.)
    assert not np.any((F >= 0) & (B >= 0))
    return jnp.asarray(F), jnp.asarray(B), jnp.asarray(R)


def one_f_one_b(
    stage_fn: StageFn,
    mesh: Mesh,
    *,
    num_microbatches: int,
    axis: str = "pp",
    activation_spec: P | None = None,
    extra_spec: P | None = None,
    extra_manual_axes: tuple[str, ...] = (),
    output: str = "replicated",
    uniform_collectives: bool | None = None,
):
    """1F1B (PipeDream-flush) pipeline schedule. Same contract and same
    bubble fraction as :func:`gpipe`; the difference is the BACKWARD.

    GPipe's backward is autodiff of the forward scan: XLA materialises
    the per-tick carry chain, so the backward holds O(M) microbatch
    activations. Here the backward is a hand-scheduled interleave
    (``jax.custom_vjp``): per slot each stage runs either one
    forward-RECOMPUTE (stage internals are never stored — inherent
    rematerialisation) or one backward, stage inputs wait in a P-slot
    circular buffer, and parameter gradients accumulate in-place. Live
    microbatch state in the backward is O(P) however large M grows —
    and growing M is exactly how the (P-1)/(M+P-1) bubble is amortised.

    Compute cost is identical to gpipe(remat=True): M forwards +
    M recompute-backwards per stage (measured on the 8-device CPU mesh
    and on-chip; see BASELINE.md round-3 pipeline rows).

    ``uniform_collectives`` (round 5; default: auto-on when
    ``extra_manual_axes`` is non-empty): with stage-internal manual
    collectives (the sp ring), the lax.switch backward makes devices
    issue DIFFERENT collective sequences in the same tick, and the
    collective rendezvous keys on (run_id, channel) with one channel
    reused — devices silently join each other's rendezvous across
    different ops and exchange the WRONG tensors. Round 5 measured
    plain 1f1b x sp gradients off by 100-400x relative on the CPU
    runtime while the loss stayed exact (the forward is uniform
    already). The uniform tick runs one vjp on every device every
    tick with select-masked outputs — identical global collective
    sequence by construction. See interleaved_one_f_one_b for the
    matching fix at virtual depth.
    """
    num_stages = mesh.shape[axis]
    act_spec = P() if activation_spec is None else activation_spec
    _validate(act_spec, output, num_microbatches, num_stages)
    manual_axes = frozenset({axis, *extra_manual_axes})
    uniform = (
        bool(extra_manual_axes) if uniform_collectives is None
        else uniform_collectives
    )
    fwd_perm = [(i, i + 1) for i in range(num_stages - 1)]
    rev_perm = [(i + 1, i) for i in range(num_stages - 1)]
    F_tbl, B_tbl, R_tbl = _1f1b_tables(num_microbatches, num_stages)
    n_slots = int(F_tbl.shape[0])
    has_extra = extra_spec is not None
    extra_in = (extra_spec,) if has_extra else ()

    @partial(
        jax.shard_map,
        mesh=mesh,
        axis_names=manual_axes,
        in_specs=(P(axis), act_spec) + extra_in,
        out_specs=_out_spec(act_spec, axis, output),
        check_vma=False,
    )
    def fwd_sharded(stage_params, xm, *maybe_em):
        # The 1F1B primal IS the GPipe forward (schedules only differ
        # in the backward); custom_vjp owns the residuals.
        params = jax.tree.map(lambda p: jnp.squeeze(p, 0), stage_params)
        idx = jax.lax.axis_index(axis)
        return _forward_ticks(
            stage_fn, params, xm, idx, axis, num_stages, output,
            em=maybe_em[0] if maybe_em else None,
        )

    @partial(
        jax.shard_map,
        mesh=mesh,
        axis_names=manual_axes,
        in_specs=(P(axis), act_spec) + extra_in
        + (_out_spec(act_spec, axis, output),),
        out_specs=(P(axis), act_spec),
        check_vma=False,
    )
    def bwd_sharded(stage_params, xm, *em_and_ybar):
        em = em_and_ybar[0] if has_extra else None
        ym_bar = em_and_ybar[-1]
        params = jax.tree.map(lambda p: jnp.squeeze(p, 0), stage_params)
        idx = jax.lax.axis_index(axis)
        is_first = idx == 0
        is_last = idx == num_stages - 1
        if output == "sharded":
            # Transpose of the forward's psum_scatter: gather the
            # sharded cotangent back to (M, ...) (only the last stage
            # reads it, but all_gather is the ring-optimal move).
            ym_bar = jax.lax.all_gather(ym_bar, axis, axis=0, tiled=True)

        mb_shape = xm.shape[1:]
        zero_mb = jnp.zeros(mb_shape, xm.dtype)
        zero_params = jax.tree.map(jnp.zeros_like, params)

        def slot(carry, t):
            xbuf, prev_act, prev_cot, dparams, dxm = carry
            f_mb = F_tbl[t, idx]
            b_mb = B_tbl[t, idx]
            r_mb = R_tbl[t, idx]
            # Deterministic hop order on order-free backends (see
            # _collective_seq): act hop -> cot hop -> stage work.
            recv_act = jax.lax.ppermute(prev_act, axis, fwd_perm)
            prev_cot = _collective_seq(prev_cot, recv_act)
            recv_cot = jax.lax.ppermute(prev_cot, axis, rev_perm)

            # Stage input arrives: from upstream (s > 0) or from xm
            # (stage 0, at its own F slot). Circular slot = m mod P.
            slot_r = jnp.where(r_mb >= 0, r_mb % num_stages, 0)
            keep_r = jax.lax.dynamic_index_in_dim(
                xbuf, slot_r, 0, keepdims=False
            )
            store_r = jnp.logical_and(r_mb >= 0, ~is_first)
            xbuf = jax.lax.dynamic_update_index_in_dim(
                xbuf, jnp.where(store_r, recv_act, keep_r), slot_r, 0
            )
            slot_f = jnp.where(f_mb >= 0, f_mb % num_stages, 0)
            x_own = jax.lax.dynamic_index_in_dim(
                xm, jnp.clip(f_mb, 0, xm.shape[0] - 1), 0, keepdims=False
            )
            keep_f = jax.lax.dynamic_index_in_dim(
                xbuf, slot_f, 0, keepdims=False
            )
            store_f = jnp.logical_and(f_mb >= 0, is_first)
            xbuf = jax.lax.dynamic_update_index_in_dim(
                xbuf, jnp.where(store_f, x_own, keep_f), slot_f, 0
            )

            def _stage_at(mb_idx):
                """stage_fn closed over this slot's side input (the
                microbatch's segment ids); identity when none."""
                if em is None:
                    return stage_fn
                e_in = jax.lax.dynamic_index_in_dim(
                    em, jnp.clip(mb_idx, 0, em.shape[0] - 1), 0,
                    keepdims=False,
                )
                return lambda p, x: stage_fn(p, x, e_in)

            slot_b = jnp.where(b_mb >= 0, b_mb % num_stages, 0)
            seed = jax.lax.dynamic_index_in_dim(
                ym_bar, jnp.clip(b_mb, 0, ym_bar.shape[0] - 1), 0,
                keepdims=False,
            )
            if uniform:
                # Uniform-collective tick (see docstring): one vjp on
                # every device every tick, outputs masked by selects —
                # garbage-input vjps may be non-finite, so never
                # multiply-mask.
                is_f = f_mb >= 0
                is_b = b_mb >= 0
                x_in = jax.lax.dynamic_index_in_dim(
                    xbuf, jnp.where(is_b, slot_b, slot_f), 0,
                    keepdims=False,
                )
                cot = jnp.where(is_last, seed, recv_cot)
                x_in = _collective_seq(x_in, recv_cot)
                cot = _collective_seq(cot, recv_cot)
                mb = jnp.where(is_b, b_mb, jnp.maximum(f_mb, 0))
                y, vjp_fn = jax.vjp(_stage_at(mb), params, x_in)
                dp_raw, dx_raw = vjp_fn(cot)
                out_act = jnp.where(is_f, y, zero_mb)
                out_cot = jnp.where(is_b, dx_raw, zero_mb)
                dx = jnp.where(is_b, dx_raw, zero_mb)
                dp = jax.tree.map(
                    lambda g, z: jnp.where(is_b, g, z), dp_raw,
                    zero_params,
                )
            else:
                def f_branch(op):
                    xbuf, _recv_cot = op
                    x_in = jax.lax.dynamic_index_in_dim(
                        xbuf, slot_f, 0, keepdims=False
                    )
                    y = _stage_at(f_mb)(params, x_in)
                    return y, zero_mb, zero_params, zero_mb

                def b_branch(op):
                    xbuf, recv_cot = op
                    x_in = jax.lax.dynamic_index_in_dim(
                        xbuf, slot_b, 0, keepdims=False
                    )
                    cot = jnp.where(is_last, seed, recv_cot)
                    _, vjp_fn = jax.vjp(_stage_at(b_mb), params, x_in)
                    dp, dx = vjp_fn(cot)
                    return zero_mb, dx, dp, dx

                def idle_branch(op):
                    return zero_mb, zero_mb, zero_params, zero_mb

                action = jnp.where(
                    f_mb >= 0, 1, jnp.where(b_mb >= 0, 2, 0)
                )
                out_act, out_cot, dp, dx = jax.lax.switch(
                    action, [idle_branch, f_branch, b_branch],
                    (xbuf, recv_cot),
                )
            dparams = jax.tree.map(jnp.add, dparams, dp)
            # Input cotangent: stage 0's backward of mb m yields dxm[m].
            slot_b = jnp.clip(b_mb, 0, xm.shape[0] - 1)
            keep_dx = jax.lax.dynamic_index_in_dim(
                dxm, slot_b, 0, keepdims=False
            )
            write_dx = jnp.logical_and(b_mb >= 0, is_first)
            dxm = jax.lax.dynamic_update_index_in_dim(
                dxm, jnp.where(write_dx, dx, keep_dx), slot_b, 0
            )
            return (xbuf, out_act, out_cot, dparams, dxm), None

        xbuf0 = jnp.zeros((num_stages,) + mb_shape, xm.dtype)
        # First hop must not race the ym_bar gather above (see the
        # interleaved engine's matching note).
        init = (xbuf0, _collective_seq(zero_mb, ym_bar),
                _collective_seq(zero_mb, ym_bar), zero_params,
                jnp.zeros_like(xm))
        (_, _, _, dparams, dxm), _ = jax.lax.scan(
            slot, init, jnp.arange(n_slots)
        )
        # dxm exists on stage 0 only; xm's spec is replicated over pp.
        dxm = jax.lax.psum(
            jnp.where(is_first, dxm, jnp.zeros_like(dxm)), axis
        )
        # Params are replicated over the extra manual axes: sum the
        # per-peer shard contributions (see interleaved engine note).
        for extra_axis in extra_manual_axes:
            dparams = jax.tree.map(
                lambda g: jax.lax.psum(g, extra_axis), dparams
            )
        dparams = jax.tree.map(lambda g: g[None], dparams)
        return dparams, dxm

    if has_extra:
        # Segment ids are integer side inputs: their cotangent is the
        # symbolic-zero float0 array custom_vjp requires for int
        # primals.
        @jax.custom_vjp
        def pipeline(stage_params, xm, em):
            return fwd_sharded(stage_params, xm, em)

        def pipeline_fwd(stage_params, xm, em):
            return fwd_sharded(stage_params, xm, em), (
                stage_params, xm, em,
            )

        def pipeline_bwd(res, ym_bar):
            stage_params, xm, em = res
            dparams, dxm = bwd_sharded(stage_params, xm, em, ym_bar)
            dem = np.zeros(em.shape, jax.dtypes.float0)
            return dparams, dxm, dem
    else:
        @jax.custom_vjp
        def pipeline(stage_params, xm):
            return fwd_sharded(stage_params, xm)

        def pipeline_fwd(stage_params, xm):
            return fwd_sharded(stage_params, xm), (stage_params, xm)

        def pipeline_bwd(res, ym_bar):
            stage_params, xm = res
            return bwd_sharded(stage_params, xm, ym_bar)

    pipeline.defvjp(pipeline_fwd, pipeline_bwd)
    return _microbatched(pipeline, num_microbatches)


def interleaved_gpipe(
    stage_fn: StageFn,
    mesh: Mesh,
    *,
    num_microbatches: int,
    virtual_stages: int,
    axis: str = "pp",
    remat: bool = False,
    activation_spec: P | None = None,
    extra_spec: P | None = None,
    extra_manual_axes: tuple[str, ...] = (),
    output: str = "replicated",
):
    """Interleaved (virtual-stage) pipeline forward, Megatron-style:
    every device holds ``V = virtual_stages`` model CHUNKS laid out
    round-robin (device d owns global stages d, d+P, ..., d+(V-1)P), so
    one microbatch visits each device V times. The win over laying the
    same depth out as V*P plain stages: the fill/drain bubble stays
    P - 1 ticks (one ring traversal) instead of V*P - 1 — at equal
    microbatch count the bubble fraction drops by ~V.

    Timing (derivable, and asserted by the parity tests): microbatch
    j of group g runs chunk v on device d at tick

        t = g*V*P + v*P + d + j,        j, d in [0,P), v in [0,V)

    which gives each device EXACTLY one unit of work per tick in
    [d, d + V*P) per group, consecutive global stages one tick apart
    (device d -> d+1, with the ring's wrap edge carrying chunk
    boundaries P-1 -> 0), and groups tiling seamlessly at V*P spacing.
    Total ticks: (M/P)*V*P + P - 1, requiring M % P == 0.

    ``stage_params`` leaves are (P, V, layers/(V*P), ...) — see
    :func:`stage_stack_interleaved`; the chunk to run each tick is
    picked by a dynamic index over the V dim (uniform compute, scan-
    friendly). The backward is autodiff of the tick scan (like
    :func:`gpipe`); ``remat=True`` recomputes chunk internals.
    """
    num_stages = mesh.shape[axis]
    if virtual_stages < 1:
        raise ValueError(f"virtual_stages must be >= 1, got {virtual_stages}")
    if num_microbatches % num_stages:
        raise ValueError(
            f"interleaved schedule needs num_microbatches="
            f"{num_microbatches} divisible by pp={num_stages} (groups "
            "of P microbatches tile the V*P-tick cycle)"
        )
    if remat:
        stage_fn = jax.checkpoint(stage_fn)
    act_spec = P() if activation_spec is None else activation_spec
    _validate(act_spec, output, num_microbatches, num_stages)
    has_extra = extra_spec is not None
    in_specs = (P(axis), act_spec) + ((extra_spec,) if has_extra else ())
    V = virtual_stages
    cycle = V * num_stages
    groups = num_microbatches // num_stages
    n_ticks = groups * cycle + num_stages - 1
    ring = [(i, (i + 1) % num_stages) for i in range(num_stages)]

    @partial(
        jax.shard_map,
        mesh=mesh,
        axis_names=frozenset({axis, *extra_manual_axes}),
        in_specs=in_specs,
        out_specs=_out_spec(act_spec, axis, output),
        check_vma=False,
    )
    def run_sharded(stage_params, xm, *maybe_em):
        # Per-device view: (1, V, L/(V*P), ...) -> (V, L/(V*P), ...).
        params = jax.tree.map(lambda p: jnp.squeeze(p, 0), stage_params)
        idx = jax.lax.axis_index(axis)
        return _interleaved_forward_ticks(
            stage_fn, params, xm, maybe_em[0] if maybe_em else None,
            idx, axis, num_stages, V, groups, output,
        )

    return _microbatched(run_sharded, num_microbatches)


def _interleaved_forward_ticks(stage_fn, params, xm, em, idx, axis,
                               num_stages, V, groups, output):
    """The interleaved forward tick scan, shared by
    :func:`interleaved_gpipe` and the interleaved-1F1B primal (which,
    like plain 1F1B, IS the interleaved forward — only backwards
    differ). See interleaved_gpipe for the unit-timing derivation."""
    n_mb = xm.shape[0]
    cycle = V * num_stages
    n_ticks = groups * cycle + num_stages - 1
    ring = [(i, (i + 1) % num_stages) for i in range(num_stages)]

    def tick(carry, t):
        state, outbuf = carry
        recv = jax.lax.ppermute(state, axis, ring)
        u = t - idx
        active = u >= 0
        g = jnp.maximum(u, 0) // cycle
        w = jnp.maximum(u, 0) % cycle
        v = w // num_stages
        j = w % num_stages
        m = jnp.clip(g * num_stages + j, 0, n_mb - 1)
        active = jnp.logical_and(active, g < groups)
        x_t = jax.lax.dynamic_index_in_dim(xm, m, 0, keepdims=False)
        # Global stage 0 (chunk 0 on DEVICE 0) consumes fresh
        # microbatches; every other unit consumes the neighbour's
        # last output (the wrap edge P-1 -> 0 carries chunk
        # boundaries v -> v+1 back to device 0).
        fresh = jnp.logical_and(
            jnp.logical_and(v == 0, idx == 0), active
        )
        x_in = jnp.where(fresh, x_t, recv)
        params_v = jax.tree.map(
            lambda p: jax.lax.dynamic_index_in_dim(
                p, v, 0, keepdims=False
            ),
            params,
        )
        if em is None:
            out = stage_fn(params_v, x_in)
        else:
            e_in = jax.lax.dynamic_index_in_dim(
                em, m, 0, keepdims=False
            )
            out = stage_fn(params_v, x_in, e_in)
        write = jnp.logical_and(
            active,
            jnp.logical_and(idx == num_stages - 1, v == V - 1),
        )
        keep = jax.lax.dynamic_index_in_dim(
            outbuf, m, 0, keepdims=False
        )
        outbuf = jax.lax.dynamic_update_index_in_dim(
            outbuf, jnp.where(write, out, keep), m, 0
        )
        return (out, outbuf), None

    init = (jnp.zeros_like(xm[0]), jnp.zeros_like(xm))
    (_, outbuf), _ = jax.lax.scan(tick, init, jnp.arange(n_ticks))
    return _emit_output(outbuf, idx, num_stages, axis, output)


def interleaved_one_f_one_b(
    stage_fn: StageFn,
    mesh: Mesh,
    *,
    num_microbatches: int,
    virtual_stages: int,
    axis: str = "pp",
    activation_spec: P | None = None,
    extra_spec: P | None = None,
    extra_manual_axes: tuple[str, ...] = (),
    output: str = "replicated",
    uniform_collectives: bool | None = None,
):
    """Interleaved 1F1B: the virtual-stage forward of
    :func:`interleaved_gpipe` with a hand-scheduled PipeDream-flush
    backward — O(P·V) live microbatch state (the static schedule's
    buffer depth, ~P·(V+1) activations) however large M grows, at
    V·P pipeline depth with the P-1-tick fill bubble.

    The slot tables come from :mod:`kubeflow_tpu.parallel.schedule1f1b`
    — SIMULATED under the Megatron discipline (per-device warmup
    ``2(P-d-1) + (V-1)P`` forwards, then strict 1B1F alternation with
    idling) and validated by an independent checker; activation and
    cotangent buffer slots are assigned by static interval colouring,
    so the executor reads/writes fixed buffer entries per slot with no
    runtime keying. Both ring directions use the FULL ring: the wrap
    edges carry chunk boundaries (activations P-1 → 0, cotangents
    0 → P-1).

    ``uniform_collectives`` (round-5; default: auto-on when
    ``extra_manual_axes`` is non-empty) resolves the round-4
    "1f1b x virtual x sp deadlock": with a second manual-collective
    axis (the sp ring) inside the stage, the old ``lax.switch``
    backward made devices issue DIFFERENT collective sequences in the
    same tick (an F device: the stage's forward ring hops; a B device:
    forward-recompute + transposed hops; an idle device: none). XLA's
    collective rendezvous keys on (run_id, channel) — and JAX reuses
    one channel across these ops — so devices joined each other's
    rendezvous across different ops and cross-blocked 100%
    reproducibly on the CPU runtime (pp∈{2,4,8} x sp∈{2,4}); the same
    divergence is undefined behaviour on any keyed-collective backend.
    The uniform tick runs one vjp on EVERY device EVERY tick with
    masked (select) outputs, so the global collective sequence is
    identical on all devices by construction — plus explicit
    data-dependency chaining (``_collective_seq``) pinning
    act-hop -> cot-hop -> stage collectives within each tick and the
    ym_bar gather before the first hop. Cost: the F ticks' unused
    transpose (~2x backward stage compute); collective-free stages
    keep the cheap switch path.
    """
    from kubeflow_tpu.parallel.schedule1f1b import (
        build_schedule,
        check_schedule,
    )

    num_stages = mesh.shape[axis]
    if virtual_stages < 1:
        raise ValueError(
            f"virtual_stages must be >= 1, got {virtual_stages}"
        )
    act_spec = P() if activation_spec is None else activation_spec
    _validate(act_spec, output, num_microbatches, num_stages)
    if num_microbatches % num_stages:
        raise ValueError(
            f"interleaved schedule needs num_microbatches="
            f"{num_microbatches} divisible by pp={num_stages}"
        )
    has_extra = extra_spec is not None
    extra_in = (extra_spec,) if has_extra else ()
    sched = build_schedule(num_microbatches, num_stages, virtual_stages)
    check_schedule(sched)  # cheap at trace time; guards builder drift
    T = sched.num_slots
    kx, kc = sched.xbuf_slots, sched.cbuf_slots
    tbl = {
        name: jnp.asarray(getattr(sched, name))
        for name in ("action", "unit_v", "unit_m", "f_in", "b_in",
                     "b_cot", "act_store", "cot_store")
    }
    ring_f = [(i, (i + 1) % num_stages) for i in range(num_stages)]
    ring_r = [(i, (i - 1) % num_stages) for i in range(num_stages)]
    manual_axes = frozenset({axis, *extra_manual_axes})
    groups = num_microbatches // num_stages
    uniform = (
        bool(extra_manual_axes) if uniform_collectives is None
        else uniform_collectives
    )

    @partial(
        jax.shard_map,
        mesh=mesh,
        axis_names=manual_axes,
        in_specs=(P(axis), act_spec) + extra_in,
        out_specs=_out_spec(act_spec, axis, output),
        check_vma=False,
    )
    def fwd_sharded(stage_params, xm, *maybe_em):
        params = jax.tree.map(lambda p: jnp.squeeze(p, 0), stage_params)
        idx = jax.lax.axis_index(axis)
        return _interleaved_forward_ticks(
            stage_fn, params, xm, maybe_em[0] if maybe_em else None,
            idx, axis, num_stages, virtual_stages, groups, output,
        )

    @partial(
        jax.shard_map,
        mesh=mesh,
        axis_names=manual_axes,
        in_specs=(P(axis), act_spec) + extra_in
        + (_out_spec(act_spec, axis, output),),
        out_specs=(P(axis), act_spec),
        check_vma=False,
    )
    def bwd_sharded(stage_params, xm, *em_and_ybar):
        em = em_and_ybar[0] if has_extra else None
        ym_bar = em_and_ybar[-1]
        params = jax.tree.map(lambda p: jnp.squeeze(p, 0), stage_params)
        idx = jax.lax.axis_index(axis)
        if output == "sharded":
            ym_bar = jax.lax.all_gather(ym_bar, axis, axis=0, tiled=True)
        mb_shape = xm.shape[1:]
        zero_mb = jnp.zeros(mb_shape, xm.dtype)
        # Per-chunk zero gradient (the switch branches return one
        # chunk's worth; accumulation scatters it at the chunk index).
        zero_pv = jax.tree.map(
            lambda p: jnp.zeros(p.shape[1:], p.dtype), params
        )

        def store(buf, value, slot):
            safe = jnp.clip(slot, 0, buf.shape[0] - 1)
            keep = jax.lax.dynamic_index_in_dim(
                buf, safe, 0, keepdims=False
            )
            return jax.lax.dynamic_update_index_in_dim(
                buf, jnp.where(slot >= 0, value, keep), safe, 0
            )

        def load(buf, slot):
            return jax.lax.dynamic_index_in_dim(
                buf, jnp.clip(slot, 0, buf.shape[0] - 1), 0,
                keepdims=False,
            )

        def slot_step(carry, t):
            xbuf, cbuf, prev_act, prev_cot, dparams, dxm = carry
            # Deterministic global collective order within the tick:
            # act hop -> cot hop -> stage-internal (sp) collectives.
            # The hops are data-independent and the stage branches pull
            # their inputs from buffers that may bypass both, so on
            # backends with no collective stream order each device
            # could otherwise issue them in its own order and
            # cross-block (_collective_seq). The chain below makes the
            # order a data dependency on every device.
            recv_act = jax.lax.ppermute(prev_act, axis, ring_f)
            prev_cot = _collective_seq(prev_cot, recv_act)
            recv_cot = jax.lax.ppermute(prev_cot, axis, ring_r)
            xbuf = store(xbuf, recv_act, tbl["act_store"][t, idx])
            cbuf = store(cbuf, recv_cot, tbl["cot_store"][t, idx])
            act_code = tbl["action"][t, idx]
            v = jnp.clip(tbl["unit_v"][t, idx], 0, virtual_stages - 1)
            m = jnp.clip(tbl["unit_m"][t, idx], 0, xm.shape[0] - 1)
            params_v = jax.tree.map(
                lambda p: jax.lax.dynamic_index_in_dim(
                    p, v, 0, keepdims=False
                ),
                params,
            )
            x_own = jax.lax.dynamic_index_in_dim(
                xm, m, 0, keepdims=False
            )
            if em is None:
                run = stage_fn
            else:
                e_in = jax.lax.dynamic_index_in_dim(
                    em, m, 0, keepdims=False
                )
                run = lambda p, x: stage_fn(p, x, e_in)

            f_slot = tbl["f_in"][t, idx]
            b_slot = tbl["b_in"][t, idx]
            c_slot = tbl["b_cot"][t, idx]
            seed = jax.lax.dynamic_index_in_dim(
                ym_bar, jnp.clip(m, 0, ym_bar.shape[0] - 1), 0,
                keepdims=False,
            )

            if uniform:
                # Uniform-collective tick (sp-composed meshes): EVERY
                # device runs one vjp (forward recompute + transpose)
                # every tick and masks the outputs with selects, so the
                # stage's manual collectives (the sp ring, fwd AND
                # transposed) execute in an identical global sequence
                # on every device — branch-divergent collective counts
                # under lax.switch are what cross-blocked the CPU
                # rendezvous (and are undefined on any keyed-collective
                # backend). Costs one transpose on F ticks and one
                # fwd+transpose on idle ticks; idle is the bubble
                # fraction, so steady-state overhead is the F ticks'
                # unused transpose (~2x backward compute), bought for a
                # schedule that is correct by construction.
                is_f = act_code == 1
                is_b = act_code == 2
                x_in = jnp.where(
                    is_b,
                    jnp.where(b_slot >= 0, load(xbuf, b_slot), x_own),
                    jnp.where(f_slot >= 0, load(xbuf, f_slot), x_own),
                )
                cot = jnp.where(
                    c_slot >= 0, load(cbuf, c_slot), seed
                )
                x_in = _collective_seq(x_in, recv_cot)
                cot = _collective_seq(cot, recv_cot)
                y, vjp_fn = jax.vjp(run, params_v, x_in)
                dpv_raw, dx_raw = vjp_fn(cot)
                # Selects, not multiplies: garbage-input vjps may
                # produce non-finite values and 0*inf would leak.
                out_act = jnp.where(is_f, y, zero_mb)
                out_cot = jnp.where(is_b, dx_raw, zero_mb)
                dx = jnp.where(is_b, dx_raw, zero_mb)
                dpv = jax.tree.map(
                    lambda g, z: jnp.where(is_b, g, z), dpv_raw,
                    zero_pv,
                )
            else:
                def f_branch(_):
                    x_in = jnp.where(
                        f_slot >= 0, load(xbuf, f_slot), x_own
                    )
                    # Stage collectives ride on x_in; pin them after
                    # both hops even when x_in bypassed the buffers.
                    x_in = _collective_seq(x_in, recv_cot)
                    y = run(params_v, x_in)
                    return y, zero_mb, zero_pv, zero_mb

                def b_branch(_):
                    x_in = jnp.where(
                        b_slot >= 0, load(xbuf, b_slot), x_own
                    )
                    cot = jnp.where(
                        c_slot >= 0, load(cbuf, c_slot), seed
                    )
                    x_in = _collective_seq(x_in, recv_cot)
                    cot = _collective_seq(cot, recv_cot)
                    _, vjp_fn = jax.vjp(run, params_v, x_in)
                    dpv, dx = vjp_fn(cot)
                    return zero_mb, dx, dpv, dx

                def idle_branch(_):
                    return zero_mb, zero_mb, zero_pv, zero_mb

                out_act, out_cot, dpv, dx = jax.lax.switch(
                    act_code, [idle_branch, f_branch, b_branch], ()
                )
            dparams = jax.tree.map(
                lambda D, g: jax.lax.dynamic_update_index_in_dim(
                    D,
                    jax.lax.dynamic_index_in_dim(
                        D, v, 0, keepdims=False
                    ) + g,
                    v, 0,
                ),
                dparams, dpv,
            )
            # Stage-0 backwards emit the input cotangent.
            write_dx = jnp.logical_and(
                act_code == 2,
                jnp.logical_and(tbl["unit_v"][t, idx] == 0, idx == 0),
            )
            keep_dx = jax.lax.dynamic_index_in_dim(
                dxm, m, 0, keepdims=False
            )
            dxm = jax.lax.dynamic_update_index_in_dim(
                dxm, jnp.where(write_dx, dx, keep_dx), m, 0
            )
            return (xbuf, cbuf, out_act, out_cot, dparams, dxm), None

        # The first tick's hop must not race the ym_bar all-gather
        # above: the scan's init has no data dependency on ym_bar, so
        # on order-free backends some devices entered the (all-device)
        # hop while their partners sat in the (sp-group) gather —
        # observed as the round-4 cross-block. Chain the hop operands'
        # init on ym_bar so every device gathers first.
        init = (
            jnp.zeros((kx,) + mb_shape, xm.dtype),
            jnp.zeros((kc,) + mb_shape, xm.dtype),
            _collective_seq(zero_mb, ym_bar),
            _collective_seq(zero_mb, ym_bar),
            jax.tree.map(jnp.zeros_like, params),
            jnp.zeros_like(xm),
        )
        (_, _, _, _, dparams, dxm), _ = jax.lax.scan(
            slot_step, init, jnp.arange(T)
        )
        dxm = jax.lax.psum(
            jnp.where(idx == 0, dxm, jnp.zeros_like(dxm)), axis
        )
        # Stage params are REPLICATED over the extra manual axes (sp):
        # each peer's vjp holds only its sequence shard's contribution,
        # and the P(axis) out-spec would silently drop the rest — the
        # AD engines get this psum inserted by shard_map's transpose
        # automatically; the hand-scheduled backward must do it itself.
        for extra_axis in extra_manual_axes:
            dparams = jax.tree.map(
                lambda g: jax.lax.psum(g, extra_axis), dparams
            )
        dparams = jax.tree.map(lambda g: g[None], dparams)
        return dparams, dxm

    if has_extra:
        @jax.custom_vjp
        def pipeline(stage_params, xm, em):
            return fwd_sharded(stage_params, xm, em)

        def pipeline_fwd(stage_params, xm, em):
            return fwd_sharded(stage_params, xm, em), (
                stage_params, xm, em,
            )

        def pipeline_bwd(res, ym_bar):
            stage_params, xm, em = res
            dparams, dxm = bwd_sharded(stage_params, xm, em, ym_bar)
            dem = np.zeros(em.shape, jax.dtypes.float0)
            return dparams, dxm, dem
    else:
        @jax.custom_vjp
        def pipeline(stage_params, xm):
            return fwd_sharded(stage_params, xm)

        def pipeline_fwd(stage_params, xm):
            return fwd_sharded(stage_params, xm), (stage_params, xm)

        def pipeline_bwd(res, ym_bar):
            stage_params, xm = res
            return bwd_sharded(stage_params, xm, ym_bar)

    pipeline.defvjp(pipeline_fwd, pipeline_bwd)
    return _microbatched(pipeline, num_microbatches)


def stage_stack_interleaved(params, num_stages: int,
                            virtual_stages: int):
    """Reshape a depth-stacked layer pytree ``(L, ...)`` into the
    interleaved stage layout ``(P, V, L/(V*P), ...)``: global stage
    c = v*P + d holds layers [c*L/C, (c+1)*L/C) and lives at
    [d, v] — device-major round-robin, so consecutive chunks sit on
    consecutive devices and each device's chunks are P apart."""
    C = num_stages * virtual_stages

    def reshape(leaf):
        depth = leaf.shape[0]
        if depth % C:
            raise ValueError(
                f"layer stack depth {depth} not divisible by "
                f"pp*virtual={C} chunks"
            )
        # (L,) -> (V, P, L/C, ...): chunk v*P + d at [v, d]; swap to
        # device-major (P, V, ...).
        return leaf.reshape(
            virtual_stages, num_stages, depth // C, *leaf.shape[1:]
        ).swapaxes(0, 1)

    return jax.tree.map(reshape, params)


def stage_stack(params, num_stages: int):
    """Reshape a depth-stacked layer pytree ``(L, ...)`` into the stage
    layout ``(P, L/P, ...)`` gpipe shards: contiguous groups of L/P
    consecutive layers per stage (row-major reshape = stage order)."""

    def reshape(leaf):
        depth = leaf.shape[0]
        if depth % num_stages:
            raise ValueError(
                f"layer stack depth {depth} not divisible by "
                f"pp={num_stages} stages"
            )
        return leaf.reshape(num_stages, depth // num_stages, *leaf.shape[1:])

    return jax.tree.map(reshape, params)
