"""Profile controller: multi-tenant namespace materialisation + plugins.

Python half of the reference profile-controller (reference
controllers/profile_controller.go:105-336 Reconcile): desired state comes
from the native core (native/src/profile.cpp); this layer owns watches,
writes, the cloud-IAM plugin chain, and finalizer-style revocation.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Protocol

from kubeflow_tpu import native
from kubeflow_tpu.controllers.runtime import (
    Controller,
    Request,
    WatchSpec,
    ensure_object,
)
from kubeflow_tpu.k8s.fake import FakeApiServer, NotFound

log = logging.getLogger(__name__)

PROFILE_API = "kubeflow.org/v1"
FINALIZER = "profile-controller.kubeflow-tpu/cleanup"


class ProfilePlugin(Protocol):
    """Cloud-IAM plugin interface (reference profile_controller.go:78-84:
    ApplyPlugin/RevokePlugin). Implementations annotate the namespace's
    ServiceAccounts with cloud identities; Revoke undoes it when the
    Profile is deleted."""

    name: str

    def apply(self, api, profile: dict, spec: dict) -> None: ...
    def revoke(self, api, profile: dict, spec: dict) -> None: ...


class WorkloadIdentityPlugin:
    """GKE Workload Identity (reference plugin_workload_identity.go:32-52):
    binds default-editor to a GCP service account via the SA annotation.
    The IAM policy call is delegated to an injectable binder so tests and
    non-GCP clusters run without the cloud API."""

    name = "WorkloadIdentity"

    def __init__(self, iam_binder=None):
        self.iam_binder = iam_binder  # fn(gsa, member, add: bool)

    def _member(self, profile: dict) -> str:
        ns = profile["metadata"]["name"]
        return f"serviceAccount:[{ns}/default-editor]"

    def apply(self, api, profile: dict, spec: dict) -> None:
        gsa = spec.get("gcpServiceAccount", "")
        ns = profile["metadata"]["name"]
        sa = api.get("v1", "ServiceAccount", "default-editor", ns)
        annotations = sa["metadata"].setdefault("annotations", {})
        if annotations.get("iam.gke.io/gcp-service-account") != gsa:
            annotations["iam.gke.io/gcp-service-account"] = gsa
            api.update(sa)
        if self.iam_binder:
            self.iam_binder(gsa, self._member(profile), True)

    def revoke(self, api, profile: dict, spec: dict) -> None:
        if self.iam_binder:
            self.iam_binder(
                spec.get("gcpServiceAccount", ""), self._member(profile), False
            )


@dataclasses.dataclass
class ProfileOptions:
    userid_header: str = "kubeflow-userid"
    userid_prefix: str = ""
    namespace_labels: dict | None = None

    def to_native(self) -> dict:
        return {
            "userIdHeader": self.userid_header,
            "userIdPrefix": self.userid_prefix,
            "namespaceLabels": self.namespace_labels or {},
        }


class ProfileReconciler:
    def __init__(
        self,
        api: FakeApiServer,
        options: ProfileOptions | None = None,
        plugins: dict[str, ProfilePlugin] | None = None,
    ):
        self.api = api
        self.options = options or ProfileOptions()
        self.plugins = plugins or {}

    def _ensure(self, desired: dict) -> None:
        ensure_object(self.api, desired)

    def reconcile(self, req: Request) -> float | None:
        try:
            profile = self.api.get(PROFILE_API, "Profile", req.name)
        except NotFound:
            return None

        # Deletion: revoke plugins, then drop our finalizer (reference
        # profile_controller.go:297-331). Only act when OUR finalizer is
        # present — a foreign finalizer holding the object must not cause
        # a revoke/patch loop.
        if profile["metadata"].get("deletionTimestamp"):
            current = profile["metadata"].get("finalizers", [])
            if FINALIZER in current:
                self._revoke_plugins(profile)
                remaining = [f for f in current if f != FINALIZER]
                self.api.patch_merge(
                    PROFILE_API, "Profile", req.name,
                    {"metadata": {"finalizers": remaining or None}},
                )
            return None

        plugin_specs = (profile.get("spec") or {}).get("plugins") or []
        if plugin_specs and FINALIZER not in profile["metadata"].get(
            "finalizers", []
        ):
            self.api.patch_merge(
                PROFILE_API, "Profile", req.name,
                {
                    "metadata": {
                        "finalizers": profile["metadata"].get("finalizers", [])
                        + [FINALIZER]
                    }
                },
            )

        out = native.invoke(
            "profile_reconcile",
            {"profile": profile, "options": self.options.to_native()},
        )
        self._ensure(out["namespace"])
        for sa in out["serviceAccounts"]:
            self._ensure(sa)
        self._ensure(out["roleBinding"])
        self._ensure(out["authorizationPolicy"])
        if out["resourceQuota"] is not None:
            self._ensure(out["resourceQuota"])

        for spec in plugin_specs:
            kind = spec.get("kind", "")
            plugin = self.plugins.get(kind)
            if plugin is None:
                log.warning("profile %s: unknown plugin %r", req.name, kind)
                continue
            plugin.apply(self.api, profile, spec.get("spec", {}))
        return None

    def _revoke_plugins(self, profile: dict) -> None:
        for spec in (profile.get("spec") or {}).get("plugins") or []:
            plugin = self.plugins.get(spec.get("kind", ""))
            if plugin is not None:
                try:
                    plugin.revoke(self.api, profile, spec.get("spec", {}))
                except Exception:
                    log.exception(
                        "plugin revoke failed for %s",
                        profile["metadata"]["name"],
                    )


def make_profile_controller(
    api: FakeApiServer,
    options: ProfileOptions | None = None,
    plugins: dict[str, ProfilePlugin] | None = None,
) -> Controller:
    return Controller(
        name="profile-controller",
        api=api,
        reconciler=ProfileReconciler(api, options, plugins),
        watches=[WatchSpec(PROFILE_API, "Profile")],
    )
