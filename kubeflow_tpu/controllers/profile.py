"""Profile controller: multi-tenant namespace materialisation + plugins.

Python half of the reference profile-controller (reference
controllers/profile_controller.go:105-336 Reconcile): desired state comes
from the native core (native/src/profile.cpp); this layer owns watches,
writes, the cloud-IAM plugin chain, and finalizer-style revocation.
"""

from __future__ import annotations

import copy
import dataclasses
import logging
from typing import Protocol

from kubeflow_tpu import native
from kubeflow_tpu.controllers.runtime import (
    Controller,
    Request,
    WatchSpec,
    ensure_object,
)
from kubeflow_tpu.k8s.fake import FakeApiServer, NotFound

log = logging.getLogger(__name__)

PROFILE_API = "kubeflow.org/v1"
FINALIZER = "profile-controller.kubeflow-tpu/cleanup"


class ProfilePlugin(Protocol):
    """Cloud-IAM plugin interface (reference profile_controller.go:78-84:
    ApplyPlugin/RevokePlugin). Implementations annotate the namespace's
    ServiceAccounts with cloud identities; Revoke undoes it when the
    Profile is deleted."""

    name: str

    def apply(self, api, profile: dict, spec: dict) -> None: ...
    def revoke(self, api, profile: dict, spec: dict) -> None: ...


class WorkloadIdentityPlugin:
    """GKE Workload Identity (reference plugin_workload_identity.go:32-52):
    binds default-editor to a GCP service account via the SA annotation.
    The IAM policy call is delegated to an injectable binder so tests and
    non-GCP clusters run without the cloud API."""

    name = "WorkloadIdentity"

    def __init__(self, iam_binder=None):
        self.iam_binder = iam_binder  # fn(gsa, member, add: bool)

    def _member(self, profile: dict) -> str:
        ns = profile["metadata"]["name"]
        return f"serviceAccount:[{ns}/default-editor]"

    def apply(self, api, profile: dict, spec: dict) -> None:
        gsa = spec.get("gcpServiceAccount", "")
        ns = profile["metadata"]["name"]
        sa = api.get("v1", "ServiceAccount", "default-editor", ns)
        annotations = sa["metadata"].setdefault("annotations", {})
        if annotations.get("iam.gke.io/gcp-service-account") != gsa:
            annotations["iam.gke.io/gcp-service-account"] = gsa
            api.update(sa)
        if self.iam_binder:
            self.iam_binder(gsa, self._member(profile), True)

    def revoke(self, api, profile: dict, spec: dict) -> None:
        if self.iam_binder:
            self.iam_binder(
                spec.get("gcpServiceAccount", ""), self._member(profile), False
            )


AWS_ANNOTATION_KEY = "eks.amazonaws.com/role-arn"
AWS_DEFAULT_AUDIENCE = "sts.amazonaws.com"
DEFAULT_SERVICE_ACCOUNT = "default-editor"
# Subject that can never appear in a real token (namespace is empty):
# written when the last trusted subject is revoked.
NO_TRUST_SENTINEL = "system:serviceaccount::none"


def role_name_from_arn(arn: str) -> str:
    """``arn:aws:iam::<acct>:role/<path>/<name>`` → ``<name>``. IAM's
    RoleName parameter excludes the path, so take the last segment
    (deliberate divergence from the reference's first-'/' split,
    plugin_iam.go getIAMRoleNameFromIAMRoleArn, which breaks on roles
    created under an IAM path)."""
    return arn.rsplit("/", 1)[-1]


def issuer_url_from_provider_arn(arn: str) -> str:
    """``arn:aws:iam::<acct>:oidc-provider/<issuer>`` → ``<issuer>``
    (reference plugin_iam.go:257-260)."""
    return arn[arn.index("/") + 1:] if "/" in arn else arn


def _edit_trust_policy(
    policy: dict, namespace: str, sa: str, add: bool
) -> tuple[dict, bool]:
    """Add/remove ``system:serviceaccount:<ns>:<sa>`` in the first
    statement's ``Condition.StringEquals[<issuer>:sub]`` list (the
    web-identity statement the reference edits — plugin_iam.go
    addServiceAccountInAssumeRolePolicy/remove...:141-255). Unlike the
    reference's full-document rebuild, this is an in-place edit: extra
    statements, non-StringEquals conditions, and custom aud values are
    preserved. Returns (new_policy, changed)."""
    new_policy = copy.deepcopy(policy)
    # The web-identity statement is the one with a Federated principal —
    # not necessarily Statement[0] (an EC2 trust statement may precede it).
    stmt = next(
        (
            s
            for s in new_policy.get("Statement", [])
            if (s.get("Principal") or {}).get("Federated")
        ),
        None,
    )
    if stmt is None:
        if not add:
            return policy, False  # nothing to revoke
        raise ValueError(
            "trust policy has no web-identity (Federated) statement to edit"
        )
    federated = stmt["Principal"]["Federated"]
    issuer = issuer_url_from_provider_arn(federated)
    sub_key = f"{issuer}:sub"
    conditions = stmt.setdefault("Condition", {}).setdefault(
        "StringEquals", {}
    )
    subjects = conditions.get(sub_key, [])
    if isinstance(subjects, str):
        subjects = [subjects]
    identity = f"system:serviceaccount:{namespace}:{sa}"
    if add:
        if identity in subjects:
            return policy, False
        subjects = [s for s in subjects if s != NO_TRUST_SENTINEL] + [identity]
        conditions.setdefault(f"{issuer}:aud", [AWS_DEFAULT_AUDIENCE])
    else:
        if identity not in subjects:
            return policy, False
        subjects = [s for s in subjects if s != identity]
        if not subjects:
            # IAM rejects empty condition lists (MalformedPolicyDocument),
            # and dropping the :sub key entirely would leave an aud-only
            # condition that ANY service account's token could satisfy.
            # Pin a subject that can never match (namespaces are nonempty
            # in real tokens) so the statement is a safe deny.
            subjects = [NO_TRUST_SENTINEL]
    conditions[sub_key] = subjects
    return new_policy, True


class AwsIamForServiceAccountPlugin:
    """IAM Roles for Service Accounts on EKS (reference plugin_iam.go
    AwsIAMForServiceAccount:22-118): annotates default-editor with the
    role ARN and inserts the namespace's service account into the role's
    web-identity trust policy. The AWS API calls are delegated to an
    injectable client (``get_assume_role_policy(role_name) -> dict``,
    ``update_assume_role_policy(role_name, policy: dict)``) so tests and
    non-AWS clusters run without the cloud SDK."""

    name = "AwsIamForServiceAccount"

    def __init__(self, iam_client=None):
        self.iam_client = iam_client

    def _annotate(self, api, namespace: str, role_arn: str | None) -> None:
        sa = api.get("v1", "ServiceAccount", DEFAULT_SERVICE_ACCOUNT, namespace)
        annotations = sa["metadata"].setdefault("annotations", {})
        if role_arn is None:
            if AWS_ANNOTATION_KEY not in annotations:
                return
            del annotations[AWS_ANNOTATION_KEY]
        else:
            if annotations.get(AWS_ANNOTATION_KEY) == role_arn:
                return
            annotations[AWS_ANNOTATION_KEY] = role_arn
        api.update(sa)

    def _edit_iam(self, spec: dict, namespace: str, add: bool) -> None:
        if spec.get("annotateOnly") or self.iam_client is None:
            return
        role = role_name_from_arn(spec["awsIamRole"])
        policy = self.iam_client.get_assume_role_policy(role)
        new_policy, changed = _edit_trust_policy(
            policy, namespace, DEFAULT_SERVICE_ACCOUNT, add
        )
        if changed:
            self.iam_client.update_assume_role_policy(role, new_policy)

    def apply(self, api, profile: dict, spec: dict) -> None:
        role_arn = spec.get("awsIamRole", "")
        if not role_arn:
            raise ValueError(
                "failed to setup service account because awsIamRole is empty"
            )
        ns = profile["metadata"]["name"]
        self._annotate(api, ns, role_arn)
        self._edit_iam(spec, ns, add=True)

    def revoke(self, api, profile: dict, spec: dict) -> None:
        ns = profile["metadata"]["name"]
        # IAM cleanup first: if the namespace/SA is already gone (cascade
        # racing the finalizer) the annotation step is a no-op, but the
        # trust-policy subject must still be removed — a stale subject
        # would grant a later re-created namespace of the same name
        # AssumeRoleWithWebIdentity access.
        self._edit_iam(spec, ns, add=False)
        try:
            self._annotate(api, ns, None)
        except NotFound:
            pass


class NamespaceLabelsFile:
    """Hot-reloaded default-namespace-labels file (reference
    profile_controller.go:370-425: fsnotify watch on the labels file;
    every change re-reconciles all Profiles so running namespaces pick
    up the new label set). mtime-polled from the controller's loop tick
    instead of inotify — same behaviour, no platform dependency.

    File format: a YAML map of label -> value (the reference's
    namespace-labels.yaml ConfigMap format)."""

    def __init__(self, path):
        import pathlib

        self.path = pathlib.Path(path)
        self._mtime: float | None = None
        self._stat_err: str | None = None
        self.labels: dict = {}
        self.load()

    def _stat(self) -> tuple[float | None, str | None]:
        """(mtime, error). A transient OSError (e.g. EACCES during a
        ConfigMap remount) is a distinct observed state, not a crash —
        changed()/load() treat it like any other state transition so
        the one-attempt-per-change guard holds."""
        try:
            return self.path.stat().st_mtime, None
        except FileNotFoundError:
            return None, None
        except OSError as exc:
            return None, f"{type(exc).__name__}: {exc}"

    def load(self) -> None:
        import yaml

        mtime, err = self._stat()
        prev_err, self._mtime, self._stat_err = self._stat_err, mtime, err
        if err is not None:
            if err != prev_err:
                log.warning("namespace labels file %s unreadable (%s); "
                            "keeping previous labels", self.path, err)
            return
        if mtime is None:
            self.labels = {}
            return
        try:
            data = yaml.safe_load(self.path.read_text())
        except Exception:
            # Malformed or unreadable content (invalid YAML, mid-write
            # read, EACCES on open): keep the previous label set rather
            # than killing the controller loop; _mtime was already
            # advanced above so this is one attempt per file change,
            # not a retry storm.
            log.exception("namespace labels file %s unreadable; keeping "
                          "previous labels", self.path)
            return
        if not isinstance(data, dict):
            log.warning("namespace labels file %s is not a YAML map; "
                        "treating as empty", self.path)
            data = {}
        self.labels = {str(k): str(v) for k, v in data.items() if v is not None}

    def changed(self) -> bool:
        mtime, err = self._stat()
        return (mtime, err) != (self._mtime, self._stat_err)


@dataclasses.dataclass
class ProfileOptions:
    userid_header: str = "kubeflow-userid"
    userid_prefix: str = ""
    namespace_labels: dict | None = None

    def to_native(self) -> dict:
        return {
            "userIdHeader": self.userid_header,
            "userIdPrefix": self.userid_prefix,
            "namespaceLabels": self.namespace_labels or {},
        }


class ProfileReconciler:
    def __init__(
        self,
        api: FakeApiServer,
        options: ProfileOptions | None = None,
        plugins: dict[str, ProfilePlugin] | None = None,
    ):
        self.api = api
        self.options = options or ProfileOptions()
        self.plugins = plugins or {}

    def _ensure(self, desired: dict) -> None:
        ensure_object(self.api, desired)

    def reconcile(self, req: Request) -> float | None:
        try:
            profile = self.api.get(PROFILE_API, "Profile", req.name)
        except NotFound:
            return None

        # Deletion: revoke plugins, then drop our finalizer (reference
        # profile_controller.go:297-331). Only act when OUR finalizer is
        # present — a foreign finalizer holding the object must not cause
        # a revoke/patch loop.
        if profile["metadata"].get("deletionTimestamp"):
            current = profile["metadata"].get("finalizers", [])
            if FINALIZER in current:
                self._revoke_plugins(profile)
                remaining = [f for f in current if f != FINALIZER]
                self.api.patch_merge(
                    PROFILE_API, "Profile", req.name,
                    {"metadata": {"finalizers": remaining or None}},
                )
            return None

        plugin_specs = (profile.get("spec") or {}).get("plugins") or []
        if plugin_specs and FINALIZER not in profile["metadata"].get(
            "finalizers", []
        ):
            self.api.patch_merge(
                PROFILE_API, "Profile", req.name,
                {
                    "metadata": {
                        "finalizers": profile["metadata"].get("finalizers", [])
                        + [FINALIZER]
                    }
                },
            )

        out = native.invoke(
            "profile_reconcile",
            {"profile": profile, "options": self.options.to_native()},
        )
        self._ensure(out["namespace"])
        for sa in out["serviceAccounts"]:
            self._ensure(sa)
        self._ensure(out["roleBinding"])
        self._ensure(out["authorizationPolicy"])
        if out["resourceQuota"] is not None:
            self._ensure(out["resourceQuota"])

        for spec in plugin_specs:
            kind = spec.get("kind", "")
            plugin = self.plugins.get(kind)
            if plugin is None:
                log.warning("profile %s: unknown plugin %r", req.name, kind)
                continue
            plugin.apply(self.api, profile, spec.get("spec", {}))
        return None

    def _revoke_plugins(self, profile: dict) -> None:
        for spec in (profile.get("spec") or {}).get("plugins") or []:
            plugin = self.plugins.get(spec.get("kind", ""))
            if plugin is not None:
                try:
                    plugin.revoke(self.api, profile, spec.get("spec", {}))
                except Exception:
                    log.exception(
                        "plugin revoke failed for %s",
                        profile["metadata"]["name"],
                    )


def make_profile_controller(
    api: FakeApiServer,
    options: ProfileOptions | None = None,
    plugins: dict[str, ProfilePlugin] | None = None,
    labels_file: str | None = None,
) -> Controller:
    options = options or ProfileOptions()
    reconciler = ProfileReconciler(api, options, plugins)
    controller = Controller(
        name="profile-controller",
        api=api,
        reconciler=reconciler,
        watches=[WatchSpec(PROFILE_API, "Profile")],
    )
    if labels_file is not None:
        watcher = NamespaceLabelsFile(labels_file)
        options.namespace_labels = dict(watcher.labels)

        def maybe_reload():
            if watcher.changed():
                watcher.load()
                options.namespace_labels = dict(watcher.labels)
                # Re-reconcile every Profile under the new label set
                # (the reference's fsnotify -> reconcile-all).
                controller.resync()

        controller.tick_hooks.append(maybe_reload)
    return controller
