"""Controller-manager observability: Prometheus metrics + health endpoints.

Capability parity with the reference controller metrics
(reference notebook-controller/pkg/metrics/metrics.go:22-99 — the
`notebook_running` gauge is computed by scraping the StatefulSet list at
collect time; create/cull counters are event-driven — and
profile-controller/controllers/monitoring.go:25-60 — request/heartbeat
counters) plus the manager's healthz/readyz endpoints
(reference notebook-controller/main.go:124-132).

Everything hangs off one ``ControllerMetrics`` registry that a manager
process shares across its controllers, exposed by ``ManagerServer`` on
``/metrics`` (Prometheus text exposition), ``/healthz`` and ``/readyz``.
"""

from __future__ import annotations

import http.server
import logging
import threading
import time
from typing import Callable, Iterable

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)
from prometheus_client.core import (
    CounterMetricFamily,
    Exemplar,
    GaugeMetricFamily,
    HistogramMetricFamily,
)
from prometheus_client.openmetrics import exposition as om_exposition

from kubeflow_tpu.k8s.fake import FakeApiServer
from kubeflow_tpu.obs import export as obs_export

log = logging.getLogger(__name__)


def bucket_tuples_with_exemplars(snap: dict) -> list:
    """BucketHistogram snapshot -> ``add_metric`` bucket tuples, with
    each captured exemplar attached as the OpenMetrics triple
    ``(le, count, Exemplar)``. The classic text exposition ignores the
    third element; the OpenMetrics renderer emits it as
    ``# {trace_id="..."} value timestamp`` on the bucket line."""
    exemplars = snap.get("exemplars") or {}
    out = []
    for le, count in snap["buckets"]:
        ex = exemplars.get(le)
        if ex:
            out.append((le, count, Exemplar(
                {"trace_id": ex["trace_id"]}, ex["value"], ex["ts"]
            )))
        else:
            out.append((le, count))
    return out


class RunningNotebooksCollector:
    """`notebook_running{namespace}` — recomputed from the live
    StatefulSet list at every scrape, exactly like the reference's
    collect-time scrape (metrics.go:82-99): an STS counts as a running
    notebook when its pod-template label ``notebook-name`` equals its own
    name."""

    def __init__(self, api: FakeApiServer):
        self.api = api
        self._last_good: dict[str, int] = {}

    def describe(self):
        return []

    def collect(self):
        fam = GaugeMetricFamily(
            "notebook_running",
            "Current running notebooks in the cluster",
            labels=["namespace"],
        )
        try:
            stss = self.api.list("apps/v1", "StatefulSet")
        except Exception as exc:
            # The scrape must outlive the apiserver: during an outage
            # /metrics is exactly where operators look (breaker state,
            # retry counters), so a failed LIST serves the last good
            # gauge instead of killing the whole exposition.
            log.warning("notebook_running scrape: list failed (%s); "
                        "serving last-known values", exc)
            stss = None
        if stss is None:
            per_ns = self._last_good
        else:
            per_ns = {}
            for sts in stss:
                labels = (
                    ((sts.get("spec") or {}).get("template") or {})
                    .get("metadata", {})
                    .get("labels", {})
                ) or {}
                if labels.get("notebook-name") == sts["metadata"]["name"]:
                    ns = sts["metadata"].get("namespace", "")
                    per_ns[ns] = per_ns.get(ns, 0) + 1
            self._last_good = per_ns
        for ns, count in sorted(per_ns.items()):
            fam.add_metric([ns], count)
        yield fam


class QueueDepthCollector:
    """`workqueue_depth{controller}` over the manager's controllers —
    the controller-runtime workqueue metric equivalent."""

    def __init__(self, controllers: Iterable):
        self.controllers = list(controllers)

    def describe(self):
        return []

    def collect(self):
        fam = GaugeMetricFamily(
            "workqueue_depth",
            "Pending reconcile requests per controller",
            labels=["controller"],
        )
        for ctrl in self.controllers:
            fam.add_metric([ctrl.name], len(ctrl.queue))
        yield fam


class ClientResilienceCollector:
    """ApiClient retry/circuit-breaker state on ``/metrics``: how hard
    the client is fighting to reach the apiserver. Read at scrape time
    from the live client (k8s/retry.py) — the breaker state gauge is
    the first thing to check when reconciles stall cluster-wide."""

    _STATE_VALUE = {"closed": 0, "half-open": 1, "open": 2}

    def __init__(self, client):
        self.client = client

    def describe(self):
        return []

    def collect(self):
        m = self.client.request_metrics
        yield CounterMetricFamily(
            "apiserver_client_request",
            "Apiserver round-trips attempted by this client",
            value=m["requests"],
        )
        yield CounterMetricFamily(
            "apiserver_client_retry",
            "Transient-failure retries issued by this client",
            value=m["retries"],
        )
        budget = self.client.retry_budget
        yield CounterMetricFamily(
            "apiserver_client_retry_budget_exhausted",
            "Retries suppressed because the client retry budget was dry",
            value=budget.exhausted_total,
        )
        breaker = self.client.breaker
        yield GaugeMetricFamily(
            "apiserver_client_circuit_breaker_state",
            "Circuit breaker state: 0 closed, 1 half-open, 2 open",
            value=self._STATE_VALUE.get(breaker.state, 0),
        )
        yield CounterMetricFamily(
            "apiserver_client_circuit_breaker_open",
            "Times the circuit breaker tripped open",
            value=breaker.opens_total,
        )
        yield CounterMetricFamily(
            "apiserver_client_circuit_breaker_fast_fail",
            "Requests fast-failed while the breaker was open",
            value=breaker.fast_fail_total,
        )
        # Round-trip latency per verb: the client keeps dependency-free
        # BucketHistograms (it cannot import prometheus_client); the
        # snapshot renders as a real histogram family at scrape time.
        snapshot = getattr(self.client, "duration_snapshot", None)
        if callable(snapshot):
            fam = HistogramMetricFamily(
                "apiserver_client_request_duration_seconds",
                "Apiserver round-trip wall time per attempt "
                "(retries observed individually)",
                labels=["verb"],
            )
            for verb, snap in sorted(snapshot().items()):
                fam.add_metric(
                    [verb],
                    buckets=bucket_tuples_with_exemplars(snap),
                    sum_value=snap["sum"],
                )
            yield fam


class ControllerMetrics:
    """The manager-wide registry plus the event-driven counters the
    reconcilers increment."""

    def __init__(self, api: FakeApiServer | None = None):
        self.registry = CollectorRegistry()
        if api is not None:
            self.registry.register(RunningNotebooksCollector(api))
            # Real ApiClient (or a chaos wrapper around one): expose its
            # retry/breaker state next to the controller metrics.
            if hasattr(api, "breaker") and hasattr(api, "request_metrics"):
                self.registry.register(ClientResilienceCollector(api))
        self.notebook_create_total = Counter(
            "notebook_create",
            "Total times of creating notebooks",
            ["namespace"],
            registry=self.registry,
        )
        self.notebook_create_failed_total = Counter(
            "notebook_create_failed",
            "Total failure times of creating notebooks",
            ["namespace"],
            registry=self.registry,
        )
        self.notebook_culling_total = Counter(
            "notebook_culling",
            "Total times of culling notebooks",
            ["namespace", "name"],
            registry=self.registry,
        )
        self.last_culling_timestamp = Gauge(
            "last_notebook_culling_timestamp_seconds",
            "Timestamp of the last notebook culling in seconds",
            ["namespace", "name"],
            registry=self.registry,
        )
        # Label discipline: object identity is namespace/name, the
        # emitting controller is "controller" — the canonical schema
        # (obs.metrics.CANONICAL_LABELS) shared with the dashboard and
        # CRUD-app registries and asserted by tests/test_obs.py. The
        # pre-obs "component" spelling is gone.
        self.request_total = Counter(
            "request_kf",
            "Number of reconcile-driven API requests",
            ["controller", "kind"],
            registry=self.registry,
        )
        self.request_failure_total = Counter(
            "request_kf_failure",
            "Number of failed reconcile-driven API requests",
            ["controller", "kind", "severity"],
            registry=self.registry,
        )
        self.service_heartbeat = Counter(
            "service_heartbeat",
            "Heartbeat signal indicating the manager is alive",
            ["controller", "severity"],
            registry=self.registry,
        )
        self.reconcile_total = Counter(
            "controller_reconcile",
            "Reconcile invocations per controller and result",
            ["controller", "result"],
            registry=self.registry,
        )
        self.reconcile_stuck_total = Counter(
            "controller_reconcile_stuck",
            "Reconciles flagged by the stuck-reconcile watchdog "
            "(mode: failures = consecutive-failure threshold, "
            "deadline = per-reconcile deadline exceeded)",
            ["controller", "mode"],
            registry=self.registry,
        )
        self.notebook_preemption_restart_total = Counter(
            "notebook_preemption_restart",
            "Coherent full-slice restarts after a TPU worker was "
            "preempted or evicted",
            ["namespace"],
            registry=self.registry,
        )
        self.notebook_reshard_total = Counter(
            "notebook_reshard",
            "Elastic topology transitions: the StatefulSet was "
            "re-emitted at a different slice shape (mode: degrade = "
            "down the fallback ladder, promote = back up)",
            ["namespace", "mode"],
            registry=self.registry,
        )
        self.inference_preemption_restart_total = Counter(
            "inferenceservice_preemption_restart",
            "Coherent full-slice restarts of an InferenceService "
            "after a TPU worker was preempted or evicted",
            ["namespace"],
            registry=self.registry,
        )
        # The latency dimension (PR 3): counters say a reconcile
        # happened; these say where the time went. Queue duration is
        # due→dequeue (controller-runtime's
        # workqueue_queue_duration_seconds — scheduled requeue delays
        # and parked backoff excluded), observed by the WorkQueue via
        # the latency_observer hook the Controller wires up — same
        # bounds as the queue's own BucketHistogram so the two views
        # of one distribution cannot diverge.
        from kubeflow_tpu.obs.metrics import LATENCY_BUCKETS

        _duration_buckets = LATENCY_BUCKETS
        self.reconcile_duration = Histogram(
            "controller_reconcile_duration_seconds",
            "Wall time of one reconcile invocation",
            ["controller"],
            registry=self.registry,
            buckets=_duration_buckets,
        )
        self.queue_duration = Histogram(
            "workqueue_queue_duration_seconds",
            "Seconds a reconcile request waited in the workqueue after "
            "becoming due (scheduled requeue delays and parked backoff "
            "excluded)",
            ["controller"],
            registry=self.registry,
            buckets=_duration_buckets,
        )

    def watch_controllers(self, controllers: Iterable) -> None:
        self.registry.register(QueueDepthCollector(controllers))

    def exposition(self, openmetrics: bool = False) -> bytes:
        # OpenMetrics is the format that carries exemplars (bucket ->
        # trace-id links); the classic 0.0.4 text stays the default so
        # existing scrapers see byte-compatible output.
        if openmetrics:
            return om_exposition.generate_latest(self.registry)
        return generate_latest(self.registry)


class ManagerServer:
    """Threaded HTTP server for /metrics, /healthz, /readyz (reference
    main.go:124-132 health endpoints + controller-runtime's metrics
    listener). ``ready`` is the manager's initial-sync signal."""

    def __init__(
        self,
        metrics: ControllerMetrics,
        port: int = 0,
        ready: Callable[[], bool] | None = None,
        enable_debug: bool = False,
        tracer=None,
        slo=None,
        fleet_api=None,
        profilers: dict | None = None,
        recorder=None,
        scheduler=None,
    ):
        self.metrics = metrics
        self.ready = ready or (lambda: True)
        # The stack-dump endpoint exposes source paths and execution
        # state; like controller-runtime's pprof listener it is strictly
        # opt-in (KFT_ENABLE_DEBUG_ENDPOINTS=true in a manager binary).
        # The trace endpoints (/debug/traces, /debug/timeline/<ns>/<n>)
        # sit behind the same gate and read the tracer's in-memory ring.
        self.enable_debug = enable_debug
        self.tracer = tracer
        # SLO surfaces (PR 9): ``slo`` is an obs.SloEngine; ``fleet_api``
        # any duck-typed api handle the fleet rollup can LIST through.
        # /fleet is a health surface like /readyz (NOT debug-gated);
        # /debug/alerts carries full alert history and sits behind the
        # debug gate with the other operator-forensics endpoints.
        self.slo = slo
        self.fleet_api = fleet_api
        # Continuous-profiling surfaces (PR 10): ``profilers`` maps
        # controller name -> PhaseProfiler (reconcile phase digests);
        # ``recorder`` is the manager-shared FlightRecorder whose ring
        # /debug/flightrecord serves live. Both sit behind the same
        # debug gate as the pprof-role endpoints.
        self.profilers = profilers or {}
        self.recorder = recorder
        # Slice-pool scheduler (PR 12): /debug/scheduler serves its
        # queue/pool document behind the same debug gate; the /fleet
        # rollup carries its pool-utilisation block.
        self.scheduler = scheduler
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            disable_nagle_algorithm = True  # scrape latency (client.py)

            def log_message(self, *args):  # quiet
                pass

            def do_GET(self):
                if self.path == "/metrics":
                    # A scrape is also a cheap liveness tick for the
                    # SLO engine (self-rate-limited), so alerts advance
                    # even when no controller loop is running.
                    if outer.slo is not None:
                        outer.slo.tick()
                    accept = self.headers.get("Accept", "")
                    openmetrics = "application/openmetrics-text" in accept
                    body = outer.metrics.exposition(
                        openmetrics=openmetrics
                    )
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        om_exposition.CONTENT_TYPE_LATEST if openmetrics
                        else "text/plain; version=0.0.4",
                    )
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path == "/fleet" and (
                    outer.fleet_api is not None or outer.slo is not None
                ):
                    import json

                    body = json.dumps(
                        outer.fleet_doc(), indent=1, default=str
                    ).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.end_headers()
                    self.wfile.write(body)
                elif (
                    self.path == "/debug/alerts"
                    and outer.enable_debug
                    and outer.slo is not None
                ):
                    import json

                    outer.slo.tick()
                    body = json.dumps(
                        outer.slo.alerts.to_dict(), indent=1, default=str
                    ).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.end_headers()
                    self.wfile.write(body)
                elif (
                    self.path == "/debug/scheduler"
                    and outer.enable_debug
                    and outer.scheduler is not None
                ):
                    import json

                    body = json.dumps(
                        outer.scheduler.to_dict(), indent=1, default=str
                    ).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path == "/debug/profile" and outer.enable_debug:
                    # Per-controller reconcile phase digests (list /
                    # desired-state / patch / status / total) plus the
                    # process-wide device-memory watermark when the
                    # backend exposes one (None on CPU control planes).
                    import json

                    from kubeflow_tpu.obs import profile as obs_profile

                    body = json.dumps({
                        "controllers": {
                            name: prof.snapshot()
                            for name, prof in sorted(
                                outer.profilers.items())
                        },
                        "memory": obs_profile.process_watermark(),
                    }, indent=1, default=str).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.end_headers()
                    self.wfile.write(body)
                elif (
                    self.path == "/debug/flightrecord"
                    and outer.enable_debug
                    and outer.recorder is not None
                ):
                    import json

                    body = json.dumps(
                        outer.recorder.to_dict(), indent=1, default=str
                    ).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path == "/healthz":
                    self.send_response(200)
                    self.end_headers()
                    self.wfile.write(b"ok")
                elif self.path == "/debug/threads" and outer.enable_debug:
                    # pprof-style live-thread dump (the reference gets
                    # this from controller-runtime's pprof listener).
                    import sys
                    import traceback

                    lines = []
                    for tid, frame in sys._current_frames().items():
                        lines.append(f"--- thread {tid} ---")
                        lines.extend(
                            line.rstrip()
                            for line in traceback.format_stack(frame)
                        )
                    body = ("\n".join(lines) + "\n").encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain")
                    self.end_headers()
                    self.wfile.write(body)
                elif (
                    self.path == "/debug/tracemalloc" and outer.enable_debug
                ):
                    # pprof heap-profile role: first hit arms
                    # tracemalloc, later hits report the top allocation
                    # sites since then.
                    import tracemalloc

                    if not tracemalloc.is_tracing():
                        tracemalloc.start()
                        body = b"tracemalloc started; GET again for stats\n"
                    else:
                        snap = tracemalloc.take_snapshot()
                        stats = snap.statistics("lineno")[:25]
                        total_kib = sum(s.size for s in stats) / 1024
                        lines = [
                            f"top {len(stats)} allocation sites "
                            f"({total_kib:.0f} KiB shown)"
                        ] + [str(s) for s in stats]
                        body = ("\n".join(lines) + "\n").encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain")
                    self.end_headers()
                    self.wfile.write(body)
                elif (
                    self.path == "/debug/traces"
                    and outer.enable_debug
                    and outer.tracer is not None
                ):
                    import json

                    body = json.dumps(obs_export.trace_summaries(
                        outer.tracer.ring.spans()
                    ), indent=1).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.end_headers()
                    self.wfile.write(body)
                elif (
                    self.path.startswith("/debug/timeline/")
                    and outer.enable_debug
                    and outer.tracer is not None
                ):
                    # /debug/timeline/<namespace>/<name>: the latest
                    # trace that touched the object, as a span tree.
                    import json

                    parts = self.path.split("/")
                    tl = None
                    if len(parts) == 5 and parts[3] and parts[4]:
                        tl = obs_export.timeline(
                            outer.tracer.ring.spans(), parts[3], parts[4]
                        )
                    if tl is None:
                        self.send_response(404)
                        self.end_headers()
                        self.wfile.write(b"no trace for that object\n")
                    else:
                        body = json.dumps(tl, indent=1).encode()
                        self.send_response(200)
                        self.send_header(
                            "Content-Type", "application/json"
                        )
                        self.end_headers()
                        self.wfile.write(body)
                elif self.path == "/readyz":
                    ok = outer.ready()
                    self.send_response(200 if ok else 503)
                    self.end_headers()
                    self.wfile.write(b"ok" if ok else b"not ready")
                else:
                    self.send_response(404)
                    self.end_headers()

            def do_POST(self):
                # First-HTTP-touch resurrect (the scheduler's touch()
                # contract): JWA details pages / gateway front doors
                # POST /touch/<namespace>/<name>[?kind=InferenceService]
                # when a user first hits a Suspended workload — the
                # scheduler re-enqueues it and the reconciler's resume
                # handshake brings it back from its parked checkpoint.
                # Debug-gated like the other operator surfaces: the
                # production front door sits inside the mesh.
                import json
                from urllib.parse import parse_qs, urlparse

                parsed = urlparse(self.path)
                parts = parsed.path.split("/")
                if (
                    len(parts) == 4
                    and parts[1] == "touch"
                    and parts[2] and parts[3]
                    and outer.enable_debug
                    and outer.scheduler is not None
                ):
                    kind = (parse_qs(parsed.query).get("kind")
                            or ["Notebook"])[0]
                    if kind not in ("Notebook", "InferenceService"):
                        self.send_response(400)
                        self.end_headers()
                        self.wfile.write(b"unknown kind\n")
                        return
                    resurrected = outer.scheduler.touch(
                        kind, parts[2], parts[3]
                    )
                    body = json.dumps({
                        "kind": kind,
                        "namespace": parts[2],
                        "name": parts[3],
                        "resurrected": bool(resurrected),
                    }).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self.send_response(404)
                    self.end_headers()

        self._httpd = http.server.ThreadingHTTPServer(("0.0.0.0", port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread: threading.Thread | None = None

    def fleet_doc(self) -> dict:
        """The ``/fleet`` document: per-namespace health cards over the
        live CRs, overlaid with the SLO engine's alert state. Also
        callable directly (tests, other surfaces)."""
        from kubeflow_tpu.obs import fleet as obs_fleet

        alerts = None
        if self.slo is not None:
            self.slo.tick()
            alerts = self.slo.alerts
        if self.fleet_api is not None:
            doc = obs_fleet.fleet_cards(self.fleet_api, alerts=alerts,
                                        scheduler=self.scheduler)
        else:
            # Same schema as fleet_cards, just with nothing to list —
            # consumers must not need to know which branch served them.
            doc = {"namespaces": {},
                   "alerts": alerts.active() if alerts else [],
                   "generated_at": time.time()}
        if self.slo is not None:
            doc["slo"] = self.slo.status()
        return doc

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="manager-http", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
