"""Lease-based leader election for controller managers.

The role controller-runtime's leader election plays in the reference
(reference notebook-controller/main.go:66-93, --leader-elect flag wired
into ctrl.Options.LeaderElection with a per-controller lease id): only
one replica of a manager reconciles at a time; a crashed leader's lease
expires and a standby takes over, which is the whole failure-recovery
story for the control plane (level-based reconciliation re-derives all
state on takeover).

Implemented against the coordination.k8s.io/v1 Lease API shape with
optimistic concurrency: acquire/renew is a read-modify-update on one
Lease object; a Conflict means another candidate won the race and the
loser backs off. ``clock`` is injectable so expiry is testable without
sleeping.

Fleet scale adds the horizontal layer (:class:`ShardedElector`): the
reconcile keyspace hashes into ``KFT_SHARDS`` shards
(:func:`shard_of` over ``namespace/name``), each shard guarded by its
own Lease. A manager replica acquires a *subset* of the shard leases —
its fair share, ``ceil(shards / live_replicas)``, where the live
replica count is read off the lease holders themselves — so N replicas
split the fleet with no external membership service, and membership
changes rebalance by the same quota rule: a replica holding more than
its share voluntarily releases surplus shards for the newcomer.
Handoff is disciplined through a :class:`ShardGate` (see
``controllers/runtime.py``): a released shard stops popping, drains
its in-flight reconcile, and only then frees the lease; the successor
resyncs the shard before reconciling it. One shard (``KFT_SHARDS=1``)
degenerates to exactly the single :class:`LeaderElector` above —
lease name, election rounds and callbacks byte-identical.
"""

from __future__ import annotations

import hashlib
import math
import os
import threading
import time
from typing import Callable

from kubeflow_tpu.controllers.time_utils import parse_rfc3339, rfc3339
from kubeflow_tpu.k8s.fake import ApiError, FakeApiServer, NotFound

LEASE_API = "coordination.k8s.io/v1"


def shard_count(default: int = 1) -> int:
    """``KFT_SHARDS``: how many per-shard leases the control plane
    runs behind (1 / unset = the classic single-leader manager)."""
    raw = os.environ.get("KFT_SHARDS", "")
    try:
        return max(1, int(raw))
    except ValueError:
        return max(1, int(default))


def shard_of(namespace: str, name: str, shards: int) -> int:
    """Stable shard for a reconcile key. sha1 over ``namespace/name``
    (NOT Python ``hash()``, which is per-process salted — every
    replica must agree on the mapping or two leaders would both own a
    key)."""
    if shards <= 1:
        return 0
    digest = hashlib.sha1(f"{namespace}/{name}".encode()).digest()
    return int.from_bytes(digest[:4], "big") % shards


class LeaderElector:
    def __init__(
        self,
        api: FakeApiServer,
        lease_name: str,
        identity: str,
        namespace: str = "kubeflow",
        lease_duration_s: float = 15.0,
        retry_period_s: float = 2.0,
        clock: Callable[[], float] = time.time,
        on_started_leading: Callable[[], None] | None = None,
        on_stopped_leading: Callable[[], None] | None = None,
    ):
        self.api = api
        self.lease_name = lease_name
        self.identity = identity
        self.namespace = namespace
        self.lease_duration_s = lease_duration_s
        self.retry_period_s = retry_period_s
        self.clock = clock
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self._leading = False
        self._stop = threading.Event()
        # Locally-observed renewal tracking (client-go leaderelection
        # semantics): expiry is measured from when *this* candidate first
        # saw the current (resourceVersion, renewTime), not from the
        # holder's clock — tolerates inter-replica clock skew, so a
        # standby with a fast clock cannot prematurely steal a healthy
        # leader's lease.
        self._observed: tuple | None = None
        self._observed_at: float = 0.0

    @property
    def is_leader(self) -> bool:
        return self._leading

    def _lease_obj(self, transitions: int) -> dict:
        now = rfc3339(int(self.clock()))
        return {
            "apiVersion": LEASE_API,
            "kind": "Lease",
            "metadata": {"name": self.lease_name, "namespace": self.namespace},
            "spec": {
                "holderIdentity": self.identity,
                "leaseDurationSeconds": int(self.lease_duration_s),
                "renewTime": now,
                "acquireTime": now,
                "leaseTransitions": transitions,
            },
        }

    def _observe(self, lease: dict) -> None:
        """Record when this candidate first saw the lease's current
        renewal; a changed (holderIdentity, renewTime) restarts the
        locally-measured expiry window. Spec fields only — keying on
        resourceVersion would let unrelated metadata writes (kubectl
        annotate, policy controllers) keep resetting the window and
        block failover from a wedged leader forever."""
        spec = lease.get("spec") or {}
        key = (spec.get("holderIdentity"), spec.get("renewTime"))
        if key != self._observed:
            self._observed = key
            self._observed_at = self.clock()

    def _expired(self, lease: dict) -> bool:
        spec = lease.get("spec") or {}
        renew = parse_rfc3339(spec.get("renewTime", ""))
        if renew is None:
            return True
        duration = spec.get("leaseDurationSeconds", self.lease_duration_s)
        return self.clock() - self._observed_at > duration

    def try_acquire_or_renew(self) -> bool:
        """One election round. Returns whether this candidate now leads.
        Called periodically (every retry_period_s when standby, well
        inside lease_duration_s when leading)."""
        try:
            lease = self.api.get(
                LEASE_API, "Lease", self.lease_name, self.namespace
            )
        except NotFound:
            try:
                self.api.create(self._lease_obj(transitions=0))
                self._set_leading(True)
                return True
            except ApiError:
                self._set_leading(False)
                return False

        self._observe(lease)
        holder = (lease.get("spec") or {}).get("holderIdentity")
        # An empty holder marks a voluntarily released lease (see
        # release()) — acquirable without waiting out observed expiry.
        if not holder or holder == self.identity or self._expired(lease):
            transitions = (lease.get("spec") or {}).get("leaseTransitions", 0)
            if holder != self.identity:
                transitions += 1
            desired = self._lease_obj(transitions)
            if holder == self.identity:
                # Renewal keeps the original acquireTime.
                desired["spec"]["acquireTime"] = (lease.get("spec") or {}).get(
                    "acquireTime", desired["spec"]["acquireTime"]
                )
            desired["metadata"]["resourceVersion"] = lease["metadata"][
                "resourceVersion"
            ]
            try:
                self.api.update(desired)
                self._set_leading(True)
                return True
            except ApiError:
                # Lost the takeover race, or (when we led) our renew
                # raced a takeover after expiry: step down.
                self._set_leading(False)
                return False
        self._set_leading(False)
        return False

    def _set_leading(self, leading: bool) -> None:
        if leading and not self._leading:
            self._leading = True
            if self.on_started_leading:
                self.on_started_leading()
        elif not leading and self._leading:
            self._leading = False
            if self.on_stopped_leading:
                self.on_stopped_leading()

    def release(self) -> None:
        """Voluntary step-down on clean shutdown (controller-runtime's
        ReleaseOnCancel): zero the renewTime so a standby takes over
        immediately instead of waiting out the lease."""
        if not self._leading:
            return
        try:
            lease = self.api.get(
                LEASE_API, "Lease", self.lease_name, self.namespace
            )
            if (lease.get("spec") or {}).get("holderIdentity") == self.identity:
                # Empty holder = released (client-go convention); expiry
                # is measured from *observation* locally, so a past
                # renewTime alone would not signal standbys.
                lease["spec"]["holderIdentity"] = ""
                lease["spec"]["renewTime"] = rfc3339(
                    int(self.clock() - self.lease_duration_s - 1)
                )
                self.api.update(lease)
        except ApiError:
            pass
        self._set_leading(False)

    def run_forever(self) -> None:
        while not self._stop.is_set():
            self.try_acquire_or_renew()
            self._stop.wait(self.retry_period_s)
        self.release()

    def start(self) -> threading.Thread:
        thread = threading.Thread(
            target=self.run_forever,
            name=f"leader-elect-{self.lease_name}",
            daemon=True,
        )
        thread.start()
        return thread

    def stop(self) -> None:
        self._stop.set()


class ShardedElector:
    """N per-shard leases, one :class:`LeaderElector` each.

    Lease names are ``<lease_name>-shard-<i>``; with ``shards == 1``
    the single lease keeps the bare ``lease_name`` so the one-shard
    configuration is indistinguishable from the classic single-leader
    manager on the wire. ``on_acquired(shard)`` / ``on_lost(shard)``
    fire on ownership transitions (the manager points them at a
    :class:`~kubeflow_tpu.controllers.runtime.ShardGate`).

    Rebalance rule: each round counts the distinct *live* lease
    holders (non-expired, by this candidate's local observation — the
    same skew-tolerant clock discipline the single elector uses) plus
    itself, takes ``quota = ceil(shards / replicas)``, acquires
    free/expired shards only while below quota, and releases its
    highest-numbered surplus shards when membership grew. Released and
    lost shards hand off through ``gate``: new pops stop first, the
    in-flight reconcile drains, and only then is the lease freed — so
    a voluntary handoff can never dual-reconcile a key. (Involuntary
    expiry of a wedged leader keeps the classic mitigation: the lease
    duration must exceed the reconcile deadline.)
    """

    def __init__(
        self,
        api: FakeApiServer,
        lease_name: str,
        identity: str,
        shards: int,
        namespace: str = "kubeflow",
        lease_duration_s: float = 15.0,
        retry_period_s: float = 2.0,
        clock: Callable[[], float] = time.time,
        gate=None,
        on_acquired: Callable[[int], None] | None = None,
        on_lost: Callable[[int], None] | None = None,
        drain_timeout_s: float = 10.0,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self.api = api
        self.identity = identity
        self.lease_name = lease_name
        self.shards = max(1, int(shards))
        self.namespace = namespace
        self.lease_duration_s = lease_duration_s
        self.retry_period_s = retry_period_s
        self.clock = clock
        self.gate = gate
        self.on_acquired = on_acquired
        self.on_lost = on_lost
        self.drain_timeout_s = drain_timeout_s
        self._sleep = sleep
        self._stop = threading.Event()
        # One elector per shard, fixed at construction.
        # analysis: allow[py-unbounded-deque]
        self.electors: list[LeaderElector] = []
        for i in range(self.shards):
            name = (lease_name if self.shards == 1
                    else f"{lease_name}-shard-{i}")
            self.electors.append(LeaderElector(
                api, name, identity,
                namespace=namespace,
                lease_duration_s=lease_duration_s,
                retry_period_s=retry_period_s,
                clock=clock,
                on_started_leading=self._started_cb(i),
                on_stopped_leading=self._stopped_cb(i),
            ))

    def _started_cb(self, shard: int):
        def cb():
            if self.gate is not None:
                self.gate.on_acquired(shard)
            if self.on_acquired is not None:
                self.on_acquired(shard)
        return cb

    def _stopped_cb(self, shard: int):
        def cb():
            if self.gate is not None:
                self.gate.on_lost(shard)
            if self.on_lost is not None:
                self.on_lost(shard)
        return cb

    def owned(self) -> frozenset[int]:
        return frozenset(
            i for i, e in enumerate(self.electors) if e.is_leader
        )

    @property
    def is_leader(self) -> bool:
        """Leads *something* — the manager readiness notion."""
        return any(e.is_leader for e in self.electors)

    # ---- membership heartbeat --------------------------------------------
    @property
    def _member_prefix(self) -> str:
        return f"{self.lease_name}-member-"

    def _heartbeat(self) -> None:
        """Renew this replica's member lease. Shard leases alone can't
        discover a standby holding NOTHING — without the heartbeat a
        saturated incumbent would never see the newcomer and never
        release its surplus shards. The member lease carries no
        authority (exclusion is the shard leases' job); it only feeds
        the fair-share quota."""
        name = f"{self._member_prefix}{self.identity}"
        now = rfc3339(int(self.clock()))
        desired = {
            "apiVersion": LEASE_API,
            "kind": "Lease",
            "metadata": {"name": name, "namespace": self.namespace},
            "spec": {
                "holderIdentity": self.identity,
                "leaseDurationSeconds": int(self.lease_duration_s),
                "renewTime": now,
            },
        }
        try:
            try:
                cur = self.api.get(LEASE_API, "Lease", name,
                                   self.namespace)
                desired["metadata"]["resourceVersion"] = (
                    cur["metadata"]["resourceVersion"]
                )
                self.api.update(desired)
            except NotFound:
                self.api.create(desired)
        except ApiError:
            pass  # missed heartbeat: tolerated within the expiry window

    def _live_members(self) -> set[str]:
        """Identities with a fresh member lease. Expiry is judged
        renewTime vs our clock with a 2x duration allowance — a wrong
        count only skews the balance quota, never shard exclusion."""
        members: set[str] = set()
        try:
            leases = self.api.list(LEASE_API, "Lease",
                                   namespace=self.namespace)
        except ApiError:
            return members
        for lease in leases:
            name = (lease.get("metadata") or {}).get("name", "")
            if not name.startswith(self._member_prefix):
                continue
            spec = lease.get("spec") or {}
            renew = parse_rfc3339(spec.get("renewTime", ""))
            if renew is None:
                continue
            if self.clock() - renew <= 2 * self.lease_duration_s:
                holder = spec.get("holderIdentity")
                if holder:
                    members.add(holder)
        return members

    # ---- one election round ---------------------------------------------
    def _observe_membership(self) -> tuple[set[str], list[int]]:
        """Read every shard lease once: the set of live holder
        identities (self included) and the shards with no live holder
        (free or expired — acquirable this round)."""
        holders = {self.identity}
        acquirable: list[int] = []
        for i, elector in enumerate(self.electors):
            if elector.is_leader:
                continue
            try:
                lease = self.api.get(
                    LEASE_API, "Lease", elector.lease_name, self.namespace
                )
            except NotFound:
                acquirable.append(i)
                continue
            except ApiError:
                continue  # unreadable this round: neither count nor take
            elector._observe(lease)
            holder = (lease.get("spec") or {}).get("holderIdentity")
            if holder and not elector._expired(lease):
                holders.add(holder)
            else:
                acquirable.append(i)
        return holders, acquirable

    def try_acquire_or_renew(self) -> frozenset[int]:
        """One sharded round: heartbeat membership, renew held leases,
        then acquire up to the fair-share quota, then release surplus
        (membership grew). Returns the shards owned after the round."""
        self._heartbeat()
        for elector in self.electors:
            if elector.is_leader:
                elector.try_acquire_or_renew()  # renew (may step down)
        holders, acquirable = self._observe_membership()
        holders |= self._live_members()
        quota = max(1, math.ceil(self.shards / max(1, len(holders))))
        owned = sorted(i for i, e in enumerate(self.electors)
                       if e.is_leader)
        # Sorted, not raw set order: which shards a replica grabs when
        # quota-limited must not depend on per-process set ordering, or
        # two replays of the same membership timeline diverge.
        for i in sorted(acquirable):
            if len(owned) >= quota:
                break
            if self.electors[i].try_acquire_or_renew():
                owned.append(i)
        # Rebalance on membership change: release highest-numbered
        # surplus shards so the newcomer's acquirable scan finds them.
        while len(owned) > quota:
            self.release_shard(owned.pop())
        return self.owned()

    def release_shard(self, shard: int) -> None:
        """Disciplined voluntary handoff of one shard: stop new pops,
        drain the in-flight reconcile, then free the lease. Without
        the drain, a successor could acquire and reconcile a key the
        old owner is still mid-reconcile on."""
        elector = self.electors[shard]
        if not elector.is_leader:
            return
        if self.gate is not None:
            self.gate.begin_drain(shard)
            # Iteration-bounded, not wall-clock-bounded: with an
            # injected no-op sleep (the simulated-time pattern) a
            # wall deadline would busy-spin for real seconds; a poll
            # budget stays bounded under any sleep implementation.
            polls = max(1, int(self.drain_timeout_s / 0.005))
            for _ in range(polls):
                if self.gate.in_flight(shard) == 0:
                    break
                self._sleep(0.005)
        elector.release()

    def release(self) -> None:
        for shard in sorted(self.owned()):
            self.release_shard(shard)
        # Clean shutdown deregisters the member heartbeat: survivors'
        # fair-share quota grows immediately instead of waiting out
        # the membership expiry window (a crash-stop still expires).
        try:
            self.api.delete(
                LEASE_API, "Lease",
                f"{self._member_prefix}{self.identity}",
                self.namespace,
            )
        except (NotFound, ApiError):
            pass

    # ---- thread driver ----------------------------------------------------
    def run_forever(self) -> None:
        while not self._stop.is_set():
            self.try_acquire_or_renew()
            self._stop.wait(self.retry_period_s)
        self.release()

    def start(self) -> threading.Thread:
        thread = threading.Thread(
            target=self.run_forever,
            name=f"shard-elect-{self.identity}",
            daemon=True,
        )
        thread.start()
        return thread

    def stop(self) -> None:
        self._stop.set()
