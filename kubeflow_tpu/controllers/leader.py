"""Lease-based leader election for controller managers.

The role controller-runtime's leader election plays in the reference
(reference notebook-controller/main.go:66-93, --leader-elect flag wired
into ctrl.Options.LeaderElection with a per-controller lease id): only
one replica of a manager reconciles at a time; a crashed leader's lease
expires and a standby takes over, which is the whole failure-recovery
story for the control plane (level-based reconciliation re-derives all
state on takeover).

Implemented against the coordination.k8s.io/v1 Lease API shape with
optimistic concurrency: acquire/renew is a read-modify-update on one
Lease object; a Conflict means another candidate won the race and the
loser backs off. ``clock`` is injectable so expiry is testable without
sleeping.
"""

from __future__ import annotations

import threading
import time
from typing import Callable

from kubeflow_tpu.controllers.time_utils import parse_rfc3339, rfc3339
from kubeflow_tpu.k8s.fake import ApiError, FakeApiServer, NotFound

LEASE_API = "coordination.k8s.io/v1"


class LeaderElector:
    def __init__(
        self,
        api: FakeApiServer,
        lease_name: str,
        identity: str,
        namespace: str = "kubeflow",
        lease_duration_s: float = 15.0,
        retry_period_s: float = 2.0,
        clock: Callable[[], float] = time.time,
        on_started_leading: Callable[[], None] | None = None,
        on_stopped_leading: Callable[[], None] | None = None,
    ):
        self.api = api
        self.lease_name = lease_name
        self.identity = identity
        self.namespace = namespace
        self.lease_duration_s = lease_duration_s
        self.retry_period_s = retry_period_s
        self.clock = clock
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self._leading = False
        self._stop = threading.Event()
        # Locally-observed renewal tracking (client-go leaderelection
        # semantics): expiry is measured from when *this* candidate first
        # saw the current (resourceVersion, renewTime), not from the
        # holder's clock — tolerates inter-replica clock skew, so a
        # standby with a fast clock cannot prematurely steal a healthy
        # leader's lease.
        self._observed: tuple | None = None
        self._observed_at: float = 0.0

    @property
    def is_leader(self) -> bool:
        return self._leading

    def _lease_obj(self, transitions: int) -> dict:
        now = rfc3339(int(self.clock()))
        return {
            "apiVersion": LEASE_API,
            "kind": "Lease",
            "metadata": {"name": self.lease_name, "namespace": self.namespace},
            "spec": {
                "holderIdentity": self.identity,
                "leaseDurationSeconds": int(self.lease_duration_s),
                "renewTime": now,
                "acquireTime": now,
                "leaseTransitions": transitions,
            },
        }

    def _observe(self, lease: dict) -> None:
        """Record when this candidate first saw the lease's current
        renewal; a changed (holderIdentity, renewTime) restarts the
        locally-measured expiry window. Spec fields only — keying on
        resourceVersion would let unrelated metadata writes (kubectl
        annotate, policy controllers) keep resetting the window and
        block failover from a wedged leader forever."""
        spec = lease.get("spec") or {}
        key = (spec.get("holderIdentity"), spec.get("renewTime"))
        if key != self._observed:
            self._observed = key
            self._observed_at = self.clock()

    def _expired(self, lease: dict) -> bool:
        spec = lease.get("spec") or {}
        renew = parse_rfc3339(spec.get("renewTime", ""))
        if renew is None:
            return True
        duration = spec.get("leaseDurationSeconds", self.lease_duration_s)
        return self.clock() - self._observed_at > duration

    def try_acquire_or_renew(self) -> bool:
        """One election round. Returns whether this candidate now leads.
        Called periodically (every retry_period_s when standby, well
        inside lease_duration_s when leading)."""
        try:
            lease = self.api.get(
                LEASE_API, "Lease", self.lease_name, self.namespace
            )
        except NotFound:
            try:
                self.api.create(self._lease_obj(transitions=0))
                self._set_leading(True)
                return True
            except ApiError:
                self._set_leading(False)
                return False

        self._observe(lease)
        holder = (lease.get("spec") or {}).get("holderIdentity")
        # An empty holder marks a voluntarily released lease (see
        # release()) — acquirable without waiting out observed expiry.
        if not holder or holder == self.identity or self._expired(lease):
            transitions = (lease.get("spec") or {}).get("leaseTransitions", 0)
            if holder != self.identity:
                transitions += 1
            desired = self._lease_obj(transitions)
            if holder == self.identity:
                # Renewal keeps the original acquireTime.
                desired["spec"]["acquireTime"] = (lease.get("spec") or {}).get(
                    "acquireTime", desired["spec"]["acquireTime"]
                )
            desired["metadata"]["resourceVersion"] = lease["metadata"][
                "resourceVersion"
            ]
            try:
                self.api.update(desired)
                self._set_leading(True)
                return True
            except ApiError:
                # Lost the takeover race, or (when we led) our renew
                # raced a takeover after expiry: step down.
                self._set_leading(False)
                return False
        self._set_leading(False)
        return False

    def _set_leading(self, leading: bool) -> None:
        if leading and not self._leading:
            self._leading = True
            if self.on_started_leading:
                self.on_started_leading()
        elif not leading and self._leading:
            self._leading = False
            if self.on_stopped_leading:
                self.on_stopped_leading()

    def release(self) -> None:
        """Voluntary step-down on clean shutdown (controller-runtime's
        ReleaseOnCancel): zero the renewTime so a standby takes over
        immediately instead of waiting out the lease."""
        if not self._leading:
            return
        try:
            lease = self.api.get(
                LEASE_API, "Lease", self.lease_name, self.namespace
            )
            if (lease.get("spec") or {}).get("holderIdentity") == self.identity:
                # Empty holder = released (client-go convention); expiry
                # is measured from *observation* locally, so a past
                # renewTime alone would not signal standbys.
                lease["spec"]["holderIdentity"] = ""
                lease["spec"]["renewTime"] = rfc3339(
                    int(self.clock() - self.lease_duration_s - 1)
                )
                self.api.update(lease)
        except ApiError:
            pass
        self._set_leading(False)

    def run_forever(self) -> None:
        while not self._stop.is_set():
            self.try_acquire_or_renew()
            self._stop.wait(self.retry_period_s)
        self.release()

    def start(self) -> threading.Thread:
        thread = threading.Thread(
            target=self.run_forever,
            name=f"leader-elect-{self.lease_name}",
            daemon=True,
        )
        thread.start()
        return thread

    def stop(self) -> None:
        self._stop.set()
