"""RFC3339 helpers shared by controllers and tests (the annotation time
format the reference uses throughout its culler —
reference culling_controller.go:266-272)."""

from __future__ import annotations

import datetime


def rfc3339(epoch: int | float) -> str:
    return datetime.datetime.fromtimestamp(
        int(epoch), tz=datetime.timezone.utc
    ).strftime("%Y-%m-%dT%H:%M:%SZ")


def parse_rfc3339(text: str) -> int | None:
    try:
        return int(
            datetime.datetime.strptime(text, "%Y-%m-%dT%H:%M:%SZ")
            .replace(tzinfo=datetime.timezone.utc)
            .timestamp()
        )
    except (ValueError, TypeError):
        return None
