"""Tensorboard controller (reference tensorboard-controller/controllers/
tensorboard_controller.go): Tensorboard CR → Deployment + Service +
VirtualService, with RWO-PVC node affinity and status from the
Deployment's conditions. Serves JAX profiler traces in this platform
(tensorboard-plugin-profile in the image)."""

from __future__ import annotations

import dataclasses
import logging

from kubeflow_tpu import native
from kubeflow_tpu.controllers.runtime import (
    Controller,
    Request,
    WatchSpec,
    ensure_object,
)
from kubeflow_tpu.k8s.fake import FakeApiServer, NotFound

log = logging.getLogger(__name__)

TENSORBOARD_API = "tensorboard.kubeflow.org/v1alpha1"


@dataclasses.dataclass
class TensorboardOptions:
    """TENSORBOARD_IMAGE / RWO_PVC_SCHEDULING env parity (reference
    tensorboard_controller.go:172,476-486)."""

    tensorboard_image: str = "tensorflow/tensorflow:2.15.0"
    use_istio: bool = False
    istio_gateway: str = "kubeflow/kubeflow-gateway"
    istio_host: str = "*"
    cluster_domain: str = "cluster.local"
    rwo_pvc_scheduling: bool = True


def find_rwo_node(api, namespace: str, claim: str) -> str:
    """Node already mounting the RWO claim (reference :208-232): the new
    pod must land there or stay Pending forever."""
    for pod in api.list("v1", "Pod", namespace=namespace):
        for vol in (pod.get("spec") or {}).get("volumes") or []:
            pvc = vol.get("persistentVolumeClaim") or {}
            if pvc.get("claimName") == claim:
                node = (pod.get("spec") or {}).get("nodeName", "")
                if node:
                    return node
    return ""


class TensorboardReconciler:
    def __init__(self, api: FakeApiServer, options: TensorboardOptions | None = None):
        self.api = api
        self.options = options or TensorboardOptions()

    def _ensure(self, desired: dict) -> None:
        ensure_object(self.api, desired)

    def reconcile(self, req: Request) -> float | None:
        try:
            tb = self.api.get(TENSORBOARD_API, "Tensorboard", req.name,
                              req.namespace)
        except NotFound:
            return None

        options = {
            "tensorboardImage": self.options.tensorboard_image,
            "useIstio": self.options.use_istio,
            "istioGateway": self.options.istio_gateway,
            "istioHost": self.options.istio_host,
            "clusterDomain": self.options.cluster_domain,
        }
        logspath = (tb.get("spec") or {}).get("logspath", "")
        if self.options.rwo_pvc_scheduling and logspath.startswith("pvc://"):
            claim = logspath[6:].split("/", 1)[0]
            node = find_rwo_node(self.api, req.namespace, claim)
            if node:
                options["rwoPvcNode"] = node

        out = native.invoke(
            "tensorboard_reconcile", {"tensorboard": tb, "options": options}
        )
        self._ensure(out["deployment"])
        self._ensure(out["service"])
        if out["virtualService"] is not None:
            self._ensure(out["virtualService"])

        # Status: mirror Deployment readiness.
        try:
            deployment = self.api.get("apps/v1", "Deployment", req.name,
                                      req.namespace)
        except NotFound:
            deployment = {}
        ready = (deployment.get("status") or {}).get("readyReplicas", 0)
        status = {
            "readyReplicas": ready,
            "conditions": (deployment.get("status") or {}).get("conditions", []),
        }
        if tb.get("status") != status:
            self.api.patch_merge(
                TENSORBOARD_API, "Tensorboard", req.name, {"status": status},
                req.namespace,
            )
        return None


def deployment_to_tensorboard(obj: dict):
    meta = obj.get("metadata", {})
    name = (meta.get("labels") or {}).get("app")
    if not name:
        return []
    return [Request(meta.get("namespace", ""), name)]


def make_tensorboard_controller(
    api: FakeApiServer, options: TensorboardOptions | None = None
) -> Controller:
    return Controller(
        name="tensorboard-controller",
        api=api,
        reconciler=TensorboardReconciler(api, options),
        watches=[
            WatchSpec(TENSORBOARD_API, "Tensorboard"),
            WatchSpec("apps/v1", "Deployment", deployment_to_tensorboard),
        ],
    )
