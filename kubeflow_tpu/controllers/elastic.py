"""Elastic slice topology: degraded-mode policy for TPU notebooks.

PR 4 closed the preempt → all-or-nothing restart → auto-resume loop,
but only onto the *exact original topology*: when a preemption leaves a
smaller node pool, the restarted workers sit Pending while valid
checkpoints age on disk. This module is the platform half of the fix
(ROADMAP item 5): an opt-in **fallback ladder** of smaller canonical
shapes the reconciler may re-emit the StatefulSet at, so training
resumes on what the cluster can actually schedule — and climbs back up
when capacity regrows.

State machine, driven once per reconcile from observed pods:

- **degrade**: expected workers Unschedulable for longer than the
  grace period (`elastic-grace-s`, the wait-for-full-shape window) →
  step one rung down the ladder, re-emit the StatefulSet at the new
  replica count / chip limits, stamp the new world size and surface
  ``status.phase=Resharding`` until the new shape is fully running.
- **promote**: running degraded and the promote interval
  (`elastic-promote-after-s`) elapsed → optimistically step one rung
  up (a reconciler cannot see free capacity for nodes that do not
  exist — it probes). If the bigger shape sits Unschedulable past the
  grace period, the degrade arm steps back down; the probe interval
  bounds the flap rate.

The data plane needs no handshake beyond what PR 4 built: the re-
emitted pods carry the new world-size env, ``run_with_checkpointing``
auto-resumes, and the checkpoint manager treats the topology-
fingerprint mismatch as an explicit cross-topology restore
(``MeshSpec.refactor`` + sharding-aware assembly re-lay params and
optimizer state onto the new mesh).

Annotations (user-facing):

- ``elastic-ladder``: opt-in; ``"auto"`` derives successive halvings
  (:func:`kubeflow_tpu.topology.fallback_ladder`) or an explicit
  ``"v5e-8,v5e-4"`` list.
- ``elastic-grace-s`` / ``elastic-promote-after-s``: the two timers.

Annotations (controller-owned state): ``elastic-shape`` (current rung,
absent = spec shape), ``elastic-world-size`` (hosts at the current
shape), ``elastic-pending-since``, ``elastic-promote-at``,
``reshard-reason`` (in-flight transition, mirrored to status).
"""

from __future__ import annotations

import dataclasses
import logging

from kubeflow_tpu import topology
from kubeflow_tpu.controllers.time_utils import parse_rfc3339, rfc3339
from kubeflow_tpu.topology import TopologyError, TpuSlice

log = logging.getLogger(__name__)

_NS = "notebooks.kubeflow-tpu.org"

ELASTIC_LADDER_KEY = f"{_NS}/elastic-ladder"
ELASTIC_GRACE_KEY = f"{_NS}/elastic-grace-s"
ELASTIC_PROMOTE_AFTER_KEY = f"{_NS}/elastic-promote-after-s"

ELASTIC_SHAPE_KEY = f"{_NS}/elastic-shape"
ELASTIC_WORLD_SIZE_KEY = f"{_NS}/elastic-world-size"
ELASTIC_PENDING_SINCE_KEY = f"{_NS}/elastic-pending-since"
ELASTIC_PROMOTE_AT_KEY = f"{_NS}/elastic-promote-at"
RESHARD_REASON_KEY = f"{_NS}/reshard-reason"

# Controller-owned bookkeeping, cleared when the opt-in goes away.
STATE_KEYS = (
    ELASTIC_SHAPE_KEY,
    ELASTIC_WORLD_SIZE_KEY,
    ELASTIC_PENDING_SINCE_KEY,
    ELASTIC_PROMOTE_AT_KEY,
    RESHARD_REASON_KEY,
)

DEFAULT_GRACE_S = 120.0
DEFAULT_PROMOTE_AFTER_S = 300.0


@dataclasses.dataclass
class ElasticDecision:
    """One reconcile pass's elastic verdict."""

    # The shape the StatefulSet must be emitted at THIS pass (the spec
    # shape unless a rung is active).
    effective: TpuSlice
    # metadata.annotations merge patch (None values delete); empty =
    # nothing to write.
    patches: dict
    # (reason, message, event_type) to record, transition-gated.
    events: list
    # Non-None while a shape transition is in flight → status.phase=
    # Resharding with this message.
    reshard_reason: str | None
    # True when ``effective`` IS the spec shape (rung 0) — the single
    # source of that judgement; callers must not re-derive it from
    # topology strings.
    at_spec_shape: bool = True


def _unschedulable(pod: dict) -> bool:
    """Explicitly Unschedulable (the scheduler said so) — a pod that is
    merely young and still Pending is not capacity evidence."""
    status = pod.get("status") or {}
    if status.get("phase") not in (None, "Pending"):
        return False
    return any(
        cond.get("type") == "PodScheduled"
        and cond.get("status") == "False"
        and cond.get("reason", "Unschedulable") == "Unschedulable"
        for cond in status.get("conditions") or []
    )


def _runs_shape(pod: dict, effective: TpuSlice) -> bool:
    """Is this pod a *running worker of the effective shape*? Phase
    Running alone is not enough: after a transition, the previous
    shape's workers are still Running with the OLD template — they are
    not the new world until the rolling replacement lands. Two
    template facts identify the shape: the per-host chip limit AND the
    world-size env (``KFT_NUM_PROCESSES``) — the limit alone cannot
    tell adjacent multi-host rungs apart (every multi-host shape of a
    generation shares chips_per_host). Facts that are not visible on
    the pod count as matching (never block a transition on data we
    cannot see)."""
    if (pod.get("status") or {}).get("phase") != "Running":
        return False
    for container in (pod.get("spec") or {}).get("containers") or []:
        limit = ((container.get("resources") or {}).get("limits")
                 or {}).get("google.com/tpu")
        try:
            if limit is not None and \
                    int(limit) != effective.chips_per_replica:
                return False
        except (TypeError, ValueError):
            pass
        for env in container.get("env") or []:
            if env.get("name") == "KFT_NUM_PROCESSES" and \
                    "value" in env:
                if str(env["value"]) != str(effective.num_hosts):
                    return False
    return True


def _seconds(anns: dict, key: str, default: float) -> float:
    try:
        value = float(anns[key])
        return value if value >= 0 else default
    except (KeyError, TypeError, ValueError):
        return default


def _demotion_advised(gate, current: TpuSlice) -> bool:
    """Consult the gate's proactive demotion arm (``should_demote``
    duck type — :class:`kubeflow_tpu.autopilot.ElasticPromotionGate`
    wired to the scheduler's pool view). Opposite fail-safe to the
    promote arm: a broken or absent gate must never reshape a healthy
    running slice, so any failure reads as "hold the shape"."""
    if not hasattr(gate, "should_demote"):
        return False
    try:
        return bool(gate.should_demote(current))
    except Exception:
        log.warning(
            "elastic demotion gate failed; holding the current shape",
            exc_info=True,
        )
        return False


def _promotion_allowed(gate, target: TpuSlice) -> bool:
    """Consult a promotion gate (``allow_promotion(target)`` duck
    type, or a plain callable). A broken gate must never wedge a
    degraded notebook at the small shape forever — on any failure the
    probe-by-emitting default stands and the probe is allowed."""
    try:
        if hasattr(gate, "allow_promotion"):
            return bool(gate.allow_promotion(target))
        return bool(gate(target))
    except Exception:
        log.warning(
            "elastic promotion gate failed; allowing the probe",
            exc_info=True,
        )
        return True


def decide(notebook: dict, pods: list | None, now: float,
           promotion_gate=None) -> ElasticDecision | None:
    """The elastic policy for one reconcile pass. Pure over its inputs
    (the CR, the already-listed pods, the injected clock) — the caller
    owns every API write. Returns None for non-TPU notebooks.

    ``promotion_gate`` (optional) is consulted before the promote arm
    fires — e.g. :class:`kubeflow_tpu.autopilot.ElasticPromotionGate`,
    which vetoes probing a bigger shape into known-shrinking capacity
    or through a goodput hole. A veto defers the probe one promote
    interval (the probe clock re-arms); without a gate — or with a
    broken one — the historical probe-by-emitting behaviour stands."""
    spec_tpu = ((notebook.get("spec") or {}).get("tpu")) or {}
    accelerator = spec_tpu.get("accelerator")
    if not accelerator:
        return None
    try:
        spec_slice = TpuSlice.parse(
            accelerator, spec_tpu.get("topology", "1x1")
        )
    except TopologyError:
        return None  # native reconcile surfaces the spec error
    meta = notebook.get("metadata") or {}
    anns = meta.get("annotations") or {}
    name = meta.get("name", "")

    raw_ladder = anns.get(ELASTIC_LADDER_KEY)
    if raw_ladder is None:
        # Not opted in: run at the spec shape; sweep stale elastic
        # state so a removed opt-in does not pin a degraded shape.
        stale = {key: None for key in STATE_KEYS if key in anns}
        return ElasticDecision(spec_slice, stale, [], None)
    try:
        rungs = [spec_slice] + topology.parse_ladder(
            spec_slice, raw_ladder
        )
    except TopologyError as exc:
        # A typo in the ladder must not trigger a surprise reshape: if
        # the notebook is currently pinned to a degraded rung, keep
        # running THAT shape (frozen — no further transitions) until
        # the annotation is fixed or removed.
        pinned = spec_slice
        shape_ann = anns.get(ELASTIC_SHAPE_KEY)
        if shape_ann:
            try:
                candidate = TpuSlice.from_shorthand(shape_ann)
                if (candidate.accelerator.name
                        == spec_slice.accelerator.name
                        and candidate.chips < spec_slice.chips):
                    pinned = candidate
            except TopologyError:
                pass
        log.warning(
            "notebook %s: invalid %s annotation (%s); elastic "
            "transitions disabled, holding shape %s", name,
            ELASTIC_LADDER_KEY, exc, pinned.shorthand,
        )
        return ElasticDecision(pinned, {}, [], None,
                               at_spec_shape=pinned is spec_slice)

    shorthands = [rung.shorthand for rung in rungs]
    shape_ann = anns.get(ELASTIC_SHAPE_KEY)
    rung = shorthands.index(shape_ann) if shape_ann in shorthands else 0
    effective = rungs[rung]
    reshard_reason = anns.get(RESHARD_REASON_KEY) or None
    grace_s = _seconds(anns, ELASTIC_GRACE_KEY, DEFAULT_GRACE_S)
    promote_after_s = _seconds(
        anns, ELASTIC_PROMOTE_AFTER_KEY, DEFAULT_PROMOTE_AFTER_S
    )

    replicas = effective.num_hosts
    expected = {f"{name}-{i}" for i in range(replicas)}
    present = {
        p["metadata"]["name"]: p
        for p in pods or []
        if p["metadata"]["name"] in expected
        and not p["metadata"].get("deletionTimestamp")
    }
    stuck = sorted(n for n, p in present.items() if _unschedulable(p))
    running = {
        n for n, p in present.items() if _runs_shape(p, effective)
    }

    patches: dict = {}
    events: list = []
    if anns.get(ELASTIC_WORLD_SIZE_KEY) != str(replicas):
        patches[ELASTIC_WORLD_SIZE_KEY] = str(replicas)

    if stuck:
        since = parse_rfc3339(anns.get(ELASTIC_PENDING_SINCE_KEY, ""))
        if since is None:
            # First sight of capacity starvation at this shape: arm the
            # wait-for-full-shape grace window.
            patches[ELASTIC_PENDING_SINCE_KEY] = rfc3339(now)
        elif now - since >= grace_s and rung + 1 < len(rungs):
            target = rungs[rung + 1]
            reshard_reason = (
                f"degrading {effective.shorthand} -> {target.shorthand}: "
                f"worker(s) {', '.join(stuck)} unschedulable for "
                f"{int(now - since)}s (> grace {int(grace_s)}s)"
            )
            patches.update({
                ELASTIC_SHAPE_KEY: target.shorthand,
                ELASTIC_WORLD_SIZE_KEY: str(target.num_hosts),
                ELASTIC_PENDING_SINCE_KEY: None,
                # Probe back up only after the interval — and restart
                # the clock on every degrade, so a failed promote probe
                # cannot flap at reconcile frequency.
                ELASTIC_PROMOTE_AT_KEY: rfc3339(now + promote_after_s),
                RESHARD_REASON_KEY: reshard_reason,
            })
            events.append((
                "SliceDegraded",
                f"{reshard_reason}; re-emitting StatefulSet at "
                f"{target.num_hosts} worker(s) x "
                f"{target.chips_per_replica} chips, training resumes "
                "from the last checkpoint on the re-factored mesh",
                "Warning",
            ))
            effective = target
        elif now - since >= grace_s:
            log.warning(
                "notebook %s: %s unschedulable past grace but already "
                "at the ladder's smallest shape (%s); waiting for "
                "capacity", name, stuck, effective.shorthand,
            )
        return ElasticDecision(
            effective, patches, events, reshard_reason,
            at_spec_shape=effective.shorthand == spec_slice.shorthand,
        )

    if ELASTIC_PENDING_SINCE_KEY in anns:
        patches[ELASTIC_PENDING_SINCE_KEY] = None
    full = expected <= running
    if reshard_reason and full:
        # The transition landed: every worker of the target shape runs.
        patches[RESHARD_REASON_KEY] = None
        reshard_reason = None
        events.append((
            "SliceResharded",
            f"running at {effective.shorthand} "
            f"({replicas} worker(s) x {effective.chips_per_replica} "
            "chips)",
            "Normal",
        ))
    if (full and reshard_reason is None and promotion_gate is not None
            and rung + 1 < len(rungs)
            and _demotion_advised(promotion_gate, effective)):
        # Proactive demotion (ROADMAP item-5 follow-up): the pool view
        # says the current shape is about to lose nodes — step DOWN
        # through the normal checkpointed reshard NOW, while every
        # worker still runs, instead of eating the preemption (an
        # unplanned all-or-nothing restart) and only then degrading
        # after the grace window.
        target = rungs[rung + 1]
        reshard_reason = (
            f"demoting {effective.shorthand} -> {target.shorthand}: "
            "capacity below the current shape (proactive step-down "
            "ahead of the preemption)"
        )
        patches.update({
            ELASTIC_SHAPE_KEY: target.shorthand,
            ELASTIC_WORLD_SIZE_KEY: str(target.num_hosts),
            ELASTIC_PENDING_SINCE_KEY: None,
            ELASTIC_PROMOTE_AT_KEY: rfc3339(now + promote_after_s),
            RESHARD_REASON_KEY: reshard_reason,
        })
        events.append((
            "SliceDegraded",
            f"{reshard_reason}; re-emitting StatefulSet at "
            f"{target.num_hosts} worker(s) x "
            f"{target.chips_per_replica} chips, training resumes "
            "from the last checkpoint on the re-factored mesh",
            "Warning",
        ))
        return ElasticDecision(
            target, patches, events, reshard_reason,
            at_spec_shape=False,
        )
    if rung == 0:
        # Nothing to promote at the spec shape; also sweep a stale
        # shape annotation (a spec/ladder edit can orphan one, and a
        # leftover value would be reinterpreted as "degraded" the
        # moment a future ladder contains it again).
        for key in (ELASTIC_PROMOTE_AT_KEY, ELASTIC_SHAPE_KEY):
            if key in anns:
                patches[key] = None
        return ElasticDecision(effective, patches, events,
                               reshard_reason)
    if full and reshard_reason is None:
        promote_at = parse_rfc3339(anns.get(ELASTIC_PROMOTE_AT_KEY, ""))
        if promote_at is None:
            patches[ELASTIC_PROMOTE_AT_KEY] = rfc3339(
                now + promote_after_s
            )
        elif now >= promote_at:
            target = rungs[rung - 1]
            if promotion_gate is not None and not _promotion_allowed(
                    promotion_gate, target):
                # Deferred: the gate says the bigger shape would land
                # in known-shrinking capacity (or the job cannot
                # afford the probe's churn) — re-arm the probe clock
                # and stay at the current rung. The gate records its
                # own veto as an autopilot action.
                patches[ELASTIC_PROMOTE_AT_KEY] = rfc3339(
                    now + promote_after_s
                )
                return ElasticDecision(
                    effective, patches, events, reshard_reason,
                    at_spec_shape=(effective.shorthand
                                   == spec_slice.shorthand),
                )
            reshard_reason = (
                f"promoting {effective.shorthand} -> "
                f"{target.shorthand}: probing regrown capacity"
            )
            patches.update({
                ELASTIC_SHAPE_KEY: (
                    target.shorthand if rung - 1 > 0 else None
                ),
                ELASTIC_WORLD_SIZE_KEY: str(target.num_hosts),
                ELASTIC_PROMOTE_AT_KEY: rfc3339(now + promote_after_s),
                RESHARD_REASON_KEY: reshard_reason,
            })
            events.append((
                "SlicePromoted",
                f"{reshard_reason}; re-emitting StatefulSet at "
                f"{target.num_hosts} worker(s) x "
                f"{target.chips_per_replica} chips",
                "Normal",
            ))
            effective = target
    return ElasticDecision(
        effective, patches, events, reshard_reason,
        at_spec_shape=effective.shorthand == spec_slice.shorthand,
    )
