"""Controller runtime: watch → rate-limited workqueue → reconcile.

The role controller-runtime's manager plays in the reference (reference
notebook-controller SetupWithManager, notebook_controller.go:691-739):
watches on the primary CRD and owned kinds feed a deduplicating,
exponential-backoff workqueue; workers call ``Reconciler.reconcile``
level-based — every invocation re-derives desired state from scratch, so
restarts and missed events self-heal.

Deterministic by construction for the test ladder: ``run_once`` drains
all pending events and reconciles synchronously; ``run_forever`` adds the
background thread + periodic resync used in real deployments.
"""

from __future__ import annotations

import heapq
import itertools
import logging
import threading
import time
from dataclasses import dataclass
from typing import Callable, Protocol

from kubeflow_tpu import obs
from kubeflow_tpu.k8s.fake import FakeApiServer, WatchEvent
from kubeflow_tpu.obs.metrics import BucketHistogram
from kubeflow_tpu.obs.profile import PhaseProfiler

log = logging.getLogger(__name__)


@dataclass(frozen=True)
class Request:
    namespace: str
    name: str


class Reconciler(Protocol):
    def reconcile(self, req: Request) -> float | None:
        """Returns requeue-after seconds, or None."""


@dataclass
class _QueueEntry:
    req: Request
    not_before: float = 0.0


class WorkQueue:
    """Deduplicating rate-limited queue (the controller-runtime shape:
    per-item exponential backoff, reset on success)."""

    def __init__(self, base_delay: float = 0.005, max_delay: float = 60.0):
        self._base = base_delay
        self._max = max_delay
        self._lock = threading.Lock()
        self._pending: dict[Request, float] = {}  # req -> not_before
        self._failures: dict[Request, int] = {}
        # Min-heap of (not_before, seq, req) mirroring _pending. Entries
        # superseded by an earlier re-add stay in the heap and are
        # skipped lazily in pop_ready (their not_before no longer
        # matches _pending) — pop is O(log n) amortised instead of the
        # former O(n log n) full sort per pop.
        self._heap: list[tuple[float, int, Request]] = []
        self._seq = itertools.count()
        # Queue-duration stamp per pending key: the moment the key
        # becomes DUE (its earliest not_before), NOT when it was
        # scheduled — controller-runtime's AddAfter semantics. A
        # deliberate requeue_after=300 or a parked backoff must read as
        # ~0 wait on a healthy controller; anything else pins the
        # workqueue_queue_duration histogram at +Inf and the metric
        # stops detecting real backlog.
        self._enqueued_at: dict[Request, float] = {}
        self.latency = BucketHistogram()
        # Optional hook (Controller wires the manager's Prometheus
        # histogram here); called OUTSIDE the queue lock.
        self.latency_observer = None

    def _schedule_locked(self, req: Request, not_before: float) -> None:
        # Caller holds self._lock (the _locked contract the
        # concurrency analysis pack enforces). Keep the earliest
        # scheduled time for duplicates: an item that is already due
        # must never be pushed back.
        cur = self._pending.get(req)
        if cur is None or not_before < cur:
            self._pending[req] = not_before
            heapq.heappush(self._heap, (not_before, next(self._seq), req))
        # Duration stamp: fresh stay takes this due-time; an earlier
        # re-add of a pending key pulls it forward (the key became due
        # sooner), a later one never pushes it back.
        stamp = self._enqueued_at.get(req)
        if cur is None or stamp is None or not_before < stamp:
            self._enqueued_at[req] = not_before

    def add(self, req: Request, delay: float = 0.0) -> None:
        with self._lock:
            self._schedule_locked(req, time.monotonic() + delay)

    def add_rate_limited(self, req: Request) -> None:
        with self._lock:
            failures = self._failures.get(req, 0)
            self._failures[req] = failures + 1
            delay = min(self._base * (2**failures), self._max)
            # Same earliest-wins rule as add(): a rate-limited re-add
            # races watch-driven adds, and pushing back an already-due
            # item would starve it behind every later arrival.
            self._schedule_locked(req, time.monotonic() + delay)

    def forget(self, req: Request) -> None:
        with self._lock:
            self._failures.pop(req, None)

    def pop_ready(self) -> Request | None:
        wait: float | None = None
        popped: Request | None = None
        with self._lock:
            now = time.monotonic()
            while self._heap:
                not_before, _, req = self._heap[0]
                cur = self._pending.get(req)
                if cur is None or cur != not_before:
                    heapq.heappop(self._heap)  # stale/superseded entry
                    continue
                if not_before > now:
                    return None  # heap min not due: nothing is
                heapq.heappop(self._heap)
                del self._pending[req]
                due_at = self._enqueued_at.pop(req, None)
                if due_at is not None:
                    wait = max(0.0, time.monotonic() - due_at)
                popped = req
                break
        if popped is None:
            return None
        if wait is not None:
            self.latency.observe(wait)
            observer = self.latency_observer
            if observer is not None:
                try:
                    observer(wait)
                except Exception:
                    log.debug("queue latency observer failed",
                              exc_info=True)
        return popped

    def latency_snapshot(self) -> dict:
        """p50/p99 due→dequeue wait (bucket upper bounds) — the
        in-process view of the workqueue_queue_duration histogram."""
        return {
            "count": self.latency.count,
            "p50": self.latency.quantile(0.50),
            "p99": self.latency.quantile(0.99),
        }

    def next_deadline(self) -> float | None:
        with self._lock:
            if not self._pending:
                return None
            return min(self._pending.values())

    def __len__(self) -> int:
        with self._lock:
            return len(self._pending)


def ensure_object(api, desired: dict) -> str:
    """Create-or-update through the native drift repair: writes only when
    an owned field differs (shared by every controller). Returns what
    happened — "created" / "updated" / "unchanged" — so callers can feed
    the create counters (reference metrics.go NotebookCreation)."""
    from kubeflow_tpu import native
    from kubeflow_tpu.k8s.fake import NotFound

    meta = desired["metadata"]
    try:
        existing = api.get(
            desired["apiVersion"], desired["kind"], meta["name"],
            meta.get("namespace"),
        )
    except NotFound:
        api.create(desired)
        return "created"
    merged = native.invoke(
        "copy_owned_fields",
        {"kind": desired["kind"], "existing": existing, "desired": desired},
    )
    if merged["changed"]:
        # A Conflict (stale read) propagates; the queue's rate limiter
        # retries the key.
        api.update(merged["merged"])
        return "updated"
    return "unchanged"


def record_event(
    api,
    involved: dict,
    reason: str,
    message: str,
    event_type: str = "Normal",
    component: str = "kubeflow-tpu-controller",
    clock: Callable[[], float] | None = None,
) -> None:
    """controller-runtime EventRecorder parity: write a v1 Event naming
    the involved object so `kubectl describe`, the JWA details page and
    the dashboard activity feed surface controller decisions.

    Like the reference recorder, repeats aggregate: a same
    (object, reason, component) event bumps count/lastTimestamp instead
    of piling up new objects — a persistently failing reconcile retried
    every minute must not grow the event list without bound. The
    aggregation target is found by a DETERMINISTIC event name
    (``<object>.<hash of kind|reason|component>``) + get/patch — one
    point read per write regardless of how many events the namespace
    holds, where a list-scan would go quadratic exactly during the
    event storms aggregation exists for. Event writes never fail a
    reconcile (fire-and-forget). ``clock`` keeps timestamps coherent
    with callers using an injected clock."""
    import hashlib
    import time as time_mod

    from kubeflow_tpu.k8s.core import Conflict, NotFound

    meta = involved.get("metadata", {})
    now = clock() if clock is not None else time_mod.time()
    stamp = time_mod.strftime("%Y-%m-%dT%H:%M:%SZ", time_mod.gmtime(now))
    namespace = meta.get("namespace", "default")
    obj_name = meta.get("name", "obj")
    key = f"{involved.get('kind', '')}|{reason}|{component}"
    suffix = hashlib.sha1(key.encode()).hexdigest()[:10]
    ev_name = f"{obj_name}.{suffix}"
    if len(ev_name) > 253:
        # DNS-subdomain cap: truncate the prefix and fold the FULL
        # object name into the hash so truncated names cannot collide
        # across objects sharing their first 242 characters (writes
        # are fire-and-forget — an over-long name would silently fail
        # forever, losing this object's aggregation entirely).
        suffix = hashlib.sha1(
            f"{obj_name}|{key}".encode()
        ).hexdigest()[:10]
        ev_name = f"{obj_name[:242]}.{suffix}"

    def bump(existing: dict) -> None:
        api.patch_merge(
            "v1", "Event", ev_name,
            {
                "count": existing.get("count", 1) + 1,
                "lastTimestamp": stamp,
                "message": message,
            },
            namespace,
        )

    try:
        try:
            bump(api.get("v1", "Event", ev_name, namespace))
            return
        except NotFound:
            pass
        try:
            api.create(
                {
                    "apiVersion": "v1",
                    "kind": "Event",
                    "metadata": {"name": ev_name, "namespace": namespace},
                    "involvedObject": {
                        "apiVersion": involved.get("apiVersion", ""),
                        "kind": involved.get("kind", ""),
                        "name": meta.get("name", ""),
                        "namespace": meta.get("namespace", ""),
                        "uid": meta.get("uid", ""),
                    },
                    "reason": reason,
                    "message": message,
                    "type": event_type,
                    "source": {"component": component},
                    "firstTimestamp": stamp,
                    "lastTimestamp": stamp,
                    "count": 1,
                }
            )
        except Conflict:
            # Lost a create race with a concurrent recorder: the event
            # exists now, fold this occurrence into it.
            bump(api.get("v1", "Event", ev_name, namespace))
    except Exception:
        log.debug("event write failed for %s/%s %s",
                  meta.get("namespace"), meta.get("name"), reason)


@dataclass
class WatchSpec:
    api_version: str
    kind: str
    # Maps a watch event object to reconcile requests (e.g. Pod -> owning
    # Notebook via labels). Default: the object itself.
    mapper: Callable[[dict], list[Request]] | None = None


class Controller:
    """One reconciler + its watches + its queue."""

    def __init__(
        self,
        name: str,
        api: FakeApiServer,
        reconciler: Reconciler,
        watches: list[WatchSpec],
        resync_period: float = 300.0,
        prom=None,  # optional ControllerMetrics for Prometheus exposition
        reconcile_deadline: float = 30.0,
        stuck_threshold: int = 10,
        clock: Callable[[], float] = time.monotonic,
        profiler: PhaseProfiler | None = None,
        recorder=None,
    ):
        self.name = name
        self.api = api
        self.reconciler = reconciler
        self.queue = WorkQueue()
        self.resync_period = resync_period
        self.prom = prom
        # Continuous profiling + black-box capture (PR 10): every
        # reconcile runs under this profiler's activation, so an
        # instrumented reconciler's phase splits (list / desired-state
        # / patch / status via obs.profile.phase) land in rolling
        # digests served at /debug/profile, and — when the manager
        # wires a shared FlightRecorder — each reconcile leaves one
        # bounded-ring snapshot an alert dump captures retroactively.
        self.profiler = profiler if profiler is not None else \
            PhaseProfiler()
        self.recorder = recorder
        # Stuck-reconcile watchdog knobs: a reconcile running past
        # reconcile_deadline, or a key failing stuck_threshold times in
        # a row, is surfaced (Degraded condition + Warning Event +
        # metrics) instead of hot-looping silently. The clock is
        # injectable so tests drive the deadline deterministically.
        self.reconcile_deadline = reconcile_deadline
        self.stuck_threshold = stuck_threshold
        self.clock = clock
        self._failure_streak: dict[Request, int] = {}
        self._degraded: set[Request] = set()
        # Request → traceparent from the object's TRACE_ANNOTATION,
        # captured off watch events / resync lists so the reconcile
        # span joins the trace that created the object (spawner POST).
        # Bounded: churn on annotated objects must not grow it forever.
        self._trace_parents: dict[Request, str] = {}
        if prom is not None and hasattr(prom, "queue_duration"):
            self.queue.latency_observer = (
                prom.queue_duration.labels(name).observe
            )
        # One entry per watch registration, fixed at construction.
        # analysis: allow[py-unbounded-deque]
        self._watch_queues = []
        for spec in watches:
            q = api.watch(spec.api_version, spec.kind)
            self._watch_queues.append((spec, q))
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._initial_synced = False
        self.metrics = {
            "reconciles": 0, "errors": 0, "requeues": 0,
            "stuck": 0, "deadline_exceeded": 0,
        }
        # Called once per loop tick (config-file watches and other
        # fsnotify-style side channels hook in here).
        self.tick_hooks: list[Callable[[], None]] = []

    def _run_tick_hooks(self) -> None:
        # Hook failures must not kill the run loop (a transient stat()
        # error on a watched config file is retried next tick, like
        # reconcile errors are).
        for hook in self.tick_hooks:
            try:
                hook()
            except Exception:
                log.exception("%s: tick hook failed", self.name)

    def _default_request(self, obj: dict) -> list[Request]:
        meta = obj.get("metadata", {})
        return [Request(meta.get("namespace", ""), meta.get("name", ""))]

    def _remember_trace_parent(self, obj: dict, req: Request) -> None:
        header = (
            (obj.get("metadata") or {}).get("annotations") or {}
        ).get(obs.TRACE_ANNOTATION)
        if not header:
            # Only the PRIMARY object may invalidate the link: a
            # delete-and-recreate without the annotation must not keep
            # parenting reconciles on the dead predecessor's trace —
            # but secondary watches (Pods, StatefulSets mapped to the
            # same request) never carry the annotation and must not
            # wipe a live link either.
            if (
                self._watch_queues
                and obj.get("kind") == self._watch_queues[0][0].kind
            ):
                self._trace_parents.pop(req, None)
            return
        if req not in self._trace_parents and len(self._trace_parents) >= 1024:
            self._trace_parents.pop(next(iter(self._trace_parents)))
        self._trace_parents[req] = header

    def _drain_watches(self) -> int:
        moved = 0
        for spec, q in self._watch_queues:
            while not q.empty():
                event: WatchEvent = q.get_nowait()
                mapper = spec.mapper or self._default_request
                for req in mapper(event.object):
                    if req.name:
                        self._remember_trace_parent(event.object, req)
                        self.queue.add(req)
                        moved += 1
        return moved

    def _process_one(self) -> bool:
        req = self.queue.pop_ready()
        if req is None:
            return False
        self.metrics["reconciles"] += 1
        # The reconcile span joins the trace that created the object
        # when its CR carries the trace annotation (spawner POST → CR →
        # watch event → here); otherwise it roots a fresh trace. Every
        # apiserver round-trip the reconciler makes nests underneath
        # via the contextvar.
        parent = obs.parse_traceparent(self._trace_parents.get(req))
        tracer = obs.get_tracer()
        started = self.clock()
        with tracer.span(
            "reconcile",
            parent=parent,
            attributes={
                "controller": self.name,
                "namespace": req.namespace,
                "name": req.name,
            },
        ) as span, self.profiler.activate() as phases:
            try:
                requeue_after = self.reconciler.reconcile(req)
            except Exception as exc:
                elapsed = self.clock() - started
                self.profiler.observe("total", elapsed)
                self._observe_duration(elapsed)
                log.exception("%s: reconcile %s failed", self.name, req)
                self.metrics["errors"] += 1
                if self.prom is not None:
                    self.prom.reconcile_total.labels(
                        self.name, "error"
                    ).inc()
                streak = self._failure_streak.get(req, 0) + 1
                self._failure_streak[req] = streak
                span.record_exception(exc)
                span.add_event("requeue_rate_limited",
                               {"failures": streak})
                if (streak >= self.stuck_threshold
                        and req not in self._degraded):
                    self._mark_degraded(req, streak)
                self.queue.add_rate_limited(req)
                self._snapshot_reconcile(req, phases, "error")
                return True
            elapsed = self.clock() - started
            self.profiler.observe("total", elapsed)
            self._observe_duration(elapsed)
            if elapsed > self.reconcile_deadline:
                # Reconciles run on shared workers and cannot be aborted
                # mid-flight; the watchdog surfaces the overrun so a
                # wedged probe or API hang is an alert, not a silent
                # stall.
                self.metrics["deadline_exceeded"] += 1
                if self.prom is not None:
                    self.prom.reconcile_stuck_total.labels(
                        self.name, "deadline"
                    ).inc()
                span.add_event("deadline_exceeded", {
                    "elapsed_s": round(elapsed, 3),
                    "deadline_s": self.reconcile_deadline,
                })
                self._record_watchdog_event(
                    req, "ReconcileDeadlineExceeded",
                    f"reconcile of {req.namespace}/{req.name} took "
                    f"{elapsed:.1f}s "
                    f"(deadline {self.reconcile_deadline:.1f}s)",
                )
            if self.prom is not None:
                self.prom.reconcile_total.labels(
                    self.name, "success"
                ).inc()
            self._failure_streak.pop(req, None)
            if req in self._degraded:
                self._clear_degraded(req)
            self.queue.forget(req)
            if requeue_after is not None:
                self.metrics["requeues"] += 1
                span.add_event("requeue_after",
                               {"delay_s": requeue_after})
                self.queue.add(req, delay=requeue_after)
            self._snapshot_reconcile(req, phases, "ok")
        return True

    def _snapshot_reconcile(self, req: Request, phases: dict,
                            outcome: str) -> None:
        """One flight-recorder snapshot per reconcile: the phase split
        the reconciler reported (list / desired-state / patch / status
        — plus the runtime's own ``total``), queue depth, and — via
        the recorder, which reads the live span — the trace id this
        reconcile ran under."""
        if self.recorder is None:
            return
        self.recorder.record(
            "reconcile",
            controller=self.name,
            namespace=req.namespace,
            name=req.name,
            outcome=outcome,
            phases={k: round(v, 6) for k, v in (phases or {}).items()},
            queue_depth=len(self.queue),
        )

    def _observe_duration(self, elapsed: float) -> None:
        if self.prom is not None and hasattr(self.prom,
                                             "reconcile_duration"):
            # The reconcile span is active here (we are inside the
            # tracer.span block); stamping its trace id as an
            # OpenMetrics exemplar links a p99 bucket on /metrics to
            # the exact trace that produced it. Only sampled spans —
            # an unsampled id resolves to no exporter.
            span = obs.current_span()
            exemplar = None
            if span is not None and span.context.sampled:
                exemplar = {"trace_id": span.context.trace_id}
            self.prom.reconcile_duration.labels(self.name).observe(
                elapsed, exemplar=exemplar
            )

    # ---- stuck-reconcile watchdog ---------------------------------------
    def _primary_object(self, req: Request) -> dict | None:
        """The CR this controller owns for ``req``, via the primary
        watch spec; None when unreachable (the apiserver may be the
        very thing that is failing)."""
        if not self._watch_queues:
            return None
        spec = self._watch_queues[0][0]
        try:
            return self.api.get(
                spec.api_version, spec.kind, req.name,
                req.namespace or None,
            )
        except Exception as exc:
            log.debug("%s: watchdog could not fetch %s: %s",
                      self.name, req, exc)
            return None

    def _record_watchdog_event(
        self, req: Request, reason: str, message: str,
        event_type: str = "Warning",
    ) -> None:
        obj = self._primary_object(req)
        if obj is None:
            return
        record_event(
            self.api, obj, reason, message, event_type=event_type,
            component=self.name,
        )

    def _patch_degraded_condition(
        self, req: Request, condition: dict | None
    ) -> None:
        """Set (or, with ``condition=None``, remove) the watchdog's
        Degraded condition on the primary CR. Removal must delete the
        ``conditions`` key outright when nothing else is left: a CR
        whose reconciler exact-compares its computed status (pvcviewer,
        tensorboard) would otherwise see a foreign leftover key and
        rewrite status forever."""
        if not self._watch_queues:  # watch-less controller: no CR to mark
            return
        spec = self._watch_queues[0][0]
        obj = self._primary_object(req)
        if obj is None:
            return
        conditions = [
            c for c in (obj.get("status") or {}).get("conditions") or []
            if c.get("type") != "Degraded"
        ]
        if condition is not None:
            conditions.append(condition)
        try:
            self.api.patch_merge(
                spec.api_version, spec.kind, req.name,
                {"status": {"conditions": conditions or None}},
                req.namespace or None,
            )
        except Exception:
            # Best-effort like event writes: the status patch must not
            # turn a degraded key into a crashed controller.
            log.debug("%s: Degraded condition patch failed for %s",
                      self.name, req)

    def _mark_degraded(self, req: Request, streak: int) -> None:
        """Consecutive-failure threshold crossed: make the stall
        visible on the CR (Degraded condition + Warning Event) instead
        of hot-looping silently. The workqueue's exponential backoff
        keeps retrying underneath; a later success clears the mark."""
        self.metrics["stuck"] += 1
        self._degraded.add(req)
        if self.prom is not None:
            self.prom.reconcile_stuck_total.labels(
                self.name, "failures"
            ).inc()
        message = (
            f"reconcile has failed {streak} consecutive times; "
            "retrying with exponential backoff"
        )
        log.warning("%s: %s/%s %s", self.name, req.namespace, req.name,
                    message)
        self._patch_degraded_condition(req, {
            "type": "Degraded",
            "status": "True",
            "reason": "ReconcileStuck",
            "message": message,
        })
        self._record_watchdog_event(req, "ReconcileStuck", message)

    def _clear_degraded(self, req: Request) -> None:
        self._degraded.discard(req)
        self._patch_degraded_condition(req, None)
        self._record_watchdog_event(
            req, "ReconcileRecovered",
            f"reconcile of {req.namespace}/{req.name} recovered",
            event_type="Normal",
        )

    def run_once(self, max_iterations: int = 100) -> int:
        """Drain watches and reconcile until quiescent (tests/dev).

        Reconciles can themselves emit watch events (status updates);
        iterate until no event and no ready work remain. Delayed requeues
        (requeue_after > 0) are left pending.
        """
        if not self._initial_synced:
            # Informer-style initial LIST: objects that predate the
            # controller get reconciled without waiting for an event.
            self.resync()
            self._initial_synced = True
        processed = 0
        self._run_tick_hooks()
        for _ in range(max_iterations):
            self._drain_watches()
            if not self._process_one():
                if not self._drain_watches():
                    break
            else:
                processed += 1
        return processed

    def run_forever(self, poll_interval: float = 0.05):
        if not self._initial_synced:
            self.resync()
            self._initial_synced = True
        last_resync = time.monotonic()
        while not self._stop.is_set():
            self._run_tick_hooks()
            self._drain_watches()
            worked = self._process_one()
            if time.monotonic() - last_resync > self.resync_period:
                last_resync = time.monotonic()
                self.resync()
            if not worked:
                self._stop.wait(poll_interval)

    def resync(self) -> int | None:
        """Re-enqueue every primary object (level-based safety net).
        A failed LIST (apiserver outage) must not kill the run loop —
        the next periodic resync retries; until then the watch stream
        and the queue's own retries keep the controller alive. Returns
        the number of objects enqueued, or None when the list failed —
        the chaos harness needs to distinguish "provably nothing to do"
        from "could not even ask"."""
        spec = self._watch_queues[0][0] if self._watch_queues else None
        if spec is None:
            return 0
        try:
            objs = self.api.list(spec.api_version, spec.kind)
        except Exception as exc:
            log.warning("%s: resync list failed (%s); retrying on the "
                        "next cycle", self.name, exc)
            return None
        count = 0
        for obj in objs:
            # Restart amnesia repair: the failure streak behind a
            # Degraded mark lives only in memory, so after a controller
            # restart the mark would never be cleared. Rebuild the
            # in-memory set from the observed CR state, and the next
            # successful reconcile removes the condition as usual.
            inherited = any(
                c.get("type") == "Degraded"
                and c.get("status") == "True"
                and c.get("reason") == "ReconcileStuck"
                for c in (obj.get("status") or {}).get("conditions") or []
            )
            for req in (spec.mapper or self._default_request)(obj):
                self._remember_trace_parent(obj, req)
                self.queue.add(req)
                count += 1
                if inherited:
                    self._degraded.add(req)
        return count

    def start(self) -> threading.Thread:
        # Controllers are restarted across leadership transitions
        # (manager.py). The previous stint's thread must be fully gone
        # before the stop signal is cleared — clearing early on a fast
        # lose/regain flap would leave two run loops reconciling the
        # same keys concurrently.
        if self._thread is not None and self._thread.is_alive():
            self._stop.set()
            self._thread.join(timeout=10.0)
            if self._thread.is_alive():
                raise RuntimeError(
                    f"{self.name}: previous run loop did not stop"
                )
        self._stop.clear()
        self._thread = threading.Thread(
            target=self.run_forever, name=self.name, daemon=True
        )
        self._thread.start()
        return self._thread

    def stop(self):
        self._stop.set()
