"""Controller runtime: watch → rate-limited workqueue → reconcile.

The role controller-runtime's manager plays in the reference (reference
notebook-controller SetupWithManager, notebook_controller.go:691-739):
watches on the primary CRD and owned kinds feed a deduplicating,
exponential-backoff workqueue; workers call ``Reconciler.reconcile``
level-based — every invocation re-derives desired state from scratch, so
restarts and missed events self-heal.

Deterministic by construction for the test ladder: ``run_once`` drains
all pending events and reconciles synchronously; ``run_forever`` adds the
background thread + periodic resync used in real deployments.
"""

from __future__ import annotations

import heapq
import itertools
import logging
import queue
import threading
import time
from dataclasses import dataclass
from typing import Callable, Protocol

from kubeflow_tpu import obs
from kubeflow_tpu.controllers.leader import shard_of
from kubeflow_tpu.k8s.core import (
    CLUSTER_SCOPED,
    GVK,
    NotFound,
    match_field_selector,
    match_label_selector,
)
from kubeflow_tpu.k8s.fake import FakeApiServer, WatchEvent, _jcopy
from kubeflow_tpu.obs.metrics import BucketHistogram
from kubeflow_tpu.obs.profile import PhaseProfiler

log = logging.getLogger(__name__)


@dataclass(frozen=True)
class Request:
    namespace: str
    name: str


class Reconciler(Protocol):
    def reconcile(self, req: Request) -> float | None:
        """Returns requeue-after seconds, or None."""


@dataclass
class _QueueEntry:
    req: Request
    not_before: float = 0.0


# Workqueue priority lanes. A delete or a preemption drain changes what
# the fleet is RUNNING; a status-only ripple changes what it SAYS — so
# under churn backlog the fast lane (deletes, deletionTimestamps,
# preempt-requested drains) pops ahead of the default lane. Ordering
# within a lane is unchanged (earliest due, then arrival), and a key
# re-added on a faster lane keeps its earliest due-time.
LANE_FAST = "fast"
LANE_DEFAULT = "default"
_LANES = (LANE_FAST, LANE_DEFAULT)
_LANE_RANK = {lane: i for i, lane in enumerate(_LANES)}


def lane_for_event(event_type: str, obj: dict) -> str:
    """Classify a watch event into a workqueue lane: deletes and
    preemption drains jump the status-churn line."""
    if event_type == "DELETED":
        return LANE_FAST
    meta = obj.get("metadata") or {}
    if meta.get("deletionTimestamp"):
        return LANE_FAST
    anns = meta.get("annotations") or {}
    if any(k.endswith("/preempt-requested") for k in anns):
        return LANE_FAST
    return LANE_DEFAULT


class WorkQueue:
    """Deduplicating rate-limited queue (the controller-runtime shape:
    per-item exponential backoff, reset on success) with keyed
    priority lanes (``LANE_FAST`` ahead of ``LANE_DEFAULT``)."""

    def __init__(self, base_delay: float = 0.005, max_delay: float = 60.0):
        self._base = base_delay
        self._max = max_delay
        self._lock = threading.Lock()
        self._pending: dict[Request, float] = {}  # req -> not_before
        self._lane: dict[Request, str] = {}      # req -> current lane
        self._failures: dict[Request, int] = {}
        # Per-lane min-heaps of (not_before, seq, req) mirroring
        # _pending. Entries superseded by an earlier re-add (or a lane
        # upgrade) stay in their heap and are skipped lazily in
        # pop_ready (their not_before/lane no longer matches) — pop is
        # O(log n) amortised instead of the former O(n log n) full
        # sort per pop.
        self._heaps: dict[str, list[tuple[float, int, Request]]] = {
            lane: [] for lane in _LANES
        }
        self._seq = itertools.count()
        # Queue-duration stamp per pending key: the moment the key
        # becomes DUE (its earliest not_before), NOT when it was
        # scheduled — controller-runtime's AddAfter semantics. A
        # deliberate requeue_after=300 or a parked backoff must read as
        # ~0 wait on a healthy controller; anything else pins the
        # workqueue_queue_duration histogram at +Inf and the metric
        # stops detecting real backlog.
        self._enqueued_at: dict[Request, float] = {}
        self.latency = BucketHistogram()
        # Optional hook (Controller wires the manager's Prometheus
        # histogram here); called OUTSIDE the queue lock.
        self.latency_observer = None

    def _schedule_locked(self, req: Request, not_before: float,
                         lane: str = LANE_DEFAULT) -> None:
        # Caller holds self._lock (the _locked contract the
        # concurrency analysis pack enforces). Keep the earliest
        # scheduled time for duplicates: an item that is already due
        # must never be pushed back. Lanes only upgrade (fast wins
        # until popped) — a delete followed by status churn must not
        # demote the key back behind the churn.
        cur = self._pending.get(req)
        cur_lane = self._lane.get(req, LANE_DEFAULT)
        if cur is not None and _LANE_RANK[lane] > _LANE_RANK[cur_lane]:
            lane = cur_lane
        if cur is None or not_before < cur or lane != cur_lane:
            due = not_before if cur is None else min(not_before, cur)
            self._pending[req] = due
            self._lane[req] = lane
            heapq.heappush(self._heaps[lane],
                           (due, next(self._seq), req))
        # Duration stamp: fresh stay takes this due-time; an earlier
        # re-add of a pending key pulls it forward (the key became due
        # sooner), a later one never pushes it back.
        stamp = self._enqueued_at.get(req)
        if cur is None or stamp is None or not_before < stamp:
            self._enqueued_at[req] = not_before

    def add(self, req: Request, delay: float = 0.0,
            lane: str = LANE_DEFAULT) -> None:
        with self._lock:
            self._schedule_locked(req, time.monotonic() + delay, lane)

    def add_rate_limited(self, req: Request) -> None:
        with self._lock:
            failures = self._failures.get(req, 0)
            self._failures[req] = failures + 1
            delay = min(self._base * (2**failures), self._max)
            # Same earliest-wins rule as add(): a rate-limited re-add
            # races watch-driven adds, and pushing back an already-due
            # item would starve it behind every later arrival.
            self._schedule_locked(req, time.monotonic() + delay)

    def forget(self, req: Request) -> None:
        with self._lock:
            self._failures.pop(req, None)

    def drop(self, predicate) -> int:
        """Remove pending keys matching ``predicate`` (shard handoff:
        a lost shard's keys must not sit in this replica's queue —
        the successor re-derives them from its own resync). Heap
        entries go stale and are skipped lazily."""
        with self._lock:
            victims = [r for r in self._pending if predicate(r)]
            for req in victims:
                self._pending.pop(req, None)
                self._lane.pop(req, None)
                self._enqueued_at.pop(req, None)
                self._failures.pop(req, None)
            return len(victims)

    def pop_ready(self, accept=None, discard=None) -> Request | None:
        """Earliest due key from the fastest non-empty lane. With
        ``accept`` (shard gating), due-but-not-yet-poppable keys are
        skipped in place — they stay pending (and their due-stamp
        keeps aging) until ownership or a drop() decides their fate.
        ``accept`` runs under the queue lock and its True verdict is
        final (the key IS popped): a gate can count the reconcile
        in-flight inside it, atomically with the pop, so a handoff
        drain can never release between accept and begin. ``discard``
        removes matching keys outright (a shard lost before it was
        ever synced: the successor re-derives its keys, holding them
        here would leak)."""
        wait: float | None = None
        popped: Request | None = None
        with self._lock:
            now = time.monotonic()
            for lane in _LANES:
                heap = self._heaps[lane]
                deferred: list[tuple[float, int, Request]] = []
                while heap:
                    not_before, seq, req = heap[0]
                    cur = self._pending.get(req)
                    if (cur is None or cur != not_before
                            or self._lane.get(req) != lane):
                        heapq.heappop(heap)  # stale/superseded entry
                        continue
                    if not_before > now:
                        break  # lane min not due: lane exhausted
                    heapq.heappop(heap)
                    if discard is not None and discard(req):
                        del self._pending[req]
                        self._lane.pop(req, None)
                        self._enqueued_at.pop(req, None)
                        self._failures.pop(req, None)
                        continue
                    if accept is not None and not accept(req):
                        deferred.append((not_before, seq, req))
                        continue
                    del self._pending[req]
                    self._lane.pop(req, None)
                    due_at = self._enqueued_at.pop(req, None)
                    if due_at is not None:
                        wait = max(0.0, time.monotonic() - due_at)
                    popped = req
                    break
                for entry in deferred:
                    heapq.heappush(heap, entry)
                if popped is not None:
                    break
        if popped is None:
            return None
        if wait is not None:
            self.latency.observe(wait)
            observer = self.latency_observer
            if observer is not None:
                try:
                    observer(wait)
                except Exception:
                    log.debug("queue latency observer failed",
                              exc_info=True)
        return popped

    def latency_snapshot(self) -> dict:
        """p50/p99 due→dequeue wait (bucket upper bounds) — the
        in-process view of the workqueue_queue_duration histogram."""
        return {
            "count": self.latency.count,
            "p50": self.latency.quantile(0.50),
            "p99": self.latency.quantile(0.99),
        }

    def next_deadline(self) -> float | None:
        with self._lock:
            if not self._pending:
                return None
            return min(self._pending.values())

    def __len__(self) -> int:
        with self._lock:
            return len(self._pending)


class ShardGate:
    """Shared shard-ownership state for one manager replica.

    The :class:`~kubeflow_tpu.controllers.leader.ShardedElector` flips
    ownership (``on_acquired``/``on_lost``) and initiates drains
    (``begin_drain``); every controller in the replica consults
    ``owns()`` before enqueuing or popping a key and brackets each
    reconcile with ``begin``/``end`` so a voluntary handoff can wait
    out the in-flight reconcile. The successor-resync discipline
    (a freshly acquired shard is re-LISTed before its keys pop) lives
    in the Controller — per controller, since each has its own queue.
    """

    def __init__(self, shards: int):
        self.shards = max(1, int(shards))
        self._lock = threading.Lock()
        self._owned: set[int] = set()
        self._draining: set[int] = set()
        self._in_flight: dict[int, int] = {}

    def shard(self, req: Request) -> int:
        return shard_of(req.namespace, req.name, self.shards)

    def on_acquired(self, shard: int) -> None:
        with self._lock:
            self._owned.add(shard)
            self._draining.discard(shard)

    def on_lost(self, shard: int) -> None:
        with self._lock:
            self._owned.discard(shard)
            self._draining.discard(shard)

    def begin_drain(self, shard: int) -> None:
        """Stop new pops of this shard's keys; ownership (and the
        lease) is surrendered only after the in-flight count hits 0."""
        with self._lock:
            self._draining.add(shard)

    def owned(self) -> frozenset[int]:
        with self._lock:
            return frozenset(self._owned)

    def owns(self, req: Request) -> bool:
        shard = self.shard(req)
        with self._lock:
            return shard in self._owned and shard not in self._draining

    def in_flight(self, shard: int) -> int:
        with self._lock:
            return self._in_flight.get(shard, 0)

    def begin(self, req: Request) -> int:
        shard = self.shard(req)
        with self._lock:
            self._in_flight[shard] = self._in_flight.get(shard, 0) + 1
        return shard

    def try_begin(self, req: Request) -> bool:
        """Ownership check + in-flight increment in ONE critical
        section: a drain (begin_drain, then wait for in_flight 0)
        serialises against this — it either sees the increment or the
        draining flag refuses the pop. Two separate owns()/begin()
        calls would leave a window where the drain observes zero
        in-flight between them and releases the lease under a
        reconcile that is about to start."""
        shard = self.shard(req)
        with self._lock:
            if shard not in self._owned or shard in self._draining:
                return False
            self._in_flight[shard] = self._in_flight.get(shard, 0) + 1
            return True

    def end(self, shard: int) -> None:
        with self._lock:
            count = self._in_flight.get(shard, 0) - 1
            if count <= 0:
                self._in_flight.pop(shard, None)
            else:
                self._in_flight[shard] = count


class Informer:
    """Watch-fed indexed store for one ``(apiVersion, kind)`` — the
    controller-runtime informer shape over the platform's apiserver
    duck type.

    Reads (``get``/``list``/``for_owner``) first drain the watch queue
    (O(delta) maintenance), then serve from the indexed store — so on
    the synchronous fake a cached read observes everything a LIST
    would, while costing O(selected) instead of O(every object of the
    kind) per call. Maintained indexes: ``(namespace, name)`` primary,
    per-namespace buckets, owner-uid (ownerReferences), and on-demand
    equality field indexes (e.g. ``involvedObject.name`` for the
    status mirror's Event joins — the per-reconcile scan that goes
    quadratic at fleet cardinality without one).

    Event application is resourceVersion-disciplined: a delivery older
    than the stored object is ignored, so duplicated or reordered
    watch deliveries (the chaos matrix's stream damage) cannot regress
    the store. Lost deliveries (drops, watch-cache compaction) are
    healed by :meth:`recover` — catch up through the store's retained
    event log, or on a compacted horizon (the 410 Gone case) count a
    relist and rebuild from a full LIST, exactly a real informer's
    ListAndWatch restart."""

    def __init__(self, api, api_version: str, kind: str):
        self.api = api
        self.api_version = api_version
        self.kind = kind
        self.gvk = GVK.from_obj({"apiVersion": api_version, "kind": kind})
        self._lock = threading.RLock()
        self._objects: dict[tuple[str, str], dict] = {}
        self._by_namespace: dict[str, set[tuple[str, str]]] = {}
        self._by_owner: dict[str, set[tuple[str, str]]] = {}
        self._field_idx: dict[str, dict[str, set[tuple[str, str]]]] = {}
        self._rv = 0
        self.relists = 0      # full re-lists taken (410 recovery)
        self.applied = 0      # watch events applied
        # Subscribe FIRST, then seed from a full list: an event landing
        # between the two is absorbed by the rv discipline.
        self._queue = api.watch(api_version, kind)
        self._relist()

    # ---- maintenance -----------------------------------------------------
    def _key(self, obj: dict) -> tuple[str, str]:
        meta = obj.get("metadata") or {}
        ns = ("" if self.kind in CLUSTER_SCOPED
              else meta.get("namespace") or "default")
        return (ns, meta.get("name", ""))

    @staticmethod
    def _obj_rv(obj: dict) -> int:
        try:
            return int((obj.get("metadata") or {})
                       .get("resourceVersion") or 0)
        except (TypeError, ValueError):
            return 0

    def _index_locked(self, key: tuple[str, str], obj: dict) -> None:
        self._unindex_locked(key)
        self._objects[key] = obj
        self._by_namespace.setdefault(key[0], set()).add(key)
        meta = obj.get("metadata") or {}
        for ref in meta.get("ownerReferences") or []:
            uid = ref.get("uid")
            if uid:
                self._by_owner.setdefault(uid, set()).add(key)
        for path, idx in self._field_idx.items():
            idx.setdefault(self._field_value(obj, path), set()).add(key)

    def _unindex_locked(self, key: tuple[str, str]) -> None:
        old = self._objects.pop(key, None)
        if old is None:
            return
        bucket = self._by_namespace.get(key[0])
        if bucket is not None:
            bucket.discard(key)
            if not bucket:
                del self._by_namespace[key[0]]
        for ref in (old.get("metadata") or {}).get("ownerReferences") or []:
            uid = ref.get("uid")
            refs = self._by_owner.get(uid)
            if refs is not None:
                refs.discard(key)
                if not refs:
                    del self._by_owner[uid]
        for path, idx in self._field_idx.items():
            value = self._field_value(old, path)
            keys = idx.get(value)
            if keys is not None:
                keys.discard(key)
                if not keys:
                    del idx[value]

    @staticmethod
    def _field_value(obj: dict, path: str) -> str:
        cur = obj
        for part in path.split("."):
            if not isinstance(cur, dict):
                return ""
            cur = cur.get(part)
        return "" if cur is None else str(cur)

    def _apply_locked(self, event: WatchEvent) -> None:
        obj = event.object
        key = self._key(obj)
        rv = self._obj_rv(obj)
        cur = self._objects.get(key)
        if cur is not None and rv < self._obj_rv(cur):
            return  # duplicated/reordered delivery: older than stored
        if event.type == "DELETED":
            self._unindex_locked(key)
        else:
            self._index_locked(key, obj)
        self.applied += 1
        self._rv = max(self._rv, rv)

    def sync(self) -> int:
        """Drain the watch queue into the store; returns events
        applied. Cheap enough to call before every read."""
        moved = 0
        with self._lock:
            while not self._queue.empty():
                try:
                    event = self._queue.get_nowait()
                except queue.Empty:
                    break  # raced another sync's drain
                self._apply_locked(event)
                moved += 1
        return moved

    def _relist(self) -> None:
        with self._lock:
            objs = self.api.list(self.api_version, self.kind)
            self._objects.clear()
            self._by_namespace.clear()
            self._by_owner.clear()
            for idx in self._field_idx.values():
                idx.clear()
            for obj in objs:
                self._index_locked(self._key(obj), obj)
                self._rv = max(self._rv, self._obj_rv(obj))
            last_rv = getattr(self.api, "last_resource_version", None)
            if last_rv is not None:
                self._rv = max(self._rv, int(last_rv))

    def recover(self) -> bool:
        """Watch-resume repair after suspected stream damage: replay
        the store's retained change log from our resourceVersion, or —
        when the horizon was compacted past us (410 Gone) — drop the
        queue backlog and rebuild from a full LIST. Returns whether a
        full relist was taken."""
        self.sync()
        events_since = getattr(self.api, "events_since", None)
        if events_since is None:
            with self._lock:
                self.relists += 1
                self._relist()
            return True
        with self._lock:
            backlog = events_since(self.gvk, self._rv)
            if backlog is None:
                # 410 Gone: our horizon is compacted away. The queued
                # deliveries predate the relist and would be skipped by
                # the rv discipline anyway; drain them now for bound.
                while not self._queue.empty():
                    try:
                        self._queue.get_nowait()
                    except queue.Empty:
                        break
                self.relists += 1
                self._relist()
                return True
            for event in backlog:
                self._apply_locked(event)
        return False

    # ---- reads -----------------------------------------------------------
    def ensure_field_index(self, path: str) -> None:
        with self._lock:
            if path in self._field_idx:
                return
            idx: dict[str, set[tuple[str, str]]] = {}
            for key, obj in self._objects.items():
                idx.setdefault(self._field_value(obj, path), set()).add(key)
            self._field_idx[path] = idx

    def get(self, name: str, namespace: str | None = None) -> dict:
        self.sync()
        ns = ("" if self.kind in CLUSTER_SCOPED
              else namespace or "default")
        with self._lock:
            obj = self._objects.get((ns, name))
            if obj is None:
                raise NotFound(
                    f"{self.kind} {namespace}/{name} not found (cache)"
                )
            return _jcopy(obj)

    def _candidates_locked(self, namespace, field_selector):
        # One equality field-selector term with an index beats the
        # namespace bucket; build the index on first use.
        if field_selector and "," not in field_selector \
                and "!=" not in field_selector:
            sep = "==" if "==" in field_selector else "="
            if sep in field_selector:
                path, value = field_selector.split(sep, 1)
                path = path.strip()
                if path not in self._field_idx:
                    self.ensure_field_index(path)
                keys = self._field_idx[path].get(value.strip(), set())
                if namespace and self.kind not in CLUSTER_SCOPED:
                    keys = {k for k in keys if k[0] == namespace}
                return keys
        if namespace and self.kind not in CLUSTER_SCOPED:
            return self._by_namespace.get(namespace, set())
        return self._objects.keys()

    def list(self, namespace: str | None = None,
             label_selector: str | None = None,
             field_selector: str | None = None) -> list[dict]:
        self.sync()
        with self._lock:
            out = []
            for key in self._candidates_locked(namespace, field_selector):
                obj = self._objects.get(key)
                if obj is None:
                    continue
                if label_selector and not match_label_selector(
                    (obj.get("metadata") or {}).get("labels") or {},
                    label_selector,
                ):
                    continue
                if field_selector and not match_field_selector(
                    obj, field_selector
                ):
                    continue
                # Candidate keys come from an index SET, but the
                # return below imposes ns/name order — append order is
                # unobservable.  # analysis: allow[det-unstable-iteration-order]
                out.append(_jcopy(obj))
        return sorted(
            out, key=lambda o: (o["metadata"].get("namespace", ""),
                                o["metadata"]["name"])
        )

    def for_owner(self, uid: str) -> list[dict]:
        self.sync()
        with self._lock:
            keys = sorted(self._by_owner.get(uid, set()))
            return [_jcopy(self._objects[k]) for k in keys
                    if k in self._objects]

    def __len__(self) -> int:
        with self._lock:
            return len(self._objects)


class InformerCache:
    """Lazily-built :class:`Informer` per kind, sharing one api handle
    — the manager-wide read path that replaces per-reconcile LISTs.
    Duck-types the apiserver's ``get``/``list`` so call sites (and
    ``node_inventory_capacity``) switch by handle swap."""

    def __init__(self, api):
        self.api = api
        self._lock = threading.Lock()
        self._informers: dict[tuple[str, str], Informer] = {}

    def informer(self, api_version: str, kind: str) -> Informer:
        key = (api_version, kind)
        with self._lock:
            inf = self._informers.get(key)
            if inf is None:
                inf = Informer(self.api, api_version, kind)
                self._informers[key] = inf
            return inf

    def get(self, api_version: str, kind: str, name: str,
            namespace: str | None = None) -> dict:
        return self.informer(api_version, kind).get(name, namespace)

    def list(self, api_version: str, kind: str,
             namespace: str | None = None,
             label_selector: str | None = None,
             field_selector: str | None = None) -> list[dict]:
        return self.informer(api_version, kind).list(
            namespace=namespace, label_selector=label_selector,
            field_selector=field_selector,
        )

    def sync(self) -> None:
        with self._lock:
            informers = list(self._informers.values())
        for inf in informers:
            inf.sync()

    def recover(self) -> int:
        """Run every informer's watch-resume repair; returns how many
        took the full-relist (410) path."""
        with self._lock:
            informers = list(self._informers.values())
        return sum(1 for inf in informers if inf.recover())

    def stats(self) -> dict:
        with self._lock:
            return {
                f"{av}/{kind}": {
                    "objects": len(inf), "applied": inf.applied,
                    "relists": inf.relists,
                }
                for (av, kind), inf in sorted(self._informers.items())
            }


class StatusBatcher:
    """Coalesced status writes: reconcilers submit merge patches;
    patches to the same object coalesce (deep merge, later wins —
    None, the merge-patch delete, survives) and one flush per
    controller loop iteration writes each key at most once. The
    reconcilers' own change gates (compare-before-write) stay the
    correctness layer; this bounds the write RATE under churn, where
    the same key reconciles many times per second and each pass would
    otherwise pay its own PATCH round-trip."""

    def __init__(self, api):
        self.api = api
        self._lock = threading.Lock()
        self._pending: dict[tuple, tuple[str, str, str, str, dict]] = {}
        self.submitted = 0
        self.coalesced = 0
        self.flushed = 0

    @staticmethod
    def _merge(dst: dict, src: dict) -> None:
        for k, v in src.items():
            if isinstance(v, dict) and isinstance(dst.get(k), dict):
                StatusBatcher._merge(dst[k], v)
            else:
                dst[k] = v

    def submit(self, api_version: str, kind: str, name: str,
               patch: dict, namespace: str | None = None) -> None:
        key = (api_version, kind, namespace or "", name)
        with self._lock:
            self.submitted += 1
            cur = self._pending.get(key)
            if cur is None:
                self._pending[key] = (
                    api_version, kind, name, namespace, _jcopy(patch)
                )
            else:
                self.coalesced += 1
                self._merge(cur[4], patch)

    def flush(self) -> int:
        with self._lock:
            batch = list(self._pending.values())
            self._pending.clear()
        wrote = 0
        for api_version, kind, name, namespace, patch in batch:
            try:
                self.api.patch_merge(api_version, kind, name, patch,
                                     namespace)
                wrote += 1
            except NotFound:
                pass  # object deleted since the reconcile: moot
            except Exception:
                # Level-based repair owns correctness: the next
                # reconcile of the key recomputes and resubmits.
                log.debug("status flush failed for %s/%s %s",
                          namespace, name, kind, exc_info=True)
        self.flushed += wrote
        return wrote

    def __len__(self) -> int:
        with self._lock:
            return len(self._pending)


def ensure_object(api, desired: dict) -> str:
    """Create-or-update through the native drift repair: writes only when
    an owned field differs (shared by every controller). Returns what
    happened — "created" / "updated" / "unchanged" — so callers can feed
    the create counters (reference metrics.go NotebookCreation)."""
    from kubeflow_tpu import native
    from kubeflow_tpu.k8s.fake import NotFound

    meta = desired["metadata"]
    try:
        existing = api.get(
            desired["apiVersion"], desired["kind"], meta["name"],
            meta.get("namespace"),
        )
    except NotFound:
        api.create(desired)
        return "created"
    merged = native.invoke(
        "copy_owned_fields",
        {"kind": desired["kind"], "existing": existing, "desired": desired},
    )
    if merged["changed"]:
        # A Conflict (stale read) propagates; the queue's rate limiter
        # retries the key.
        api.update(merged["merged"])
        return "updated"
    return "unchanged"


def record_event(
    api,
    involved: dict,
    reason: str,
    message: str,
    event_type: str = "Normal",
    component: str = "kubeflow-tpu-controller",
    clock: Callable[[], float] | None = None,
) -> None:
    """controller-runtime EventRecorder parity: write a v1 Event naming
    the involved object so `kubectl describe`, the JWA details page and
    the dashboard activity feed surface controller decisions.

    Like the reference recorder, repeats aggregate: a same
    (object, reason, component) event bumps count/lastTimestamp instead
    of piling up new objects — a persistently failing reconcile retried
    every minute must not grow the event list without bound. The
    aggregation target is found by a DETERMINISTIC event name
    (``<object>.<hash of kind|reason|component>``) + get/patch — one
    point read per write regardless of how many events the namespace
    holds, where a list-scan would go quadratic exactly during the
    event storms aggregation exists for. Event writes never fail a
    reconcile (fire-and-forget). ``clock`` keeps timestamps coherent
    with callers using an injected clock."""
    import hashlib
    import time as time_mod

    from kubeflow_tpu.k8s.core import Conflict, NotFound

    meta = involved.get("metadata", {})
    now = clock() if clock is not None else time_mod.time()
    stamp = time_mod.strftime("%Y-%m-%dT%H:%M:%SZ", time_mod.gmtime(now))
    namespace = meta.get("namespace", "default")
    obj_name = meta.get("name", "obj")
    key = f"{involved.get('kind', '')}|{reason}|{component}"
    suffix = hashlib.sha1(key.encode()).hexdigest()[:10]
    ev_name = f"{obj_name}.{suffix}"
    if len(ev_name) > 253:
        # DNS-subdomain cap: truncate the prefix and fold the FULL
        # object name into the hash so truncated names cannot collide
        # across objects sharing their first 242 characters (writes
        # are fire-and-forget — an over-long name would silently fail
        # forever, losing this object's aggregation entirely).
        suffix = hashlib.sha1(
            f"{obj_name}|{key}".encode()
        ).hexdigest()[:10]
        ev_name = f"{obj_name[:242]}.{suffix}"

    def bump(existing: dict) -> None:
        api.patch_merge(
            "v1", "Event", ev_name,
            {
                "count": existing.get("count", 1) + 1,
                "lastTimestamp": stamp,
                "message": message,
            },
            namespace,
        )

    try:
        try:
            bump(api.get("v1", "Event", ev_name, namespace))
            return
        except NotFound:
            pass
        try:
            api.create(
                {
                    "apiVersion": "v1",
                    "kind": "Event",
                    "metadata": {"name": ev_name, "namespace": namespace},
                    "involvedObject": {
                        "apiVersion": involved.get("apiVersion", ""),
                        "kind": involved.get("kind", ""),
                        "name": meta.get("name", ""),
                        "namespace": meta.get("namespace", ""),
                        "uid": meta.get("uid", ""),
                    },
                    "reason": reason,
                    "message": message,
                    "type": event_type,
                    "source": {"component": component},
                    "firstTimestamp": stamp,
                    "lastTimestamp": stamp,
                    "count": 1,
                }
            )
        except Conflict:
            # Lost a create race with a concurrent recorder: the event
            # exists now, fold this occurrence into it.
            bump(api.get("v1", "Event", ev_name, namespace))
    except Exception:
        log.debug("event write failed for %s/%s %s",
                  meta.get("namespace"), meta.get("name"), reason)


@dataclass
class WatchSpec:
    api_version: str
    kind: str
    # Maps a watch event object to reconcile requests (e.g. Pod -> owning
    # Notebook via labels). Default: the object itself.
    mapper: Callable[[dict], list[Request]] | None = None


class Controller:
    """One reconciler + its watches + its queue."""

    def __init__(
        self,
        name: str,
        api: FakeApiServer,
        reconciler: Reconciler,
        watches: list[WatchSpec],
        resync_period: float = 300.0,
        prom=None,  # optional ControllerMetrics for Prometheus exposition
        reconcile_deadline: float = 30.0,
        stuck_threshold: int = 10,
        clock: Callable[[], float] = time.monotonic,
        profiler: PhaseProfiler | None = None,
        recorder=None,
        shard_gate: ShardGate | None = None,
        status_batcher: StatusBatcher | None = None,
        cache: "InformerCache | None" = None,
    ):
        self.name = name
        self.api = api
        self.reconciler = reconciler
        self.queue = WorkQueue()
        self.resync_period = resync_period
        self.prom = prom
        # The reconciler's informer cache (when wired): every periodic
        # resync also runs the caches' watch-resume repair, so stream
        # damage heals at the same cadence as the level-based LIST.
        self.cache = cache
        # Horizontal sharding (fleet scale): with a gate, this replica
        # only enqueues/pops keys of shards it owns, and a freshly
        # acquired shard is resynced (re-LISTed) before its keys pop —
        # the successor-resync half of the handoff discipline. None =
        # the classic own-everything controller, byte-identical.
        self.shard_gate = shard_gate
        self._shard_synced: set[int] = set()
        # Coalesced status writes (fleet scale): reconcilers that take
        # a status_writer submit here; the run loop flushes once per
        # iteration so churn on one key costs one PATCH per cycle.
        self.status_batcher = status_batcher
        # Continuous profiling + black-box capture (PR 10): every
        # reconcile runs under this profiler's activation, so an
        # instrumented reconciler's phase splits (list / desired-state
        # / patch / status via obs.profile.phase) land in rolling
        # digests served at /debug/profile, and — when the manager
        # wires a shared FlightRecorder — each reconcile leaves one
        # bounded-ring snapshot an alert dump captures retroactively.
        self.profiler = profiler if profiler is not None else \
            PhaseProfiler()
        self.recorder = recorder
        # Stuck-reconcile watchdog knobs: a reconcile running past
        # reconcile_deadline, or a key failing stuck_threshold times in
        # a row, is surfaced (Degraded condition + Warning Event +
        # metrics) instead of hot-looping silently. The clock is
        # injectable so tests drive the deadline deterministically.
        self.reconcile_deadline = reconcile_deadline
        self.stuck_threshold = stuck_threshold
        self.clock = clock
        self._failure_streak: dict[Request, int] = {}
        self._degraded: set[Request] = set()
        # Request → traceparent from the object's TRACE_ANNOTATION,
        # captured off watch events / resync lists so the reconcile
        # span joins the trace that created the object (spawner POST).
        # Bounded: churn on annotated objects must not grow it forever.
        self._trace_parents: dict[Request, str] = {}
        if prom is not None and hasattr(prom, "queue_duration"):
            self.queue.latency_observer = (
                prom.queue_duration.labels(name).observe
            )
        # One entry per watch registration, fixed at construction.
        # analysis: allow[py-unbounded-deque]
        self._watch_queues = []
        for spec in watches:
            q = api.watch(spec.api_version, spec.kind)
            self._watch_queues.append((spec, q))
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._initial_synced = False
        self.metrics = {
            "reconciles": 0, "errors": 0, "requeues": 0,
            "stuck": 0, "deadline_exceeded": 0,
        }
        # Called once per loop tick (config-file watches and other
        # fsnotify-style side channels hook in here).
        self.tick_hooks: list[Callable[[], None]] = []

    def _run_tick_hooks(self) -> None:
        # Hook failures must not kill the run loop (a transient stat()
        # error on a watched config file is retried next tick, like
        # reconcile errors are).
        for hook in self.tick_hooks:
            try:
                hook()
            except Exception:
                log.exception("%s: tick hook failed", self.name)

    def _default_request(self, obj: dict) -> list[Request]:
        meta = obj.get("metadata", {})
        return [Request(meta.get("namespace", ""), meta.get("name", ""))]

    def _remember_trace_parent(self, obj: dict, req: Request) -> None:
        header = (
            (obj.get("metadata") or {}).get("annotations") or {}
        ).get(obs.TRACE_ANNOTATION)
        if not header:
            # Only the PRIMARY object may invalidate the link: a
            # delete-and-recreate without the annotation must not keep
            # parenting reconciles on the dead predecessor's trace —
            # but secondary watches (Pods, StatefulSets mapped to the
            # same request) never carry the annotation and must not
            # wipe a live link either.
            if (
                self._watch_queues
                and obj.get("kind") == self._watch_queues[0][0].kind
            ):
                self._trace_parents.pop(req, None)
            return
        if req not in self._trace_parents and len(self._trace_parents) >= 1024:
            self._trace_parents.pop(next(iter(self._trace_parents)))
        self._trace_parents[req] = header

    def _drain_watches(self) -> int:
        moved = 0
        gate = self.shard_gate
        for spec, q in self._watch_queues:
            while not q.empty():
                event: WatchEvent = q.get_nowait()
                mapper = spec.mapper or self._default_request
                lane = lane_for_event(event.type, event.object)
                for req in mapper(event.object):
                    if req.name:
                        if gate is not None and not gate.owns(req):
                            # Another replica's shard: its own watch
                            # stream (or its acquire-time resync)
                            # carries this key; holding it here would
                            # grow a standby's queue without bound.
                            continue
                        self._remember_trace_parent(event.object, req)
                        self.queue.add(req, lane=lane)
                        moved += 1
        return moved

    # ---- shard handoff ---------------------------------------------------
    def _accept_and_begin(self, req: Request) -> bool:
        """Pop filter under sharding: only keys of shards this replica
        owns AND has resynced since acquiring (the successor must
        re-derive the shard's level state before reconciling it). On
        acceptance the reconcile is counted in-flight inside the
        gate's own critical section (``try_begin``) — a voluntary
        handoff's drain check can never observe zero between the
        ownership check and the reconcile starting (the
        dual-reconcile TOCTOU window). The synced set is
        controller-thread-local, so reading it outside the gate lock
        is safe."""
        gate = self.shard_gate
        if gate.shard(req) not in self._shard_synced:
            return False
        return gate.try_begin(req)

    def _discard_unowned(self, req: Request) -> bool:
        """Queue-eviction filter under sharding: a pending key whose
        shard this replica no longer owns at all (e.g. acquired and
        lost between two loop iterations, before it was ever synced)
        is dead weight — the next owner re-derives it from its own
        acquire-time resync."""
        return self.shard_gate.shard(req) not in self.shard_gate.owned()

    def _sync_owned_shards(self) -> None:
        """Reconcile this controller's view of shard ownership with
        the gate: lost shards drop their queued keys (the successor
        re-derives them), newly acquired shards are resynced before
        their keys become poppable."""
        gate = self.shard_gate
        if gate is None:
            return
        owned = gate.owned()
        lost = self._shard_synced - owned
        if lost:
            self._shard_synced -= lost
            self.queue.drop(lambda req: gate.shard(req) in lost)
        fresh = owned - self._shard_synced
        if fresh:
            self.resync(shards=fresh)
            self._shard_synced |= fresh

    def _process_one(self) -> bool:
        gate = self.shard_gate
        if gate is None:
            req = self.queue.pop_ready()
        else:
            # accept counts the reconcile in-flight atomically with
            # the pop (see _accept_and_begin).
            req = self.queue.pop_ready(
                accept=self._accept_and_begin,
                discard=self._discard_unowned,
            )
        if req is None:
            return False
        try:
            return self._reconcile_one(req)
        finally:
            if gate is not None:
                gate.end(gate.shard(req))

    def _reconcile_one(self, req: Request) -> bool:
        self.metrics["reconciles"] += 1
        # The reconcile span joins the trace that created the object
        # when its CR carries the trace annotation (spawner POST → CR →
        # watch event → here); otherwise it roots a fresh trace. Every
        # apiserver round-trip the reconciler makes nests underneath
        # via the contextvar.
        parent = obs.parse_traceparent(self._trace_parents.get(req))
        tracer = obs.get_tracer()
        started = self.clock()
        with tracer.span(
            "reconcile",
            parent=parent,
            attributes={
                "controller": self.name,
                "namespace": req.namespace,
                "name": req.name,
            },
        ) as span, self.profiler.activate() as phases:
            try:
                requeue_after = self.reconciler.reconcile(req)
            except Exception as exc:
                elapsed = self.clock() - started
                self.profiler.observe("total", elapsed)
                self._observe_duration(elapsed)
                log.exception("%s: reconcile %s failed", self.name, req)
                self.metrics["errors"] += 1
                if self.prom is not None:
                    self.prom.reconcile_total.labels(
                        self.name, "error"
                    ).inc()
                streak = self._failure_streak.get(req, 0) + 1
                self._failure_streak[req] = streak
                span.record_exception(exc)
                span.add_event("requeue_rate_limited",
                               {"failures": streak})
                if (streak >= self.stuck_threshold
                        and req not in self._degraded):
                    self._mark_degraded(req, streak)
                self.queue.add_rate_limited(req)
                self._snapshot_reconcile(req, phases, "error")
                return True
            elapsed = self.clock() - started
            self.profiler.observe("total", elapsed)
            self._observe_duration(elapsed)
            if elapsed > self.reconcile_deadline:
                # Reconciles run on shared workers and cannot be aborted
                # mid-flight; the watchdog surfaces the overrun so a
                # wedged probe or API hang is an alert, not a silent
                # stall.
                self.metrics["deadline_exceeded"] += 1
                if self.prom is not None:
                    self.prom.reconcile_stuck_total.labels(
                        self.name, "deadline"
                    ).inc()
                span.add_event("deadline_exceeded", {
                    "elapsed_s": round(elapsed, 3),
                    "deadline_s": self.reconcile_deadline,
                })
                self._record_watchdog_event(
                    req, "ReconcileDeadlineExceeded",
                    f"reconcile of {req.namespace}/{req.name} took "
                    f"{elapsed:.1f}s "
                    f"(deadline {self.reconcile_deadline:.1f}s)",
                )
            if self.prom is not None:
                self.prom.reconcile_total.labels(
                    self.name, "success"
                ).inc()
            self._failure_streak.pop(req, None)
            if req in self._degraded:
                self._clear_degraded(req)
            self.queue.forget(req)
            if requeue_after is not None:
                self.metrics["requeues"] += 1
                span.add_event("requeue_after",
                               {"delay_s": requeue_after})
                self.queue.add(req, delay=requeue_after)
            self._snapshot_reconcile(req, phases, "ok")
        return True

    def _snapshot_reconcile(self, req: Request, phases: dict,
                            outcome: str) -> None:
        """One flight-recorder snapshot per reconcile: the phase split
        the reconciler reported (list / desired-state / patch / status
        — plus the runtime's own ``total``), queue depth, and — via
        the recorder, which reads the live span — the trace id this
        reconcile ran under."""
        if self.recorder is None:
            return
        self.recorder.record(
            "reconcile",
            controller=self.name,
            namespace=req.namespace,
            name=req.name,
            outcome=outcome,
            phases={k: round(v, 6) for k, v in (phases or {}).items()},
            queue_depth=len(self.queue),
        )

    def _observe_duration(self, elapsed: float) -> None:
        if self.prom is not None and hasattr(self.prom,
                                             "reconcile_duration"):
            # The reconcile span is active here (we are inside the
            # tracer.span block); stamping its trace id as an
            # OpenMetrics exemplar links a p99 bucket on /metrics to
            # the exact trace that produced it. Only sampled spans —
            # an unsampled id resolves to no exporter.
            span = obs.current_span()
            exemplar = None
            if span is not None and span.context.sampled:
                exemplar = {"trace_id": span.context.trace_id}
            self.prom.reconcile_duration.labels(self.name).observe(
                elapsed, exemplar=exemplar
            )

    # ---- stuck-reconcile watchdog ---------------------------------------
    def _primary_object(self, req: Request) -> dict | None:
        """The CR this controller owns for ``req``, via the primary
        watch spec; None when unreachable (the apiserver may be the
        very thing that is failing)."""
        if not self._watch_queues:
            return None
        spec = self._watch_queues[0][0]
        try:
            return self.api.get(
                spec.api_version, spec.kind, req.name,
                req.namespace or None,
            )
        except Exception as exc:
            log.debug("%s: watchdog could not fetch %s: %s",
                      self.name, req, exc)
            return None

    def _record_watchdog_event(
        self, req: Request, reason: str, message: str,
        event_type: str = "Warning",
    ) -> None:
        obj = self._primary_object(req)
        if obj is None:
            return
        record_event(
            self.api, obj, reason, message, event_type=event_type,
            component=self.name,
        )

    def _patch_degraded_condition(
        self, req: Request, condition: dict | None
    ) -> None:
        """Set (or, with ``condition=None``, remove) the watchdog's
        Degraded condition on the primary CR. Removal must delete the
        ``conditions`` key outright when nothing else is left: a CR
        whose reconciler exact-compares its computed status (pvcviewer,
        tensorboard) would otherwise see a foreign leftover key and
        rewrite status forever."""
        if not self._watch_queues:  # watch-less controller: no CR to mark
            return
        spec = self._watch_queues[0][0]
        obj = self._primary_object(req)
        if obj is None:
            return
        conditions = [
            c for c in (obj.get("status") or {}).get("conditions") or []
            if c.get("type") != "Degraded"
        ]
        if condition is not None:
            conditions.append(condition)
        try:
            self.api.patch_merge(
                spec.api_version, spec.kind, req.name,
                {"status": {"conditions": conditions or None}},
                req.namespace or None,
            )
        except Exception:
            # Best-effort like event writes: the status patch must not
            # turn a degraded key into a crashed controller.
            log.debug("%s: Degraded condition patch failed for %s",
                      self.name, req)

    def _mark_degraded(self, req: Request, streak: int) -> None:
        """Consecutive-failure threshold crossed: make the stall
        visible on the CR (Degraded condition + Warning Event) instead
        of hot-looping silently. The workqueue's exponential backoff
        keeps retrying underneath; a later success clears the mark."""
        self.metrics["stuck"] += 1
        self._degraded.add(req)
        if self.prom is not None:
            self.prom.reconcile_stuck_total.labels(
                self.name, "failures"
            ).inc()
        message = (
            f"reconcile has failed {streak} consecutive times; "
            "retrying with exponential backoff"
        )
        log.warning("%s: %s/%s %s", self.name, req.namespace, req.name,
                    message)
        self._patch_degraded_condition(req, {
            "type": "Degraded",
            "status": "True",
            "reason": "ReconcileStuck",
            "message": message,
        })
        self._record_watchdog_event(req, "ReconcileStuck", message)

    def _clear_degraded(self, req: Request) -> None:
        self._degraded.discard(req)
        self._patch_degraded_condition(req, None)
        self._record_watchdog_event(
            req, "ReconcileRecovered",
            f"reconcile of {req.namespace}/{req.name} recovered",
            event_type="Normal",
        )

    def run_once(self, max_iterations: int = 100) -> int:
        """Drain watches and reconcile until quiescent (tests/dev).

        Reconciles can themselves emit watch events (status updates);
        iterate until no event and no ready work remain. Delayed requeues
        (requeue_after > 0) are left pending.
        """
        if not self._initial_synced:
            # Informer-style initial LIST: objects that predate the
            # controller get reconciled without waiting for an event.
            # Under sharding the acquire-time resync inside
            # _sync_owned_shards IS the initial sync for everything
            # this replica owns — a second full LIST would double the
            # O(n) startup cost for no behavioural gain.
            if self.shard_gate is None:
                self.resync()
            else:
                self._sync_owned_shards()
            self._initial_synced = True
        processed = 0
        self._run_tick_hooks()
        self._sync_owned_shards()
        for _ in range(max_iterations):
            self._drain_watches()
            if not self._process_one():
                if not self._drain_watches():
                    break
            else:
                processed += 1
        # ONE flush per drain cycle: flushing per item would pay the
        # same PATCH rate as the direct write path and coalesce
        # nothing.
        if self.status_batcher is not None:
            self.status_batcher.flush()
        return processed

    def run_forever(self, poll_interval: float = 0.05):
        if not self._initial_synced:
            if self.shard_gate is None:
                self.resync()
            else:
                self._sync_owned_shards()
            self._initial_synced = True
        last_resync = time.monotonic()
        while not self._stop.is_set():
            self._run_tick_hooks()
            self._sync_owned_shards()
            self._drain_watches()
            worked = self._process_one()
            if self.status_batcher is not None and (
                not worked or len(self.status_batcher) >= 64
            ):
                # Coalesce across the burst, flush on idle (or at a
                # size bound so a busy loop can't defer status
                # visibility unboundedly).
                self.status_batcher.flush()
            if time.monotonic() - last_resync > self.resync_period:
                last_resync = time.monotonic()
                self.resync()
            if not worked:
                self._stop.wait(poll_interval)

    def resync(self, shards: set[int] | frozenset[int] | None = None
               ) -> int | None:
        """Re-enqueue every primary object (level-based safety net).
        A failed LIST (apiserver outage) must not kill the run loop —
        the next periodic resync retries; until then the watch stream
        and the queue's own retries keep the controller alive. Returns
        the number of objects enqueued, or None when the list failed —
        the chaos harness needs to distinguish "provably nothing to do"
        from "could not even ask". With a shard gate, only owned keys
        enqueue; ``shards`` narrows further to a freshly acquired
        subset (the successor-resync half of the handoff)."""
        spec = self._watch_queues[0][0] if self._watch_queues else None
        if spec is None:
            return 0
        if self.cache is not None:
            # Informer watch-resume repair rides the resync cadence: a
            # compacted/damaged stream re-lists here, so the cache can
            # never stay stale longer than one resync period. Failures
            # (the apiserver may be the thing that's down) retry next
            # cycle like the LIST below.
            try:
                self.cache.recover()
            except Exception as exc:
                log.warning("%s: informer recovery failed (%s); "
                            "retrying on the next cycle",
                            self.name, exc)
        try:
            objs = self.api.list(spec.api_version, spec.kind)
        except Exception as exc:
            log.warning("%s: resync list failed (%s); retrying on the "
                        "next cycle", self.name, exc)
            return None
        gate = self.shard_gate
        count = 0
        for obj in objs:
            # Restart amnesia repair: the failure streak behind a
            # Degraded mark lives only in memory, so after a controller
            # restart the mark would never be cleared. Rebuild the
            # in-memory set from the observed CR state, and the next
            # successful reconcile removes the condition as usual.
            inherited = any(
                c.get("type") == "Degraded"
                and c.get("status") == "True"
                and c.get("reason") == "ReconcileStuck"
                for c in (obj.get("status") or {}).get("conditions") or []
            )
            for req in (spec.mapper or self._default_request)(obj):
                if gate is not None:
                    shard = gate.shard(req)
                    if shards is not None and shard not in shards:
                        continue
                    if shards is None and not gate.owns(req):
                        continue
                self._remember_trace_parent(obj, req)
                self.queue.add(req)
                count += 1
                if inherited:
                    self._degraded.add(req)
        return count

    def start(self) -> threading.Thread:
        # Controllers are restarted across leadership transitions
        # (manager.py). The previous stint's thread must be fully gone
        # before the stop signal is cleared — clearing early on a fast
        # lose/regain flap would leave two run loops reconciling the
        # same keys concurrently.
        if self._thread is not None and self._thread.is_alive():
            self._stop.set()
            self._thread.join(timeout=10.0)
            if self._thread.is_alive():
                raise RuntimeError(
                    f"{self.name}: previous run loop did not stop"
                )
        self._stop.clear()
        self._thread = threading.Thread(
            target=self.run_forever, name=self.name, daemon=True
        )
        self._thread.start()
        return self._thread

    def stop(self):
        self._stop.set()
