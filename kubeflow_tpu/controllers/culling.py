"""Culling controller: probe idle notebooks, scale them to zero.

Python half of the reference culler (reference
controllers/culling_controller.go:78-162): periodically probes each
Notebook's Jupyter ``/api/kernels`` endpoint over the cluster network and
feeds the result to the native decision engine (native/src/culler.cpp),
which owns annotation bookkeeping and the stop decision. TPU delta: an
injectable ``tpu_busy_probe`` (device-metrics signal) vetoes culling a
slice mid-run even when kernels look idle.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Callable

from kubeflow_tpu import native
from kubeflow_tpu.controllers.runtime import (
    Controller,
    Request,
    WatchSpec,
    record_event,
)
from kubeflow_tpu.controllers.time_utils import parse_rfc3339
from kubeflow_tpu.k8s.fake import FakeApiServer, NotFound

log = logging.getLogger(__name__)

NOTEBOOK_API = "kubeflow.org/v1beta1"

# Probe signature: (namespace, name) -> list of kernel dicts, or None when
# the notebook is unreachable. Production uses HTTP GET
# http://<name>.<ns>.svc/notebook/<ns>/<name>/api/kernels (reference
# getNotebookApiKernels, culling_controller.go:202-241); tests inject.
KernelProbe = Callable[[str, str], list | None]


def http_kernel_probe(
    timeout: float = 5.0,
    url_for: Callable[[str, str], str] | None = None,
) -> KernelProbe:
    """``url_for`` overrides the target URL (tests point it at a local
    fixture server; production uses the in-cluster Service DNS)."""
    import json
    import urllib.request

    def default_url(namespace: str, name: str) -> str:
        return (
            f"http://{name}.{namespace}.svc.cluster.local"
            f"/notebook/{namespace}/{name}/api/kernels"
        )

    url_for = url_for or default_url

    def probe(namespace: str, name: str):
        try:
            with urllib.request.urlopen(
                url_for(namespace, name), timeout=timeout
            ) as resp:
                body = json.loads(resp.read().decode())
        except Exception as exc:
            # Unreachable counts as "no signal", not an error — but say
            # so: a notebook that never becomes probeable would
            # otherwise look permanently active with zero trace.
            log.debug("kernel probe %s/%s failed: %s", namespace, name, exc)
            return None
        # The contract is a kernel LIST; any other shape (an error page
        # that parses as JSON, a dict) counts as unreachable, matching
        # the reference's unmarshal-failure branch
        # (culling_controller.go:232-239).
        return body if isinstance(body, list) else None

    return probe


def http_tpu_busy_probe(
    threshold_pct: float = 5.0,
    port: int = 8431,
    timeout: float = 5.0,
    cluster_domain: str = "cluster.local",
    url_for: Callable[[str, str], str] | None = None,
) -> Callable[[str, str], bool]:
    """TPU-idle signal (SURVEY §7 hard part d): a raw JAX process has no
    ``/api/kernels``, so the culler also scrapes the duty-cycle exporter
    the jupyter-jax-tpu image runs on every host
    (images/jupyter-jax-tpu/s6/services.d/tpu-metrics) via the rank-0
    pod's stable headless-service DNS. Busy (=veto culling) when the
    TensorCore duty cycle exceeds ``threshold_pct``; unreachable or
    unparsable metrics count as not-busy so a wedged exporter cannot pin
    a slice forever (kernel-idleness still gates the actual stop)."""
    import urllib.request

    def default_url(namespace: str, name: str) -> str:
        return (
            f"http://{name}-0.{name}-hosts.{namespace}.svc.{cluster_domain}"
            f":{port}/metrics"
        )

    url_for = url_for or default_url

    def probe(namespace: str, name: str) -> bool:
        try:
            with urllib.request.urlopen(
                url_for(namespace, name), timeout=timeout
            ) as resp:
                text = resp.read().decode()
        except Exception as exc:
            # Not-busy by design (a wedged exporter must not pin the
            # slice), but leave a trace for the operator.
            log.debug("tpu busy probe %s/%s failed: %s", namespace, name, exc)
            return False
        return parse_duty_cycle(text) > threshold_pct

    return probe


def parse_duty_cycle(metrics_text: str) -> float:
    """Max ``tpu_duty_cycle_percent`` sample from Prometheus text
    exposition (one series per chip). Only that exact metric name is
    matched (not name-prefix extensions), and the value is the field
    right after the name+labels — a trailing exposition timestamp is
    ignored."""
    best = 0.0
    for line in metrics_text.splitlines():
        line = line.strip()
        name, _, rest = line.partition("{")
        if rest:  # labelled series: value follows the closing brace
            rest = rest.partition("}")[2]
        else:
            name, _, rest = line.partition(" ")
        if name.strip() != "tpu_duty_cycle_percent":
            continue
        fields = rest.split()
        if not fields:
            continue
        try:
            value = float(fields[0])
        except ValueError:
            continue
        best = max(best, value)
    return best


@dataclasses.dataclass
class CullingOptions:
    """ENABLE_CULLING / CULL_IDLE_TIME / IDLENESS_CHECK_PERIOD env parity
    (reference initGlobalVars, culling_controller.go:405-438)."""

    enabled: bool = False
    cull_idle_time_min: int = 1440
    idleness_check_period_min: int = 1

    def to_native(self) -> dict:
        return {
            "cullIdleTimeMin": self.cull_idle_time_min,
            "idlenessCheckPeriodMin": self.idleness_check_period_min,
        }


class CullingReconciler:
    def __init__(
        self,
        api: FakeApiServer,
        kernel_probe: KernelProbe,
        options: CullingOptions | None = None,
        tpu_busy_probe: Callable[[str, str], bool] | None = None,
        clock: Callable[[], float] = time.time,
        prom=None,  # optional ControllerMetrics (metrics.py)
        scheduler=None,  # scheduler.SlicePoolScheduler (or None)
        cache=None,  # runtime.InformerCache (or None: plain gets)
    ):
        self.api = api
        self.kernel_probe = kernel_probe
        self.options = options or CullingOptions()
        self.tpu_busy_probe = tpu_busy_probe
        self.clock = clock
        self.prom = prom
        self.scheduler = scheduler
        self.cache = cache

    def reconcile(self, req: Request) -> float | None:
        if not self.options.enabled:
            return None
        try:
            notebook = self.api.get(
                NOTEBOOK_API, "Notebook", req.name, req.namespace
            )
        except NotFound:
            return None

        # Cheap pre-checks BEFORE the (networked) kernel probe — mirrors
        # the reference's ordering (culling_controller.go:96-137): skip
        # stopped notebooks and honour the check-timestamp rate limit so
        # every watch event doesn't cost an HTTP round-trip.
        annotations = notebook["metadata"].get("annotations") or {}
        period_sec = 60.0 * self.options.idleness_check_period_min
        if "kubeflow-resource-stopped" in annotations:
            return period_sec
        last_check = parse_rfc3339(
            annotations.get(
                "notebooks.kubeflow.org/last_activity_check_timestamp", ""
            )
        )
        now = int(self.clock())
        if last_check is not None and now - last_check < period_sec:
            return period_sec - (now - last_check)

        # Pod must exist before idleness accounting starts (reference
        # culling_controller.go:107-118). Through the informer when
        # one is wired: the culler's periodic sweep across N notebooks
        # is N point reads — the cache makes them store lookups.
        pod_source = self.cache if self.cache is not None else self.api
        try:
            pod_source.get("v1", "Pod", f"{req.name}-0", req.namespace)
        except NotFound:
            return period_sec

        kernels = self.kernel_probe(req.namespace, req.name)
        config = self.options.to_native()

        def decide() -> dict:
            return native.invoke(
                "cull_decide",
                {
                    "notebook": notebook,
                    "kernels": kernels,
                    "nowEpoch": int(self.clock()),
                    "config": config,
                },
            )

        decision = decide()
        if decision["action"] == "stop" and self.tpu_busy_probe is not None:
            # Lazy TPU probe: the (networked, possibly slow) duty-cycle
            # scrape only runs when the kernel signal alone would cull —
            # N active notebooks cost zero extra HTTP round-trips.
            if self.tpu_busy_probe(req.namespace, req.name):
                config["tpuBusy"] = True
                decision = decide()
        reclaim = (
            decision["action"] == "stop"
            and self.scheduler is not None
            and bool((notebook.get("spec") or {}).get("tpu"))
            and self.scheduler.tracks("Notebook", req.namespace,
                                      req.name)
        )
        if reclaim:
            # Scheduler-managed slice: the idle verdict feeds the pool
            # instead of the hard stop — the scheduler drains through
            # the checkpoint grace path, scales to zero
            # (status.phase=Suspended) and returns the chips; first
            # touch resurrects via the resume handshake. The idleness
            # bookkeeping is still written, but NOT the stop
            # annotation (a kubeflow-resource-stopped slice would need
            # a manual start; a Suspended one comes back by itself).
            # A slice the scheduler does NOT track (e.g. an
            # invalid-topology spec the gate skipped) instead falls
            # through to the normal stop below: idle chips must never
            # be held by a workload no scheduler can reclaim. For a
            # tracked one, mark_reclaimable is idempotent — False when
            # already draining/suspended, which stays on this branch
            # so the hard stop never races an in-flight reclaim.
            annotations = {
                k: v for k, v in decision["annotations"].items()
                if k != "kubeflow-resource-stopped"
            }
            if annotations:
                self.api.patch_merge(
                    NOTEBOOK_API, "Notebook", req.name,
                    {"metadata": {"annotations": annotations}},
                    req.namespace,
                )
            if self.scheduler.mark_reclaimable(
                "Notebook", req.namespace, req.name,
                now=self.clock(),
            ):
                log.info("marked idle notebook %s/%s reclaimable",
                         req.namespace, req.name)
                record_event(
                    self.api, notebook, "SliceReclaimable",
                    f"Notebook {req.name} idle past the threshold; "
                    "checkpointing, then scaling to zero (chips "
                    "return to the slice pool; first touch "
                    "resurrects)",
                    component="notebook-culler",
                    clock=self.clock,
                )
            return float(decision["requeueAfterSec"])
        if decision["action"] in ("update-annotations", "stop"):
            self.api.patch_merge(
                NOTEBOOK_API,
                "Notebook",
                req.name,
                {"metadata": {"annotations": decision["annotations"]}},
                req.namespace,
            )
            if decision["action"] == "stop":
                log.info("culled idle notebook %s/%s", req.namespace, req.name)
                record_event(
                    self.api, notebook, "Culled",
                    f"Notebook {req.name} idle past the threshold; "
                    "scaled to zero (volumes retained)",
                    component="notebook-culler",
                    clock=self.clock,
                )
                if self.prom is not None:
                    # Reference NotebookCullingCount + culling-timestamp
                    # gauge (metrics.go:46-59).
                    self.prom.notebook_culling_total.labels(
                        req.namespace, req.name
                    ).inc()
                    self.prom.last_culling_timestamp.labels(
                        req.namespace, req.name
                    ).set(int(self.clock()))
        return float(decision["requeueAfterSec"])


def make_culling_controller(
    api: FakeApiServer,
    kernel_probe: KernelProbe | None = None,
    options: CullingOptions | None = None,
    tpu_busy_probe: Callable[[str, str], bool] | None = None,
    clock: Callable[[], float] = time.time,
    prom=None,
    scheduler=None,
    cache=None,
    shard_gate=None,
) -> Controller:
    reconciler = CullingReconciler(
        api,
        kernel_probe or http_kernel_probe(),
        options,
        tpu_busy_probe,
        clock,
        prom=prom,
        scheduler=scheduler,
        cache=cache,
    )
    return Controller(
        name="culling-controller",
        api=api,
        reconciler=reconciler,
        watches=[WatchSpec(NOTEBOOK_API, "Notebook")],
        resync_period=60.0,
        prom=prom,
        shard_gate=shard_gate,
        cache=cache,
    )
