"""Scheduler-verdict plumbing shared by the workload reconcilers.

The notebook and InferenceService reconcilers consult the slice-pool
scheduler the same way: read whether the gang's world is already
materialised (restart adoption), apply the verdict's annotation
patches, stamp the resume handshake, record the transition events,
and ack the handshake once it is durable. One implementation keeps
the handshake semantics — patch BEFORE event BEFORE ack, so a crashed
reconcile retries level-based — from drifting between CRDs.
"""

from __future__ import annotations

from kubeflow_tpu.controllers.runtime import Request, record_event
from kubeflow_tpu.k8s.fake import NotFound


def observed_running(api, req: Request) -> bool:
    """Is the workload's StatefulSet already holding replicas? The
    restart-adoption signal: a scheduler whose in-memory state died
    with the previous manager must grandfather a running gang as
    ADMITTED instead of re-queueing it (and scaling a live slice to
    zero without the checkpoint drain)."""
    try:
        sts = api.get("apps/v1", "StatefulSet", req.name,
                      req.namespace)
    except NotFound:
        return False
    try:
        return int((sts.get("spec") or {}).get("replicas") or 0) > 0
    except (TypeError, ValueError):
        return False


def apply_verdict(
    api,
    api_version: str,
    kind: str,
    obj: dict,
    req: Request,
    verdict,
    scheduler,
    clock,
    resume_key: str | None,
    resume_message: str,
) -> None:
    """Apply one :class:`~kubeflow_tpu.scheduler.SchedulingVerdict` to
    the CR: annotation merge patch (+ local mirror, the elastic
    discipline), the durable resume stamp (``resume_key``), the
    change-gated transition events, and the handshake ack. The ack
    only happens after the patch landed — the scheduler re-delivers
    ``resume_from`` until then."""
    anns = obj.setdefault("metadata", {}).setdefault("annotations", {})
    patches = dict(verdict.annotations or {})
    if verdict.resume_from is not None and resume_key is not None:
        patches[resume_key] = verdict.resume_from
    if patches:
        api.patch_merge(
            api_version, kind, req.name,
            {"metadata": {"annotations": patches}},
            req.namespace,
        )
        for key, value in patches.items():
            if value is None:
                anns.pop(key, None)
            else:
                anns[key] = value
    cur_phase = (obj.get("status") or {}).get("phase")
    if verdict.resume_from is not None:
        record_event(
            api, obj, "SliceResumed",
            resume_message.format(step=verdict.resume_from),
            clock=clock,
        )
        # Handshake durable (the patch above would have raised
        # otherwise): stop the scheduler re-delivering it.
        scheduler.ack_resume(kind, req.namespace, req.name)
    elif verdict.phase and verdict.phase != cur_phase:
        record_event(
            api, obj, f"Slice{verdict.phase}",
            verdict.reason or f"scheduler: {verdict.phase}",
            event_type=("Warning" if verdict.phase == "Preempting"
                        else "Normal"),
            clock=clock,
        )
