"""PVCViewer controller (reference pvcviewer-controller/controllers/
pvcviewer_controller.go + api/v1alpha1/pvcviewer_webhook.go): PVCViewer
CR → filebrowser Deployment + Service + VirtualService with the viewer
URL in status; defaulting applied controller-side (the reference uses a
defaulting webhook)."""

from __future__ import annotations

import dataclasses
import logging

from kubeflow_tpu import native
from kubeflow_tpu.controllers.runtime import (
    Controller,
    Request,
    WatchSpec,
    ensure_object,
)
from kubeflow_tpu.controllers.tensorboard import (
    deployment_to_tensorboard as deployment_to_owner,
    find_rwo_node,
)
from kubeflow_tpu.k8s.fake import FakeApiServer, NotFound

log = logging.getLogger(__name__)

PVCVIEWER_API = "kubeflow.org/v1alpha1"


@dataclasses.dataclass
class PvcViewerOptions:
    viewer_image: str = "filebrowser/filebrowser:v2"
    use_istio: bool = False
    istio_gateway: str = "kubeflow/kubeflow-gateway"
    istio_host: str = "*"
    cluster_domain: str = "cluster.local"


class PvcViewerReconciler:
    def __init__(self, api: FakeApiServer, options: PvcViewerOptions | None = None):
        self.api = api
        self.options = options or PvcViewerOptions()

    def _ensure(self, desired: dict) -> None:
        ensure_object(self.api, desired)

    def reconcile(self, req: Request) -> float | None:
        try:
            viewer = self.api.get(PVCVIEWER_API, "PVCViewer", req.name,
                                  req.namespace)
        except NotFound:
            return None

        options = {
            "viewerImage": self.options.viewer_image,
            "useIstio": self.options.use_istio,
            "istioGateway": self.options.istio_gateway,
            "istioHost": self.options.istio_host,
            "clusterDomain": self.options.cluster_domain,
        }
        spec = viewer.get("spec") or {}
        if spec.get("rwoScheduling", True) and spec.get("pvc"):
            node = find_rwo_node(self.api, req.namespace, spec["pvc"])
            if node:
                options["rwoPvcNode"] = node

        out = native.invoke(
            "pvcviewer_reconcile", {"viewer": viewer, "options": options}
        )
        self._ensure(out["deployment"])
        self._ensure(out["service"])
        if out["virtualService"] is not None:
            self._ensure(out["virtualService"])

        try:
            deployment = self.api.get("apps/v1", "Deployment", req.name,
                                      req.namespace)
        except NotFound:
            deployment = {}
        status = {
            "ready": bool((deployment.get("status") or {}).get("readyReplicas")),
            "url": out["url"],
        }
        # Compare (and patch) only the keys this reconciler owns:
        # status may also carry foreign keys — e.g. the runtime
        # watchdog's Degraded condition — and comparing the whole dict
        # against an exact computed value would rewrite status forever.
        cur = viewer.get("status") or {}
        if {k: cur.get(k) for k in status} != status:
            self.api.patch_merge(
                PVCVIEWER_API, "PVCViewer", req.name, {"status": status},
                req.namespace,
            )
        return None


def make_pvcviewer_controller(
    api: FakeApiServer, options: PvcViewerOptions | None = None
) -> Controller:
    return Controller(
        name="pvcviewer-controller",
        api=api,
        reconciler=PvcViewerReconciler(api, options),
        watches=[
            WatchSpec(PVCVIEWER_API, "PVCViewer"),
            # Deployment readiness must refresh status.ready promptly
            # (the reference controller Owns() the Deployment).
            WatchSpec("apps/v1", "Deployment", deployment_to_owner),
        ],
    )
