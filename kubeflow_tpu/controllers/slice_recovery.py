"""Observed-mesh TPU preemption recovery, shared across controllers.

The notebook controller grew this logic for multi-host slices (PR 2);
the InferenceService controller needs the identical state machine —
the failure physics (jax.distributed wedging on a partial mesh) do
not care which CRD owns the StatefulSet. Extracted here so both
reconcilers drive ONE implementation, parameterised by the CRD
coordinates, the annotation keys and two policy hooks:

- ``on_first_restart()`` — fired once per recovery (not per retry
  pass); the callers bump their preemption-restart counters here.
- ``on_rebaseline(patch, anns, replicas)`` — fired when an entirely
  fresh full set re-baselines after a recovery; callers append their
  resume handshake (the notebook controller stamps the
  checkpoint-resume annotations and records SliceRestarted here).

Semantics (unchanged from the notebook controller, pinned by
tests/test_chaos.py): membership is tracked as a pod-name→uid map
annotation; a MIX of survivors and missing/replaced workers is a
partial mesh and every surviving pod is deleted in one pass (deletes
BEFORE the annotation write, so a crash mid-loop retries the restart
instead of recording it as done); an entirely fresh full set
re-baselines; replicas <= 1 needs no mesh protection and clears any
leftover bookkeeping.
"""

from __future__ import annotations

import dataclasses
import json
import logging

from kubeflow_tpu.controllers.runtime import Request, record_event
from kubeflow_tpu.k8s.fake import NotFound

log = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class SliceAnnotations:
    """The per-CRD annotation namespace the recovery state lives in."""

    observed_mesh: str
    restart_reason: str
    preemption_restarts: str


def recover_slice(
    api,
    api_version: str,
    kind: str,
    obj: dict,
    req: Request,
    sts: dict | None,
    pods: list | None,
    keys: SliceAnnotations,
    on_first_restart=None,
    on_rebaseline=None,
) -> str | None:
    """One recovery pass for ``obj``'s slice. Returns the restart
    reason while a recovery is in flight (callers surface it as
    phase=Restarting), else None. ``sts``/``pods`` are the caller's
    already-fetched StatefulSet and label-selected pod list — this
    runs on every reconcile, so it must not re-fetch what the caller
    already has."""

    def patch_annotations(annotations: dict) -> None:
        api.patch_merge(
            api_version, kind, req.name,
            {"metadata": {"annotations": annotations}},
            req.namespace,
        )

    if pods is None or sts is None:  # non-TPU, or STS not yet created
        return None
    replicas = (sts.get("spec") or {}).get("replicas") or 0
    anns = (obj.get("metadata") or {}).get("annotations") or {}
    reason = anns.get(keys.restart_reason)
    if replicas <= 1:
        # Single host (or stopped): the statefulset controller's own
        # pod recreation is already coherent — no mesh to protect.
        # Drop any leftover baseline: workers recreated on a later
        # scale-up must not read as preempted replacements.
        stale = {k: None for k in (keys.observed_mesh,
                                   keys.restart_reason) if k in anns}
        if stale:
            patch_annotations(stale)
        return None
    expected = {f"{req.name}-{i}" for i in range(replicas)}
    current = {
        p["metadata"]["name"]: p["metadata"].get("uid", "")
        for p in pods
        if p["metadata"]["name"] in expected
        and not p["metadata"].get("deletionTimestamp")
    }
    observed: dict | None = None
    raw = anns.get(keys.observed_mesh)
    if raw:
        try:
            parsed = json.loads(raw)
            if isinstance(parsed, dict):
                observed = parsed
        except ValueError:
            observed = None
    full = expected <= set(current)
    if observed is None:
        # First sight of a complete slice: baseline it. Partial sets
        # are still forming — baselining one would brand the late
        # arrivals as "replacements".
        if full:
            patch_annotations({
                keys.observed_mesh: json.dumps(current, sort_keys=True),
            })
        return reason
    survivors = {n for n, uid in current.items()
                 if observed.get(n) == uid}
    # Only workers the baseline KNEW can be "gone": a missing ordinal
    # never in the mesh is a scale-up still materialising, not a
    # preemption.
    missing = {n for n in expected - set(current) if n in observed}
    replaced = {n for n, uid in current.items()
                if n in observed and observed[n] != uid}
    if full and not survivors:
        # Entirely fresh full set: the slice came back together
        # (post-restart, or a coherent rollout). Re-baseline and clear
        # the in-flight marker.
        patch: dict = {
            keys.observed_mesh: json.dumps(current, sort_keys=True),
        }
        if reason:
            patch[keys.restart_reason] = None
            if on_rebaseline is not None:
                on_rebaseline(patch, anns, replicas)
        patch_annotations(patch)
        return None
    if full and not missing and not replaced:
        # Healthy steady state; clear a stale marker if a previous
        # recovery pass died between its deletes and this point, and
        # re-baseline after a replica-count change — stale ordinals
        # left behind by a scale-down (or fresh ones added by a
        # scale-up) must not read as preemptions later.
        patch = {}
        if reason:
            patch[keys.restart_reason] = None
        if set(observed) != set(current):
            patch[keys.observed_mesh] = json.dumps(
                current, sort_keys=True
            )
        if patch:
            patch_annotations(patch)
        return None
    if survivors and (missing or replaced):
        # Partial mesh: some workers survived while others are gone or
        # already recreated — jax.distributed cannot survive that.
        # Recycle every present pod in one pass; deletes come BEFORE
        # the annotation write so a crash mid-loop retries the restart
        # instead of recording it as done.
        gone = sorted(missing | replaced)
        reason = (
            f"TPU worker(s) {', '.join(gone)} preempted or evicted; "
            f"restarting all {replicas} workers (a multi-host slice "
            "cannot run on a partial mesh)"
        )
        record_event(
            api, obj, "TPUWorkerPreempted", reason,
            event_type="Warning",
        )
        deleted = 0
        for pod_name in sorted(current):
            try:
                api.delete("v1", "Pod", pod_name, req.namespace)
                deleted += 1
            except NotFound:
                pass
        first_pass = anns.get(keys.restart_reason) is None
        if deleted and first_pass and on_first_restart is not None:
            on_first_restart()
        patch = {keys.restart_reason: reason}
        if first_pass:
            patch[keys.preemption_restarts] = str(
                int(anns.get(keys.preemption_restarts, "0") or 0) + 1
            )
        patch_annotations(patch)
        return reason
    # Mesh still forming (fresh-but-incomplete, or everything gone):
    # wait for the statefulset controller; keep the marker visible.
    return reason
