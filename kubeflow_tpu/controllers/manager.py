"""Controller-manager composition: the reference main.go equivalent.

Ties together what reference notebook-controller/main.go:57-147 wires
with flags + env: the controllers (culler gated by ENABLE_CULLING,
main.go:110-122), the metrics/health listener (main.go:124-132), and
optional leader election (main.go:66-93). Standby replicas run the
elector only; controllers start on acquiring the lease and stop on
losing it (level-based reconciliation makes takeover safe — the new
leader's initial LIST re-derives everything).
"""

from __future__ import annotations

import os
import uuid

from kubeflow_tpu.controllers.culling import (
    CullingOptions,
    make_culling_controller,
)
from kubeflow_tpu.controllers.leader import (
    LeaderElector,
    ShardedElector,
    shard_count,
)
from kubeflow_tpu.controllers.metrics import ControllerMetrics, ManagerServer
from kubeflow_tpu.controllers.notebook import (
    NotebookOptions,
    make_notebook_controller,
)
from kubeflow_tpu.controllers.runtime import InformerCache, ShardGate
from kubeflow_tpu.k8s.fake import FakeApiServer
from kubeflow_tpu.obs.envknob import env_bool as _env_bool


def make_default_slo_engine(prom: ControllerMetrics, api=None,
                            clock=None, recorder=None, scheduler=None):
    """The control-plane SLO set every manager ships with
    (obs.slo defaults; KFT_SLO_* env tunes targets/thresholds):
    reconcile duration, workqueue queue-wait, and — when the api handle
    counts availability (real ApiClient, chaos proxy) — apiserver
    availability; with a slice-pool ``scheduler``, the gang-admission
    queue-wait objective rides along so the scheduler's cost is judged
    by the same burn-rate machinery. With a ``recorder`` (the
    manager-shared FlightRecorder), any alert going firing dumps the
    reconcile snapshot ring — the black-box window leading up to the
    burn."""
    from kubeflow_tpu import obs
    from kubeflow_tpu.obs import slo as obs_slo

    kwargs = {"clock": clock} if clock is not None else {}
    evaluator = obs_slo.BurnRateEvaluator(**kwargs)
    engine = obs.SloEngine(evaluator=evaluator, recorder=recorder)
    engine.register(obs_slo.reconcile_duration_objective(prom))
    engine.register(obs_slo.queue_wait_objective(prom))
    if api is not None and hasattr(api, "availability_counts"):
        engine.register(obs_slo.apiserver_availability_objective(api))
    if scheduler is not None and getattr(scheduler, "enabled", True):
        from kubeflow_tpu.scheduler import scheduler_queue_wait_objective

        engine.register(scheduler_queue_wait_objective(scheduler))
    return engine


# Distinguishes "caller said nothing" (build the default engine) from
# an explicit slo=None (disable the SLO layer entirely).
_DEFAULT_SLO = object()


def options_from_env() -> tuple[NotebookOptions, CullingOptions]:
    """Env parity with the reference kustomize params.env contract
    (reference notebook-controller/config/manager/params.env:5-7 and
    culling_controller.go initGlobalVars :405-438)."""
    nb = NotebookOptions(
        use_istio=_env_bool("USE_ISTIO"),
        istio_gateway=os.environ.get(
            "ISTIO_GATEWAY", "kubeflow/kubeflow-gateway"
        ),
        istio_host=os.environ.get("ISTIO_HOST", "*"),
        cluster_domain=os.environ.get("CLUSTER_DOMAIN", "cluster.local"),
        add_fs_group=_env_bool("ADD_FSGROUP", True),
    )
    cull = CullingOptions(
        enabled=_env_bool("ENABLE_CULLING"),
        cull_idle_time_min=int(os.environ.get("CULL_IDLE_TIME", "1440")),
        idleness_check_period_min=int(
            os.environ.get("IDLENESS_CHECK_PERIOD", "1")
        ),
    )
    return nb, cull


class Manager:
    """Runs a set of controllers behind one metrics/health server and,
    optionally, one leader-election lease."""

    def __init__(
        self,
        api: FakeApiServer,
        controllers: list,
        prom: ControllerMetrics | None = None,
        http_port: int | None = 0,
        leader_elect: bool = False,
        lease_name: str = "controller-manager",
        identity: str | None = None,
        lease_namespace: str = "kubeflow",
        clock=None,
        slo=_DEFAULT_SLO,
        recorder=None,
        autopilot=None,
        scheduler=None,
        shards: int | None = None,
    ):
        self.api = api
        self.controllers = controllers
        self.prom = prom
        # Horizontal sharding (KFT_SHARDS): with more than one shard
        # and leader election on, this replica runs a ShardedElector
        # over per-shard leases and every controller pops only the
        # keys of shards it owns (ShardGate). One shard keeps the
        # classic single-leader manager byte-identical — same lease
        # name, same start/stop-on-transition controller lifecycle.
        self.shards = (shard_count() if shards is None
                       else max(1, int(shards)))
        self.shard_gate = None
        # Slice-pool scheduler (PR 12): a disabled one (KFT_SCHEDULER=0)
        # is treated exactly like none at all — no collector, no SLO
        # objective, no debug surface, no tick hook; behaviour stays
        # byte-identical to the scheduler-less manager.
        if scheduler is not None and not getattr(
                scheduler, "enabled", True):
            scheduler = None
        self.scheduler = scheduler
        self._threads: list = []
        self._running = False
        self.server = None
        # Black-box capture (PR 10): ONE flight recorder shared by
        # every controller in this manager — each reconcile leaves one
        # bounded-ring snapshot (phase split, queue depth, trace id) —
        # and by the SLO engine, which dumps the ring to a JSONL
        # artifact on any pending→firing transition. Controllers built
        # with their own recorder keep it (explicit beats shared).
        from kubeflow_tpu.obs.recorder import FlightRecorder

        self.recorder = (recorder if recorder is not None
                         else FlightRecorder())
        for ctrl in controllers:
            if getattr(ctrl, "recorder", None) is None:
                ctrl.recorder = self.recorder
        # The judging layer over the manager's own telemetry (PR 9):
        # default burn-rate SLOs registered over the registry's
        # reconcile/queue histograms and — when the api handle counts
        # availability (real ApiClient, chaos proxy) — the apiserver
        # availability objective. Injectable for deterministic tests;
        # an explicit None disables the layer.
        if slo is _DEFAULT_SLO:
            slo = (make_default_slo_engine(prom, api,
                                           recorder=self.recorder,
                                           scheduler=scheduler)
                   if prom is not None else None)
        self.slo = slo
        if scheduler is not None:
            if prom is not None and hasattr(prom, "registry"):
                from kubeflow_tpu.scheduler import SchedulerCollector

                prom.registry.register(SchedulerCollector(scheduler))
            for ctrl in controllers:
                hooks = getattr(ctrl, "tick_hooks", None)
                if hooks is not None:
                    # Drain grace deadlines must expire even when no
                    # watch event fires (the elastic-timer discipline).
                    hooks.append(scheduler.tick)
        if self.slo is not None:
            for ctrl in controllers:
                hooks = getattr(ctrl, "tick_hooks", None)
                if hooks is not None:
                    # Self-rate-limited: tens of loop ticks per second
                    # collapse to one sample per min_interval_s.
                    hooks.append(self.slo.tick)
        # Actuation (PR 11): an Autopilot subscribes to the manager's
        # alert transitions and rides the controller tick hooks for its
        # sustained-signal actuators (both self-rate-limited). Its
        # actions render on /metrics as autopilot_actions_total.
        self.autopilot = autopilot
        if autopilot is not None:
            autopilot.attach(self.slo)
            if autopilot.recorder is None:
                autopilot.recorder = self.recorder
            if prom is not None and hasattr(prom, "registry"):
                from kubeflow_tpu.autopilot import AutopilotCollector

                prom.registry.register(AutopilotCollector(autopilot))
            for ctrl in controllers:
                hooks = getattr(ctrl, "tick_hooks", None)
                if hooks is not None:
                    hooks.append(autopilot.tick)
        if prom is not None and http_port is not None:
            prom.watch_controllers(controllers)
            from kubeflow_tpu import obs

            self.server = ManagerServer(
                prom,
                port=http_port,
                ready=self.ready,
                # pprof-role endpoints (/debug/threads, /debug/tracemalloc)
                # and the trace endpoints (/debug/traces, /debug/timeline)
                # are strictly opt-in, like controller-runtime's pprof
                # listener.
                enable_debug=_env_bool("KFT_ENABLE_DEBUG_ENDPOINTS"),
                tracer=obs.get_tracer(),
                slo=self.slo,
                fleet_api=api,
                # Reconcile phase digests (/debug/profile) + the shared
                # snapshot ring (/debug/flightrecord), debug-gated like
                # the pprof-role endpoints.
                profilers={
                    ctrl.name: ctrl.profiler
                    for ctrl in controllers
                    if getattr(ctrl, "profiler", None) is not None
                },
                recorder=self.recorder,
                scheduler=scheduler,
            )
        self.elector = None
        if leader_elect:
            kwargs = {}
            if clock is not None:
                kwargs["clock"] = clock
            # Downward-API convention: with POD_NAME injected (the
            # controller deployments do), the lease holder is the
            # pod name — legible in kubectl. Applies to EVERY
            # manager, not just the notebook controller.
            me = (identity or os.environ.get("POD_NAME")
                  or f"manager-{uuid.uuid4().hex[:8]}")
            if self.shards > 1:
                # Sharded mode: controllers run on every replica from
                # start() on — ownership is per-key through the gate,
                # not per-process through start/stop.
                self.shard_gate = ShardGate(self.shards)
                for ctrl in controllers:
                    if getattr(ctrl, "shard_gate", None) is None:
                        ctrl.shard_gate = self.shard_gate
                self.elector = ShardedElector(
                    api, lease_name, me, self.shards,
                    namespace=lease_namespace,
                    gate=self.shard_gate,
                    **kwargs,
                )
            else:
                self.elector = LeaderElector(
                    api,
                    lease_name,
                    me,
                    namespace=lease_namespace,
                    on_started_leading=self._start_controllers,
                    on_stopped_leading=self._stop_controllers,
                    **kwargs,
                )

    def ready(self) -> bool:
        """Readiness = serving; standbys are ready without leading (they
        must pass probes to stay in the replica pool)."""
        return True

    @property
    def is_leader(self) -> bool:
        return self.elector.is_leader if self.elector else self._running

    def _start_controllers(self) -> None:
        if self._running:
            return
        self._running = True
        self._threads = [ctrl.start() for ctrl in self.controllers]

    def _stop_controllers(self) -> None:
        if not self._running:
            return
        self._running = False
        for ctrl in self.controllers:
            ctrl.stop()
        self._threads = []

    def start(self) -> None:
        if self.server is not None:
            self.server.start()
        if self.elector is not None:
            if self.shard_gate is not None:
                # Sharded replicas run their controllers immediately;
                # the gate keeps them idle until shards are owned AND
                # resynced, so a standby burns no reconciles.
                self._start_controllers()
            self.elector.start()
        else:
            self._start_controllers()

    def stop(self) -> None:
        if self.elector is not None:
            self.elector.stop()
            self.elector.release()
        self._stop_controllers()
        if self.server is not None:
            self.server.stop()


def make_notebook_manager(
    api: FakeApiServer,
    leader_elect: bool | None = None,
    http_port: int | None = 0,
    identity: str | None = None,
    kernel_probe=None,
    tpu_busy_probe=None,
) -> Manager:
    """The notebook-controller binary: notebook reconciler + culler (+
    metrics), configured from env exactly like the reference manager.
    ``KFT_INFORMER=0`` opts out of the shared informer cache (plain
    per-reconcile LISTs); with ``KFT_SHARDS>1`` the notebook
    controller's status writes also batch through a StatusBatcher."""
    from kubeflow_tpu.controllers.runtime import StatusBatcher

    nb_opts, cull_opts = options_from_env()
    prom = ControllerMetrics(api)
    cache = (InformerCache(api) if _env_bool("KFT_INFORMER", True)
             else None)
    shards = shard_count()
    batcher = StatusBatcher(api) if shards > 1 else None
    controllers = [make_notebook_controller(
        api, nb_opts, prom=prom, cache=cache, status_batcher=batcher,
    )]
    controllers.append(
        make_culling_controller(
            api,
            kernel_probe=kernel_probe,
            options=cull_opts,
            tpu_busy_probe=tpu_busy_probe,
            prom=prom,
            cache=cache,
        )
    )
    if leader_elect is None:
        leader_elect = _env_bool("LEADER_ELECT")
    return Manager(
        api,
        controllers,
        prom=prom,
        http_port=http_port,
        leader_elect=leader_elect,
        lease_name="notebook-controller",
        identity=identity,
        shards=shards,
    )


if __name__ == "__main__":
    from kubeflow_tpu.entrypoints import run_notebook_controller

    run_notebook_controller()
