"""InferenceService controller: CR → StatefulSet + Services + status.

The control plane of the serving stack (kubeflow_tpu/serving/ is the
data plane): an ``InferenceService`` CR names a model directory and a
TPU slice; the reconciler emits the same multi-host StatefulSet shape
the notebook controller emits — TPU topology node selectors and
per-host chip limits from :mod:`kubeflow_tpu.topology`, jax.distributed
env, headless per-replica DNS, ``Parallel`` pod management — plus a
ClusterIP Service fronting the gateway port, and mirrors
``status.phase`` / ``status.readyReplicas`` / ``status.endpoint`` onto
the CR. Observed-mesh preemption recovery is the shared state machine
(:mod:`controllers.slice_recovery`): a partially preempted slice is
restarted all-or-nothing and surfaces as ``phase=Restarting``.

Desired-state generation is Python (unlike the notebook controller's
native core): the serving controller is new platform surface, not a
reference-parity port, and keeping it here keeps the CRD iterable.
The serving env itself (model dir, max batch, gateway port) is NOT
stamped by the controller — the admission webhook's
``inference_env_poddefault`` injects it namespace-wide, alongside the
checkpoint vars, so per-namespace defaults stay in one place and the
controller's template cannot conflict with them.
"""

from __future__ import annotations

import logging
import time

from kubeflow_tpu.controllers.runtime import (
    Controller,
    Request,
    WatchSpec,
    ensure_object,
    record_event,
)
from kubeflow_tpu.controllers.scheduling import (
    apply_verdict,
)
from kubeflow_tpu.controllers.scheduling import (
    observed_running as sched_observed_running,
)
from kubeflow_tpu.controllers.slice_recovery import (
    SliceAnnotations,
    recover_slice,
)
from kubeflow_tpu.k8s.fake import FakeApiServer, NotFound
from kubeflow_tpu.topology import TopologyError, TpuSlice

log = logging.getLogger(__name__)

INFERENCE_API = "serving.kubeflow.org/v1alpha1"

# Preemption-recovery bookkeeping, the inference CRD's namespace of the
# notebook controller's annotations (slice_recovery.py holds the state
# machine).
OBSERVED_MESH_KEY = "inference.kubeflow-tpu.org/observed-mesh"
RESTART_REASON_KEY = "inference.kubeflow-tpu.org/restart-reason"
PREEMPTION_RESTARTS_KEY = "inference.kubeflow-tpu.org/preemption-restarts"
# Scheduler resurrect handshake (the notebook CRD's resume-expected
# contract, in this CRD's namespace): the step a resurrected gateway
# is expected to restore from — durable BEFORE the scheduler's
# re-deliver-until-acked handshake is acked.
RESUME_EXPECTED_KEY = "inference.kubeflow-tpu.org/resume-expected-step"

DEFAULT_GATEWAY_PORT = 8800
DEFAULT_IMAGE = "kubeflow-tpu/inference-gateway:latest"
POD_INDEX_LABEL = "apps.kubernetes.io/pod-index"
COORDINATOR_PORT = 8476  # native/src/notebook.cpp kCoordinatorPort


def gateway_port(svc: dict) -> int:
    return int((svc.get("spec") or {}).get("port")
               or DEFAULT_GATEWAY_PORT)


def spec_replicas(svc: dict) -> int:
    """``spec.replicas`` (>= 1; junk coerces to 1) — the horizontal
    gateway count the autopilot's scale actuator patches. Honoured by
    the StatefulSet only for non-TPU services: on a TPU slice the
    replica count IS the slice's host gang (jax.distributed needs every
    host), so there the field and the desired-replicas annotation
    record capacity intent for the fleet-router tier instead."""
    try:
        return max(1, int((svc.get("spec") or {}).get("replicas") or 1))
    except (TypeError, ValueError):
        return 1


def _slice_for(svc: dict) -> TpuSlice | None:
    tpu = (svc.get("spec") or {}).get("tpu") or {}
    if not tpu.get("accelerator"):
        return None
    return TpuSlice.parse(tpu["accelerator"], tpu.get("topology", "1x1"))


def _owner_ref(svc: dict) -> dict:
    meta = svc.get("metadata") or {}
    return {
        "apiVersion": INFERENCE_API,
        "kind": "InferenceService",
        "name": meta.get("name", ""),
        "uid": meta.get("uid", ""),
        "controller": True,
        "blockOwnerDeletion": True,
    }


def _meta(name: str, svc: dict) -> dict:
    return {
        "name": name,
        "namespace": svc["metadata"]["namespace"],
        "labels": {"inferenceservice-name": svc["metadata"]["name"]},
        "ownerReferences": [_owner_ref(svc)],
    }


def desired_statefulset(svc: dict) -> dict:
    """The serving StatefulSet: notebook-controller multi-host
    mechanics (topology selectors, per-host chips, jax.distributed
    env, Parallel management) around the gateway container."""
    name = svc["metadata"]["name"]
    ns = svc["metadata"]["namespace"]
    spec = svc.get("spec") or {}
    tpu_slice = _slice_for(svc)
    replicas = (tpu_slice.num_hosts if tpu_slice
                else spec_replicas(svc))
    port = gateway_port(svc)
    container: dict = {
        "name": "gateway",
        "image": spec.get("image") or DEFAULT_IMAGE,
        "ports": [{"name": "http-gateway", "containerPort": port,
                   "protocol": "TCP"}],
        # The port is per-CR and the controller owns it end to end
        # (containerPort, Service, status.endpoint, and the env the
        # gateway binds): the inference-env PodDefault deliberately
        # does NOT set KFT_SERVING_PORT, or the conflict-checked merge
        # would reject pods whenever a CR picked a non-default port.
        "env": [{"name": "KFT_SERVING_PORT", "value": str(port)}],
    }
    pod_spec: dict = {"containers": [container]}
    if tpu_slice is not None:
        container["resources"] = {
            "limits": dict(tpu_slice.container_resources()),
            "requests": dict(tpu_slice.container_resources()),
        }
        pod_spec["nodeSelector"] = dict(tpu_slice.node_selectors())
        container["env"].append({
            "name": "TPU_WORKER_ID",
            "valueFrom": {"fieldRef": {
                "fieldPath":
                    f"metadata.labels['{POD_INDEX_LABEL}']"}},
        })
        container["env"].append({
            "name": "KFT_NUM_PROCESSES", "value": str(replicas)})
        if replicas > 1:
            hosts = ",".join(
                f"{name}-{i}.{name}-hosts.{ns}.svc"
                for i in range(replicas)
            )
            container["env"].append({
                "name": "TPU_WORKER_HOSTNAMES", "value": hosts})
            container["env"].append({
                "name": "KFT_COORDINATOR_ADDRESS",
                "value": f"{name}-0.{name}-hosts.{ns}.svc:"
                         f"{COORDINATOR_PORT}",
            })
    return {
        "apiVersion": "apps/v1",
        "kind": "StatefulSet",
        "metadata": _meta(name, svc),
        "spec": {
            "replicas": replicas,
            "serviceName": f"{name}-hosts",
            # Gang start: jax.distributed needs every host up before
            # rank 0's coordinator barrier completes (notebook.cpp).
            "podManagementPolicy": "Parallel",
            "selector": {"matchLabels": {"statefulset": name}},
            "template": {
                "metadata": {
                    "labels": {
                        "statefulset": name,
                        "inferenceservice-name": name,
                        # PodDefault selectors: the webhook injects the
                        # serving env (inference_env_poddefault) and
                        # the TPU slice env (tpu_env_poddefault).
                        "inference-env": "true",
                        "tpu-env": "true",
                    },
                },
                "spec": pod_spec,
            },
        },
    }


def desired_services(svc: dict) -> list[dict]:
    """Headless per-replica DNS (multi-host coordination) + the
    gateway front Service. Requests fan to EVERY host's gateway pod —
    all hosts run the same program and serve the same engine — so the
    front selector does NOT pin to rank 0 the way the notebook's
    Jupyter service does; multi-host decode coherence is the data
    plane's concern."""
    name = svc["metadata"]["name"]
    port = gateway_port(svc)
    headless = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": _meta(f"{name}-hosts", svc),
        "spec": {
            "clusterIP": "None",
            "publishNotReadyAddresses": True,
            "selector": {"statefulset": name},
            "ports": [{"name": "http-gateway", "port": port,
                       "targetPort": port}],
        },
    }
    front = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": _meta(name, svc),
        "spec": {
            "type": "ClusterIP",
            "selector": {"statefulset": name},
            "ports": [{"name": f"http-{name}", "port": port,
                       "targetPort": port, "protocol": "TCP"}],
        },
    }
    return [headless, front]


def endpoint_for(svc: dict) -> str:
    name = svc["metadata"]["name"]
    ns = svc["metadata"]["namespace"]
    return f"http://{name}.{ns}.svc:{gateway_port(svc)}"


def pod_to_inference_requests(obj: dict) -> list[Request]:
    """Map Pod/StatefulSet events back to the owning InferenceService
    via the inferenceservice-name label (the notebook controller's
    mapping discipline)."""
    meta = obj.get("metadata", {})
    name = (meta.get("labels") or {}).get("inferenceservice-name")
    if not name:
        return []
    return [Request(meta.get("namespace", ""), name)]


class InferenceReconciler:
    def __init__(self, api: FakeApiServer, prom=None, scheduler=None,
                 clock=time.time, cache=None, status_writer=None):
        self.api = api
        self.prom = prom
        self.scheduler = scheduler
        self.clock = clock
        self.cache = cache
        self.status_writer = status_writer

    def _list_pods(self, req: Request) -> list:
        """The slice's pods via the informer's namespace index when a
        cache is wired (the notebook reconciler's discipline), else
        the plain LIST."""
        source = self.cache if self.cache is not None else self.api
        return source.list(
            "v1", "Pod", namespace=req.namespace,
            label_selector=f"inferenceservice-name={req.name}",
        )

    def reconcile(self, req: Request) -> float | None:
        try:
            svc = self.api.get(
                INFERENCE_API, "InferenceService", req.name,
                req.namespace,
            )
        except NotFound:
            # Deleted: children garbage-collect via ownerReferences;
            # the pool admission is released.
            if self.scheduler is not None:
                self.scheduler.release(
                    "InferenceService", req.namespace, req.name
                )
            return None
        try:
            desired = desired_statefulset(svc)
        except TopologyError as exc:
            # Permanent spec error (typo'd accelerator/topology):
            # retrying cannot fix it, so surface it on the CR and
            # settle — a spec UPDATE re-triggers reconciliation. The
            # status write is change-gated or the patch's own watch
            # event would re-run this forever.
            message = f"invalid spec.tpu: {exc}"
            cur = svc.get("status") or {}
            if (cur.get("phase"), cur.get("message")) != ("Failed",
                                                          message):
                record_event(
                    self.api, svc, "InvalidSpec", message,
                    event_type="Warning",
                )
                self.api.patch_merge(
                    INFERENCE_API, "InferenceService", req.name,
                    {"status": {"phase": "Failed",
                                "message": message}},
                    req.namespace,
                )
            return None
        # Slice-pool gate: serving schedules out of the same chip pool
        # as notebooks/training — an unadmitted gang runs at zero
        # replicas and the CR says why (status.phase=Queued/Suspended).
        sched_verdict = self._schedule(svc, req)
        if sched_verdict is not None and not sched_verdict.admitted:
            desired["spec"]["replicas"] = 0
        try:
            sts_result = ensure_object(self.api, desired)
        except Exception as exc:
            record_event(
                self.api, svc, "CreateFailed",
                f"StatefulSet for inference service {req.name} "
                f"failed: {exc}",
                event_type="Warning",
            )
            raise
        if sts_result == "created":
            record_event(
                self.api, svc, "Created",
                f"Created StatefulSet for inference service "
                f"{req.name}",
            )
        for child in desired_services(svc):
            ensure_object(self.api, child)
        # One STS get + one pod list shared by recovery and the status
        # mirror — same fetch discipline as the notebook reconciler.
        try:
            sts = self.api.get(
                "apps/v1", "StatefulSet", req.name, req.namespace
            )
        except NotFound:
            sts = None
        pods = self._list_pods(req)
        restart_reason = self._preemption_recovery(svc, req, sts, pods)
        self._update_status(svc, restart_reason, sts, pods,
                            sched_verdict=sched_verdict)
        return None

    def _schedule(self, svc: dict, req: Request):
        """Consult the slice-pool scheduler with the TPU gang demand
        (non-TPU gateway pools are not pool-scheduled — their replica
        count is the autopilot's horizontal-scale territory)."""
        if self.scheduler is None:
            return None
        try:
            tpu_slice = _slice_for(svc)
        except TopologyError:
            return None  # the InvalidSpec branch surfaces it
        if tpu_slice is None:
            return None
        anns = svc.setdefault("metadata", {}).setdefault(
            "annotations", {}
        )
        verdict = self.scheduler.decide(
            "InferenceService", req.namespace, req.name,
            tpu_slice.chips, anns, now=self.clock(),
            observed_running=sched_observed_running(self.api, req),
        )
        apply_verdict(
            self.api, INFERENCE_API, "InferenceService", svc, req,
            verdict, self.scheduler, self.clock,
            resume_key=RESUME_EXPECTED_KEY,
            resume_message="admitted from Suspended; the gateway "
                           "resumes serving from checkpoint step "
                           "{step}",
        )
        return verdict

    def _preemption_recovery(
        self, svc: dict, req: Request,
        sts: dict | None, pods: list | None,
    ) -> str | None:
        def on_first_restart():
            if self.prom is not None:
                self.prom.inference_preemption_restart_total.labels(
                    req.namespace
                ).inc()

        def on_rebaseline(patch: dict, anns: dict, replicas: int):
            record_event(
                self.api, svc, "SliceRestarted",
                f"all {replicas} TPU workers recreated; "
                "jax.distributed mesh re-forming; the gateway resumes "
                "serving from the latest valid checkpoint",
            )

        return recover_slice(
            self.api, INFERENCE_API, "InferenceService", svc, req,
            sts, pods,
            SliceAnnotations(
                observed_mesh=OBSERVED_MESH_KEY,
                restart_reason=RESTART_REASON_KEY,
                preemption_restarts=PREEMPTION_RESTARTS_KEY,
            ),
            on_first_restart=on_first_restart,
            on_rebaseline=on_rebaseline,
        )

    def _update_status(self, svc: dict, restart_reason: str | None,
                       sts: dict | None, pods: list,
                       sched_verdict=None) -> None:
        name = svc["metadata"]["name"]
        ns = svc["metadata"]["namespace"]
        replicas = ((sts or {}).get("spec") or {}).get("replicas") or 0
        expected = {f"{name}-{i}" for i in range(replicas)}
        ready = 0
        for pod in pods:
            if pod["metadata"]["name"] not in expected:
                continue
            conditions = (pod.get("status") or {}).get("conditions") or []
            if any(c.get("type") == "Ready"
                   and c.get("status") == "True" for c in conditions):
                ready += 1
        if sched_verdict is not None and sched_verdict.phase:
            # The scheduler's view wins: a Queued/Suspended slice holds
            # zero replicas on purpose — "Stopped" would misreport a
            # deliberate pool decision.
            phase = sched_verdict.phase
        elif restart_reason:
            phase = "Restarting"
        elif sts is None or replicas == 0:
            phase = "Stopped" if sts is not None else "Pending"
        elif ready == replicas:
            phase = "Running"
        else:
            phase = "Pending"
        status: dict = {
            "phase": phase,
            "readyReplicas": ready,
            "replicas": replicas,
            "endpoint": endpoint_for(svc),
        }
        if restart_reason:
            status["restartReason"] = restart_reason
        if sched_verdict is not None and sched_verdict.phase:
            if sched_verdict.reason:
                status["schedulingReason"] = sched_verdict.reason
            if sched_verdict.queue_position is not None:
                status["queuePosition"] = sched_verdict.queue_position
        cur = svc.get("status") or {}
        own = {k: cur.get(k) for k in status}
        if own == status and all(
            (key in cur) == (key in status)
            for key in ("restartReason", "schedulingReason",
                        "queuePosition")
        ):
            return
        patch = dict(status)
        if not restart_reason and "restartReason" in cur:
            # Merge-patch semantics: a completed recovery's marker must
            # be deleted explicitly or it lingers forever.
            patch["restartReason"] = None
        for key in ("schedulingReason", "queuePosition"):
            # Same rule for the scheduler's markers once re-admitted.
            if key not in status and key in cur:
                patch[key] = None
        if "message" in cur:
            # Same rule for a healed InvalidSpec failure's message — a
            # recovered CR must not read Running + stale error text.
            patch["message"] = None
        if self.status_writer is not None:
            self.status_writer.submit(
                INFERENCE_API, "InferenceService", name,
                {"status": patch}, ns,
            )
        else:
            self.api.patch_merge(
                INFERENCE_API, "InferenceService", name,
                {"status": patch}, ns,
            )


def make_inference_controller(
    api: FakeApiServer,
    prom=None,
    scheduler=None,
    clock=time.time,
    cache=None,
    status_batcher=None,
    shard_gate=None,
) -> Controller:
    reconciler = InferenceReconciler(api, prom=prom, scheduler=scheduler,
                                     clock=clock, cache=cache,
                                     status_writer=status_batcher)
    return Controller(
        name="inference-controller",
        api=api,
        reconciler=reconciler,
        watches=[
            WatchSpec(INFERENCE_API, "InferenceService"),
            WatchSpec("apps/v1", "StatefulSet",
                      pod_to_inference_requests),
            WatchSpec("v1", "Pod", pod_to_inference_requests),
        ],
        prom=prom,
        shard_gate=shard_gate,
        status_batcher=status_batcher,
        cache=cache,
    )
