"""Notebook controller: Notebook CR → StatefulSet + Services + route.

The Python half of the controller: watches and API writes. All policy —
desired-state generation (TPU replicas, env, selectors), drift repair,
status derivation — happens in the native core (native/src/notebook.cpp),
capability parity with the reference notebook-controller
(reference controllers/notebook_controller.go:89-225 Reconcile).
"""

from __future__ import annotations

import dataclasses
import logging

from kubeflow_tpu import native
from kubeflow_tpu.controllers.runtime import (
    Controller,
    Request,
    WatchSpec,
    ensure_object,
    record_event,
)
from kubeflow_tpu.k8s.fake import FakeApiServer, NotFound

log = logging.getLogger(__name__)

NOTEBOOK_API = "kubeflow.org/v1beta1"


@dataclasses.dataclass
class NotebookOptions:
    """Mirrors the reference controller's env config (USE_ISTIO,
    ISTIO_GATEWAY, ISTIO_HOST, CLUSTER_DOMAIN, ADD_FSGROUP —
    reference notebook_controller.go:202-208,427,489-512)."""

    use_istio: bool = False
    istio_gateway: str = "kubeflow/kubeflow-gateway"
    istio_host: str = "*"
    cluster_domain: str = "cluster.local"
    add_fs_group: bool = True

    def to_native(self) -> dict:
        return {
            "useIstio": self.use_istio,
            "istioGateway": self.istio_gateway,
            "istioHost": self.istio_host,
            "clusterDomain": self.cluster_domain,
            "addFsGroup": self.add_fs_group,
        }


def event_involves_notebook(event: dict, name: str) -> bool:
    """Does this Event belong to notebook ``name``? Matches the object
    itself (Notebook/STS, exact name) or its replica pods ("nb-0",
    "nb-1", …). The Pod-kind check keeps a sibling notebook literally
    named "<name>-<digits>" (its Notebook/STS objects match the ordinal
    pattern) from leaking in. Shared by the controller's status mirror
    and the JWA details-page events route."""
    ref = event.get("involvedObject") or {}
    obj_name = ref.get("name", "")
    if obj_name == name:
        return True
    prefix, _, suffix = obj_name.rpartition("-")
    return (
        ref.get("kind", "Pod") == "Pod"
        and prefix == name
        and suffix.isdigit()
    )


def pod_to_notebook_requests(obj: dict) -> list[Request]:
    """Map Pod/StatefulSet events back to the owning Notebook via the
    notebook-name label (reference predNBPodIsLabeled + event mapping,
    notebook_controller.go:653-664)."""
    meta = obj.get("metadata", {})
    name = (meta.get("labels") or {}).get("notebook-name")
    if not name:
        return []
    return [Request(meta.get("namespace", ""), name)]


class NotebookReconciler:
    def __init__(
        self,
        api: FakeApiServer,
        options: NotebookOptions | None = None,
        prom=None,  # optional ControllerMetrics (metrics.py)
    ):
        self.api = api
        self.options = options or NotebookOptions()
        self.prom = prom

    def _ensure(self, desired: dict) -> str:
        return ensure_object(self.api, desired)

    def reconcile(self, req: Request) -> float | None:
        try:
            notebook = self.api.get(
                NOTEBOOK_API, "Notebook", req.name, req.namespace
            )
        except NotFound:
            # Deleted: children are garbage-collected via ownerReferences.
            return None

        out = native.invoke(
            "notebook_reconcile",
            {"notebook": notebook, "options": self.options.to_native()},
        )
        try:
            sts_result = self._ensure(out["statefulset"])
        except Exception as exc:
            # EventRecorder parity (reference notebook_controller.go:139-169
            # records create failures onto the CR).
            record_event(
                self.api, notebook, "CreateFailed",
                f"StatefulSet for notebook {req.name} failed: {exc}",
                event_type="Warning",
            )
            if self.prom is not None:
                # Only a failed *creation* counts (reference
                # NotebookFailCreation); a Conflict while drift-repairing
                # an existing STS is a routine retry, not a create failure.
                try:
                    self.api.get("apps/v1", "StatefulSet", req.name, req.namespace)
                except NotFound:
                    self.prom.notebook_create_failed_total.labels(
                        req.namespace
                    ).inc()
            raise
        if sts_result == "created":
            record_event(
                self.api, notebook, "Created",
                f"Created StatefulSet for notebook {req.name}",
            )
            if self.prom is not None:
                # Counts new notebook materialisations, like the
                # reference's NotebookCreation counter on first create.
                self.prom.notebook_create_total.labels(req.namespace).inc()
        for svc in out["services"]:
            self._ensure(svc)
        if out["virtualService"] is not None:
            self._ensure(out["virtualService"])

        self._gang_restart(notebook, req)
        self._update_status(notebook)
        return None

    def _gang_restart(self, notebook: dict, req: Request) -> None:
        """SURVEY §7 hard part (b): a lone rank restart wedges the rest
        of the slice's jax.distributed — recycle all pods together. The
        decision (restart-counter bookkeeping) is native policy
        (native/src/notebook.cpp notebook_gang_restart)."""
        if not (notebook.get("spec") or {}).get("tpu"):
            return
        pods = self.api.list(
            "v1", "Pod", namespace=req.namespace,
            label_selector=f"notebook-name={req.name}",
        )
        decision = native.invoke(
            "notebook_gang_restart", {"notebook": notebook, "pods": pods}
        )
        if decision["action"] == "none":
            return
        if decision["action"] == "restart":
            record_event(
                self.api, notebook, "GangRestart",
                "A replica restarted; recycling all "
                f"{len(decision['deletePods'])} pods so jax.distributed "
                "re-forms the slice",
                event_type="Warning",
            )
            # Deletes BEFORE the baseline advance: the deletes are
            # idempotent, so a crash mid-loop retries the restart on the
            # next pass — advancing the baseline first would record the
            # crash as handled while pods are still wedged.
            for pod_name in decision["deletePods"]:
                try:
                    self.api.delete("v1", "Pod", pod_name, req.namespace)
                except NotFound:
                    pass
        self.api.patch_merge(
            NOTEBOOK_API, "Notebook", req.name,
            {"metadata": {"annotations": decision["annotations"]}},
            req.namespace,
        )

    def _update_status(self, notebook: dict) -> None:
        name = notebook["metadata"]["name"]
        ns = notebook["metadata"]["namespace"]
        try:
            sts = self.api.get("apps/v1", "StatefulSet", name, ns)
        except NotFound:
            sts = {}
        try:
            pod = self.api.get("v1", "Pod", f"{name}-0", ns)
        except NotFound:
            pod = {}
        # Field-selected server-side (apiserver supports
        # involvedObject.name on events): without it this list is
        # O(all events in the namespace) per reconcile and the status
        # mirror goes quadratic across N notebooks. Pod events carry
        # the pod's own name ("nb-0"), so one selected list per replica
        # joins them — replicas+1 point lists, bounded by slice size,
        # never by namespace population. The kind check stays
        # client-side (event_involves_notebook). Known trade-off: after
        # a scale-down (spec 3->1), events for leftover higher-ordinal
        # pods (nb-2) are no longer mirrored — those pods are being
        # torn down, and their terminal events age out of the window
        # anyway; scanning status.replicas too would re-add them if
        # that ever matters.
        replicas = max(
            ((notebook.get("spec") or {}).get("tpu") or {})
            .get("replicas", 1), 1,
        )
        events = []
        for involved in [name] + [f"{name}-{i}" for i in range(replicas)]:
            events.extend(
                e
                for e in self.api.list(
                    "v1", "Event", namespace=ns,
                    field_selector=f"involvedObject.name={involved}",
                )
                if event_involves_notebook(e, name)
            )
        status = native.invoke(
            "notebook_status",
            {
                "notebook": notebook,
                "statefulset": sts,
                "pod": pod,
                "events": events,
            },
        )
        if notebook.get("status") != status:
            self.api.patch_merge(
                NOTEBOOK_API, "Notebook", name, {"status": status}, ns
            )


def make_notebook_controller(
    api: FakeApiServer,
    options: NotebookOptions | None = None,
    prom=None,
) -> Controller:
    reconciler = NotebookReconciler(api, options, prom=prom)
    return Controller(
        name="notebook-controller",
        api=api,
        reconciler=reconciler,
        watches=[
            WatchSpec(NOTEBOOK_API, "Notebook"),
            WatchSpec("apps/v1", "StatefulSet", pod_to_notebook_requests),
            WatchSpec("v1", "Pod", pod_to_notebook_requests),
        ],
        prom=prom,
    )
