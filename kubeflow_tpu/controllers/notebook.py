"""Notebook controller: Notebook CR → StatefulSet + Services + route.

The Python half of the controller: watches and API writes. All policy —
desired-state generation (TPU replicas, env, selectors), drift repair,
status derivation — happens in the native core (native/src/notebook.cpp),
capability parity with the reference notebook-controller
(reference controllers/notebook_controller.go:89-225 Reconcile).
"""

from __future__ import annotations

import copy
import dataclasses
import logging
import time

from kubeflow_tpu import native
from kubeflow_tpu.controllers import elastic
from kubeflow_tpu.controllers.runtime import (
    Controller,
    Request,
    WatchSpec,
    ensure_object,
    record_event,
)
from kubeflow_tpu.controllers.scheduling import (
    apply_verdict,
)
from kubeflow_tpu.controllers.scheduling import (
    observed_running as sched_observed_running,
)
from kubeflow_tpu.controllers.slice_recovery import (
    SliceAnnotations,
    recover_slice,
)
from kubeflow_tpu.k8s.fake import FakeApiServer, NotFound
from kubeflow_tpu.obs.profile import phase as profile_phase
from kubeflow_tpu.topology import TopologyError, TpuSlice

log = logging.getLogger(__name__)

NOTEBOOK_API = "kubeflow.org/v1beta1"

# Preemption-recovery bookkeeping (metadata.annotations). OBSERVED_MESH
# maps worker pod name -> uid, the last slice membership known to form a
# coherent jax.distributed mesh; RESTART_REASON marks a full-slice
# restart in flight (mirrored into status as phase=Restarting).
OBSERVED_MESH_KEY = "notebooks.kubeflow-tpu.org/observed-mesh"
RESTART_REASON_KEY = "notebooks.kubeflow-tpu.org/restart-reason"
PREEMPTION_RESTARTS_KEY = "notebooks.kubeflow-tpu.org/preemption-restarts"

# Checkpoint/resume handshake with the data plane. CHECKPOINT_STEP is
# stamped by the training side (models/checkpoint.py manager commits →
# the in-image reporter mirrors checkpoint_last_committed_step here);
# on SliceRestarted the reconciler copies it into RESUME_EXPECTED — the
# step the restarted slice is expected to resume from — and surfaces it
# as status.resumedFromStep for kubectl/dashboard.
CHECKPOINT_STEP_KEY = "notebooks.kubeflow-tpu.org/checkpoint-last-step"
RESUME_EXPECTED_KEY = "notebooks.kubeflow-tpu.org/resume-expected-step"


@dataclasses.dataclass
class NotebookOptions:
    """Mirrors the reference controller's env config (USE_ISTIO,
    ISTIO_GATEWAY, ISTIO_HOST, CLUSTER_DOMAIN, ADD_FSGROUP —
    reference notebook_controller.go:202-208,427,489-512)."""

    use_istio: bool = False
    istio_gateway: str = "kubeflow/kubeflow-gateway"
    istio_host: str = "*"
    cluster_domain: str = "cluster.local"
    add_fs_group: bool = True

    def to_native(self) -> dict:
        return {
            "useIstio": self.use_istio,
            "istioGateway": self.istio_gateway,
            "istioHost": self.istio_host,
            "clusterDomain": self.cluster_domain,
            "addFsGroup": self.add_fs_group,
        }


def event_involves_notebook(event: dict, name: str) -> bool:
    """Does this Event belong to notebook ``name``? Matches the object
    itself (Notebook/STS, exact name) or its replica pods ("nb-0",
    "nb-1", …). The Pod-kind check keeps a sibling notebook literally
    named "<name>-<digits>" (its Notebook/STS objects match the ordinal
    pattern) from leaking in. Shared by the controller's status mirror
    and the JWA details-page events route."""
    ref = event.get("involvedObject") or {}
    obj_name = ref.get("name", "")
    if obj_name == name:
        return True
    prefix, _, suffix = obj_name.rpartition("-")
    return (
        ref.get("kind", "Pod") == "Pod"
        and prefix == name
        and suffix.isdigit()
    )


def pod_to_notebook_requests(obj: dict) -> list[Request]:
    """Map Pod/StatefulSet events back to the owning Notebook via the
    notebook-name label (reference predNBPodIsLabeled + event mapping,
    notebook_controller.go:653-664)."""
    meta = obj.get("metadata", {})
    name = (meta.get("labels") or {}).get("notebook-name")
    if not name:
        return []
    return [Request(meta.get("namespace", ""), name)]


class NotebookReconciler:
    def __init__(
        self,
        api: FakeApiServer,
        options: NotebookOptions | None = None,
        prom=None,  # optional ControllerMetrics (metrics.py)
        clock=time.time,  # elastic grace/promote timers (injectable)
        promotion_gate=None,  # autopilot.ElasticPromotionGate (or None)
        scheduler=None,  # scheduler.SlicePoolScheduler (or None)
        cache=None,  # runtime.InformerCache (or None: plain LISTs)
        status_writer=None,  # runtime.StatusBatcher (or None: direct)
    ):
        self.api = api
        self.options = options or NotebookOptions()
        self.prom = prom
        self.clock = clock
        self.promotion_gate = promotion_gate
        self.scheduler = scheduler
        self.cache = cache
        self.status_writer = status_writer

    def _ensure(self, desired: dict) -> str:
        return ensure_object(self.api, desired)

    def _list_pods(self, req: Request) -> list:
        """The slice's pods — through the informer's namespace index
        when a cache is wired (at fleet cardinality a per-reconcile
        LIST scans every pod in the cluster), else the plain LIST."""
        source = self.cache if self.cache is not None else self.api
        return source.list(
            "v1", "Pod", namespace=req.namespace,
            label_selector=f"notebook-name={req.name}",
        )

    def _patch_status(self, name: str, ns: str, patch: dict) -> None:
        """Status writes coalesce through the controller's batcher
        when one is wired (one PATCH per key per loop iteration under
        churn), else write directly — same merge-patch either way."""
        if self.status_writer is not None:
            self.status_writer.submit(
                NOTEBOOK_API, "Notebook", name, {"status": patch}, ns
            )
        else:
            self.api.patch_merge(
                NOTEBOOK_API, "Notebook", name, {"status": patch}, ns
            )

    def reconcile(self, req: Request) -> float | None:
        # Phase attribution (PR 10): the four classic reconcile costs
        # — read the world ("list"), compute what it should be
        # ("desired-state"), write the difference ("patch"), mirror it
        # back ("status") — reported through the contextvar profiler
        # the runtime activates around this call; a no-op outside one.
        with profile_phase("list"):
            try:
                notebook = self.api.get(
                    NOTEBOOK_API, "Notebook", req.name, req.namespace
                )
            except NotFound:
                # Deleted: children are garbage-collected via
                # ownerReferences; its pool admission is released.
                if self.scheduler is not None:
                    self.scheduler.release(
                        "Notebook", req.namespace, req.name
                    )
                return None

            # One pod list shared by the elastic decision, gang
            # restart, preemption recovery and the status mirror — all
            # on the exact request path whose retry volume this
            # platform meters. Pods only change between controller
            # passes (the pod simulator / kubelet, never this
            # reconciler's own ensures), so listing before
            # desired-state generation is safe AND lets the elastic
            # policy steer what gets generated.
            pods = None
            if (notebook.get("spec") or {}).get("tpu"):
                pods = self._list_pods(req)
        with profile_phase("desired-state"):
            reshard_reason, elastic_shape = self._elastic(
                notebook, req, pods)
            # Slice-pool gate: the scheduler is consulted BEFORE the
            # StatefulSet is emitted (the elastic.py steering
            # discipline) — an unadmitted gang runs at zero replicas,
            # its chips stay in the pool, and status says why.
            sched_verdict = self._schedule(notebook, req, elastic_shape)
            native_notebook = notebook
            if elastic_shape is not None:
                # Degraded-mode override: desired state is generated at
                # the active rung's topology — the StatefulSet is
                # re-emitted at the new replica count / per-host chip
                # limits and the pods get the matching world-size env.
                # The CR's spec is never touched; the override lives in
                # annotations.
                native_notebook = copy.deepcopy(notebook)
                native_notebook["spec"]["tpu"]["topology"] = \
                    elastic_shape.topology
            out = native.invoke(
                "notebook_reconcile",
                {"notebook": native_notebook,
                 "options": self.options.to_native()},
            )
            if sched_verdict is not None and not sched_verdict.admitted:
                # Gang all-or-nothing: a Queued/Suspended slice holds
                # zero replicas (never a partial gang), so the pod
                # simulator / statefulset controller prunes its pods
                # and the chips return to the pool.
                out["statefulset"]["spec"]["replicas"] = 0
        # One "patch" observation per reconcile: STS, events and
        # services are all "write the difference" — two separate
        # profile_phase("patch") blocks would double the digest's n
        # and halve its percentiles relative to the other phases.
        with profile_phase("patch"):
            try:
                sts_result = self._ensure(out["statefulset"])
            except Exception as exc:
                # EventRecorder parity (reference notebook_controller.go:139-169
                # records create failures onto the CR).
                record_event(
                    self.api, notebook, "CreateFailed",
                    f"StatefulSet for notebook {req.name} failed: {exc}",
                    event_type="Warning",
                )
                if self.prom is not None:
                    # Only a failed *creation* counts (reference
                    # NotebookFailCreation); a Conflict while drift-repairing
                    # an existing STS is a routine retry, not a create failure.
                    try:
                        self.api.get("apps/v1", "StatefulSet", req.name, req.namespace)
                    except NotFound:
                        self.prom.notebook_create_failed_total.labels(
                            req.namespace
                        ).inc()
                raise
            if sts_result == "created":
                record_event(
                    self.api, notebook, "Created",
                    f"Created StatefulSet for notebook {req.name}",
                )
                if self.prom is not None:
                    # Counts new notebook materialisations, like the
                    # reference's NotebookCreation counter on first create.
                    self.prom.notebook_create_total.labels(req.namespace).inc()
            for svc in out["services"]:
                self._ensure(svc)
            if out["virtualService"] is not None:
                self._ensure(out["virtualService"])

        with profile_phase("status"):
            # STS re-fetched after the ensure so recovery and the
            # status mirror see the replica count just emitted (an
            # elastic transition changes it within this very pass).
            try:
                sts = self.api.get(
                    "apps/v1", "StatefulSet", req.name, req.namespace
                )
            except NotFound:
                sts = None
            self._gang_restart(notebook, req, pods)
            restart_reason = self._preemption_recovery(
                notebook, req, sts, pods)
            self._update_status(notebook, restart_reason, sts, pods,
                                reshard_reason=reshard_reason,
                                elastic_shape=elastic_shape,
                                sched_verdict=sched_verdict)
        return None

    # ---- slice-pool scheduling -------------------------------------------
    def _schedule(self, notebook: dict, req: Request, elastic_shape):
        """Consult the slice-pool scheduler with the gang demand of the
        EFFECTIVE shape (the elastic rung when one is active — a
        degraded slice demands only what it will actually run).
        Applies the verdict's annotation patches and the resume
        handshake; returns the verdict, or None when no scheduler is
        wired / the notebook holds no TPU slice."""
        if self.scheduler is None:
            return None
        tpu = ((notebook.get("spec") or {}).get("tpu")) or {}
        if not tpu.get("accelerator"):
            return None
        try:
            slice_ = elastic_shape or TpuSlice.parse(
                tpu["accelerator"], tpu.get("topology", "1x1")
            )
        except TopologyError:
            return None  # native reconcile surfaces the spec error
        anns = notebook.setdefault("metadata", {}).setdefault(
            "annotations", {}
        )
        verdict = self.scheduler.decide(
            "Notebook", req.namespace, req.name, slice_.chips, anns,
            now=self.clock(),
            observed_running=sched_observed_running(self.api, req),
        )
        # Resurrect handshake: same contract as SliceRestarted — the
        # fresh slice is expected to pick up from the step the
        # suspension parked at.
        apply_verdict(
            self.api, NOTEBOOK_API, "Notebook", notebook, req,
            verdict, self.scheduler, self.clock,
            resume_key=RESUME_EXPECTED_KEY,
            resume_message="admitted from Suspended; training resumes "
                           "from checkpoint step {step}",
        )
        return verdict

    # ---- elastic topology ------------------------------------------------
    def _elastic(self, notebook: dict, req: Request, pods: list | None):
        """Run the degraded-mode policy (controllers/elastic.py) and
        apply its verdict: annotation patches, transition events, and
        the effective shape the desired-state generation must use.
        Returns ``(reshard_reason, effective_slice_or_None)`` — None
        when the spec shape is in force."""
        decision = elastic.decide(notebook, pods, self.clock(),
                                  promotion_gate=self.promotion_gate)
        if decision is None:
            return None, None
        if decision.patches:
            self.api.patch_merge(
                NOTEBOOK_API, "Notebook", req.name,
                {"metadata": {"annotations": decision.patches}},
                req.namespace,
            )
            anns = notebook.setdefault("metadata", {}).setdefault(
                "annotations", {}
            )
            for key, value in decision.patches.items():
                if value is None:
                    anns.pop(key, None)
                else:
                    anns[key] = value
        reshard_modes = {"SliceDegraded": "degrade",
                         "SlicePromoted": "promote"}
        for reason, message, event_type in decision.events:
            record_event(
                self.api, notebook, reason, message,
                event_type=event_type,
            )
            mode = reshard_modes.get(reason)
            if mode and self.prom is not None and hasattr(
                self.prom, "notebook_reshard_total"
            ):
                self.prom.notebook_reshard_total.labels(
                    req.namespace, mode
                ).inc()
        return (
            decision.reshard_reason,
            None if decision.at_spec_shape else decision.effective,
        )

    def _gang_restart(self, notebook: dict, req: Request,
                      pods: list | None) -> None:
        """SURVEY §7 hard part (b): a lone rank restart wedges the rest
        of the slice's jax.distributed — recycle all pods together. The
        decision (restart-counter bookkeeping) is native policy
        (native/src/notebook.cpp notebook_gang_restart)."""
        if pods is None:  # non-TPU notebook: nothing gang-scheduled
            return
        decision = native.invoke(
            "notebook_gang_restart", {"notebook": notebook, "pods": pods}
        )
        if decision["action"] == "none":
            return
        if decision["action"] == "restart":
            record_event(
                self.api, notebook, "GangRestart",
                "A replica restarted; recycling all "
                f"{len(decision['deletePods'])} pods so jax.distributed "
                "re-forms the slice",
                event_type="Warning",
            )
            # Deletes BEFORE the baseline advance: the deletes are
            # idempotent, so a crash mid-loop retries the restart on the
            # next pass — advancing the baseline first would record the
            # crash as handled while pods are still wedged.
            for pod_name in decision["deletePods"]:
                try:
                    self.api.delete("v1", "Pod", pod_name, req.namespace)
                except NotFound:
                    pass
        self.api.patch_merge(
            NOTEBOOK_API, "Notebook", req.name,
            {"metadata": {"annotations": decision["annotations"]}},
            req.namespace,
        )

    # ---- TPU preemption recovery ----------------------------------------
    def _preemption_recovery(
        self, notebook: dict, req: Request,
        sts: dict | None, pods: list | None,
    ) -> str | None:
        """GKE preemption / eviction recovery for multi-host slices.

        The gang-restart path catches a *crashed* container (restartCount
        advance); this one catches a *vanished or replaced* worker pod —
        what a node-pool preemption looks like. The state machine lives
        in :func:`controllers.slice_recovery.recover_slice` (shared with
        the InferenceService controller); the notebook-specific policy —
        the preemption-restart counter and the checkpoint-resume
        handshake on re-baseline — rides the hooks.

        Returns the restart reason while a recovery is in flight (fed
        into status as phase=Restarting), else None.
        """

        def on_first_restart():
            if self.prom is not None:
                self.prom.notebook_preemption_restart_total.labels(
                    req.namespace
                ).inc()

        def on_rebaseline(patch: dict, anns: dict, replicas: int):
            # Resume handshake: the fresh slice is expected to pick up
            # from the last checkpoint step the data plane reported
            # ("0" = no checkpoint known, fresh start).
            resume_step = anns.get(CHECKPOINT_STEP_KEY, "0")
            patch[RESUME_EXPECTED_KEY] = resume_step
            notebook.setdefault("metadata", {}).setdefault(
                "annotations", {}
            )[RESUME_EXPECTED_KEY] = resume_step
            record_event(
                self.api, notebook, "SliceRestarted",
                f"all {replicas} TPU workers recreated; "
                "jax.distributed mesh re-forming; training resumes "
                f"from checkpoint step {resume_step}",
            )

        return recover_slice(
            self.api, NOTEBOOK_API, "Notebook", notebook, req, sts,
            pods,
            SliceAnnotations(
                observed_mesh=OBSERVED_MESH_KEY,
                restart_reason=RESTART_REASON_KEY,
                preemption_restarts=PREEMPTION_RESTARTS_KEY,
            ),
            on_first_restart=on_first_restart,
            on_rebaseline=on_rebaseline,
        )

    def _update_status(self, notebook: dict,
                       restart_reason: str | None = None,
                       sts: dict | None = None,
                       pods: list | None = None,
                       reshard_reason: str | None = None,
                       elastic_shape=None,
                       sched_verdict=None) -> None:
        name = notebook["metadata"]["name"]
        ns = notebook["metadata"]["namespace"]
        sts = sts or {}
        if pods is not None:
            # TPU notebooks: reconcile already listed the slice pods.
            pod = next(
                (p for p in pods
                 if p["metadata"]["name"] == f"{name}-0"), {},
            )
        else:
            try:
                pod = self.api.get("v1", "Pod", f"{name}-0", ns)
            except NotFound:
                pod = {}
        # Field-selected server-side (apiserver supports
        # involvedObject.name on events): without it this list is
        # O(all events in the namespace) per reconcile and the status
        # mirror goes quadratic across N notebooks. Pod events carry
        # the pod's own name ("nb-0"), so one selected list per replica
        # joins them — replicas+1 point lists, bounded by slice size,
        # never by namespace population. The kind check stays
        # client-side (event_involves_notebook). Known trade-off: after
        # a scale-down (spec 3->1), events for leftover higher-ordinal
        # pods (nb-2) are no longer mirrored — those pods are being
        # torn down, and their terminal events age out of the window
        # anyway; scanning status.replicas too would re-add them if
        # that ever matters.
        replicas = max(
            ((notebook.get("spec") or {}).get("tpu") or {})
            .get("replicas", 1), 1,
        )
        events = []
        event_source = self.cache if self.cache is not None else self.api
        for involved in [name] + [f"{name}-{i}" for i in range(replicas)]:
            events.extend(
                e
                for e in event_source.list(
                    "v1", "Event", namespace=ns,
                    field_selector=f"involvedObject.name={involved}",
                )
                if event_involves_notebook(e, name)
            )
        status = native.invoke(
            "notebook_status",
            {
                "notebook": notebook,
                "statefulset": sts,
                "pod": pod,
                "events": events,
            },
        )
        cur_status = notebook.get("status") or {}
        if reshard_reason:
            # An elastic shape transition is in flight: it supersedes a
            # lingering restart marker (the preemption that *triggered*
            # the degrade) — Resharding tells the operator what the
            # platform is actually doing about the lost capacity.
            status["phase"] = "Resharding"
            status["reshardReason"] = reshard_reason
        elif restart_reason:
            # A coherent full-slice restart is in flight (preemption
            # recovery): surface it where the dashboard and kubectl
            # look, on top of the native-derived status.
            status["phase"] = "Restarting"
            status["restartReason"] = restart_reason
        if sched_verdict is not None and sched_verdict.phase:
            # The scheduler's view wins over restart/reshard markers: a
            # Queued/Suspended slice has no pods, so "Restarting" would
            # describe machinery that is deliberately parked; while
            # Preempting, the drain is what the operator must see.
            status["phase"] = sched_verdict.phase
            if sched_verdict.reason:
                status["schedulingReason"] = sched_verdict.reason
            if sched_verdict.queue_position is not None:
                status["queuePosition"] = sched_verdict.queue_position
        if elastic_shape is not None:
            # Running (or converging) degraded: the effective shape and
            # world size, for kubectl/dashboard — absent when the spec
            # shape is in force.
            status["elasticShape"] = elastic_shape.shorthand
            status["elasticWorldSize"] = elastic_shape.num_hosts
        # Resume visibility: once a SliceRestarted stamped the expected
        # resume step, keep it on status until the next restart
        # rewrites it — "this notebook last resumed from step N".
        resume_raw = (
            (notebook.get("metadata") or {}).get("annotations") or {}
        ).get(RESUME_EXPECTED_KEY)
        if resume_raw is not None:
            try:
                status["resumedFromStep"] = int(resume_raw)
            except (TypeError, ValueError):
                log.warning(
                    "notebook %s/%s: non-numeric %s annotation %r",
                    ns, name, RESUME_EXPECTED_KEY, resume_raw,
                )
        if cur_status != status:
            patch = dict(status)
            # Merge-patch semantics: stale markers from a completed
            # recovery/transition must be removed explicitly (null
            # deletes), or they would linger forever. "phase" is only
            # controller-owned while a restart/reshard is in flight.
            for key in ("phase", "restartReason", "reshardReason",
                        "resumedFromStep", "elasticShape",
                        "elasticWorldSize", "schedulingReason",
                        "queuePosition"):
                if key not in status and key in cur_status:
                    patch[key] = None
            # Same discipline one level down: merging an emptier
            # containerState over {"running": {}} is a no-op (a merge
            # patch cannot shrink a dict by being smaller), which would
            # re-patch forever once a worker regresses Running→Pending
            # (an elastic probe at a too-big shape does exactly that).
            cur_cs = cur_status.get("containerState")
            new_cs = status.get("containerState")
            if isinstance(cur_cs, dict) and isinstance(new_cs, dict):
                removed = {k: None for k in cur_cs if k not in new_cs}
                if removed:
                    patch["containerState"] = {**new_cs, **removed}
            self._patch_status(name, ns, patch)


def make_notebook_controller(
    api: FakeApiServer,
    options: NotebookOptions | None = None,
    prom=None,
    clock=time.time,
    promotion_gate=None,
    scheduler=None,
    cache=None,
    status_batcher=None,
    shard_gate=None,
) -> Controller:
    reconciler = NotebookReconciler(api, options, prom=prom, clock=clock,
                                    promotion_gate=promotion_gate,
                                    scheduler=scheduler, cache=cache,
                                    status_writer=status_batcher)
    return Controller(
        name="notebook-controller",
        api=api,
        reconciler=reconciler,
        watches=[
            WatchSpec(NOTEBOOK_API, "Notebook"),
            WatchSpec("apps/v1", "StatefulSet", pod_to_notebook_requests),
            WatchSpec("v1", "Pod", pod_to_notebook_requests),
        ],
        prom=prom,
        shard_gate=shard_gate,
        status_batcher=status_batcher,
        cache=cache,
    )
