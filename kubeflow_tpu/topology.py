"""TPU accelerator / topology model — single source of truth.

The reference platform models accelerators as an opaque GPU vendor+count
pair injected into container limits (reference
``crud-web-apps/jupyter/backend/apps/common/form.py:226-250`` and
``spawner_ui_config.yaml:120-143``). TPU slices need more structure: a
slice has an accelerator generation, a physical topology (ICI torus
dims), a chips-per-host machine shape, and — for multi-host slices — a
replica count that MUST equal the number of hosts. This module owns that
math for every component:

- notebook controller: replicas, ``google.com/tpu`` limits, GKE selectors
- PodDefault webhook / spawner: topology validation and presets
- ResourceQuota (profiles): ``google.com/tpu`` accounting
"""

from __future__ import annotations

import dataclasses
import math


@dataclasses.dataclass(frozen=True)
class Accelerator:
    """One TPU generation as GKE exposes it."""

    name: str                 # short name used in CRs ("v5e")
    gke_accelerator: str      # cloud.google.com/gke-tpu-accelerator value
    ndims: int                # ICI torus dimensionality (2 or 3)
    chips_per_host: int       # chips per VM in multi-host slices
    max_single_host_chips: int  # largest slice that fits one host
    peak_bf16_flops: float    # per-chip dense bf16 peak, FLOP/s
    vmem_bytes: int           # per-core VMEM a Pallas program can hold


# ~16 MiB of VMEM per TensorCore on every shipped generation — the
# budget every Pallas kernel's resident blocks (double-buffered) plus
# scratch must fit. Single source of truth for the kernel lint's
# krn-vmem-budget cap and any runtime tile-size selection.
_VMEM_PER_CORE = 16 * 1024 * 1024

ACCELERATORS: dict[str, Accelerator] = {
    "v4": Accelerator("v4", "tpu-v4-podslice", 3, 4, 4, 275e12,
                      _VMEM_PER_CORE),
    "v5e": Accelerator("v5e", "tpu-v5-lite-podslice", 2, 4, 8, 197e12,
                       _VMEM_PER_CORE),
    "v5p": Accelerator("v5p", "tpu-v5p-slice", 3, 4, 4, 459e12,
                       _VMEM_PER_CORE),
    "v6e": Accelerator("v6e", "tpu-v6e-slice", 2, 4, 8, 918e12,
                       _VMEM_PER_CORE),
}


def min_vmem_bytes() -> int:
    """Smallest per-core VMEM across the fleet's generations — the cap
    a kernel must fit to run on any shipped slice."""
    return min(acc.vmem_bytes for acc in ACCELERATORS.values())

# jax ``device.device_kind`` substrings → accelerator short name.
# Longest match wins ("v5 lite" must beat "v5"); the spellings are the
# ones PJRT has actually reported across runtime versions.
_DEVICE_KIND_PATTERNS: dict[str, str] = {
    "v5 lite": "v5e", "v5litepod": "v5e", "v5e": "v5e",
    "v6 lite": "v6e", "v6e": "v6e",
    "v5p": "v5p", "v5": "v5p",
    "v4": "v4",
}

# MFU denominator for non-TPU smoke runs (CPU tier-1, laptops): a
# nominal finite peak so telemetry stays well-defined — the absolute
# MFU value is meaningless off-TPU, finiteness is the contract.
NOMINAL_HOST_PEAK_FLOPS = 197e12


def accelerator_for_device_kind(kind: str) -> Accelerator | None:
    """Map a jax ``device_kind`` string to the accelerator table entry,
    or None for non-TPU devices."""
    kind = (kind or "").lower()
    for pattern, name in sorted(
        _DEVICE_KIND_PATTERNS.items(), key=lambda kv: -len(kv[0])
    ):
        if pattern in kind:
            return ACCELERATORS[name]
    return None


def peak_flops_for_device_kind(
    kind: str, default: float = NOMINAL_HOST_PEAK_FLOPS
) -> float:
    """Per-chip bf16 peak FLOP/s for a jax device kind — the single
    MFU denominator shared by bench.py and obs.telemetry."""
    acc = accelerator_for_device_kind(kind)
    return acc.peak_bf16_flops if acc is not None else default

# Canonical topology string for a chip count (2-D generations).
_TOPO_2D = {
    1: "1x1", 4: "2x2", 8: "2x4", 16: "4x4", 32: "4x8",
    64: "8x8", 128: "8x16", 256: "16x16",
}
# 3-D generations (v4/v5p): chips -> torus dims.
_TOPO_3D = {
    4: "2x2x1", 8: "2x2x2", 16: "2x2x4", 32: "2x4x4", 64: "4x4x4",
    128: "4x4x8", 256: "4x8x8", 512: "8x8x8",
}

GKE_ACCELERATOR_LABEL = "cloud.google.com/gke-tpu-accelerator"
GKE_TOPOLOGY_LABEL = "cloud.google.com/gke-tpu-topology"
TPU_RESOURCE = "google.com/tpu"


class TopologyError(ValueError):
    pass


@dataclasses.dataclass(frozen=True)
class TpuSlice:
    """A validated (accelerator, topology) pair, e.g. ("v5e", "4x4")."""

    accelerator: Accelerator
    topology: str

    @classmethod
    def parse(cls, accelerator: str, topology: str) -> "TpuSlice":
        acc = ACCELERATORS.get(accelerator)
        if acc is None:
            raise TopologyError(
                f"unknown accelerator {accelerator!r}; known: {sorted(ACCELERATORS)}"
            )
        try:
            dims = [int(d) for d in topology.split("x")]
        except ValueError:
            raise TopologyError(f"malformed topology {topology!r}")
        if not dims or any(d < 1 for d in dims):
            raise TopologyError(f"malformed topology {topology!r}")
        if len(dims) != acc.ndims:
            raise TopologyError(
                f"{accelerator} topologies are {acc.ndims}-D, got {topology!r}"
            )
        table = _TOPO_2D if acc.ndims == 2 else _TOPO_3D
        if topology not in table.values():
            raise TopologyError(
                f"{topology!r} is not a valid {accelerator} slice; "
                f"valid: {sorted(table.values())}"
            )
        return cls(acc, topology)

    @classmethod
    def from_shorthand(cls, shorthand: str) -> "TpuSlice":
        """Parse "v5e-16" (accelerator-chips) into the canonical slice."""
        try:
            name, chips_s = shorthand.rsplit("-", 1)
            chips = int(chips_s)
        except ValueError:
            raise TopologyError(f"malformed shorthand {shorthand!r}")
        acc = ACCELERATORS.get(name)
        if acc is None:
            raise TopologyError(f"unknown accelerator {name!r}")
        table = _TOPO_2D if acc.ndims == 2 else _TOPO_3D
        if chips not in table:
            raise TopologyError(
                f"no canonical {name} topology for {chips} chips; "
                f"valid counts: {sorted(table)}"
            )
        return cls.parse(name, table[chips])

    @property
    def chips(self) -> int:
        return math.prod(int(d) for d in self.topology.split("x"))

    @property
    def num_hosts(self) -> int:
        if self.chips <= self.accelerator.max_single_host_chips:
            return 1
        return self.chips // self.accelerator.chips_per_host

    @property
    def chips_per_replica(self) -> int:
        return self.chips // self.num_hosts

    @property
    def is_multihost(self) -> bool:
        return self.num_hosts > 1

    @property
    def peak_bf16_flops(self) -> float:
        """Whole-slice dense bf16 peak — the MFU denominator for a
        workload spanning every chip in the slice."""
        return self.chips * self.accelerator.peak_bf16_flops

    @property
    def shorthand(self) -> str:
        return f"{self.accelerator.name}-{self.chips}"

    def node_selectors(self) -> dict[str, str]:
        return {
            GKE_ACCELERATOR_LABEL: self.accelerator.gke_accelerator,
            GKE_TOPOLOGY_LABEL: self.topology,
        }

    def container_resources(self) -> dict[str, str]:
        """Per-pod (= per-host) TPU resource limits."""
        return {TPU_RESOURCE: str(self.chips_per_replica)}


def fallback_ladder(slice_: TpuSlice) -> list[TpuSlice]:
    """Degraded-mode shapes for elastic resume, largest first.

    Successive halvings of the chip count within the same accelerator
    generation, down to one full host's worth of chips (a fraction of a
    host is not a schedulable TPU shape): v5e-16 → [v5e-8, v5e-4].
    Every rung is a canonical GKE topology, so the controller can
    re-emit the StatefulSet for any of them verbatim. The slice itself
    is NOT in the ladder — rung 0 is always the spec's own shape.
    """
    acc = slice_.accelerator
    table = _TOPO_2D if acc.ndims == 2 else _TOPO_3D
    out = []
    chips = slice_.chips // 2
    while chips >= acc.chips_per_host:
        if chips in table:
            out.append(TpuSlice.parse(acc.name, table[chips]))
        chips //= 2
    return out


def parse_ladder(slice_: TpuSlice, raw: str) -> list[TpuSlice]:
    """A fallback ladder from its annotation value: ``"auto"`` derives
    :func:`fallback_ladder`; otherwise a comma-separated shorthand list
    ("v5e-8,v5e-4"). Raises :class:`TopologyError` on malformed
    entries, a different accelerator generation (a slice cannot change
    generation by being preempted), or a non-decreasing chip sequence
    (the ladder must be a strict fallback order)."""
    raw = (raw or "").strip()
    if not raw or raw.lower() == "auto":
        return fallback_ladder(slice_)
    rungs = []
    prev = slice_.chips
    for token in raw.split(","):
        rung = TpuSlice.from_shorthand(token.strip())
        if rung.accelerator.name != slice_.accelerator.name:
            raise TopologyError(
                f"ladder rung {token.strip()!r} is a different "
                f"accelerator than the slice ({slice_.shorthand})"
            )
        if rung.chips >= prev:
            raise TopologyError(
                f"ladder must strictly decrease in chips: {raw!r}"
            )
        prev = rung.chips
        rungs.append(rung)
    return rungs


def spawner_presets(accelerators: list[str] | None = None) -> list[dict]:
    """Topology options for the spawner UI config (replaces the reference's
    GPU vendors list, ``spawner_ui_config.yaml:120-143``)."""
    out = []
    for name in accelerators or ["v5e", "v6e"]:
        acc = ACCELERATORS[name]
        table = _TOPO_2D if acc.ndims == 2 else _TOPO_3D
        for chips in sorted(table):
            sl = TpuSlice.parse(name, table[chips])
            out.append(
                {
                    "accelerator": name,
                    "topology": sl.topology,
                    "shorthand": sl.shorthand,
                    "chips": sl.chips,
                    "hosts": sl.num_hosts,
                    "multihost": sl.is_multihost,
                }
            )
    return out
