"""PodDefault admission webhook.

Python process wrapper around the native merge engine
(native/src/poddefault.cpp). Capability parity with the reference
admission-webhook (reference components/admission-webhook/main.go:
serve :748-793, mutatePods :639-744); the TPU-native delta is the
shipped ``tpu-env`` PodDefault that wires every selecting pod for
jax.distributed on a slice.
"""

from kubeflow_tpu.webhook.server import (
    AdmissionHandler,
    WebhookServer,
    inference_env_poddefault,
    register_with_fake,
    tpu_env_poddefault,
)

__all__ = [
    "AdmissionHandler",
    "WebhookServer",
    "inference_env_poddefault",
    "register_with_fake",
    "tpu_env_poddefault",
]
