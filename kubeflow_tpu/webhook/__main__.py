from kubeflow_tpu.entrypoints import run_admission_webhook

run_admission_webhook()
