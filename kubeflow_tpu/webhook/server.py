"""AdmissionReview v1 handling + HTTPS server for the PodDefault webhook.

Flow (reference admission-webhook/main.go serve :748-793 → mutatePods
:639-744, rebuilt): decode AdmissionReview, list PodDefaults in the
pod's namespace, hand both to the native merge engine, return a
base64 JSONPatch response — or an allowed:false with the aggregated
conflict message (the apiserver surfaces it to the creating client;
failurePolicy decides what happens when the webhook itself is down).
"""

from __future__ import annotations

import base64
import http.server
import json
import logging
import os
import ssl
import threading
import urllib.parse
from typing import Callable

from kubeflow_tpu import native, obs

log = logging.getLogger(__name__)

PODDEFAULT_API = "kubeflow.org/v1alpha1"

# fn(namespace) -> list of PodDefault dicts.
PodDefaultLister = Callable[[str], list]


class CachedPodDefaultLister:
    """Last-known-good PodDefault lister with bounded staleness.

    With ``failurePolicy: Fail``, a webhook that cannot list PodDefaults
    turns every apiserver blip into a cluster-wide pod-creation outage.
    This wrapper serves the most recent successful per-namespace list
    when the live read raises, but only for ``max_stale_s`` — past that
    the error propagates (reject rather than mutate from an arbitrarily
    old world). Clock is injectable for deterministic tests."""

    def __init__(self, inner: PodDefaultLister, max_stale_s: float = 120.0,
                 clock=None):
        import time as _time

        self.inner = inner
        self.max_stale_s = max_stale_s
        self._clock = clock or _time.monotonic
        self._lock = threading.Lock()
        self._cache: dict[str, tuple[float, list]] = {}  # ns -> (at, items)
        self.stale_serves_total = 0

    def __call__(self, namespace: str) -> list:
        try:
            items = self.inner(namespace)
        except Exception as exc:
            with self._lock:
                entry = self._cache.get(namespace)
                if entry is not None:
                    at, items = entry
                    if self._clock() - at <= self.max_stale_s:
                        self.stale_serves_total += 1
                        log.warning(
                            "PodDefault list for %s failed (%s); serving "
                            "cached list aged %.1fs",
                            namespace, exc, self._clock() - at,
                        )
                        return items
            raise
        with self._lock:
            self._cache[namespace] = (self._clock(), items)
        return items


class AdmissionHandler:
    def __init__(self, list_poddefaults: PodDefaultLister):
        self.list_poddefaults = list_poddefaults

    def review(self, review: dict) -> dict:
        """AdmissionReview in → AdmissionReview out (always 200-shaped;
        malformed requests produce allowed:false, never an exception)."""
        request = review.get("request") or {}
        uid = request.get("uid", "")
        response: dict = {"uid": uid, "allowed": True}
        out = {
            "apiVersion": "admission.k8s.io/v1",
            "kind": "AdmissionReview",
            "response": response,
        }
        try:
            if request.get("kind", {}).get("kind") not in (None, "Pod"):
                return out  # not ours: allow untouched
            pod = request.get("object")
            if not isinstance(pod, dict):
                raise ValueError("admission request has no pod object")
            namespace = request.get("namespace") or pod.get("metadata", {}).get(
                "namespace", "default"
            )
            poddefaults = self.list_poddefaults(namespace)
            result = native.invoke(
                "poddefault_mutate",
                {"pod": pod, "poddefaults": poddefaults},
            )
            if result["conflicts"]:
                response["allowed"] = False
                response["status"] = {
                    "message": "; ".join(result["conflicts"]),
                    "code": 400,
                }
                return out
            if result["applied"] and result["patch"]:
                response["patchType"] = "JSONPatch"
                response["patch"] = base64.b64encode(
                    json.dumps(result["patch"]).encode()
                ).decode()
            return out
        except Exception as exc:  # malformed review: reject, don't crash
            log.exception("admission review failed")
            response["allowed"] = False
            response["status"] = {"message": str(exc), "code": 400}
            return out


class PvcViewerAdmissionHandler:
    """Defaulting + validating admission for PVCViewer CRs (role of the
    reference pvcviewer_webhook.go served from the same webhook binary
    here — second path next to /apply-poddefault). Invalid CRs are
    rejected at admission instead of failing late in the reconciler."""

    def review(self, review: dict) -> dict:
        request = review.get("request") or {}
        uid = request.get("uid", "")
        response: dict = {"uid": uid, "allowed": True}
        out = {
            "apiVersion": "admission.k8s.io/v1",
            "kind": "AdmissionReview",
            "response": response,
        }
        try:
            kind = request.get("kind", {}).get("kind")
            if kind not in (None, "PVCViewer"):
                return out  # not ours: allow untouched
            viewer = request.get("object")
            if not isinstance(viewer, dict):
                raise ValueError("admission request has no PVCViewer object")
            result = native.invoke(
                "pvcviewer_admit",
                {
                    "viewer": viewer,
                    # Fallback identity for generateName creates (object
                    # metadata.name is still empty at admission time).
                    "requestName": request.get("name") or "",
                    "requestNamespace": request.get("namespace") or "",
                },
            )
            if result["errors"]:
                response["allowed"] = False
                response["status"] = {
                    "message": "; ".join(result["errors"]),
                    "code": 400,
                }
                return out
            if result["patch"]:
                response["patchType"] = "JSONPatch"
                response["patch"] = base64.b64encode(
                    json.dumps(result["patch"]).encode()
                ).decode()
            return out
        except Exception as exc:  # malformed review: reject, don't crash
            log.exception("pvcviewer admission failed")
            response["allowed"] = False
            response["status"] = {"message": str(exc), "code": 400}
            return out


class WebhookServer:
    """Threaded HTTPS server exposing the admission paths
    (/apply-poddefault for pod mutation, /admit-pvcviewer for PVCViewer
    defaulting+validation) + /healthz. TLS optional for tests;
    production mounts cert-manager certs the way the reference's
    certwatcher does, reference config.go:43-60."""

    def __init__(
        self,
        handler: AdmissionHandler,
        port: int = 4443,
        certfile: str | None = None,
        keyfile: str | None = None,
        cert_watch_period_s: float = 10.0,
        pvcviewer_handler: "PvcViewerAdmissionHandler | None" = None,
    ):
        self.handler = handler
        self.routes = {
            "/apply-poddefault": handler.review,
            "/admit-pvcviewer": (
                pvcviewer_handler or PvcViewerAdmissionHandler()
            ).review,
        }
        outer = self

        class _HTTPHandler(http.server.BaseHTTPRequestHandler):
            # Admission sits on the pod-create critical path; Nagle +
            # delayed ACK would add ~40ms per review (client.py).
            disable_nagle_algorithm = True

            def log_message(self, fmt, *args):
                log.debug("webhook: " + fmt, *args)

            def do_GET(self):
                if self.path in ("/healthz", "/readyz"):
                    body = b'{"status":"ok"}'
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self.send_error(404)

            def do_POST(self):
                # The apiserver appends query params (?timeout=10s):
                # match on the path component only.
                path = urllib.parse.urlsplit(self.path).path
                review_fn = outer.routes.get(path.rstrip("/"))
                if review_fn is None:
                    self.send_error(404)
                    return
                length = int(self.headers.get("Content-Length", 0))
                try:
                    review = json.loads(self.rfile.read(length) or b"{}")
                except json.JSONDecodeError:
                    self.send_error(400, "bad JSON")
                    return
                # Admission sits inside the pod-create critical path:
                # wrap the review in a span (continuing an upstream
                # traceparent when the caller sends one) so the
                # mutate/reject decision and its latency land in the
                # same trace as the reconcile that triggered it.
                request = review.get("request") or {}
                parent = obs.parse_traceparent(
                    self.headers.get("traceparent")
                )
                with obs.get_tracer().span(
                    f"admission {path.rstrip('/')}",
                    parent=parent,
                    attributes={
                        "namespace": request.get("namespace", ""),
                        "name": request.get("name", ""),
                        "kind": (request.get("kind") or {}).get(
                            "kind", ""
                        ),
                    },
                ) as span:
                    out = review_fn(review)
                    response = out.get("response") or {}
                    span.set_attribute(
                        "allowed", bool(response.get("allowed"))
                    )
                    span.set_attribute(
                        "patched", bool(response.get("patch"))
                    )
                    if not response.get("allowed"):
                        span.status = "error"
                reply = json.dumps(out).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(reply)))
                self.end_headers()
                self.wfile.write(reply)

        self._server = http.server.ThreadingHTTPServer(("", port), _HTTPHandler)
        self._ssl_context: ssl.SSLContext | None = None
        self._cert_watcher: threading.Thread | None = None
        self._cert_stop = threading.Event()
        if certfile:
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(certfile, keyfile)
            self._server.socket = ctx.wrap_socket(
                self._server.socket, server_side=True
            )
            self._ssl_context = ctx
            self._start_cert_watcher(
                certfile, keyfile, period_s=cert_watch_period_s
            )

    def _start_cert_watcher(
        self, certfile: str, keyfile: str | None, period_s: float = 10.0
    ) -> None:
        """certwatcher parity (reference config.go:43-60): cert-manager
        rotates the mounted secret in place; new handshakes must pick up
        the fresh chain without a restart. mtime-polled; a mid-rotation
        read (cert/key momentarily mismatched) just retries next tick."""

        def mtimes():
            out = []
            for path in (certfile, keyfile):
                if not path:
                    continue
                try:
                    out.append(os.path.getmtime(path))
                except OSError:
                    out.append(None)
            return out

        last = mtimes()

        def watch():
            nonlocal last
            warned_for = None
            while not self._cert_stop.wait(period_s):
                current = mtimes()
                if current == last:
                    continue
                try:
                    self._ssl_context.load_cert_chain(certfile, keyfile)
                    last = current
                    warned_for = None
                    log.info("webhook TLS certificate reloaded")
                except (ssl.SSLError, OSError):
                    # One warning per distinct rotation attempt, not per
                    # tick — a persistently unreadable key would
                    # otherwise spam identical lines forever.
                    if current != warned_for:
                        warned_for = current
                        log.warning(
                            "webhook TLS reload failed (rotation in "
                            "progress?); keeping previous certificate"
                        )

        self._cert_watcher = threading.Thread(
            target=watch, name="webhook-certwatcher", daemon=True
        )
        self._cert_watcher.start()

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> threading.Thread:
        thread = threading.Thread(
            target=self._server.serve_forever, name="poddefault-webhook",
            daemon=True,
        )
        thread.start()
        return thread

    def stop(self):
        self._cert_stop.set()
        self._server.shutdown()


def register_with_fake(api) -> None:
    """Wire the webhook into the fake apiserver's admission chain so pods
    created in tests/dev traverse the same mutation path the real
    apiserver would route through the HTTPS endpoint."""

    def lister(namespace: str) -> list:
        return api.list(PODDEFAULT_API, "PodDefault", namespace=namespace)

    def hook(pod: dict) -> dict:
        namespace = pod.get("metadata", {}).get("namespace", "default")
        result = native.invoke(
            "poddefault_mutate",
            {"pod": pod, "poddefaults": lister(namespace)},
        )
        if result["conflicts"]:
            from kubeflow_tpu.k8s.fake import ApiError

            raise ApiError("; ".join(result["conflicts"]))
        return result["pod"]

    api.register_admission("Pod", hook)

    def pvcviewer_hook(viewer: dict) -> dict:
        result = native.invoke("pvcviewer_admit", {"viewer": viewer})
        if result["errors"]:
            from kubeflow_tpu.k8s.fake import ApiError

            raise ApiError("; ".join(result["errors"]))
        return result["viewer"]

    api.register_admission("PVCViewer", pvcviewer_hook)


def apply_json_patch(obj: dict, patch: list) -> dict:
    """Apply exactly the RFC 6902 subset the native diff engine emits
    (native/src/poddefault.cpp json_patch_diff): add / replace / remove
    on OBJECT member paths — arrays are always replaced wholesale at
    their object key, never indexed into. Anything else is rejected
    loudly rather than half-applied."""
    import copy

    out = copy.deepcopy(obj)
    for op in patch:
        parts = [
            p.replace("~1", "/").replace("~0", "~")
            for p in op["path"].lstrip("/").split("/")
        ]
        parent = out
        for part in parts[:-1]:
            if not isinstance(parent, dict):
                raise ValueError(
                    f"unsupported patch path {op['path']!r}: array "
                    "traversal is outside the engine's emitted subset"
                )
            parent = parent.setdefault(part, {})
        if not isinstance(parent, dict):
            raise ValueError(
                f"unsupported patch path {op['path']!r}: array "
                "indexing is outside the engine's emitted subset"
            )
        last = parts[-1]
        kind = op["op"]
        if kind in ("add", "replace"):
            parent[last] = op["value"]
        elif kind == "remove":
            parent.pop(last, None)
        else:
            raise ValueError(f"unsupported patch op {kind!r}")
    return out


class CABundleInjector:
    """cert-manager-less caBundle propagation.

    The reference delegates CA injection to cert-manager's ca-injector
    (admission-webhook/manifests/overlays/cert-manager/certificate.yaml
    — the `cert-manager.io/inject-ca-from` annotation); without
    cert-manager the MutatingWebhookConfiguration's
    ``clientConfig.caBundle`` is a manifest constant that rotating the
    CA silently breaks (the apiserver starts rejecting the webhook's
    serving cert, and with failurePolicy=Fail that blocks pod CREATEs).

    This injector closes the loop from inside the webhook binary: poll
    the mounted CA file and, whenever its bytes change (and once at
    startup — level-based, so a restart converges regardless of missed
    events), patch EVERY webhook entry in the named configuration with
    the base64 bundle. Update conflicts and transient apiserver errors
    retry on the next tick, same posture as the serving-cert watcher.
    """

    def __init__(self, api, ca_file: str,
                 config_name: str = "admission-webhook",
                 period_s: float = 10.0):
        self.api = api
        self.ca_file = ca_file
        self.config_name = config_name
        self.period_s = period_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def inject_once(self) -> bool:
        """One level-based pass; returns True if the config was
        patched. Safe to call directly (tests, pre-serve sync).

        Truly level-based: the LIVE config is read every tick and
        repaired whenever any entry's caBundle differs from the
        mounted CA — so external drift (a manifest re-apply restoring
        a stale constant, a recreated configuration) heals within one
        period, not only on the next CA rotation."""
        try:
            with open(self.ca_file, "rb") as fh:
                ca = fh.read()
        except OSError:
            return False  # not mounted (yet): keep previous state
        if not ca:
            return False
        bundle = base64.b64encode(ca).decode()
        try:
            cfg = self.api.get(
                "admissionregistration.k8s.io/v1",
                "MutatingWebhookConfiguration", self.config_name,
            )
            changed = False
            for hook in cfg.get("webhooks", []):
                client = hook.setdefault("clientConfig", {})
                if client.get("caBundle") != bundle:
                    client["caBundle"] = bundle
                    changed = True
            if changed:
                self.api.update(cfg)
                log.info(
                    "caBundle injected into %s (%d webhooks)",
                    self.config_name, len(cfg.get("webhooks", [])),
                )
            return changed
        except Exception as exc:  # conflict / outage: retry next tick
            log.warning("caBundle injection failed (will retry): %s", exc)
            return False

    def start(self) -> "CABundleInjector":
        self.inject_once()

        def loop():
            while not self._stop.wait(self.period_s):
                self.inject_once()

        self._thread = threading.Thread(
            target=loop, name="ca-bundle-injector", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)


def register_remote_webhook(api, url: str, cafile: str | None = None,
                            timeout: float = 10.0) -> None:
    """Play the APISERVER's side of the MutatingWebhookConfiguration:
    every pod CREATE on the fake is wrapped into an AdmissionReview,
    POSTed to a real webhook process over HTTPS, and the returned
    JSONPatch is applied (or the rejection surfaced). This is how the
    processes-tier conformance exercises the deployed admission path
    end to end without a cluster."""
    import ssl
    import urllib.request

    ctx = ssl.create_default_context(cafile=cafile) if cafile else None

    def hook(pod: dict) -> dict:
        review = {
            "apiVersion": "admission.k8s.io/v1",
            "kind": "AdmissionReview",
            "request": {
                "uid": pod.get("metadata", {}).get("name", "uid"),
                "kind": {"kind": "Pod"},
                "namespace": pod.get("metadata", {}).get(
                    "namespace", "default"
                ),
                "operation": "CREATE",
                "object": pod,
            },
        }
        req = urllib.request.Request(
            url,
            data=json.dumps(review).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=timeout, context=ctx) as r:
            out = json.loads(r.read())
        response = out.get("response") or {}
        if not response.get("allowed", False):
            from kubeflow_tpu.k8s.fake import ApiError

            raise ApiError(
                (response.get("status") or {}).get("message",
                                                   "admission denied")
            )
        if response.get("patch"):
            patch = json.loads(base64.b64decode(response["patch"]))
            return apply_json_patch(pod, patch)
        return pod

    api.register_admission("Pod", hook)


def tpu_env_poddefault(namespace: str) -> dict:
    """The platform-shipped PodDefault: selecting pods get slice-ready
    env (the jupyter-jax-tpu image's sitecustomize then calls
    kubeflow_tpu.parallel.initialize_from_env) and the TPU toleration.
    The per-rank env (TPU_WORKER_ID, hostnames, coordinator) comes from
    the notebook controller; this PodDefault covers what is common to
    every TPU pod in the namespace — including the checkpoint/resume
    contract (models/checkpoint.py manager_from_env reads these): the
    checkpoint root on the workspace PVC and the save cadence, tuned so
    a preemption loses at most ~100 steps or 5 minutes of work."""
    return {
        "apiVersion": PODDEFAULT_API,
        "kind": "PodDefault",
        "metadata": {"name": "tpu-env", "namespace": namespace},
        "spec": {
            "desc": "Configure TPU slice environment (jax.distributed)",
            "selector": {"matchLabels": {"tpu-env": "true"}},
            "env": [
                {"name": "JAX_PLATFORMS", "value": "tpu,cpu"},
                # Fail fast instead of silently hiding chips when the
                # device plugin hands us fewer than requested.
                {"name": "TPU_MIN_LOG_LEVEL", "value": "0"},
                # Crash-consistent checkpointing (ISSUE 4): root on the
                # PVC that survives slice restarts; cadence by steps
                # AND wall clock, whichever fires first.
                {"name": "KFT_CHECKPOINT_DIR",
                 "value": "/home/jovyan/checkpoints"},
                {"name": "KFT_CHECKPOINT_EVERY_STEPS", "value": "100"},
                {"name": "KFT_CHECKPOINT_EVERY_S", "value": "300"},
            ],
            "tolerations": [
                {
                    "key": "google.com/tpu",
                    "operator": "Exists",
                    "effect": "NoSchedule",
                }
            ],
        },
    }


def inference_env_poddefault(
    namespace: str,
    model_dir: str = "/home/jovyan/checkpoints",
    max_batch: int = 8,
    max_len: int = 2048,
) -> dict:
    """The serving-side PodDefault: InferenceService pods (the
    controller labels them ``inference-env: "true"``) get the
    namespace-wide gateway env — model directory and batching limits —
    injected at admission, ALONGSIDE the checkpoint vars from
    :func:`tpu_env_poddefault` (the controller also stamps
    ``tpu-env``). The controller deliberately does not set THESE env
    vars itself (namespace defaults live in one PodDefault, and the
    conflict-checked merge would reject pods if both sides disagreed);
    the split runs the other way for ``KFT_SERVING_PORT``, which is
    per-CR and controller-owned — it must never appear here.
    ``kubeflow_tpu.serving.__main__`` is the in-pod consumer."""
    return {
        "apiVersion": PODDEFAULT_API,
        "kind": "PodDefault",
        "metadata": {"name": "inference-env", "namespace": namespace},
        "spec": {
            "desc": "Configure the inference gateway environment",
            "selector": {"matchLabels": {"inference-env": "true"}},
            "env": [
                # The checkpoint root the hot-swap reload watches —
                # same PVC path the training PodDefault checkpoints to,
                # so a train-then-serve namespace works out of the box.
                {"name": "KFT_SERVING_MODEL_DIR", "value": model_dir},
                {"name": "KFT_SERVING_MAX_BATCH",
                 "value": str(max_batch)},
                {"name": "KFT_SERVING_MAX_LEN", "value": str(max_len)},
            ],
        },
    }
