"""Seeded fault schedules: the deterministic script a chaos run follows.

A schedule is a list of windows over an *operation counter* (every API
call through the ChaosApiServer advances it by one), not wall-clock
time — controllers in the test ladder run synchronously, so op counts
are reproducible where timestamps are not. Each window names a fault
kind, the ops it covers, an injection rate, and optional verb/kind
filters; rate draws come from one seeded ``random.Random``, so the
full fault sequence is a pure function of (seed, op sequence).

Watch-channel faults (drop / dup / reorder / compact) are a separate
per-event stream drawn from the same generator: the proxy's wrapped
watch queues consult ``next_watch_action`` once per delivered event.
"""

from __future__ import annotations

import random
from dataclasses import dataclass

# API-call fault kinds.
ERROR = "error"          # transient HTTP error (status, optional Retry-After)
CONFLICT = "conflict"    # 409 optimistic-concurrency storm
NOT_FOUND = "not_found"  # spurious 404 flap on reads
LATENCY = "latency"      # slow round-trip (injected sleep)
BLACKOUT = "blackout"    # apiserver fully dark: every verb fails

# Watch-event fault kinds.
DROP = "drop"
DUP = "dup"
REORDER = "reorder"
COMPACT = "compact"      # watch-cache compaction: pending backlog lost

_WRITE_VERBS = frozenset({"create", "update", "patch_merge", "delete"})
_READ_VERBS = frozenset({"get", "list"})


@dataclass(frozen=True)
class Fault:
    """One injected fault occurrence, as handed to the proxy."""

    kind: str
    status: int = 503
    retry_after: float | None = None
    latency_s: float = 0.0


@dataclass(frozen=True)
class CapacityEvent:
    """One point on a slice-capacity timeline: at ``at_s`` (scenario
    seconds, jitter already applied) the schedulable TPU pool becomes
    ``chips`` chips (None = unbounded)."""

    at_s: float
    chips: int | None


@dataclass(frozen=True)
class _Window:
    kind: str
    start: int
    end: int | None  # exclusive; None = forever
    rate: float
    verbs: frozenset[str] | None
    kinds: frozenset[str] | None
    status: int
    retry_after: float | None
    latency_s: float

    def covers(self, op: int, verb: str, obj_kind: str) -> bool:
        if op < self.start or (self.end is not None and op >= self.end):
            return False
        if self.verbs is not None and verb not in self.verbs:
            return False
        if self.kinds is not None and obj_kind not in self.kinds:
            return False
        return True


class FaultSchedule:
    """Composable, seeded fault script.

    Builder methods return ``self`` so schedules read as one
    expression::

        FaultSchedule(seed=7).conflict_storm(0, 40).blackout(60, 90)

    Determinism contract: with a fixed seed AND a fixed sequence of
    (op, verb, kind) queries — which synchronous test runs guarantee —
    the injected faults are identical on every replay.
    """

    def __init__(self, seed: int = 0):
        self.seed = seed
        self._rng = random.Random(seed)
        # Builder-filled at scenario construction; bounded by the
        # author's fault list.  # analysis: allow[py-unbounded-deque]
        self._windows: list[_Window] = []
        self._watch_rates: dict[str, float] = {}
        self._watch_budget: dict[str, int | None] = {}
        # Capacity events draw jitter from their OWN seeded generator:
        # the draw happens at build time (one per event, in insertion
        # order), so adding an API-fault window never shifts a capacity
        # event's instant — the two fault planes stay independently
        # reproducible.
        self._capacity_rng = random.Random((seed << 1) ^ 0x5CA1AB1E)
        # Same builder discipline as _windows.
        # analysis: allow[py-unbounded-deque]
        self._capacity: list[CapacityEvent] = []

    # ---- builders --------------------------------------------------------
    def add(
        self,
        kind: str,
        start: int = 0,
        end: int | None = None,
        rate: float = 1.0,
        verbs=None,
        kinds=None,
        status: int = 503,
        retry_after: float | None = None,
        latency_s: float = 0.0,
    ) -> "FaultSchedule":
        self._windows.append(_Window(
            kind=kind, start=start, end=end, rate=rate,
            verbs=frozenset(verbs) if verbs else None,
            kinds=frozenset(kinds) if kinds else None,
            status=status, retry_after=retry_after, latency_s=latency_s,
        ))
        return self

    def errors(self, start: int = 0, end: int | None = None,
               rate: float = 0.3, status: int = 503,
               retry_after: float | None = None) -> "FaultSchedule":
        """Transient 5xx/429 on any verb (the retry-policy diet)."""
        return self.add(ERROR, start, end, rate, status=status,
                        retry_after=retry_after)

    def conflict_storm(self, start: int = 0, end: int | None = None,
                       rate: float = 0.5) -> "FaultSchedule":
        """409s on writes — stale-read storms under churn."""
        return self.add(CONFLICT, start, end, rate, verbs=_WRITE_VERBS)

    def not_found_flaps(self, start: int = 0, end: int | None = None,
                        rate: float = 0.2, kinds=None) -> "FaultSchedule":
        """Spurious 404 on reads (a lagging watch cache's view)."""
        return self.add(NOT_FOUND, start, end, rate, verbs=_READ_VERBS,
                        kinds=kinds)

    def latency_spikes(self, start: int = 0, end: int | None = None,
                       rate: float = 0.2,
                       latency_s: float = 0.01) -> "FaultSchedule":
        return self.add(LATENCY, start, end, rate, latency_s=latency_s)

    def blackout(self, start: int, end: int) -> "FaultSchedule":
        """Full apiserver outage: every call in [start, end) fails."""
        return self.add(BLACKOUT, start, end, rate=1.0)

    def watch_faults(self, drop: float = 0.0, dup: float = 0.0,
                     reorder: float = 0.0, compact: float = 0.0,
                     max_compactions: int | None = 1) -> "FaultSchedule":
        """Per-delivered-event damage rates for wrapped watch queues.
        ``max_compactions`` bounds the most destructive fault (each
        compaction throws away the whole pending backlog)."""
        for kind, rate in ((DROP, drop), (DUP, dup), (REORDER, reorder),
                           (COMPACT, compact)):
            if rate:
                self._watch_rates[kind] = rate
        self._watch_budget[COMPACT] = max_compactions
        return self

    def clear_watch_faults(self) -> "FaultSchedule":
        """Disarm the watch-damage plane (the soak's storm-then-repair
        arc: damage the streams, then prove informer recovery against
        clean delivery). API-call windows are untouched."""
        self._watch_rates.clear()
        return self

    def clear_api_faults(self, at_op: int | None = None) -> "FaultSchedule":
        """Repair the API-fault plane, symmetric with
        :meth:`clear_watch_faults`: with no argument every window is
        dropped; with ``at_op`` the repair lands at that op — windows
        still open are closed there, windows not yet started are
        dropped, and fully-past windows are kept so the storm's
        history stays queryable. Watch damage and the capacity
        timeline are untouched (per-track repair composes)."""
        if at_op is None:
            self._windows.clear()
            return self
        kept = []
        for w in self._windows:
            if w.start >= at_op:
                continue
            if w.end is None or w.end > at_op:
                w = _Window(
                    kind=w.kind, start=w.start, end=at_op, rate=w.rate,
                    verbs=w.verbs, kinds=w.kinds, status=w.status,
                    retry_after=w.retry_after, latency_s=w.latency_s,
                )
            kept.append(w)
        self._windows[:] = kept
        return self

    def restore_capacity(self, at_s: float,
                         jitter_s: float = 0.0) -> "FaultSchedule":
        """Capacity-track repair, symmetric with the fault-plane
        clears: re-emit the pool's baseline — the FIRST scripted
        capacity, i.e. the pre-weather pool (None when nothing was
        scripted: unbounded) — at ``at_s``. Draws jitter exactly like
        :meth:`capacity`, from the capacity plane's own generator, so
        a storm-then-repair arc composes without shifting any other
        track's instants."""
        baseline = self._capacity[0].chips if self._capacity else None
        return self.capacity(at_s, baseline, jitter_s=jitter_s)

    def capacity(self, at_s: float, chips: int | None,
                 jitter_s: float = 0.0) -> "FaultSchedule":
        """Add a capacity event: at ``at_s`` (± a uniform draw within
        ``jitter_s``, taken NOW from the seeded generator) the
        schedulable TPU pool shrinks or regrows to ``chips`` chips
        (None = unbounded). The elastic chaos scenarios script whole
        preempt-then-regrow weather this way::

            FaultSchedule(seed=7).capacity(0, 16)      # full pool
                .capacity(100, 8, jitter_s=5)          # preemption
                .capacity(400, 16, jitter_s=5)         # capacity back

        Events keep their insertion order even when jitter would swap
        two instants — a regrow scripted after a shrink stays after it.
        """
        jitter = (
            self._capacity_rng.uniform(-jitter_s, jitter_s)
            if jitter_s else 0.0
        )
        at = max(0.0, float(at_s) + jitter)
        if self._capacity and at < self._capacity[-1].at_s:
            at = self._capacity[-1].at_s
        self._capacity.append(CapacityEvent(
            at, None if chips is None else int(chips)
        ))
        return self

    # ---- queries (proxy side) -------------------------------------------
    def capacity_at(self, now_s: float) -> int | None:
        """The chip capacity in force at scenario time ``now_s`` —
        the latest event at or before it (None before the first event:
        unbounded)."""
        chips = None
        for event in self._capacity:
            if event.at_s > now_s:
                break
            chips = event.chips
        return chips

    def capacity_events(self) -> list[CapacityEvent]:
        return list(self._capacity)

    def fault_for(self, op: int, verb: str, kind: str) -> Fault | None:
        """The fault (if any) to inject for API call number ``op``.
        First matching window that fires wins; BLACKOUT windows always
        fire regardless of rate draws (an outage is not probabilistic).
        """
        for win in self._windows:
            if not win.covers(op, verb, kind):
                continue
            if win.kind != BLACKOUT and self._rng.random() >= win.rate:
                continue
            return Fault(win.kind, status=win.status,
                         retry_after=win.retry_after,
                         latency_s=win.latency_s)
        return None

    def next_watch_action(self) -> str | None:
        """One draw per delivered watch event: None = deliver clean."""
        for kind, rate in self._watch_rates.items():
            if self._rng.random() >= rate:
                continue
            budget = self._watch_budget.get(kind)
            if budget is not None:
                if budget <= 0:
                    continue
                self._watch_budget[kind] = budget - 1
            return kind
        return None

    def describe(self) -> str:
        parts = [f"seed={self.seed}"]
        for w in self._windows:
            span = f"[{w.start},{'∞' if w.end is None else w.end})"
            parts.append(f"{w.kind}{span}@{w.rate:g}")
        for kind, rate in self._watch_rates.items():
            parts.append(f"watch-{kind}@{rate:g}")
        for event in self._capacity:
            chips = "∞" if event.chips is None else event.chips
            parts.append(f"capacity@{event.at_s:g}s={chips}")
        return " ".join(parts)
