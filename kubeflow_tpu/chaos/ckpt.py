"""Data-plane chaos: kill-mid-save and checkpoint corruption faults.

The control-plane tier injects apiserver weather; this module injects
the *storage* weather a preempted TPU worker actually produces — a
SIGKILL landing between shard writes, a shard file truncated by a dying
kernel, a manifest whose shard vanished from a misbehaving PVC. All of
it drives :class:`kubeflow_tpu.models.checkpoint.CheckpointManager`'s
crash-consistency contract: a step is either fully committed and
digest-clean, or it is skipped by ``restore_latest_valid``.

``CheckpointKiller`` plugs into the manager's ``hook`` parameter and
raises :class:`SimulatedCrash` at a named save point — the in-process
equivalent of SIGKILL: the save stops mid-protocol and nothing cleans
up, leaving exactly the torn on-disk state a real crash leaves.
"""

from __future__ import annotations

import json
import os

from kubeflow_tpu.models.checkpoint import MANIFEST_NAME

# Save points a CheckpointKiller can target, in protocol order.
KILL_POINTS = (
    "shard_written",    # after this process's shard payload is durable
    "pre_manifest",     # after the commit barrier, before the manifest
    "manifest_written",  # manifest durable in the tmp dir, before rename
    "committed",        # after the rename commit (GC never runs)
)


class SimulatedCrash(Exception):
    """The process died here. Raised by CheckpointKiller so a save
    abandons the protocol exactly where a SIGKILL would."""


class CheckpointKiller:
    """Raise :class:`SimulatedCrash` the ``occurrence``-th time the
    manager reaches ``point``. Install via
    ``CheckpointManager(..., hook=CheckpointKiller("pre_manifest"))``.

    ``seen`` counts every hook event by point so tests can assert the
    kill actually fired (a killer that never triggers proves nothing —
    same posture as ``ChaosApiServer.injected``)."""

    def __init__(self, point: str, occurrence: int = 1):
        if point not in KILL_POINTS:
            raise ValueError(
                f"unknown kill point {point!r}; one of {KILL_POINTS}"
            )
        self.point = point
        self.occurrence = int(occurrence)
        self.fired = False
        self.seen: dict[str, int] = {}

    def __call__(self, point: str, info: dict) -> None:
        self.seen[point] = self.seen.get(point, 0) + 1
        if point == self.point and self.seen[point] == self.occurrence:
            self.fired = True
            raise SimulatedCrash(
                f"simulated SIGKILL at {point} "
                f"(occurrence {self.occurrence}, info {info})"
            )


# ---------------------------------------------------------------------------
# post-commit corruption (what a sick PVC / dying kernel leaves behind)
# ---------------------------------------------------------------------------


def _step_dir(directory, step: int) -> str:
    return os.path.join(os.fspath(directory), str(int(step)))


def _shard_files(step_dir: str, suffix: str) -> list[str]:
    with open(os.path.join(step_dir, MANIFEST_NAME), "rb") as fh:
        manifest = json.load(fh)
    return sorted(
        name for name in manifest.get("files", {}) if name.endswith(suffix)
    )


def truncate_shard(directory, step: int, keep_bytes: int = 8) -> str:
    """Truncate the first shard payload of a committed step — the torn
    write a crash mid-flush leaves on a non-atomic filesystem. Returns
    the damaged file's name."""
    step_dir = _step_dir(directory, step)
    name = _shard_files(step_dir, ".bin")[0]
    path = os.path.join(step_dir, name)
    size = os.path.getsize(path)
    with open(path, "rb+") as fh:
        fh.truncate(min(keep_bytes, size))
    return name


def drop_shard(directory, step: int) -> str:
    """Delete the first shard payload while keeping the manifest — the
    manifest-present-but-shard-missing state. Returns the removed
    file's name."""
    step_dir = _step_dir(directory, step)
    name = _shard_files(step_dir, ".bin")[0]
    os.unlink(os.path.join(step_dir, name))
    return name


def flip_shard_bytes(directory, step: int, offset: int = 0) -> str:
    """Silently corrupt shard content (bit rot): same length, different
    bytes — only the content digests can catch it."""
    step_dir = _step_dir(directory, step)
    name = _shard_files(step_dir, ".bin")[0]
    path = os.path.join(step_dir, name)
    with open(path, "rb+") as fh:
        fh.seek(offset)
        byte = fh.read(1)
        fh.seek(offset)
        fh.write(bytes([byte[0] ^ 0xFF]) if byte else b"\xff")
    return name
