"""The cluster-side actors the fake apiserver does not model.

``StatefulSetPodSimulator`` plays the statefulset-controller + kubelet:
it materialises pods ``<sts>-0..N-1`` from every StatefulSet's template
(fresh uid per incarnation, one synthetic node per ordinal — the GKE
multi-host TPU layout, one worker pod per TPU VM) and removes
higher-ordinal pods after a scale-down. Recreation is *per pod*, like
the real statefulset controller — which is exactly why slice coherence
must be enforced by the notebook reconciler, not assumed here.

``PreemptionInjector`` kills TPU workers the way GKE preempts a node
pool VM: the node is tainted with the impending-termination taint,
then its pod is deleted out from under the workload. Preemption is
cluster weather, not apiserver weather — the two can and do overlap, so
the injector must not *lose* a preemption just because its API writes
landed inside an injected blackout: every call retries through a
``RetryPolicy`` (GCE's node-termination handler behaves the same way —
the VM IS going away; the delete eventually lands). Tests that want
the old overlap-free behavior point the injector at the inner
(un-chaosed) API.
"""

from __future__ import annotations

import time

from kubeflow_tpu.k8s.core import ApiError, Conflict, NotFound
from kubeflow_tpu.k8s.retry import RETRIABLE_STATUS, RetryPolicy

# The taint GKE places on a node about to lose its capacity
# (spot/preemptible reclaim and maintenance both surface this way).
PREEMPTION_TAINT_KEY = "cloud.google.com/impending-node-termination"


class StatefulSetPodSimulator:
    """Materialise StatefulSet pod sets against a fake apiserver."""

    def __init__(self, api, node_prefix: str = "tpu-node"):
        self.api = api
        self.node_prefix = node_prefix
        self.created_total = 0
        self.deleted_total = 0

    def node_name(self, sts_name: str, ordinal: int) -> str:
        return f"{self.node_prefix}-{sts_name}-{ordinal}"

    def _pod_for(self, sts: dict, ordinal: int) -> dict:
        meta = sts["metadata"]
        template = ((sts.get("spec") or {}).get("template")) or {}
        labels = dict(
            (template.get("metadata") or {}).get("labels") or {}
        )
        tpl_spec = template.get("spec") or {}
        containers = [
            {
                "name": c.get("name", "main"),
                "image": c.get("image", ""),
                "resources": c.get("resources", {}),
            }
            for c in tpl_spec.get("containers") or []
        ] or [{"name": "main", "image": ""}]
        return {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": f"{meta['name']}-{ordinal}",
                "namespace": meta.get("namespace", "default"),
                "labels": labels,
                "ownerReferences": [{
                    "apiVersion": "apps/v1",
                    "kind": "StatefulSet",
                    "name": meta["name"],
                    "uid": meta.get("uid", ""),
                }],
            },
            "spec": {
                "nodeName": self.node_name(meta["name"], ordinal),
                "containers": containers,
            },
            "status": {
                "phase": "Running",
                "conditions": [{"type": "Ready", "status": "True"}],
                "containerStatuses": [
                    {
                        "name": c["name"],
                        "ready": True,
                        "restartCount": 0,
                        "state": {"running": {}},
                    }
                    for c in containers
                ],
            },
        }

    def step(self) -> int:
        """One control-loop pass: create missing pods, prune pods whose
        ordinal is past the current replica count. Returns the number
        of changes made (0 = the pod world is settled)."""
        changed = 0
        for sts in self.api.list("apps/v1", "StatefulSet"):
            meta = sts["metadata"]
            ns = meta.get("namespace", "default")
            replicas = (sts.get("spec") or {}).get("replicas")
            replicas = 1 if replicas is None else int(replicas)
            for ordinal in range(replicas):
                name = f"{meta['name']}-{ordinal}"
                try:
                    self.api.get("v1", "Pod", name, ns)
                except NotFound:
                    self.api.create(self._pod_for(sts, ordinal))
                    self.created_total += 1
                    changed += 1
            # Scale-down: the statefulset controller removes the
            # highest ordinals first; order is irrelevant to the fake.
            for pod in self.api.list(
                "v1", "Pod", namespace=ns,
                label_selector=None,
            ):
                pod_name = pod["metadata"]["name"]
                prefix, _, suffix = pod_name.rpartition("-")
                if prefix != meta["name"] or not suffix.isdigit():
                    continue
                if int(suffix) >= replicas:
                    try:
                        self.api.delete("v1", "Pod", pod_name, ns)
                        self.deleted_total += 1
                        changed += 1
                    except NotFound:
                        pass
        return changed


class PreemptionInjector:
    """GKE-shaped TPU preemption: taint the node, delete its pod.

    ``retry_policy`` paces the API calls through apiserver weather: a
    preemption decided by the cloud provider is not cancellable, so a
    503/blackout on the pod delete must be retried until it lands, not
    dropped (the workload would keep running on a VM that is going
    away, and the chaos scenario would silently test nothing).
    ``NotFound`` is still terminal — the pod being gone IS the goal."""

    def __init__(self, api, retry_policy: RetryPolicy | None = None,
                 sleep=time.sleep):
        self.api = api
        self.retry_policy = retry_policy or RetryPolicy(
            max_attempts=8, base_delay=0.001, max_delay=0.05
        )
        self._sleep = sleep
        self.retries_total = 0
        self.preempted: list[tuple[str, str]] = []  # (namespace, pod)

    def _retrying(self, fn, *args, **kwargs):
        """Run one API call through the retry policy. Same doctrine as
        the client (k8s/retry.py): only transient statuses retry;
        NotFound is terminal (the pod being gone IS the goal) and
        Conflict propagates — a stale world-view is only fixed by a
        re-read, which the caller owns."""
        attempt = 0
        while True:
            try:
                return fn(*args, **kwargs)
            except (NotFound, Conflict):
                raise
            except ApiError as exc:
                if getattr(exc, "code", None) not in RETRIABLE_STATUS:
                    raise
                if attempt + 1 >= self.retry_policy.max_attempts:
                    raise
                self._sleep(self.retry_policy.delay(
                    attempt, getattr(exc, "retry_after", None)
                ))
                attempt += 1
                self.retries_total += 1

    def _taint_node(self, node_name: str) -> None:
        """Best-effort read-modify-write with conflict re-reads: the
        taint is advisory (the delete is the preemption), so after the
        attempt budget it is abandoned rather than raised."""
        taint = {"key": PREEMPTION_TAINT_KEY, "effect": "NoSchedule"}
        for attempt in range(self.retry_policy.max_attempts):
            try:
                node = self._retrying(self.api.get, "v1", "Node",
                                      node_name)
            except NotFound:
                try:
                    self._retrying(self.api.create, {
                        "apiVersion": "v1",
                        "kind": "Node",
                        "metadata": {"name": node_name},
                        "spec": {"taints": [taint]},
                    })
                    return
                except Conflict:
                    # Raced with the node appearing: re-read, re-apply.
                    self._sleep(self.retry_policy.delay(attempt))
                    continue
            taints = (node.get("spec") or {}).get("taints") or []
            if any(t.get("key") == PREEMPTION_TAINT_KEY for t in taints):
                return
            try:
                self._retrying(
                    self.api.patch_merge,
                    "v1", "Node", node_name,
                    {"spec": {"taints": taints + [taint]}},
                )
                return
            except Conflict:
                self._sleep(self.retry_policy.delay(attempt))

    def preempt_pod(self, namespace: str, name: str) -> str | None:
        """Preempt one pod; returns the tainted node's name (None when
        the pod was already gone)."""
        try:
            pod = self._retrying(self.api.get, "v1", "Pod", name, namespace)
        except NotFound:
            return None
        node_name = (pod.get("spec") or {}).get("nodeName") or ""
        if node_name:
            self._taint_node(node_name)
        try:
            self._retrying(self.api.delete, "v1", "Pod", name, namespace)
        except NotFound:
            return None
        self.preempted.append((namespace, name))
        return node_name or None

    def preempt_worker(self, namespace: str, notebook: str,
                       ordinal: int) -> str | None:
        """Preempt TPU worker ``ordinal`` of a notebook's slice."""
        return self.preempt_pod(namespace, f"{notebook}-{ordinal}")

    def recover_node(self, node_name: str) -> None:
        """Clear the termination taint (the replacement VM arriving).
        Conflict re-reads like _taint_node; best-effort past the attempt
        budget."""
        for attempt in range(self.retry_policy.max_attempts):
            try:
                node = self._retrying(self.api.get, "v1", "Node",
                                      node_name)
            except NotFound:
                return
            taints = [
                t for t in (node.get("spec") or {}).get("taints") or []
                if t.get("key") != PREEMPTION_TAINT_KEY
            ]
            try:
                self._retrying(
                    self.api.patch_merge,
                    "v1", "Node", node_name, {"spec": {"taints": taints}},
                )
                return
            except Conflict:
                self._sleep(self.retry_policy.delay(attempt))
