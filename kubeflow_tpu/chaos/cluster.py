"""The cluster-side actors the fake apiserver does not model.

``StatefulSetPodSimulator`` plays the statefulset-controller + kubelet:
it materialises pods ``<sts>-0..N-1`` from every StatefulSet's template
(fresh uid per incarnation, one synthetic node per ordinal — the GKE
multi-host TPU layout, one worker pod per TPU VM) and removes
higher-ordinal pods after a scale-down. Recreation is *per pod*, like
the real statefulset controller — which is exactly why slice coherence
must be enforced by the notebook reconciler, not assumed here.

``PreemptionInjector`` kills TPU workers the way GKE preempts a node
pool VM: the node is tainted with the impending-termination taint,
then its pod is deleted out from under the workload. Preemption is
cluster weather, not apiserver weather — the two can and do overlap, so
the injector must not *lose* a preemption just because its API writes
landed inside an injected blackout: every call retries through a
``RetryPolicy`` (GCE's node-termination handler behaves the same way —
the VM IS going away; the delete eventually lands). Tests that want
the old overlap-free behavior point the injector at the inner
(un-chaosed) API.
"""

from __future__ import annotations

import hashlib
import json
import time

from kubeflow_tpu.k8s.core import ApiError, Conflict, NotFound
from kubeflow_tpu.k8s.retry import RETRIABLE_STATUS, RetryPolicy

# The taint GKE places on a node about to lose its capacity
# (spot/preemptible reclaim and maintenance both surface this way).
PREEMPTION_TAINT_KEY = "cloud.google.com/impending-node-termination"

# Simulator bookkeeping: which StatefulSet template a pod was built
# from (the controller-revision-hash stand-in), so the opt-in rolling
# replacement can tell a re-emitted template from a scale change.
TEMPLATE_HASH_ANNOTATION = "chaos.kubeflow-tpu.org/template-hash"


class StatefulSetPodSimulator:
    """Materialise StatefulSet pod sets against a fake apiserver.

    ``capacity_chips`` bounds the schedulable TPU pool (None =
    unbounded, the historical behaviour): a pod whose ``google.com/tpu``
    limit does not fit the remaining capacity is created **Pending**
    with an Unschedulable ``PodScheduled`` condition and no node —
    exactly what a notebook sees when a preemption shrank the node pool
    — and is bound (node + Running + Ready) by a later ``step()`` once
    capacity regrows. The elastic chaos scenarios drive this through
    :meth:`PreemptionInjector.apply_capacity`.

    ``recreate_on_template_change=True`` additionally recycles pods
    whose recorded template hash no longer matches the StatefulSet's
    template (the rolling replacement a real statefulset controller
    performs when the controller re-emits new chip limits/env). Off by
    default: the legacy tests pin scale-only reconciliation where a
    survivor keeps its identity across a topology edit.
    """

    def __init__(self, api, node_prefix: str = "tpu-node",
                 capacity_chips: int | None = None,
                 recreate_on_template_change: bool = False,
                 gc_orphans: bool = False):
        self.api = api
        self.node_prefix = node_prefix
        self.capacity_chips = capacity_chips
        self.recreate_on_template_change = recreate_on_template_change
        # Fleet-scale opt-in: prune pods whose owning StatefulSet is
        # gone (the garbage collector's role). Off by default — legacy
        # chaos tests pin that a bare pod outlives its StatefulSet.
        self.gc_orphans = gc_orphans
        # Correlated-domain weather (chaos.world): nodes whose domain
        # is in ``lost_domains`` (per ``domain_of``) take no bindings;
        # their pods are created/kept Pending until the rack repairs.
        self.domain_of = None
        self.lost_domains: set[int] = set()
        self.created_total = 0
        self.deleted_total = 0
        self.pending_total = 0
        self.bound_total = 0

    def node_name(self, sts_name: str, ordinal: int) -> str:
        return f"{self.node_prefix}-{sts_name}-{ordinal}"

    @staticmethod
    def _template_hash(sts: dict) -> str:
        template = ((sts.get("spec") or {}).get("template")) or {}
        return hashlib.sha256(
            json.dumps(template, sort_keys=True).encode()
        ).hexdigest()[:16]

    @staticmethod
    def pod_chips(pod: dict) -> int:
        """google.com/tpu chips one pod demands (its first container's
        limit — the layout the notebook controller emits)."""
        for c in (pod.get("spec") or {}).get("containers") or []:
            limit = ((c.get("resources") or {}).get("limits") or {}).get(
                "google.com/tpu"
            )
            if limit is not None:
                try:
                    return int(limit)
                except (TypeError, ValueError):
                    return 0
        return 0

    @staticmethod
    def _is_bound(pod: dict) -> bool:
        return bool((pod.get("spec") or {}).get("nodeName")) and not (
            pod.get("metadata") or {}
        ).get("deletionTimestamp")

    def _used_chips(self) -> int:
        return sum(
            self.pod_chips(p)
            for p in self.api.list("v1", "Pod")
            if self._is_bound(p)
        )

    def _pod_for(self, sts: dict, ordinal: int, bound: bool = True) -> dict:
        meta = sts["metadata"]
        template = ((sts.get("spec") or {}).get("template")) or {}
        labels = dict(
            (template.get("metadata") or {}).get("labels") or {}
        )
        tpl_spec = template.get("spec") or {}
        containers = [
            {
                "name": c.get("name", "main"),
                "image": c.get("image", ""),
                "resources": c.get("resources", {}),
            }
            for c in tpl_spec.get("containers") or []
        ] or [{"name": "main", "image": ""}]
        pod = {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": f"{meta['name']}-{ordinal}",
                "namespace": meta.get("namespace", "default"),
                "labels": labels,
                "annotations": {
                    TEMPLATE_HASH_ANNOTATION: self._template_hash(sts),
                },
                "ownerReferences": [{
                    "apiVersion": "apps/v1",
                    "kind": "StatefulSet",
                    "name": meta["name"],
                    "uid": meta.get("uid", ""),
                }],
            },
            "spec": {
                "containers": containers,
            },
        }
        if bound:
            pod["spec"]["nodeName"] = self.node_name(
                meta["name"], ordinal
            )
            pod["status"] = {
                "phase": "Running",
                "conditions": [{"type": "Ready", "status": "True"}],
                "containerStatuses": [
                    {
                        "name": c["name"],
                        "ready": True,
                        "restartCount": 0,
                        "state": {"running": {}},
                    }
                    for c in containers
                ],
            }
        else:
            pod["status"] = {
                "phase": "Pending",
                "conditions": [{
                    "type": "PodScheduled",
                    "status": "False",
                    "reason": "Unschedulable",
                    "message": "0/0 nodes have free google.com/tpu "
                               "(simulated capacity exhausted)",
                }],
                "containerStatuses": [],
            }
        return pod

    def _fits(self, chips: int, used: int) -> bool:
        if self.capacity_chips is None or chips <= 0:
            return True
        return used + chips <= self.capacity_chips

    def _bind(self, sts: dict, ordinal: int, pod: dict) -> None:
        """A Pending pod's node arrives: bind + run it, same identity
        (the real scheduler binds the existing pod object — a regrown
        pool must NOT look like a pod replacement to the observed-mesh
        recovery)."""
        bound = self._pod_for(sts, ordinal, bound=True)
        self.api.patch_merge(
            "v1", "Pod", pod["metadata"]["name"],
            {"spec": {"nodeName": bound["spec"]["nodeName"]},
             "status": bound["status"]},
            pod["metadata"].get("namespace", "default"),
        )

    def _node_lost(self, sts_name: str, ordinal: int) -> bool:
        if not self.lost_domains or self.domain_of is None:
            return False
        return (self.domain_of(self.node_name(sts_name, ordinal))
                in self.lost_domains)

    def step(self) -> int:
        """One control-loop pass: create missing pods (Pending when the
        TPU pool is exhausted or the node's failure domain is lost),
        bind Pending pods capacity now covers, prune pods whose ordinal
        is past the current replica count, and (opt-in) recycle pods
        built from a stale template / GC pods whose StatefulSet is
        gone. Returns the number of changes made (0 = settled).

        One ``Pod`` list per pass, indexed by ``(namespace, name
        prefix) -> {ordinal: pod}`` — the fleet-scale soak rides this
        tick at 10k-CR cardinality, where the per-StatefulSet re-list
        it replaces was O(all pods) per StatefulSet."""
        changed = 0
        pods = list(self.api.list("v1", "Pod"))
        used = sum(self.pod_chips(p) for p in pods
                   if self._is_bound(p))
        by_owner: dict[tuple[str, str], dict[int, dict]] = {}
        for pod in pods:
            pod_ns = pod["metadata"].get("namespace", "default")
            prefix, _, suffix = pod["metadata"]["name"].rpartition("-")
            if suffix.isdigit():
                by_owner.setdefault((pod_ns, prefix), {})[
                    int(suffix)] = pod
        statefulsets = list(self.api.list("apps/v1", "StatefulSet"))
        live_sts = {(s["metadata"].get("namespace", "default"),
                     s["metadata"]["name"]) for s in statefulsets}
        for sts in statefulsets:
            meta = sts["metadata"]
            ns = meta.get("namespace", "default")
            replicas = (sts.get("spec") or {}).get("replicas")
            replicas = 1 if replicas is None else int(replicas)
            tpl_hash = self._template_hash(sts)
            owned = by_owner.get((ns, meta["name"]), {})
            for ordinal in range(replicas):
                name = f"{meta['name']}-{ordinal}"
                pod = owned.get(ordinal)
                if pod is None:
                    fresh = self._pod_for(sts, ordinal, bound=True)
                    chips = self.pod_chips(fresh)
                    if (self._fits(chips, used)
                            and not self._node_lost(meta["name"],
                                                    ordinal)):
                        self.api.create(fresh)
                        used += chips
                    else:
                        self.api.create(
                            self._pod_for(sts, ordinal, bound=False)
                        )
                        self.pending_total += 1
                    self.created_total += 1
                    changed += 1
                    continue
                if (self.recreate_on_template_change
                        and (pod["metadata"].get("annotations") or {})
                        .get(TEMPLATE_HASH_ANNOTATION, tpl_hash)
                        != tpl_hash):
                    # Rolling replacement: the controller re-emitted the
                    # template (new chip limits / world-size env); the
                    # old incarnation is recycled and recreated from
                    # the new template on the next pass.
                    try:
                        self.api.delete("v1", "Pod", name, ns)
                        if self._is_bound(pod):
                            used -= self.pod_chips(pod)
                        self.deleted_total += 1
                        changed += 1
                    except NotFound:
                        pass
                    continue
                if not self._is_bound(pod) and not (
                    pod["metadata"].get("deletionTimestamp")
                ):
                    chips = self.pod_chips(pod)
                    if (self._fits(chips, used)
                            and not self._node_lost(meta["name"],
                                                    ordinal)):
                        self._bind(sts, ordinal, pod)
                        used += chips
                        self.bound_total += 1
                        changed += 1
            # Scale-down: the statefulset controller removes the
            # highest ordinals first; order is irrelevant to the fake.
            for ordinal in sorted(owned):
                if ordinal < replicas:
                    continue
                pod = owned[ordinal]
                try:
                    self.api.delete("v1", "Pod",
                                    pod["metadata"]["name"], ns)
                    if self._is_bound(pod):
                        used -= self.pod_chips(pod)
                    self.deleted_total += 1
                    changed += 1
                except NotFound:
                    pass
        if self.gc_orphans:
            for pod in pods:
                refs = (pod["metadata"].get("ownerReferences")) or []
                owner = next((r for r in refs
                              if r.get("kind") == "StatefulSet"), None)
                if owner is None:
                    continue
                pod_ns = pod["metadata"].get("namespace", "default")
                if (pod_ns, owner.get("name")) in live_sts:
                    continue
                try:
                    self.api.delete("v1", "Pod",
                                    pod["metadata"]["name"], pod_ns)
                    self.deleted_total += 1
                    changed += 1
                except NotFound:
                    pass
        return changed


class PreemptionInjector:
    """GKE-shaped TPU preemption: taint the node, delete its pod.

    ``retry_policy`` paces the API calls through apiserver weather: a
    preemption decided by the cloud provider is not cancellable, so a
    503/blackout on the pod delete must be retried until it lands, not
    dropped (the workload would keep running on a VM that is going
    away, and the chaos scenario would silently test nothing).
    ``NotFound`` is still terminal — the pod being gone IS the goal."""

    def __init__(self, api, retry_policy: RetryPolicy | None = None,
                 sleep=time.sleep):
        self.api = api
        self.retry_policy = retry_policy or RetryPolicy(
            max_attempts=8, base_delay=0.001, max_delay=0.05
        )
        self._sleep = sleep
        self.retries_total = 0
        # Injection record the scenario asserts on: bounded by the
        # schedule's event count.  # analysis: allow[py-unbounded-deque]
        self.preempted: list[tuple[str, str]] = []  # (namespace, pod)
        # Capacity-timeline state: the chip bound currently enforced
        # and the nodes this injector tainted to enforce it (cleared
        # when the pool regrows).
        self.capacity_chips: int | None = None
        self._capacity_tainted: set[str] = set()

    def _retrying(self, fn, *args, **kwargs):
        """Run one API call through the retry policy. Same doctrine as
        the client (k8s/retry.py): only transient statuses retry;
        NotFound is terminal (the pod being gone IS the goal) and
        Conflict propagates — a stale world-view is only fixed by a
        re-read, which the caller owns."""
        attempt = 0
        while True:
            try:
                return fn(*args, **kwargs)
            except (NotFound, Conflict):
                raise
            except ApiError as exc:
                if getattr(exc, "code", None) not in RETRIABLE_STATUS:
                    raise
                if attempt + 1 >= self.retry_policy.max_attempts:
                    raise
                self._sleep(self.retry_policy.delay(
                    attempt, getattr(exc, "retry_after", None)
                ))
                attempt += 1
                self.retries_total += 1

    def _taint_node(self, node_name: str) -> None:
        """Best-effort read-modify-write with conflict re-reads: the
        taint is advisory (the delete is the preemption), so after the
        attempt budget it is abandoned rather than raised."""
        taint = {"key": PREEMPTION_TAINT_KEY, "effect": "NoSchedule"}
        for attempt in range(self.retry_policy.max_attempts):
            try:
                node = self._retrying(self.api.get, "v1", "Node",
                                      node_name)
            except NotFound:
                try:
                    self._retrying(self.api.create, {
                        "apiVersion": "v1",
                        "kind": "Node",
                        "metadata": {"name": node_name},
                        "spec": {"taints": [taint]},
                    })
                    return
                except Conflict:
                    # Raced with the node appearing: re-read, re-apply.
                    self._sleep(self.retry_policy.delay(attempt))
                    continue
            taints = (node.get("spec") or {}).get("taints") or []
            if any(t.get("key") == PREEMPTION_TAINT_KEY for t in taints):
                return
            try:
                self._retrying(
                    self.api.patch_merge,
                    "v1", "Node", node_name,
                    {"spec": {"taints": taints + [taint]}},
                )
                return
            except Conflict:
                self._sleep(self.retry_policy.delay(attempt))

    def preempt_pod(self, namespace: str, name: str) -> str | None:
        """Preempt one pod; returns the tainted node's name (None when
        the pod was already gone)."""
        try:
            pod = self._retrying(self.api.get, "v1", "Pod", name, namespace)
        except NotFound:
            return None
        node_name = (pod.get("spec") or {}).get("nodeName") or ""
        if node_name:
            self._taint_node(node_name)
        try:
            self._retrying(self.api.delete, "v1", "Pod", name, namespace)
        except NotFound:
            return None
        self.preempted.append((namespace, name))
        return node_name or None

    def preempt_worker(self, namespace: str, notebook: str,
                       ordinal: int) -> str | None:
        """Preempt TPU worker ``ordinal`` of a notebook's slice."""
        return self.preempt_pod(namespace, f"{notebook}-{ordinal}")

    def apply_capacity(self, schedule, now_s: float,
                       sim: StatefulSetPodSimulator) -> int | None:
        """Advance cluster capacity to ``schedule.capacity_at(now_s)``
        (a :class:`~kubeflow_tpu.chaos.schedule.FaultSchedule` with
        capacity events — the same seeded script every other chaos run
        follows). On a shrink, bound pods beyond the new budget are
        preempted GKE-style (taint + delete), highest ordinals first —
        the cloud reclaiming VMs out from under the workload. On a
        regrow, this injector's termination taints are cleared (the
        replacement VMs arriving) and the simulator's next ``step()``
        binds what now fits. Returns the chip bound now in force."""
        chips = schedule.capacity_at(now_s)
        if chips == self.capacity_chips:
            return chips
        grew = (chips is None or
                (self.capacity_chips is not None
                 and chips > self.capacity_chips))
        self.capacity_chips = chips
        sim.capacity_chips = chips
        if grew:
            for node in sorted(self._capacity_tainted):
                self.recover_node(node)
            self._capacity_tainted.clear()
            return chips
        # Shrink: reclaim bound pods until usage fits. Highest ordinal
        # first within each slice — deterministic, and matches GKE
        # draining a node pool from its newest VMs. Sort on the PARSED
        # ordinal: plain name order would put "nb-9" after "nb-15".
        def reclaim_key(pod):
            name = pod["metadata"]["name"]
            prefix, _, suffix = name.rpartition("-")
            ordinal = int(suffix) if suffix.isdigit() else -1
            return (prefix, ordinal)

        bound = sorted(
            (p for p in self._retrying(self.api.list, "v1", "Pod")
             if sim._is_bound(p) and sim.pod_chips(p) > 0),
            key=reclaim_key, reverse=True,
        )
        used = sum(sim.pod_chips(p) for p in bound)
        for pod in bound:
            if chips is None or used <= chips:
                break
            node = self.preempt_pod(
                pod["metadata"].get("namespace", "default"),
                pod["metadata"]["name"],
            )
            if node:
                self._capacity_tainted.add(node)
            used -= sim.pod_chips(pod)
        return chips

    def recover_node(self, node_name: str) -> None:
        """Clear the termination taint (the replacement VM arriving).
        Conflict re-reads like _taint_node; best-effort past the attempt
        budget."""
        for attempt in range(self.retry_policy.max_attempts):
            try:
                node = self._retrying(self.api.get, "v1", "Node",
                                      node_name)
            except NotFound:
                return
            taints = [
                t for t in (node.get("spec") or {}).get("taints") or []
                if t.get("key") != PREEMPTION_TAINT_KEY
            ]
            try:
                self._retrying(
                    self.api.patch_merge,
                    "v1", "Node", node_name, {"spec": {"taints": taints}},
                )
                return
            except Conflict:
                self._sleep(self.retry_policy.delay(attempt))
