"""The cluster-side actors the fake apiserver does not model.

``StatefulSetPodSimulator`` plays the statefulset-controller + kubelet:
it materialises pods ``<sts>-0..N-1`` from every StatefulSet's template
(fresh uid per incarnation, one synthetic node per ordinal — the GKE
multi-host TPU layout, one worker pod per TPU VM) and removes
higher-ordinal pods after a scale-down. Recreation is *per pod*, like
the real statefulset controller — which is exactly why slice coherence
must be enforced by the notebook reconciler, not assumed here.

``PreemptionInjector`` kills TPU workers the way GKE preempts a node
pool VM: the node is tainted with the impending-termination taint,
then its pod is deleted out from under the workload. The injector
talks to the *inner* (un-chaosed) API on purpose: preemption is
cluster weather, not apiserver weather, and must land even while the
proxy is injecting request faults.
"""

from __future__ import annotations

from kubeflow_tpu.k8s.core import NotFound

# The taint GKE places on a node about to lose its capacity
# (spot/preemptible reclaim and maintenance both surface this way).
PREEMPTION_TAINT_KEY = "cloud.google.com/impending-node-termination"


class StatefulSetPodSimulator:
    """Materialise StatefulSet pod sets against a fake apiserver."""

    def __init__(self, api, node_prefix: str = "tpu-node"):
        self.api = api
        self.node_prefix = node_prefix
        self.created_total = 0
        self.deleted_total = 0

    def node_name(self, sts_name: str, ordinal: int) -> str:
        return f"{self.node_prefix}-{sts_name}-{ordinal}"

    def _pod_for(self, sts: dict, ordinal: int) -> dict:
        meta = sts["metadata"]
        template = ((sts.get("spec") or {}).get("template")) or {}
        labels = dict(
            (template.get("metadata") or {}).get("labels") or {}
        )
        tpl_spec = template.get("spec") or {}
        containers = [
            {
                "name": c.get("name", "main"),
                "image": c.get("image", ""),
                "resources": c.get("resources", {}),
            }
            for c in tpl_spec.get("containers") or []
        ] or [{"name": "main", "image": ""}]
        return {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": f"{meta['name']}-{ordinal}",
                "namespace": meta.get("namespace", "default"),
                "labels": labels,
                "ownerReferences": [{
                    "apiVersion": "apps/v1",
                    "kind": "StatefulSet",
                    "name": meta["name"],
                    "uid": meta.get("uid", ""),
                }],
            },
            "spec": {
                "nodeName": self.node_name(meta["name"], ordinal),
                "containers": containers,
            },
            "status": {
                "phase": "Running",
                "conditions": [{"type": "Ready", "status": "True"}],
                "containerStatuses": [
                    {
                        "name": c["name"],
                        "ready": True,
                        "restartCount": 0,
                        "state": {"running": {}},
                    }
                    for c in containers
                ],
            },
        }

    def step(self) -> int:
        """One control-loop pass: create missing pods, prune pods whose
        ordinal is past the current replica count. Returns the number
        of changes made (0 = the pod world is settled)."""
        changed = 0
        for sts in self.api.list("apps/v1", "StatefulSet"):
            meta = sts["metadata"]
            ns = meta.get("namespace", "default")
            replicas = (sts.get("spec") or {}).get("replicas")
            replicas = 1 if replicas is None else int(replicas)
            for ordinal in range(replicas):
                name = f"{meta['name']}-{ordinal}"
                try:
                    self.api.get("v1", "Pod", name, ns)
                except NotFound:
                    self.api.create(self._pod_for(sts, ordinal))
                    self.created_total += 1
                    changed += 1
            # Scale-down: the statefulset controller removes the
            # highest ordinals first; order is irrelevant to the fake.
            for pod in self.api.list(
                "v1", "Pod", namespace=ns,
                label_selector=None,
            ):
                pod_name = pod["metadata"]["name"]
                prefix, _, suffix = pod_name.rpartition("-")
                if prefix != meta["name"] or not suffix.isdigit():
                    continue
                if int(suffix) >= replicas:
                    try:
                        self.api.delete("v1", "Pod", pod_name, ns)
                        self.deleted_total += 1
                        changed += 1
                    except NotFound:
                        pass
        return changed


class PreemptionInjector:
    """GKE-shaped TPU preemption: taint the node, delete its pod."""

    def __init__(self, api):
        self.api = api
        self.preempted: list[tuple[str, str]] = []  # (namespace, pod)

    def _taint_node(self, node_name: str) -> None:
        taint = {"key": PREEMPTION_TAINT_KEY, "effect": "NoSchedule"}
        try:
            node = self.api.get("v1", "Node", node_name)
        except NotFound:
            self.api.create({
                "apiVersion": "v1",
                "kind": "Node",
                "metadata": {"name": node_name},
                "spec": {"taints": [taint]},
            })
            return
        taints = (node.get("spec") or {}).get("taints") or []
        if not any(t.get("key") == PREEMPTION_TAINT_KEY for t in taints):
            self.api.patch_merge(
                "v1", "Node", node_name,
                {"spec": {"taints": taints + [taint]}},
            )

    def preempt_pod(self, namespace: str, name: str) -> str | None:
        """Preempt one pod; returns the tainted node's name (None when
        the pod was already gone)."""
        try:
            pod = self.api.get("v1", "Pod", name, namespace)
        except NotFound:
            return None
        node_name = (pod.get("spec") or {}).get("nodeName") or ""
        if node_name:
            self._taint_node(node_name)
        try:
            self.api.delete("v1", "Pod", name, namespace)
        except NotFound:
            return None
        self.preempted.append((namespace, name))
        return node_name or None

    def preempt_worker(self, namespace: str, notebook: str,
                       ordinal: int) -> str | None:
        """Preempt TPU worker ``ordinal`` of a notebook's slice."""
        return self.preempt_pod(namespace, f"{notebook}-{ordinal}")

    def recover_node(self, node_name: str) -> None:
        """Clear the termination taint (the replacement VM arriving)."""
        try:
            node = self.api.get("v1", "Node", node_name)
        except NotFound:
            return
        taints = [
            t for t in (node.get("spec") or {}).get("taints") or []
            if t.get("key") != PREEMPTION_TAINT_KEY
        ]
        self.api.patch_merge(
            "v1", "Node", node_name, {"spec": {"taints": taints}}
        )
