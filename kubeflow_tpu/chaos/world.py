"""Composable scenario worlds: one seeded timeline DSL for every
replay-deterministic harness.

The three scenario harnesses (game day, contention, soak) each grew
their own scripting idiom — tick-fraction phases, ad-hoc
``FaultSchedule`` capacity chains, a harness-global ``random.Random``
for churn. Composing them (ROADMAP item 5: autopilot actuation UNDER
10k churn, not next to it) needs one builder where adding a track can
never shift another track's instants. That property is the whole
design:

- **Typed tracks.** ``traffic`` (request weather phases), ``capacity``
  (chip-pool weather), ``api`` (op-indexed fault windows on a probe
  plane), ``tenants`` (arrival/churn mixes + scripted arrivals), and
  ``domains`` (correlated failure: racks). A harness reads its script
  from the built :class:`ScenarioWorld` instead of hardcoding it.
- **Per-track derived RNG streams.** Every track that draws randomness
  draws from its own generator, derived as a pure function of
  ``(seed, track name)`` via :func:`derive_stream` — the same
  construction ``FaultSchedule`` already uses to keep capacity jitter
  independent of fault-window rate draws. Composing a new track onto a
  world never consumes another track's draws, so every existing
  instant stays put (``tests/test_world.py`` pins this).
- **Correlated failure domains.** ``domains(n)`` assigns every
  simulator node to a rack by ordinal; a ``domain_loss`` event
  taints + deletes every worker bound in that domain in one instant —
  multi-host slices spanning the rack partial-fail simultaneously —
  and subtracts the rack's chips from :meth:`ScenarioWorld.capacity_at`
  until the matching ``domain_repair``. The world duck-types the
  ``capacity_at`` surface, so :meth:`PreemptionInjector.apply_capacity`
  and the slice-pool scheduler read base weather and rack losses as
  one merged timeline.

One world instance drives one run: replays build a fresh world from
the same ``(seed, parameters)`` and every ``replay_digest`` gate built
on top stays byte-identical (and Pack C lint-clean — no wall clocks,
no unseeded RNG, no salted hashes anywhere on the digest path).
"""

from __future__ import annotations

import hashlib
import random
from dataclasses import dataclass

from kubeflow_tpu.chaos.schedule import FaultSchedule


class Clock:
    """The injected scenario clock every component of a world run
    shares (the game-day determinism constraint: no component may see
    wall time)."""

    def __init__(self, t: float = 0.0):
        self.t = float(t)

    def __call__(self) -> float:
        return self.t

    def advance(self, s: float) -> float:
        self.t += s
        return self.t


def derive_stream(seed: int, track: str) -> random.Random:
    """A track's private generator: a pure function of (seed, track
    name), so two tracks of one world — or the same track across
    replays — can never interleave draws. sha256 keys the derivation
    (stable across processes; the salted builtin ``hash`` would not
    be)."""
    digest = hashlib.sha256(f"{int(seed)}:{track}".encode()).digest()
    return random.Random(int.from_bytes(digest[:8], "big"))


@dataclass(frozen=True)
class TrafficPhase:
    """One request-weather phase, bounded by tick fractions so the
    same arc compresses with the run length. Knob fields are the
    autopilot-facing signals a harness applies to its serving stub:
    latency observations (``ttft_s``/``itl_s``, ``observations`` per
    tick), slot pressure (``occupancy`` "full"/"idle" +
    ``queue_depth``), and the adversarial ``prompt_len`` (prompt-length
    abuse against chunked-prefill admission)."""

    name: str
    start: float
    end: float
    ttft_s: float | None = None
    itl_s: float | None = None
    observations: int = 10
    occupancy: str | None = None
    queue_depth: int = 0
    prompt_len: int | None = None


@dataclass(frozen=True)
class TenantMix:
    """One tenant population: the namespaces it lands in, its
    (topology, chips) and priority distributions, and op-kind weights
    for seeded churn. The harness draws from the world's per-track
    stream; the mix is only the declarative shape."""

    name: str
    namespaces: tuple[str, ...]
    topologies: tuple[tuple[str, int], ...]
    priorities: tuple[int, ...]
    weights: tuple[tuple[str, float], ...] = ()

    def thresholds(self) -> tuple[tuple[str, float], ...]:
        """Cumulative roll thresholds in declaration order (the churn
        idiom: one uniform draw selects the op kind)."""
        acc, out = 0.0, []
        for op, weight in self.weights:
            acc += weight
            out.append((op, acc))
        return tuple(out)


@dataclass(frozen=True)
class Arrival:
    """One scripted tenant event at a tick fraction: a named CR
    arriving (``notebook`` / ``inference``) or a first-touch
    (``touch``) resurrecting a suspended slice."""

    at: float
    kind: str
    namespace: str
    name: str
    topology: str | None = None
    priority: int = 0


@dataclass(frozen=True)
class DomainEvent:
    """One correlated-domain instant (jitter already applied):
    ``loss`` removes ``chips`` from the schedulable pool and kills
    every worker bound in the domain; ``repair`` returns them."""

    at_s: float
    kind: str
    domain: int
    chips: int


class WorldBuilder:
    """Fluent track-by-track scenario author. All instants are tick
    fractions of ``ticks * tick_s`` scenario seconds, so one timeline
    compresses or stretches without re-authoring."""

    def __init__(self, seed: int, ticks: int, tick_s: float = 30.0):
        self.seed = int(seed)
        self.ticks = int(ticks)
        self.tick_s = float(tick_s)
        # Track declarations, bounded by the scenario author's script.
        # analysis: allow[py-unbounded-deque]
        self._traffic: list[TrafficPhase] = []
        # analysis: allow[py-unbounded-deque]
        self._capacity: list[tuple[float, int | None, float, bool]] = []
        # analysis: allow[py-unbounded-deque]
        self._api: list[tuple[str, float, float, int]] = []
        self._tenants: dict[str, TenantMix] = {}
        # analysis: allow[py-unbounded-deque]
        self._arrivals: list[Arrival] = []
        self._domains = 0
        # analysis: allow[py-unbounded-deque]
        self._domain_events: list[tuple[float, str, int, int, float]] = []

    # ---- traffic track ---------------------------------------------------
    def traffic(self, name: str, start: float, end: float, *,
                ttft_s: float | None = None, itl_s: float | None = None,
                observations: int = 10, occupancy: str | None = None,
                queue_depth: int = 0,
                prompt_len: int | None = None) -> "WorldBuilder":
        self._traffic.append(TrafficPhase(
            name=name, start=float(start), end=float(end),
            ttft_s=ttft_s, itl_s=itl_s, observations=int(observations),
            occupancy=occupancy, queue_depth=int(queue_depth),
            prompt_len=prompt_len,
        ))
        return self

    # ---- capacity track --------------------------------------------------
    def capacity(self, at: float, chips: int | None,
                 jitter_s: float = 0.0) -> "WorldBuilder":
        """Chip-pool weather at tick fraction ``at``. Jitter draws come
        from the FaultSchedule's own capacity generator at build time,
        in declaration order — the stream the pre-world harnesses
        already used, so their pinned digests survive the refactor."""
        self._capacity.append((float(at), chips, float(jitter_s), False))
        return self

    def capacity_restore(self, at: float,
                         jitter_s: float = 0.0) -> "WorldBuilder":
        """The symmetric repair arc: re-emit the pool's baseline (the
        first scripted capacity) at ``at`` via
        :meth:`FaultSchedule.restore_capacity`."""
        self._capacity.append((float(at), None, float(jitter_s), True))
        return self

    # ---- API-fault track (probe plane) -----------------------------------
    def api_blackout(self, start: float, end: float,
                     ops_per_tick: int) -> "WorldBuilder":
        """An apiserver blackout over tick fractions, mapped onto op
        counts through a fixed probe-op budget per tick (the game-day
        availability-plane construction). Windows land on the world's
        ``probe_schedule`` so controller-plane traffic never parks on
        real-time backoff."""
        self._api.append(("blackout", float(start), float(end),
                          int(ops_per_tick)))
        return self

    # ---- tenant track ----------------------------------------------------
    def tenants(self, name: str, *, namespaces, topologies, priorities,
                weights=None) -> "WorldBuilder":
        self._tenants[name] = TenantMix(
            name=name,
            namespaces=tuple(namespaces),
            topologies=tuple((t, int(c)) for t, c in topologies),
            priorities=tuple(int(p) for p in priorities),
            weights=tuple((op, float(w))
                          for op, w in (weights or {}).items()),
        )
        return self

    def arrival(self, at: float, kind: str, namespace: str, name: str,
                topology: str | None = None,
                priority: int = 0) -> "WorldBuilder":
        self._arrivals.append(Arrival(
            at=float(at), kind=kind, namespace=namespace, name=name,
            topology=topology, priority=int(priority),
        ))
        return self

    # ---- correlated-domain track -----------------------------------------
    def domains(self, count: int) -> "WorldBuilder":
        """Rack assignment for the pod simulator's nodes: ordinal
        modulo ``count`` (one worker per rack per slice, the layout
        where a rack loss partial-fails every multi-host slice)."""
        self._domains = max(0, int(count))
        return self

    def domain_loss(self, at: float, domain: int, chips: int,
                    jitter_s: float = 0.0) -> "WorldBuilder":
        self._domain_events.append(
            (float(at), "loss", int(domain), int(chips),
             float(jitter_s)))
        return self

    def domain_repair(self, at: float, domain: int,
                      jitter_s: float = 0.0) -> "WorldBuilder":
        self._domain_events.append(
            (float(at), "repair", int(domain), 0, float(jitter_s)))
        return self

    # ---- materialise -----------------------------------------------------
    def build(self) -> "ScenarioWorld":
        duration_s = self.ticks * self.tick_s
        schedule = FaultSchedule(seed=self.seed)
        for at, chips, jitter_s, restore in self._capacity:
            if restore:
                schedule.restore_capacity(at * duration_s,
                                          jitter_s=jitter_s)
            else:
                schedule.capacity(at * duration_s, chips,
                                  jitter_s=jitter_s)
        probe_schedule = FaultSchedule(
            seed=derive_stream(self.seed, "api-faults").randrange(2**31))
        api_instants = []
        for kind, start, end, ops_per_tick in self._api:
            b0 = int(start * self.ticks) * ops_per_tick
            b1 = int(end * self.ticks) * ops_per_tick
            probe_schedule.blackout(b0, b1)
            api_instants.append([kind, b0, b1])
        domain_rng = derive_stream(self.seed, "domains")
        events = []
        for at, kind, domain, chips, jitter_s in self._domain_events:
            jitter = (domain_rng.uniform(-jitter_s, jitter_s)
                      if jitter_s else 0.0)
            events.append(DomainEvent(
                at_s=max(0.0, at * duration_s + jitter),
                kind=kind, domain=domain, chips=chips,
            ))
        events.sort(key=lambda e: e.at_s)
        return ScenarioWorld(
            seed=self.seed, ticks=self.ticks, tick_s=self.tick_s,
            schedule=schedule, probe_schedule=probe_schedule,
            traffic=tuple(self._traffic),
            tenant_mixes=dict(self._tenants),
            arrivals=tuple(self._arrivals),
            domains=self._domains,
            domain_events=tuple(events),
            api_instants=api_instants,
        )


class ScenarioWorld:
    """One built timeline: the declarative script a harness replays.

    Runtime state (per-track streams, fired domain events, taints to
    undo) lives here too — one world instance drives ONE run; replays
    construct a fresh world from the same (seed, parameters)."""

    def __init__(self, *, seed, ticks, tick_s, schedule, probe_schedule,
                 traffic, tenant_mixes, arrivals, domains,
                 domain_events, api_instants):
        self.seed = seed
        self.ticks = ticks
        self.tick_s = tick_s
        self.duration_s = ticks * tick_s
        self.schedule = schedule
        self.probe_schedule = probe_schedule
        self.traffic = traffic
        self.tenant_mixes = tenant_mixes
        self.arrivals = arrivals
        self.domains = domains
        self.domain_events = domain_events
        self._api_instants = api_instants
        self._streams: dict[str, random.Random] = {}
        self._domain_cursor = 0
        self._lost: dict[int, int] = {}
        self._domain_tainted: dict[int, set[str]] = {}
        # Fired-event record the composed scenarios digest; bounded by
        # the scripted event count.  # analysis: allow[py-unbounded-deque]
        self.domain_log: list[dict] = []

    # ---- streams ---------------------------------------------------------
    def stream(self, track: str) -> random.Random:
        """The track's private generator (created on first use; stable
        per (seed, track))."""
        rng = self._streams.get(track)
        if rng is None:
            rng = derive_stream(self.seed, track)
            self._streams[track] = rng
        return rng

    # ---- tick geometry ---------------------------------------------------
    def tick_of(self, fraction: float) -> int:
        return int(fraction * self.ticks)

    def traffic_active(self, tick: int) -> tuple[TrafficPhase, ...]:
        return tuple(
            p for p in self.traffic
            if self.tick_of(p.start) <= tick < self.tick_of(p.end)
        )

    def arrivals_at(self, tick: int) -> tuple[Arrival, ...]:
        return tuple(a for a in self.arrivals
                     if self.tick_of(a.at) == tick)

    # ---- merged capacity view --------------------------------------------
    def capacity_at(self, now_s: float) -> int | None:
        """Base capacity weather minus every currently-lost domain's
        chips — the one pool view schedulers, injectors and promotion
        gates share (duck-types ``FaultSchedule.capacity_at``)."""
        chips = self.schedule.capacity_at(now_s)
        if chips is None or not self._lost:
            return chips
        return max(0, chips - sum(self._lost.values()))

    def lost_domains(self) -> frozenset[int]:
        return frozenset(self._lost)

    def domain_of(self, node_name: str) -> int | None:
        """Rack assignment by trailing node ordinal (simulator nodes
        are ``<prefix>-<sts>-<ordinal>``: worker k of every slice
        shares rack ``k % domains``)."""
        if not self.domains:
            return None
        _prefix, _, suffix = node_name.rpartition("-")
        if not suffix.isdigit():
            return None
        return int(suffix) % self.domains

    def slice_capacity(self, chips: int, hosts: int) -> int:
        """One slice's reachable chips under the current domain
        weather: workers on lost racks are unreachable even when the
        fleet pool still has headroom — the per-slice capacity view an
        elastic promotion gate should consult."""
        if not self._lost or not self.domains or hosts <= 0:
            return chips
        per_host = chips // max(1, hosts)
        lost_hosts = sum(
            1 for ordinal in range(hosts)
            if ordinal % self.domains in self._lost
        )
        return max(0, chips - per_host * lost_hosts)

    # ---- domain applier --------------------------------------------------
    def apply_domains(self, now_s: float, injector, sim) -> list[dict]:
        """Fire every domain event due by ``now_s``: a loss taints +
        deletes every bound worker in the rack in one instant (the
        correlated failure) and starts subtracting its chips from
        :meth:`capacity_at`; a repair clears this world's taints and
        stops the subtraction. The simulator is marked so nothing
        rebinds onto a lost rack until repair. Fired events land in
        ``domain_log`` (replay-deterministic: scripted instants,
        sorted victims)."""
        fired = []
        while self._domain_cursor < len(self.domain_events):
            event = self.domain_events[self._domain_cursor]
            if event.at_s > now_s:
                break
            self._domain_cursor += 1
            if event.kind == "loss":
                self._lost[event.domain] = event.chips
                sim.lost_domains.add(event.domain)
                sim.domain_of = self.domain_of
                victims = sorted(
                    (p["metadata"].get("namespace", "default"),
                     p["metadata"]["name"])
                    for p in injector.api.list("v1", "Pod")
                    if sim._is_bound(p)
                    and self.domain_of(
                        (p.get("spec") or {}).get("nodeName") or ""
                    ) == event.domain
                )
                tainted = self._domain_tainted.setdefault(
                    event.domain, set())
                for ns, name in victims:
                    node = injector.preempt_pod(ns, name)
                    if node:
                        tainted.add(node)
                fired.append({
                    "kind": "domain_loss", "domain": event.domain,
                    "at_s": round(event.at_s, 3),
                    "chips": event.chips, "pods": len(victims),
                })
            else:
                self._lost.pop(event.domain, None)
                sim.lost_domains.discard(event.domain)
                for node in sorted(
                        self._domain_tainted.pop(event.domain, ())):
                    injector.recover_node(node)
                fired.append({
                    "kind": "domain_repair", "domain": event.domain,
                    "at_s": round(event.at_s, 3),
                })
        if fired:
            # Push the merged capacity view into the injector/sim so
            # the rack's chips leave (or rejoin) the bindable pool in
            # the same instant as the pod deletions.
            injector.apply_capacity(self, now_s, sim)
            self.domain_log.extend(fired)
        return fired

    # ---- introspection ---------------------------------------------------
    def instants(self) -> dict:
        """Every track's materialised instants — the isolation
        contract's observable: composing a new track must leave every
        other track's entry here byte-identical."""
        return {
            "traffic": [
                [p.name, self.tick_of(p.start), self.tick_of(p.end)]
                for p in self.traffic
            ],
            "capacity": [
                [round(e.at_s, 6), e.chips]
                for e in self.schedule.capacity_events()
            ],
            "api": [list(row) for row in self._api_instants],
            "tenants": sorted(self.tenant_mixes) + [
                [a.kind, self.tick_of(a.at), a.namespace, a.name]
                for a in self.arrivals
            ],
            "domains": [
                [e.kind, e.domain, round(e.at_s, 6), e.chips]
                for e in self.domain_events
            ],
        }

    def manifest(self) -> dict:
        """The world's deterministic self-description, safe to fold
        into a ``replay_digest`` payload."""
        return {
            "seed": self.seed,
            "ticks": self.ticks,
            "tick_s": self.tick_s,
            "domains": self.domains,
            "instants": self.instants(),
        }

    def describe(self) -> str:
        parts = [f"world seed={self.seed} ticks={self.ticks}"
                 f" tick_s={self.tick_s:g}"]
        parts.append(self.schedule.describe())
        for p in self.traffic:
            parts.append(f"traffic:{p.name}[{p.start:g},{p.end:g})")
        for e in self.domain_events:
            parts.append(f"domain-{e.kind}:{e.domain}@{e.at_s:g}s")
        return " ".join(parts)
