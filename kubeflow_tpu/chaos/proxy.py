"""ChaosApiServer: a fault-injecting proxy over any duck-typed API.

Sits where the network sits in a real cluster — between the controllers
and the apiserver — and injects the failures the network and the
apiserver actually produce, on the schedule's deterministic script.
Wraps anything exposing the FakeApiServer interface (the fake itself,
or a real ApiClient); everything not explicitly intercepted passes
through untouched, so webhook listers, metrics collectors and fixtures
keep working against the wrapped handle.

Faults surface as the exceptions the real client raises (ApiError with
a status code, Conflict, NotFound), so every retry/backoff/watchdog
layer above sees exactly what it would see in production. Watch queues
come back wrapped in ``ChaosWatchQueue``, which damages the event
stream (drop / duplicate / reorder / compact) at delivery time.
"""

from __future__ import annotations

import contextlib
import itertools
import queue
import time
from collections import deque

from kubeflow_tpu import obs
from kubeflow_tpu.chaos import schedule as sched
from kubeflow_tpu.chaos.schedule import Fault, FaultSchedule
from kubeflow_tpu.k8s.core import ApiError, Conflict, NotFound


class ChaosWatchQueue:
    """Duck-type of the queue.Queue a watch returns, applying the
    schedule's per-event damage when events are pulled. Only the two
    methods the controller runtime uses (``empty``/``get_nowait``) plus
    ``get``/``put`` for harness compatibility are provided."""

    def __init__(self, inner: queue.Queue, schedule: FaultSchedule,
                 stats: dict):
        self._inner = inner
        self._schedule = schedule
        self._stats = stats
        self._pending: deque = deque()

    def _pull(self) -> None:
        while True:
            try:
                ev = self._inner.get_nowait()
            except queue.Empty:
                return
            action = self._schedule.next_watch_action()
            if action == sched.DROP:
                self._stats["watch_dropped"] += 1
                continue
            if action == sched.DUP:
                self._stats["watch_duplicated"] += 1
                self._pending.append(ev)
                self._pending.append(ev)
                continue
            if action == sched.REORDER and self._pending:
                # Deliver this event before its predecessor — the
                # out-of-order delivery a re-connecting informer can see.
                self._stats["watch_reordered"] += 1
                prev = self._pending.pop()
                self._pending.append(ev)
                self._pending.append(prev)
                continue
            if action == sched.COMPACT:
                # Watch-cache compaction: the whole pending backlog is
                # beyond the horizon. Level-based resync is the only
                # repair, exactly like a 410 Gone without re-list.
                self._stats["watch_compacted"] += 1
                self._pending.clear()
                while True:
                    try:
                        self._inner.get_nowait()
                    except queue.Empty:
                        break
                continue
            self._pending.append(ev)

    def empty(self) -> bool:
        self._pull()
        return not self._pending

    def get_nowait(self):
        self._pull()
        if not self._pending:
            raise queue.Empty
        return self._pending.popleft()

    def get(self, timeout: float | None = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            try:
                return self.get_nowait()
            except queue.Empty:
                if deadline is not None and time.monotonic() >= deadline:
                    raise
                time.sleep(0.005)

    def put(self, item) -> None:
        self._inner.put(item)


class ChaosApiServer:
    """Fault-injecting proxy with the FakeApiServer interface.

    ``injected`` counts faults by kind so tests can assert the schedule
    actually fired (a schedule that never triggers proves nothing).
    ``sleep`` is injectable so latency faults cost no wall-clock in
    tests that don't care about it.
    """

    def __init__(self, inner, schedule: FaultSchedule, sleep=time.sleep):
        self.inner = inner
        self.schedule = schedule
        self._sleep = sleep
        self._ops = itertools.count()
        self.injected: dict[str, int] = {
            sched.ERROR: 0, sched.CONFLICT: 0, sched.NOT_FOUND: 0,
            sched.LATENCY: 0, sched.BLACKOUT: 0,
            "watch_dropped": 0, "watch_duplicated": 0,
            "watch_reordered": 0, "watch_compacted": 0,
        }
        self.ops_total = 0
        # Availability as the controllers experience it through this
        # proxy: one event per gated op, bad when the injected fault is
        # a 5xx/429/blackout (conflicts, 404 flaps and latency are the
        # apiserver *working*). Same (good, total) shape as the real
        # client's availability_counts(), so the apiserver SLO can sit
        # on either side of the chaos boundary.
        self._avail_bad = 0

    # ---- fault gate ------------------------------------------------------
    def _traced(self, verb: str, kind: str):
        """An ``api <verb>`` child span when a trace is active (the
        reconcile or http span above this call), else a no-op. The
        apiserver-call layer of a trace comes from here in chaos runs —
        injected faults land as events on exactly the call they hit,
        so a trace reads "503 injected HERE, retried, succeeded"."""
        if obs.current_span() is None:
            return contextlib.nullcontext(None)
        return obs.get_tracer().span(
            f"api {verb}", attributes={"verb": verb, "kind": kind},
        )

    def _gate(self, verb: str, kind: str, span=None) -> None:
        op = next(self._ops)
        self.ops_total = op + 1
        fault = self.schedule.fault_for(op, verb, kind)
        if fault is None:
            return
        self.injected[fault.kind] = self.injected.get(fault.kind, 0) + 1
        if fault.kind == sched.BLACKOUT or (
            fault.kind == sched.ERROR
            and (fault.status >= 500 or fault.status == 429)
        ):
            self._avail_bad += 1
        self._raise(fault, verb, kind, op, span)

    def availability_counts(self) -> tuple[int, int]:
        """Cumulative ``(good, total)`` ops through the fault gate —
        the apiserver-availability SLO source shape."""
        total = self.ops_total
        return total - self._avail_bad, total

    def _raise(self, fault: Fault, verb: str, kind: str, op: int,
               span=None) -> None:
        where = f"op {op} {verb} {kind}"
        if span is not None:
            span.add_event("chaos.fault", {
                "fault": fault.kind,
                "status": fault.status,
                "op": op,
                "verb": verb,
            })
        if fault.kind == sched.LATENCY:
            self._sleep(fault.latency_s)
            return
        if fault.kind == sched.CONFLICT:
            raise Conflict(f"chaos: injected conflict ({where})")
        if fault.kind == sched.NOT_FOUND:
            raise NotFound(f"chaos: injected 404 flap ({where})")
        if fault.kind == sched.BLACKOUT:
            raise ApiError(f"chaos: apiserver blackout ({where})", 503)
        err = ApiError(
            f"chaos: injected {fault.status} ({where})", fault.status
        )
        # Carried the way the real client reads it off the response
        # headers; informational for assertions on 429 handling.
        err.retry_after = fault.retry_after
        raise err

    # ---- intercepted verbs ----------------------------------------------
    def create(self, obj: dict, namespace: str | None = None,
               dry_run: bool = False) -> dict:
        kind = obj.get("kind", "")
        with self._traced("create", kind) as span:
            self._gate("create", kind, span)
            return self.inner.create(obj, namespace=namespace,
                                     dry_run=dry_run)

    def get(self, api_version: str, kind: str, name: str,
            namespace: str | None = None) -> dict:
        with self._traced("get", kind) as span:
            self._gate("get", kind, span)
            return self.inner.get(api_version, kind, name, namespace)

    def list(self, api_version: str, kind: str, namespace: str | None = None,
             label_selector: str | None = None,
             field_selector: str | None = None) -> list[dict]:
        with self._traced("list", kind) as span:
            self._gate("list", kind, span)
            return self.inner.list(api_version, kind, namespace=namespace,
                                   label_selector=label_selector,
                                   field_selector=field_selector)

    def update(self, obj: dict, dry_run: bool = False) -> dict:
        kind = obj.get("kind", "")
        with self._traced("update", kind) as span:
            self._gate("update", kind, span)
            return self.inner.update(obj, dry_run=dry_run)

    def patch_merge(self, api_version: str, kind: str, name: str,
                    patch: dict, namespace: str | None = None) -> dict:
        with self._traced("patch_merge", kind) as span:
            self._gate("patch_merge", kind, span)
            return self.inner.patch_merge(api_version, kind, name, patch,
                                          namespace)

    def delete(self, api_version: str, kind: str, name: str,
               namespace: str | None = None) -> None:
        with self._traced("delete", kind) as span:
            self._gate("delete", kind, span)
            return self.inner.delete(api_version, kind, name, namespace)

    def apply(self, obj: dict) -> dict:
        kind = obj.get("kind", "")
        with self._traced("apply", kind) as span:
            self._gate("apply", kind, span)
            return self.inner.apply(obj)

    def watch(self, api_version: str, kind: str, *args, **kwargs):
        q = self.inner.watch(api_version, kind, *args, **kwargs)
        return ChaosWatchQueue(q, self.schedule, self.injected)

    # ---- passthrough -----------------------------------------------------
    def __getattr__(self, name):
        # Everything else (read_pod_logs, set_pod_logs, register_admission,
        # list_with_rv, events_since, breaker/request_metrics on a real
        # client, ...) is the inner API's business.
        return getattr(self.inner, name)
