"""Convergence driver for chaos runs.

``run_once`` drains what is ready *now*; rate-limited retries park keys
with a future ``not_before``, and dropped watch events or spurious
NotFound reads are only repaired by the periodic resync ``run_forever``
would perform. This driver plays run_forever's role deterministically,
and — unlike a wall-clock loop — it must *prove* convergence, not just
observe a momentary lull: a reconcile that was lied to (injected 404 on
the primary get) leaves no pending work behind, so queue emptiness
alone is a false signal.

Convergence therefore means: ``settle_rounds`` consecutive rounds in
which (a) every controller's resync LIST succeeded, (b) draining the
re-enqueued keys changed nothing in the store (resourceVersion stable),
(c) simulators made no changes, and (d) no retry is parked for later.
Fault schedules are op-bounded, so the verification rounds themselves
push the op counter past every fault window — the loop cannot wedge
inside a storm. The round bound turns "self-healing" into an assertable
property: convergence within ``max_rounds`` or AssertionError.
"""

from __future__ import annotations

import time


def clamp_backoff(controller, base_delay: float = 0.001,
                  max_delay: float = 0.05) -> None:
    """Shrink a controller's workqueue backoff so chaos suites retry in
    milliseconds, not the production 60s cap. Call before the first
    reconcile — semantics (dedup, earliest-wins, per-key exponential
    growth) are untouched, only the timescale."""
    controller.queue._base = base_delay
    controller.queue._max = max_delay


def _store_rv(controllers) -> int:
    """Monotonic write marker for the backing store. FakeApiServer (and
    the chaos proxy wrapping it, via passthrough) expose
    ``last_resource_version``; anything else falls back to 0, which
    degrades the settled check to queue/resync evidence only."""
    for ctrl in controllers:
        rv = getattr(ctrl.api, "last_resource_version", None)
        if rv is not None:
            return int(rv)
    return 0


def run_to_convergence(
    controllers,
    sims=(),
    max_rounds: int = 400,
    settle_rounds: int = 2,
    resync_every: int = 5,
    sleep=time.sleep,
    run_once_iterations: int = 100,
) -> int:
    """Drive controllers (+ pod simulators) until the world is provably
    settled for ``settle_rounds`` consecutive rounds. Returns the number
    of rounds taken — callers assert it against their bound, making
    reconcile cost under chaos a regression-checked number.

    ``run_once_iterations`` is each round's per-controller reconcile
    budget. At fleet cardinality it must exceed the primary-object
    count: every resync re-enqueues the whole keyspace, and a budget
    below it can never drain the queue the resync just refilled — the
    loop would burn ``max_rounds`` without ever reaching a quiet
    round (the 10k-CR soak's finding)."""
    quiet = 0
    rounds = 0
    while quiet < settle_rounds:
        rounds += 1
        if rounds > max_rounds:
            raise AssertionError(
                f"no convergence within {max_rounds} rounds "
                f"(queues: {[len(c.queue) for c in controllers]})"
            )
        rv_before = _store_rv(controllers)
        sim_changed = 0
        for sim in sims:
            sim_changed += sim.step()
        # Level-based repair: periodically during the run, and on EVERY
        # candidate-settled round — a round only counts as quiet when a
        # successful full re-list found nothing to fix.
        resync_ok = True
        if quiet > 0 or rounds == 1 or rounds % resync_every == 0:
            for ctrl in controllers:
                resync_ok = (ctrl.resync() is not None) and resync_ok
        for ctrl in controllers:
            ctrl.run_once(max_iterations=run_once_iterations)
        parked = [
            d for d in (c.queue.next_deadline() for c in controllers)
            if d is not None
        ]
        if parked:
            # Retries backing off: wait them out (bounded), keep going.
            wait = min(parked) - time.monotonic()
            if wait > 0:
                sleep(min(wait, 0.05))
        if (
            sim_changed
            or parked
            or not resync_ok
            or _store_rv(controllers) != rv_before
        ):
            quiet = 0
        else:
            quiet += 1
    return rounds
