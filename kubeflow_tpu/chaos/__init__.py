"""Deterministic chaos harness for the control plane.

Jepsen-style fault schedules for the platform's own runtime: a seeded
``FaultSchedule`` drives a ``ChaosApiServer`` proxy that injects
apiserver weather (transient 5xx/429 with Retry-After, Conflict storms,
NotFound flaps, latency spikes, blackouts) and watch-channel damage
(dropped / duplicated / reordered events, 410-style compaction) into
any duck-typed API — the in-memory FakeApiServer or the real ApiClient.
``PreemptionInjector`` kills TPU worker pods the way GKE does (node
taint + pod delete); ``StatefulSetPodSimulator`` plays the
kubelet/statefulset-controller role the fake apiserver does not, so pod
lifecycle chaos runs entirely in process. ``run_to_convergence`` drives
controllers (plus simulators) to a quiescent state with the periodic
resync run_forever would provide, bounding the reconcile count.

Everything is seeded and clock-free: the same schedule replays the same
fault sequence, so tests/test_chaos.py can assert the post-chaos world
equals the fault-free one, exactly.
"""

from kubeflow_tpu.chaos.cluster import (  # noqa: F401
    PREEMPTION_TAINT_KEY,
    TEMPLATE_HASH_ANNOTATION,
    PreemptionInjector,
    StatefulSetPodSimulator,
)
from kubeflow_tpu.chaos.harness import run_to_convergence  # noqa: F401
from kubeflow_tpu.chaos.proxy import ChaosApiServer, ChaosWatchQueue  # noqa: F401
from kubeflow_tpu.chaos.schedule import (  # noqa: F401
    CapacityEvent,
    Fault,
    FaultSchedule,
)
from kubeflow_tpu.chaos.world import (  # noqa: F401
    Arrival,
    Clock,
    DomainEvent,
    ScenarioWorld,
    TenantMix,
    TrafficPhase,
    WorldBuilder,
    derive_stream,
)

# Data-plane checkpoint faults resolve lazily: chaos.ckpt reaches into
# models.checkpoint (jax + the training stack), which the control-plane
# tier above must not pay for at import time.
_CKPT_EXPORTS = (
    "CheckpointKiller",
    "SimulatedCrash",
    "KILL_POINTS",
    "truncate_shard",
    "drop_shard",
    "flip_shard_bytes",
)


def __getattr__(name):
    if name in _CKPT_EXPORTS:
        from kubeflow_tpu.chaos import ckpt

        return getattr(ckpt, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
