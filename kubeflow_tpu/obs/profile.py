"""Continuous phase-level profiling for the platform's hot loops.

PR 9's burn-rate alerts can say *that* an SLO burned and PR 3's traces
can say *which* request was slow; nothing attributed *where the time
went* inside one unit of hot-loop work — a training step (fetch /
step / save / publish), a batcher cycle (admit / prefill / decode /
verify / commit), a reconcile (list / desired-state / patch / status).
:class:`PhaseProfiler` is that attribution layer: always-on, cheap
(one ``perf_counter`` pair + a lock-guarded deque append per phase,
single-digit microseconds), with rolling per-phase percentile digests
readable live at ``/debug/profile`` and stamped into
``StepTelemetry`` records and flight-recorder snapshots.

Propagation is ``contextvars``-based, like the tracer: a driver (the
training loop, the scheduler thread, the controller runtime) activates
its profiler around one unit of work, and any code underneath —
however deep — attributes a phase with the module-level :func:`phase`
helper without plumbing a handle. Outside an activation the helper is
a no-op, so library code can be instrumented unconditionally.

Device-memory watermarks ride along where the runtime exposes them
(``jax.local_devices()[i].memory_stats()`` on TPU/GPU backends); on
CPU — and in processes that never import jax — :func:`memory_watermark`
degrades to ``None`` after one cached probe, so the control plane pays
nothing for a data-plane feature.

Environment:

- ``KFT_PROFILE_WINDOW`` — rolling digest window per phase (default
  512 most-recent durations; percentiles are exact over the window).
- ``KFT_PROFILE_MEMORY`` — "0" disables watermark sampling entirely
  (default on; unavailable backends cost one probe then nothing).
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time
from collections import deque
from typing import Callable

from kubeflow_tpu.obs.envknob import env_bool, env_number

# The profiler whose digests module-level phase() records into, plus
# the per-activation accumulator dict (one unit of work's phase
# seconds) — both carried on contextvars so instrumentation points
# need no handle and threads/contexts never share an activation.
_ACTIVE: contextvars.ContextVar["PhaseProfiler | None"] = \
    contextvars.ContextVar("kubeflow_tpu_obs_active_profiler", default=None)
_SCOPE: contextvars.ContextVar[dict | None] = \
    contextvars.ContextVar("kubeflow_tpu_obs_profile_scope", default=None)


class PhaseDigest:
    """Rolling-window duration digest for one named phase.

    Keeps the last ``window`` observations (deque, oldest evicted) plus
    cumulative count/total, and answers nearest-rank percentiles exactly
    over the window: for ``n`` retained values sorted ascending,
    ``percentile(q)`` is the value at rank ``max(1, ceil(q * n))`` —
    hand-computable, no interpolation. Not thread-safe on its own; the
    owning :class:`PhaseProfiler` serializes access."""

    __slots__ = ("_window", "count", "total_s", "max_s", "last_s")

    def __init__(self, window: int = 512):
        self._window: deque = deque(maxlen=max(1, int(window)))
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0
        self.last_s = 0.0

    def observe(self, seconds: float) -> None:
        seconds = max(float(seconds), 0.0)
        self._window.append(seconds)
        self.count += 1
        self.total_s += seconds
        self.last_s = seconds
        if seconds > self.max_s:
            self.max_s = seconds

    def percentile(self, q: float) -> float:
        """Nearest-rank percentile over the rolling window; 0.0 when
        empty."""
        if not self._window:
            return 0.0
        values = sorted(self._window)
        q = min(max(float(q), 0.0), 1.0)
        # ceil(q * n) without floats drifting: -(-a // b) idiom over
        # a scaled integer would be overkill; guard the edges instead.
        rank = int(q * len(values))
        if rank < q * len(values):
            rank += 1
        rank = min(max(rank, 1), len(values))
        return values[rank - 1]

    def snapshot(self) -> dict:
        return {
            "count": self.count,
            "window": len(self._window),
            "total_s": round(self.total_s, 6),
            "last_s": round(self.last_s, 6),
            "max_s": round(self.max_s, 6),
            "p50_s": round(self.percentile(0.50), 6),
            "p90_s": round(self.percentile(0.90), 6),
            "p99_s": round(self.percentile(0.99), 6),
        }


class PhaseProfiler:
    """Per-phase wall-time attribution with rolling percentile digests.

    One profiler per hot loop (one per controller, one per serving
    engine, one per training run). The loop either calls
    :meth:`phase` directly (it holds the handle) or activates the
    profiler around one unit of work (:meth:`activate`) so deeper code
    reports through the module-level :func:`phase` helper. The
    activation scope also accumulates this unit's per-phase seconds —
    the dict the flight recorder snapshots.

    Thread-safe: digests mutate under one lock, and ``snapshot()`` /
    ``compact()`` read under the same lock, so ``/debug/profile``
    handler threads can read while the hot loop writes."""

    def __init__(
        self,
        window: int | None = None,
        clock: Callable[[], float] = time.perf_counter,
        memory: bool | None = None,
    ):
        self.window = (window if window is not None
                       else env_number("KFT_PROFILE_WINDOW", 512, cast=int))
        self._clock = clock
        if memory is None:
            memory = env_bool("KFT_PROFILE_MEMORY", True)
        self.memory = bool(memory)
        self._lock = threading.Lock()
        self._digests: dict[str, PhaseDigest] = {}

    # ---- recording -------------------------------------------------------
    def observe(self, name: str, seconds: float) -> None:
        """Record one phase duration. Also accumulates into the current
        activation scope when THIS profiler is the active one (a
        foreign activation on the same thread must not absorb another
        loop's phases)."""
        with self._lock:
            digest = self._digests.get(name)
            if digest is None:
                digest = self._digests[name] = PhaseDigest(self.window)
            digest.observe(seconds)
        if _ACTIVE.get() is self:
            scope = _SCOPE.get()
            if scope is not None:
                scope[name] = scope.get(name, 0.0) + max(
                    float(seconds), 0.0
                )

    @contextlib.contextmanager
    def phase(self, name: str):
        """``with profiler.phase("decode"):`` — time the block into the
        named digest (and the active scope)."""
        t0 = self._clock()
        try:
            yield
        finally:
            self.observe(name, self._clock() - t0)

    @contextlib.contextmanager
    def activate(self):
        """Install this profiler as the contextvar-active one and open
        a fresh per-unit scope; yields the scope dict (phase name →
        accumulated seconds for this unit of work)."""
        scope: dict[str, float] = {}
        token = _ACTIVE.set(self)
        scope_token = _SCOPE.set(scope)
        try:
            yield scope
        finally:
            _SCOPE.reset(scope_token)
            _ACTIVE.reset(token)

    # ---- reading ---------------------------------------------------------
    def snapshot(self) -> dict:
        """{phase: full digest snapshot} — the ``/debug/profile``
        document body."""
        with self._lock:
            return {
                name: digest.snapshot()
                for name, digest in sorted(self._digests.items())
            }

    def compact(self) -> dict:
        """{phase: {p50_s, p99_s, n}} — the small form stamped into
        ``/v1/status`` and StepTelemetry records."""
        with self._lock:
            return {
                name: {
                    "p50_s": round(digest.percentile(0.50), 6),
                    "p99_s": round(digest.percentile(0.99), 6),
                    "n": digest.count,
                }
                for name, digest in sorted(self._digests.items())
            }

    def watermark(self) -> dict | None:
        """Device-memory watermark when sampling is enabled and the
        backend exposes it; None otherwise (CPU-safe no-op)."""
        if not self.memory:
            return None
        return memory_watermark()


# ---------------------------------------------------------------------------
# module-level context helpers
# ---------------------------------------------------------------------------


def active_profiler() -> PhaseProfiler | None:
    """The profiler activated on this thread/context, or None."""
    return _ACTIVE.get()


@contextlib.contextmanager
def phase(name: str):
    """Attribute the block to ``name`` on the contextvar-active
    profiler; a cheap no-op when none is active — library code
    (reconcilers, checkpoint helpers) instruments unconditionally."""
    prof = _ACTIVE.get()
    if prof is None:
        yield
        return
    with prof.phase(name):
        yield


def active_digest() -> dict | None:
    """Compact digest of the active profiler (for StepTelemetry's
    per-step stamp), or None outside an activation / before any
    phase landed."""
    prof = _ACTIVE.get()
    if prof is None:
        return None
    digest = prof.compact()
    return digest or None


# ---------------------------------------------------------------------------
# device-memory watermarks
# ---------------------------------------------------------------------------

# One probe decides availability for the process lifetime: CPU
# backends (and processes without jax) must not re-pay an import or an
# exception per hot-loop snapshot.
_MEM_PROBE_LOCK = threading.Lock()
_MEM_DEVICES: list | None = None
_MEM_PROBED = False


def _probe_devices() -> list | None:
    global _MEM_DEVICES, _MEM_PROBED
    with _MEM_PROBE_LOCK:
        if _MEM_PROBED:
            return _MEM_DEVICES
        _MEM_PROBED = True
        _MEM_DEVICES = None
        try:
            import jax

            devices = jax.local_devices()
        except Exception:  # analysis: allow[py-broad-except]
            # No jax (control-plane process) or no initialized backend:
            # the watermark is simply unavailable here.
            return None
        for device in devices:
            stats_fn = getattr(device, "memory_stats", None)
            if stats_fn is None:
                return None
            try:
                if not stats_fn():
                    return None  # CPU: None or {} — no watermark story
            except Exception:  # analysis: allow[py-broad-except]
                return None
        _MEM_DEVICES = list(devices)
        return _MEM_DEVICES


def memory_watermark(devices: list | None = None) -> dict | None:
    """Summed HBM usage across local devices via ``memory_stats()``:
    ``{"devices", "bytes_in_use", "peak_bytes_in_use", "bytes_limit"}``
    (keys omitted when the backend doesn't report them). Returns None
    where stats are unavailable (CPU, no jax) — the documented no-op
    fallback. ``devices`` is injectable for tests."""
    if devices is None:
        devices = _probe_devices()
    if not devices:
        return None
    # One memory_stats() runtime call per device (not per key): this
    # runs on hot-path snapshots, and per-device stats should be read
    # from ONE consistent snapshot anyway.
    per_device: list[dict] = []
    for device in devices:
        try:
            per_device.append(device.memory_stats() or {})
        except Exception:  # analysis: allow[py-broad-except]
            return None  # a device went away: no partial answers
    out: dict = {"devices": len(devices)}
    for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
        values = [int(s[key]) for s in per_device if key in s]
        if values:
            out[key] = sum(values)
    return out if len(out) > 1 else None


def process_watermark() -> dict | None:
    """:func:`memory_watermark` gated on ``KFT_PROFILE_MEMORY`` — the
    same kill switch :meth:`PhaseProfiler.watermark` honors, for
    handlers (the manager's ``/debug/profile``) that hold no profiler
    with the flag baked in."""
    if not env_bool("KFT_PROFILE_MEMORY", True):
        return None
    return memory_watermark()


def reset_memory_probe() -> None:
    """Forget the cached availability verdict (tests re-probe with
    injected devices; a real process never needs this)."""
    global _MEM_DEVICES, _MEM_PROBED
    with _MEM_PROBE_LOCK:
        _MEM_DEVICES = None
        _MEM_PROBED = False


# ---------------------------------------------------------------------------
# overhead measurement
# ---------------------------------------------------------------------------


def measure_overhead_s(iterations: int = 2000) -> float:
    """Mean seconds one ``phase()`` record costs on this host (enter +
    clock pair + locked digest append + scope accumulate). The bench
    smoke compares this against the measured decode-phase p50 to hold
    the <2% hot-path overhead budget."""
    iterations = max(1, int(iterations))
    profiler = PhaseProfiler(window=64, memory=False)
    with profiler.activate():
        t0 = time.perf_counter()
        for _ in range(iterations):
            with profiler.phase("overhead-probe"):
                pass
        elapsed = time.perf_counter() - t0
    return elapsed / iterations
