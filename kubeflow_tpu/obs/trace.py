"""Spans and W3C trace-context propagation (stdlib only).

The shape is OpenTelemetry's, cut down to what the platform threads
through its own processes: a ``Span`` is a named interval with
attributes, timestamped events and an error status; a ``Tracer`` mints
spans, tracks the current one on a ``contextvars.ContextVar`` (so
propagation crosses function boundaries without plumbing arguments),
samples at the root, and hands finished spans to exporters.

Context crosses process boundaries two ways:

- synchronously, on the W3C ``traceparent`` header
  (``00-<32 hex trace id>-<16 hex span id>-<2 hex flags>``) — parse
  with :func:`parse_traceparent`, emit with :func:`format_traceparent`;
- asynchronously, through etcd: the spawner stamps the same header
  value into the :data:`TRACE_ANNOTATION` metadata annotation on the
  CR it creates, and the controller runtime parents its reconcile
  spans on it — the only way a trace can follow a request across the
  watch/workqueue gap, where no HTTP headers exist.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import random
import re
import threading
import time
from collections import deque
from typing import Callable

# Metadata annotation carrying a traceparent value across the async
# hop (spawner POST -> CR -> watch event -> reconcile).
TRACE_ANNOTATION = "obs.kubeflow-tpu.org/traceparent"

_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})"
    r"(?:-[^\s]*)?$"
)

_CURRENT: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "kubeflow_tpu_obs_current_span", default=None
)


class SpanContext:
    """The propagated identity of a span: (trace id, span id, sampled).

    Immutable; ``sampled`` rides the traceparent flags byte (bit 0) so
    a sampling decision made at the edge holds across every process the
    trace visits."""

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: str, span_id: str, sampled: bool = True):
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = sampled

    def __repr__(self):
        return (f"SpanContext(trace_id={self.trace_id!r}, "
                f"span_id={self.span_id!r}, sampled={self.sampled})")


def parse_traceparent(header: str | None) -> SpanContext | None:
    """W3C traceparent → SpanContext, or None for anything malformed.

    Per the spec: exactly-sized lowercase hex fields, version ``ff``
    invalid, all-zero trace or span id invalid. Trailing fields from
    future versions are tolerated; a malformed header NEVER raises —
    the caller just starts a fresh trace."""
    if not header or not isinstance(header, str):
        return None
    m = _TRACEPARENT_RE.match(header.strip())
    if m is None:
        return None
    version, trace_id, span_id, flags = m.groups()
    if version == "ff":
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    try:
        sampled = bool(int(flags, 16) & 0x01)
    except ValueError:  # unreachable given the regex, but never raise
        return None
    return SpanContext(trace_id, span_id, sampled)


def format_traceparent(ctx: SpanContext) -> str:
    return (f"00-{ctx.trace_id}-{ctx.span_id}-"
            f"{'01' if ctx.sampled else '00'}")


def current_span() -> "Span | None":
    """The span active on this thread/context, or None."""
    return _CURRENT.get()


class Span:
    """One named interval. Mutate only before :meth:`end` (the tracer's
    context manager ends it); ``to_dict`` is the export form."""

    # OTel's default span-event cap: a span held open across a long
    # incident (a watch loop, a stuck reconcile) must not accumulate
    # events without bound. The OLDEST events are evicted (and
    # counted) — the tail leading into a failure is the forensic
    # window worth keeping.
    MAX_EVENTS = 128

    def __init__(
        self,
        name: str,
        context: SpanContext,
        parent_id: str | None,
        clock: Callable[[], float],
        on_end: Callable[["Span"], None],
        attributes: dict | None = None,
    ):
        self.name = name
        self.context = context
        self.parent_id = parent_id
        self.attributes: dict = dict(attributes or {})
        self.events: deque = deque(maxlen=self.MAX_EVENTS)
        self.dropped_events = 0
        self.status = "ok"
        self.start_time = clock()
        self.end_time: float | None = None
        self._clock = clock
        self._on_end = on_end
        self._ended = False

    # ---- mutation --------------------------------------------------------
    def set_attribute(self, key: str, value) -> "Span":
        self.attributes[key] = value
        return self

    def add_event(self, name: str, attributes: dict | None = None) -> "Span":
        if len(self.events) == self.MAX_EVENTS:
            self.dropped_events += 1  # the append below evicts the oldest
        self.events.append({
            "name": name,
            "time": self._clock(),
            "attributes": dict(attributes or {}),
        })
        return self

    def record_exception(self, exc: BaseException) -> "Span":
        self.status = "error"
        return self.add_event("exception", {
            "type": type(exc).__name__,
            "message": str(exc)[:300],
        })

    def end(self) -> None:
        if self._ended:
            return
        self._ended = True
        self.end_time = self._clock()
        self._on_end(self)

    # ---- export ----------------------------------------------------------
    def to_dict(self) -> dict:
        end = self.end_time if self.end_time is not None else self._clock()
        return {
            "name": self.name,
            "trace_id": self.context.trace_id,
            "span_id": self.context.span_id,
            "parent_id": self.parent_id,
            "start": self.start_time,
            "end": end,
            "duration_ms": round((end - self.start_time) * 1000, 3),
            "status": self.status,
            "attributes": dict(self.attributes),
            "events": list(self.events),
            **({"dropped_events": self.dropped_events}
               if self.dropped_events else {}),
        }


# Distinguishes "no parent passed: inherit the current span" from an
# explicit parent=None ("start a new root trace").
_INHERIT = object()


class Tracer:
    """Span factory + context manager + sampling + export fan-out.

    Always keeps a bounded in-memory ring of finished spans (the
    ``/debug/traces`` data source); an optional extra exporter (JSONL)
    receives the same stream. Head-based sampling: the decision is
    drawn once at the root (``OBS_TRACE_SAMPLE``) and inherited by
    children and remote continuations via the traceparent flags, so a
    trace is always complete-or-absent, never ragged."""

    def __init__(
        self,
        exporter=None,
        sample_rate: float | None = None,
        ring_capacity: int | None = None,
        clock: Callable[[], float] = time.time,
        rng: random.Random | None = None,
    ):
        from kubeflow_tpu.obs.export import RingExporter

        if sample_rate is None:
            try:
                sample_rate = float(os.environ.get("OBS_TRACE_SAMPLE", "1"))
            except ValueError:
                sample_rate = 1.0
        if ring_capacity is None:
            try:
                ring_capacity = int(
                    os.environ.get("OBS_RING_CAPACITY", "512")
                )
            except ValueError:
                ring_capacity = 512
        self.sample_rate = min(max(sample_rate, 0.0), 1.0)
        self.ring = RingExporter(capacity=ring_capacity)
        self.exporter = exporter
        self.clock = clock
        # Seedable for deterministic sampling tests; lock-protected —
        # random.Random is not thread-safe and spans start on watch,
        # server and worker threads concurrently.
        self._rng = rng or random.Random()
        self._lock = threading.Lock()

    # ---- ids -------------------------------------------------------------
    @staticmethod
    def _new_trace_id() -> str:
        return os.urandom(16).hex()

    @staticmethod
    def _new_span_id() -> str:
        return os.urandom(8).hex()

    def _sampled(self) -> bool:
        if self.sample_rate >= 1.0:
            return True
        if self.sample_rate <= 0.0:
            return False
        with self._lock:
            return self._rng.random() < self.sample_rate

    # ---- span lifecycle --------------------------------------------------
    def start_span(
        self,
        name: str,
        parent: "SpanContext | Span | None" = _INHERIT,
        attributes: dict | None = None,
    ) -> Span:
        """Start (but do not activate) a span. ``parent`` defaults to
        the current span; pass an explicit SpanContext (remote parent)
        or None (force a new root)."""
        if parent is _INHERIT:
            cur = _CURRENT.get()
            parent = cur.context if cur is not None else None
        elif isinstance(parent, Span):
            parent = parent.context
        if parent is None:
            ctx = SpanContext(
                self._new_trace_id(), self._new_span_id(), self._sampled()
            )
            parent_id = None
        else:
            ctx = SpanContext(
                parent.trace_id, self._new_span_id(), parent.sampled
            )
            parent_id = parent.span_id
        return Span(
            name, ctx, parent_id, clock=self.clock, on_end=self._export,
            attributes=attributes,
        )

    @contextlib.contextmanager
    def span(
        self,
        name: str,
        parent: "SpanContext | Span | None" = _INHERIT,
        attributes: dict | None = None,
    ):
        """``with tracer.span("reconcile") as sp:`` — activates the
        span on the current context, records an uncaught exception as
        an error status, always ends + exports."""
        sp = self.start_span(name, parent=parent, attributes=attributes)
        token = _CURRENT.set(sp)
        try:
            yield sp
        except BaseException as exc:
            sp.record_exception(exc)
            raise
        finally:
            _CURRENT.reset(token)
            sp.end()

    def _export(self, span: Span) -> None:
        if not span.context.sampled:
            return
        doc = span.to_dict()
        self.ring.export(doc)
        if self.exporter is not None:
            try:
                self.exporter.export(doc)
            except Exception:  # analysis: allow[py-broad-except]
                # Telemetry must never take down the traced code path:
                # a full disk under OBS_JSONL_PATH drops spans, not
                # requests.
                pass
