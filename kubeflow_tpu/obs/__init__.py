"""Platform observability: spans, structured logs, step telemetry.

One user action on this platform crosses at least five processes —
spawner POST → apiserver → admission webhook → controller reconcile →
apiserver again — and the counters on ``/metrics`` can only say that
each hop happened, not where the 40 seconds went. This package is the
correlation layer: dependency-free Dapper-style spans propagated on the
W3C ``traceparent`` header (and, across the async hop through etcd, on
a CR annotation), exporters (bounded in-memory ring + JSONL), a JSON
log formatter that stamps trace/span ids on every record, and
``StepTelemetry`` for the training side (per-step wall time,
examples/sec, MFU against the per-topology peak-FLOPs tables).

Everything here is stdlib-only so the k8s client, the webhook and the
controllers can import it without growing their images;
``telemetry.py`` alone touches prometheus_client, lazily.

Environment:

- ``OBS_TRACE_SAMPLE``  — root-span sample rate in [0, 1] (default 1.0)
- ``OBS_JSONL_PATH``    — when set, the default tracer also appends
  every finished span as one JSON line to this file
- ``OBS_RING_CAPACITY`` — spans retained in memory for ``/debug/traces``
  (default 512)
"""

from __future__ import annotations

import os
import threading

from kubeflow_tpu.obs.alerts import AlertManager, SloEngine
from kubeflow_tpu.obs.export import (
    JsonlExporter,
    MultiExporter,
    RingExporter,
    span_tree,
    timeline,
    trace_summaries,
)
from kubeflow_tpu.obs.fleet import GoodputAnnotationPublisher, fleet_cards
from kubeflow_tpu.obs.logging import (
    JsonLogFormatter,
    configure_structured_logging,
)
from kubeflow_tpu.obs.metrics import BucketHistogram, CANONICAL_LABELS
from kubeflow_tpu.obs.profile import (
    PhaseDigest,
    PhaseProfiler,
    memory_watermark,
)
from kubeflow_tpu.obs.recorder import FlightRecorder
from kubeflow_tpu.obs.slo import BurnRateEvaluator, Objective
from kubeflow_tpu.obs.telemetry import GoodputMeter, StepTelemetry
from kubeflow_tpu.obs.trace import (
    TRACE_ANNOTATION,
    Span,
    SpanContext,
    Tracer,
    current_span,
    format_traceparent,
    parse_traceparent,
)

__all__ = [
    "AlertManager",
    "BucketHistogram",
    "BurnRateEvaluator",
    "CANONICAL_LABELS",
    "FlightRecorder",
    "GoodputAnnotationPublisher",
    "GoodputMeter",
    "PhaseDigest",
    "PhaseProfiler",
    "JsonLogFormatter",
    "JsonlExporter",
    "Measurement",
    "MultiExporter",
    "Objective",
    "RingExporter",
    "SloEngine",
    "Span",
    "SpanContext",
    "StepTelemetry",
    "TRACE_ANNOTATION",
    "Tracer",
    "Verdict",
    "configure_structured_logging",
    "current_span",
    "fleet_cards",
    "format_traceparent",
    "get_tracer",
    "host_noise_sentinel",
    "memory_watermark",
    "timed_trials",
    "parse_traceparent",
    "set_tracer",
    "span_tree",
    "timeline",
    "trace_summaries",
]

_PERFWATCH_EXPORTS = {
    "Measurement", "Verdict", "host_noise_sentinel", "timed_trials",
}


def __getattr__(name: str):
    """Perfwatch symbols resolve lazily so ``python -m
    kubeflow_tpu.obs.perfwatch`` (the gate CLI) doesn't import the
    module twice through the package (runpy's double-import warning)."""
    if name in _PERFWATCH_EXPORTS:
        from kubeflow_tpu.obs import perfwatch

        return getattr(perfwatch, name)
    raise AttributeError(name)


_tracer: Tracer | None = None
_tracer_lock = threading.Lock()


def get_tracer() -> Tracer:
    """The process-wide default tracer, created lazily from the OBS_*
    environment (every instrumentation point calls this, so swapping
    the tracer via :func:`set_tracer` re-routes the whole process)."""
    global _tracer
    if _tracer is None:
        with _tracer_lock:
            if _tracer is None:
                exporter = None
                path = os.environ.get("OBS_JSONL_PATH")
                if path:
                    exporter = JsonlExporter(path)
                _tracer = Tracer(exporter=exporter)
    return _tracer


def set_tracer(tracer: Tracer | None) -> None:
    """Replace (or with ``None`` reset) the process-wide tracer —
    tests install a private tracer + exporter and restore after."""
    global _tracer
    with _tracer_lock:
        _tracer = tracer
