"""Structured JSON logging, trace-correlated (stdlib only).

Every record becomes one JSON object with a fixed schema — the contract
``testing/gh-actions/obs_gate.sh`` enforces on tier-1 runs:

    {"ts": <RFC3339 UTC>, "level": "WARNING", "logger": "kubeflow_tpu.x",
     "msg": "...", "trace_id": "...", "span_id": "..."}

``trace_id``/``span_id`` appear whenever a span is active on the
emitting thread (obs.trace contextvar) — the join key between a log
line and the trace that produced it. Caller-supplied ``extra=`` fields
ride along verbatim; unserializable values degrade to ``repr`` rather
than crash the logging path.
"""

from __future__ import annotations

import json
import logging
import time
import traceback

# Keys every structured record carries (the obs gate's schema check).
SCHEMA_KEYS = ("ts", "level", "logger", "msg")

# logging.LogRecord's own attributes: everything else on the record is
# a caller-supplied extra= field and is forwarded into the JSON object.
_RESERVED = frozenset(vars(
    logging.LogRecord("", 0, "", 0, "", (), None)
)) | {"message", "asctime", "taskName"}


class JsonLogFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        doc: dict = {
            "ts": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime(record.created)
            ),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        from kubeflow_tpu.obs.trace import current_span

        span = current_span()
        if span is not None:
            doc["trace_id"] = span.context.trace_id
            doc["span_id"] = span.context.span_id
        for key, value in record.__dict__.items():
            if key not in _RESERVED and key not in doc:
                doc[key] = value
        if record.exc_info and record.exc_info[0] is not None:
            doc["exc"] = "".join(
                traceback.format_exception(*record.exc_info)
            ).rstrip()
        try:
            return json.dumps(doc, default=repr)
        except (TypeError, ValueError):
            # A pathological extra (e.g. a key that is not a string)
            # must not lose the message: fall back to the schema core.
            return json.dumps({k: doc[k] for k in SCHEMA_KEYS})


_CONFIGURED_MARK = "_kubeflow_tpu_obs_handler"


def configure_structured_logging(
    level: int = logging.INFO,
    stream=None,
    logger_name: str = "kubeflow_tpu",
) -> logging.Handler:
    """Attach a JSON handler to the platform's logger tree. Idempotent:
    a second call re-uses the existing handler (entrypoints and tests
    both call it). Returns the handler so callers can retarget or
    detach it."""
    logger = logging.getLogger(logger_name)
    for handler in logger.handlers:
        if getattr(handler, _CONFIGURED_MARK, False):
            handler.setLevel(level)
            logger.setLevel(level)
            return handler
    handler = logging.StreamHandler(stream)
    handler.setFormatter(JsonLogFormatter())
    handler.setLevel(level)
    setattr(handler, _CONFIGURED_MARK, True)
    logger.addHandler(handler)
    logger.setLevel(level)
    # The platform logger owns its records now: without this, the root
    # logger's (basicConfig) handler would print every record a second
    # time, unstructured.
    logger.propagate = False
    return handler
