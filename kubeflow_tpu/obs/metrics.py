"""Metric primitives + the platform-wide label schema (stdlib only).

``BucketHistogram`` is a thread-safe cumulative-bucket histogram for
code that must not depend on prometheus_client (the k8s client, the
workqueue): collectors render its snapshot as a real Prometheus
histogram family at scrape time.

``CANONICAL_LABELS`` is the single label vocabulary every registry in
the platform draws from — asserted by tests/test_obs.py across the
controller-manager, dashboard and CRUD-app registries, so dashboards
can join series across components without per-exporter relabeling.
"""

from __future__ import annotations

import threading

# The only label names any platform collector may use. Object identity
# is always spelled namespace/name/controller (never ns/nb/component);
# the rest are enumerated per-metric dimensions ("phase" is the
# serving scheduler's prefill/decode split — PR 6). "le"/"quantile"
# are the exposition-format internals histograms/summaries emit.
CANONICAL_LABELS = frozenset({
    "namespace", "name", "controller",
    "accelerator", "verb", "kind", "result", "mode", "severity",
    "method", "endpoint", "code", "outcome", "phase",
    "le", "quantile",
})

# Default bounds. Queue latency and reconcile duration share the
# controller-runtime-ish spread (sub-ms dedup hits up to parked-retry
# minutes); apiserver round-trips top out lower.
LATENCY_BUCKETS = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)
REQUEST_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0,
)


class BucketHistogram:
    """Fixed-bucket cumulative histogram: observe / snapshot / quantile.

    The snapshot is exposition-shaped — cumulative counts per upper
    bound, "+Inf" last — so a custom collector can hand it straight to
    ``HistogramMetricFamily.add_metric``."""

    def __init__(self, buckets: tuple[float, ...] = LATENCY_BUCKETS):
        self._bounds = tuple(sorted(float(b) for b in buckets))
        if not self._bounds:
            raise ValueError("at least one bucket bound required")
        self._counts = [0] * (len(self._bounds) + 1)  # +1 = overflow
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        value = float(value)
        idx = len(self._bounds)
        for i, bound in enumerate(self._bounds):
            if value <= bound:
                idx = i
                break
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._count += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def snapshot(self) -> dict:
        """{"count", "sum", "buckets": [("0.005", cum), ..., ("+Inf", n)]}"""
        with self._lock:
            counts = list(self._counts)
            total = self._count
            acc_sum = self._sum
        buckets: list[tuple[str, int]] = []
        cumulative = 0
        for bound, count in zip(self._bounds, counts):
            cumulative += count
            buckets.append((repr(bound), cumulative))
        buckets.append(("+Inf", total))
        return {"count": total, "sum": acc_sum, "buckets": buckets}

    def quantile(self, q: float) -> float:
        """Upper bound of the bucket containing the q-quantile (the
        usual histogram-quantile resolution); inf when it landed in
        the overflow bucket, 0.0 when empty."""
        with self._lock:
            counts = list(self._counts)
            total = self._count
        if total == 0:
            return 0.0
        target = max(1, int(q * total + 0.5))
        cumulative = 0
        for bound, count in zip(self._bounds, counts):
            cumulative += count
            if cumulative >= target:
                return bound
        return float("inf")
