"""Metric primitives + the platform-wide label schema (stdlib only).

``BucketHistogram`` is a thread-safe cumulative-bucket histogram for
code that must not depend on prometheus_client (the k8s client, the
workqueue): collectors render its snapshot as a real Prometheus
histogram family at scrape time.

``CANONICAL_LABELS`` is the single label vocabulary every registry in
the platform draws from — asserted by tests/test_obs.py across the
controller-manager, dashboard and CRUD-app registries, so dashboards
can join series across components without per-exporter relabeling.
"""

from __future__ import annotations

import threading
import time

# The only label names any platform collector may use. Object identity
# is always spelled namespace/name/controller (never ns/nb/component);
# the rest are enumerated per-metric dimensions ("phase" is the
# serving scheduler's prefill/decode split — PR 6; "actuator" is the
# autopilot's bounded actuator-name set — PR 11). "le"/"quantile"
# are the exposition-format internals histograms/summaries emit.
CANONICAL_LABELS = frozenset({
    "namespace", "name", "controller",
    "accelerator", "verb", "kind", "result", "mode", "severity",
    "method", "endpoint", "code", "outcome", "phase", "actuator",
    "le", "quantile",
})

# Default bounds. Queue latency and reconcile duration share the
# controller-runtime-ish spread (sub-ms dedup hits up to parked-retry
# minutes); apiserver round-trips top out lower.
LATENCY_BUCKETS = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)
REQUEST_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0,
)


class BucketHistogram:
    """Fixed-bucket cumulative histogram: observe / snapshot / quantile.

    The snapshot is exposition-shaped — cumulative counts per upper
    bound, "+Inf" last — so a custom collector can hand it straight to
    ``HistogramMetricFamily.add_metric``.

    With ``exemplars=True`` each bucket additionally remembers the most
    recent observation that landed in it together with the trace id
    active at the time (OpenMetrics exemplars): a p99 spike on the
    rendered histogram then links straight to the trace that caused it
    instead of being an anonymous bucket count. Capture is opt-in —
    most histograms have no span in scope and should not pay the
    lookup — and records only *sampled* spans (an unsampled trace id
    resolves to nothing in any exporter, which would send an operator
    hunting for a trace that never existed)."""

    def __init__(self, buckets: tuple[float, ...] = LATENCY_BUCKETS,
                 exemplars: bool = False):
        self._bounds = tuple(sorted(float(b) for b in buckets))
        if not self._bounds:
            raise ValueError("at least one bucket bound required")
        self._counts = [0] * (len(self._bounds) + 1)  # +1 = overflow
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()
        self._exemplars_enabled = bool(exemplars)
        # bucket index -> (trace_id, observed value, unix timestamp)
        self._exemplars: dict[int, tuple[str, float, float]] = {}

    def observe(self, value: float, trace_id: str | None = None) -> None:
        value = float(value)
        idx = len(self._bounds)
        for i, bound in enumerate(self._bounds):
            if value <= bound:
                idx = i
                break
        if self._exemplars_enabled and trace_id is None:
            # Lazy sibling import keeps the no-exemplar path free of it.
            from kubeflow_tpu.obs.trace import current_span

            span = current_span()
            if span is not None and span.context.sampled:
                trace_id = span.context.trace_id
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._count += 1
            if self._exemplars_enabled and trace_id:
                self._exemplars[idx] = (trace_id, value, time.time())

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def snapshot(self) -> dict:
        """{"count", "sum", "buckets": [("0.005", cum), ..., ("+Inf", n)]}
        plus, when exemplar capture is on, ``"exemplars"``: bucket
        upper-bound string -> {"trace_id", "value", "ts"}."""
        with self._lock:
            counts = list(self._counts)
            total = self._count
            acc_sum = self._sum
            exemplars = (
                dict(self._exemplars) if self._exemplars_enabled else None
            )
        buckets: list[tuple[str, int]] = []
        cumulative = 0
        for bound, count in zip(self._bounds, counts):
            cumulative += count
            buckets.append((repr(bound), cumulative))
        buckets.append(("+Inf", total))
        snap = {"count": total, "sum": acc_sum, "buckets": buckets}
        if exemplars is not None:
            labels = [repr(b) for b in self._bounds] + ["+Inf"]
            snap["exemplars"] = {
                labels[idx]: {"trace_id": tid, "value": val, "ts": ts}
                for idx, (tid, val, ts) in sorted(exemplars.items())
            }
        return snap

    def quantile(self, q: float) -> float:
        """Upper bound of the bucket containing the q-quantile (the
        usual histogram-quantile resolution); inf when it landed in
        the overflow bucket, 0.0 when empty."""
        with self._lock:
            counts = list(self._counts)
            total = self._count
        if total == 0:
            return 0.0
        target = max(1, int(q * total + 0.5))
        cumulative = 0
        for bound, count in zip(self._bounds, counts):
            cumulative += count
            if cumulative >= target:
                return bound
        return float("inf")
