"""Perf observatory: noise-banded measurement, anchors, verdicts.

Every bench round before this module was single-shot-and-hope: one
timed pass per section, one scalar anchor per config, and a
``vs_baseline`` ratio that cannot say whether 0.88 is a kernel
regression or a noisy host (BENCH_r03 recorded a decode section 15%
under anchor while a local rerun of the same commit read 25% over).
This module makes the benchmark trajectory a first-class observability
subsystem, the way ``obs/slo.py`` did for SLOs and ``obs/profile.py``
did for hot-loop phases:

- **Multi-trial protocol** — :func:`timed_trials` /
  :class:`Measurement`: warmup runs discarded, N timed trials,
  nearest-rank median (the exact :class:`PhaseDigest` percentile math,
  hand-computable) plus a MAD-derived noise band; trials farther than
  ``reject`` scaled-MADs from the median are dropped and reported, so
  one GC pause or relay hiccup cannot smear the band.
- **Host-noise sentinel** — :func:`host_noise_sentinel` measures what
  the "quiet-host protocol" used to eyeball: timer-tick jitter,
  scheduler sleep overshoot, and background load, graded
  ``quiet``/``noisy``/``loud``. The grade stamps every round and sets
  the verdict tolerance floor (:func:`band_floor_for`) — a loud host
  widens the band instead of minting false regressions.
- **Provenance** — :func:`provenance` records jax/jaxlib versions,
  backend platform, device kind, git revision and the ``KFT_DECODE_*``
  dispatch knobs in effect, so a cross-round comparison can tell a
  kernel change from an image bump or a flipped env flag.
  :func:`provenance_mismatches` is the comparability test the verdict
  engine consults (git rev is informational, never a mismatch).
- **Anchor registry** — ``PERF_ANCHORS.json``
  (:func:`load_anchors` / :func:`pin_anchors`): per-section anchor
  value, noise band and provenance, written atomically
  (tmp + ``os.replace``).
- **Verdict engine** — :func:`classify` / :func:`judge_records`: each
  section reads ``improved`` / ``regressed`` / ``within-noise``
  against its banded anchor (tolerance = anchor band + measurement
  band + the noise-grade floor); a provenance mismatch reads
  ``incomparable``, never ``regressed``. :func:`verdict_exit_code` is
  nonzero exactly when something regressed — the CI perf gate
  (``testing/gh-actions/perf_gate.sh``).
- **Trajectory ledger** — append-only ``PERF_TRAJECTORY.jsonl``
  (:func:`append_ledger`, atomic, deduped on round+section) turning
  BENCH_r01…rNN into one time series; ``python -m
  kubeflow_tpu.obs.perfwatch report`` renders the trend table.

Stdlib + existing obs primitives only; jax is consulted through
``sys.modules`` so a process that never imported it (a remote-target
load client, the control plane) pays nothing.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time
from typing import Callable, Iterable

from kubeflow_tpu.obs.profile import PhaseDigest

SCHEMA = "kft.perfwatch/v1"
ANCHORS_SCHEMA = "kft.perf-anchors/v1"
DEFAULT_ANCHORS_PATH = "PERF_ANCHORS.json"
DEFAULT_TRAJECTORY_PATH = "PERF_TRAJECTORY.jsonl"

# MAD -> sigma-equivalent scale for normally distributed noise; the
# band half-width is MAD_SIGMA * MAD so "one band" reads like one
# standard deviation of a robust estimator, not an outlier-dragged one.
MAD_SIGMA = 1.4826

# Verdict tolerance floor per host-noise grade: even a zero-MAD trial
# set (3 identical readings) cannot honestly claim sub-percent
# resolution, and a loud host cannot claim much at all.
BAND_FLOORS = {"quiet": 0.02, "noisy": 0.05, "loud": 0.10}

# Dispatch-configuration env knobs recorded in provenance: these
# change WHICH kernel path a decode section measures, so two rounds
# differing on any of them are not the same experiment.
PROVENANCE_ENV_PREFIXES = ("KFT_DECODE_",)
PROVENANCE_ENV_EXTRA = ("KFT_BENCH_PRESET", "KFT_BENCH_DECODE_PATH")

GRADES = ("quiet", "noisy", "loud")


# ---------------------------------------------------------------------------
# percentile / band math (PhaseDigest's nearest-rank, reused verbatim)
# ---------------------------------------------------------------------------


def nearest_rank(values: Iterable[float], q: float) -> float:
    """Nearest-rank percentile via :class:`PhaseDigest` — the same
    exact, hand-computable math the profiler digests use (rank
    ``max(1, ceil(q*n))`` over the sorted values)."""
    values = list(values)
    if not values:
        return 0.0
    digest = PhaseDigest(window=len(values))
    for value in values:
        digest.observe(value)
    return digest.percentile(q)


def median_mad(values: Iterable[float]) -> tuple[float, float]:
    """(nearest-rank median, nearest-rank MAD). MAD — the median of
    absolute deviations from the median — is the robust spread
    estimator: one straggler trial moves it far less than a stddev."""
    values = list(values)
    med = nearest_rank(values, 0.5)
    mad = nearest_rank((abs(v - med) for v in values), 0.5)
    return med, mad


def noise_band(values: Iterable[float],
               floor: float | None = None) -> dict:
    """The banded summary of one trial set: median, MAD, relative
    half-width ``rel`` (``MAD_SIGMA * mad / median``, floored at
    ``floor`` when given) and the absolute ``lo``/``hi`` edges."""
    values = list(values)
    med, mad = median_mad(values)
    rel = (MAD_SIGMA * mad / med) if med > 0 else 0.0
    if floor is not None:
        rel = max(rel, float(floor))
    return {
        "n": len(values),
        "median": round(med, 6),
        "mad": round(mad, 6),
        "rel": round(rel, 6),
        "lo": round(med * (1.0 - rel), 6),
        "hi": round(med * (1.0 + rel), 6),
    }


def band_floor_for(grade: str | None) -> float:
    """The verdict tolerance floor this noise grade earns (unknown
    grades read as loud: no grade, no benefit of the doubt)."""
    return BAND_FLOORS.get(grade or "", BAND_FLOORS["loud"])


# ---------------------------------------------------------------------------
# the multi-trial measurement protocol
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Measurement:
    """One section's multi-trial measurement: kept trial values (in
    measurement order), rejected outliers, and the band over the kept
    set. ``median`` is the headline value."""

    values: list[float]
    rejected: list[float]
    median: float
    band: dict
    # Compact per-phase digests (dispatch/sync) when the trials ran
    # under a PhaseProfiler activation (bench KFT_BENCH_TELEMETRY=1).
    phases: dict | None = None

    @classmethod
    def from_values(cls, values: Iterable[float], *,
                    reject: float = 4.0,
                    band_floor: float | None = None) -> "Measurement":
        """Band a raw trial set. Outlier rejection: with >= 4 trials
        (below that every value counts), trials farther than
        ``reject`` scaled-MADs from the median are dropped and the
        band recomputed over the survivors; a degenerate MAD of zero
        rejects nothing (identical trials have no outliers)."""
        values = [float(v) for v in values]
        if not values:
            raise ValueError("a measurement needs at least one trial")
        kept, rejected = values, []
        if len(values) >= 4:
            med, mad = median_mad(values)
            spread = MAD_SIGMA * mad
            if spread > 0:
                kept = [v for v in values
                        if abs(v - med) <= reject * spread]
                rejected = [v for v in values
                            if abs(v - med) > reject * spread]
                if not kept:  # pathological set: keep everything
                    kept, rejected = values, []
        band = noise_band(kept, floor=band_floor)
        return cls(kept, rejected, band["median"], band)

    def as_rate(self, work: float) -> "Measurement":
        """The same trials re-expressed as ``work / seconds`` (trials
        are usually timed in seconds; records usually report rates).
        Outliers were already rejected on the time axis."""
        rate = Measurement.from_values(
            [work / v for v in self.values if v > 0], reject=float("inf")
        )
        rate.phases = self.phases
        return rate

    def to_dict(self, ndigits: int = 6) -> dict:
        out = {
            "trials": [round(v, ndigits) for v in self.values],
            "band": self.band,
        }
        if self.rejected:
            out["rejected_trials"] = [
                round(v, ndigits) for v in self.rejected
            ]
        if self.phases:
            out["phases"] = self.phases
        return out


def timed_trials(thunk: Callable[[], object], *, trials: int = 3,
                 warmup: int = 0,
                 clock: Callable[[], float] = time.perf_counter,
                 reject: float = 4.0,
                 band_floor: float | None = None) -> Measurement:
    """THE measurement protocol: run ``thunk`` ``warmup`` times
    untimed (compile, caches, first-touch stragglers), then ``trials``
    timed passes, and band the per-trial seconds. ``thunk`` must force
    its own completion (device_get on the result — the bench relay
    rule); the clock pair wraps exactly one trial."""
    for _ in range(max(0, int(warmup))):
        thunk()
    seconds = []
    for _trial in range(max(1, int(trials))):
        t0 = clock()
        thunk()
        seconds.append(clock() - t0)
    return Measurement.from_values(seconds, reject=reject,
                                   band_floor=band_floor)


# ---------------------------------------------------------------------------
# host-noise sentinel
# ---------------------------------------------------------------------------


def host_noise_sentinel(*, spin_samples: int = 4000, sleeps: int = 5,
                        sleep_s: float = 0.001,
                        clock: Callable[[], float] = time.perf_counter,
                        sleep: Callable[[float], None] = time.sleep,
                        loadavg: Callable[[], tuple] | None = None,
                        cpu_count: Callable[[], int | None] | None = None,
                        ) -> dict:
    """Measure the host, not the kernel: timer-tick jitter (p99 of
    successive ``clock()`` deltas over a tight spin), scheduler noise
    (p90 overshoot of a 1 ms sleep — a loaded box hands the CPU back
    late), and 1-minute load per core. The ``grade`` automates the
    quiet-host protocol BASELINE.md used to invoke by hand; every
    collaborator is injectable so tests grade deterministically."""
    deltas: list[float] = []
    prev = clock()
    for _ in range(max(2, int(spin_samples))):
        now = clock()
        if now > prev:
            deltas.append(now - prev)
        prev = now
    timer_p99 = nearest_rank(deltas, 0.99) if deltas else 0.0

    overshoots: list[float] = []
    for _ in range(max(0, int(sleeps))):
        t0 = clock()
        sleep(sleep_s)
        overshoots.append(max(clock() - t0 - sleep_s, 0.0))
    overshoot_p90 = nearest_rank(overshoots, 0.90) if overshoots else 0.0

    load1 = None
    try:
        load1 = float((loadavg or os.getloadavg)()[0])
    except (OSError, AttributeError):  # platform without loadavg
        load1 = None
    cpus = (cpu_count or os.cpu_count)() or 1
    load_ratio = (load1 / cpus) if load1 is not None else None

    if (load_ratio is not None and load_ratio >= 1.0) \
            or overshoot_p90 >= 0.020:
        grade = "loud"
    elif (load_ratio is not None and load_ratio >= 0.25) \
            or overshoot_p90 >= 0.002:
        grade = "noisy"
    else:
        grade = "quiet"
    return {
        "grade": grade,
        "timer_p99_s": round(timer_p99, 9),
        "sched_overshoot_p90_s": round(overshoot_p90, 6),
        "load1": round(load1, 3) if load1 is not None else None,
        "cpus": cpus,
        "load_ratio": round(load_ratio, 4)
        if load_ratio is not None else None,
    }


# ---------------------------------------------------------------------------
# provenance
# ---------------------------------------------------------------------------


def _git_rev(start: str | None = None) -> str | None:
    """Current git revision, stdlib-only: walk up to ``.git``, read
    HEAD, dereference one level. None outside a checkout."""
    directory = os.path.abspath(start or os.getcwd())
    while True:
        git_dir = os.path.join(directory, ".git")
        if os.path.isdir(git_dir):
            break
        parent = os.path.dirname(directory)
        if parent == directory:
            return None
        directory = parent
    try:
        with open(os.path.join(git_dir, "HEAD")) as fh:
            head = fh.read().strip()
        if head.startswith("ref:"):
            ref = head.split(None, 1)[1]
            ref_path = os.path.join(git_dir, *ref.split("/"))
            if os.path.exists(ref_path):
                with open(ref_path) as fh:
                    return fh.read().strip()
            packed = os.path.join(git_dir, "packed-refs")
            with open(packed) as fh:
                for line in fh:
                    if line.strip().endswith(ref):
                        return line.split()[0]
            return None
        return head
    except (OSError, IndexError):
        return None


def provenance(env: dict | None = None) -> dict:
    """The record's "what was measured under" block: jax/jaxlib
    versions, backend platform + device kind, git revision, and every
    dispatch-relevant env knob in effect (``KFT_DECODE_*`` plus the
    explicit extras). jax is read from ``sys.modules`` only — a
    process that never imported it reports ``platform: None`` instead
    of paying the import."""
    environ = os.environ if env is None else env
    knobs = {
        key: environ[key]
        for key in sorted(environ)
        if key.startswith(PROVENANCE_ENV_PREFIXES)
        or key in PROVENANCE_ENV_EXTRA
    }
    out: dict = {
        "git_rev": _git_rev(),
        "python": ".".join(str(v) for v in sys.version_info[:3]),
        "jax": None,
        "jaxlib": None,
        "platform": None,
        "device": None,
        "env": knobs,
    }
    jax = sys.modules.get("jax")
    if jax is not None:
        out["jax"] = getattr(jax, "__version__", None)
        jaxlib = sys.modules.get("jaxlib")
        if jaxlib is None:
            try:
                import jaxlib  # cheap: jax already imported it
            except ImportError:
                jaxlib = None
        out["jaxlib"] = getattr(jaxlib, "__version__", None)
        try:
            out["platform"] = jax.default_backend()
            devices = jax.devices()
            out["device"] = str(
                getattr(devices[0], "device_kind", "")
            ) or None
        except RuntimeError:  # no initialized backend
            pass
    return out


# Fields whose mismatch makes two rounds different experiments. The
# git rev is deliberately absent: code changes are exactly what a
# verdict is supposed to judge, not refuse to judge.
COMPARABILITY_FIELDS = ("platform", "device", "jax", "jaxlib")


def provenance_mismatches(measured: dict | None,
                          anchored: dict | None) -> list[str]:
    """Fields on which the two provenance blocks disagree — nonempty
    means 'incomparable', the verdict that tells an image bump or a
    flipped KFT_DECODE_* knob apart from a kernel regression."""
    a, b = measured or {}, anchored or {}
    mismatched = [
        field for field in COMPARABILITY_FIELDS
        if a.get(field) != b.get(field)
    ]
    env_a = a.get("env") or {}
    env_b = b.get("env") or {}
    for key in sorted(set(env_a) | set(env_b)):
        if env_a.get(key) != env_b.get(key):
            mismatched.append(f"env:{key}")
    return mismatched


# ---------------------------------------------------------------------------
# records
# ---------------------------------------------------------------------------


def make_record(section: str, metric: str, unit: str,
                measurement: Measurement, *, noise: dict | None = None,
                prov: dict | None = None,
                extra: dict | None = None) -> dict:
    """One schema'd perfwatch record — the shape bench sections, the
    serve_qps gateway summary, and any future perf source share, so
    one verdict engine and one ledger serve them all."""
    record = dict(extra or {})
    record.update({
        "schema": SCHEMA,
        "section": section,
        "metric": metric,
        "unit": unit,
        # 6 digits, matching the band edges: coarser rounding can push
        # a seconds-scale value outside its own lo..hi band.
        "value": round(measurement.median, 6),
        **measurement.to_dict(),
        "noise": noise if noise is not None else host_noise_sentinel(),
        "provenance": prov if prov is not None else provenance(),
    })
    return record


def validate_record(record: object) -> list[str]:
    """Schema check; returns the list of problems (empty == valid).
    Extra keys are always fine — the schema is a floor, not a fence."""
    problems: list[str] = []
    if not isinstance(record, dict):
        return ["record is not an object"]

    def _number(value) -> bool:
        return isinstance(value, (int, float)) \
            and not isinstance(value, bool)

    if record.get("schema") != SCHEMA:
        problems.append(f"schema must be {SCHEMA!r}")
    for key in ("section", "metric", "unit"):
        if not (isinstance(record.get(key), str) and record.get(key)):
            problems.append(f"{key} must be a non-empty string")
    if not (_number(record.get("value")) and record.get("value", -1) >= 0):
        problems.append("value must be a non-negative number")
    trials = record.get("trials")
    if not (isinstance(trials, list) and trials
            and all(_number(t) for t in trials)):
        problems.append("trials must be a non-empty list of numbers")
    band = record.get("band")
    if not isinstance(band, dict):
        problems.append("band must be an object")
    else:
        for key in ("n", "median", "mad", "rel", "lo", "hi"):
            if not _number(band.get(key)):
                problems.append(f"band.{key} must be a number")
        if _number(band.get("lo")) and _number(band.get("hi")) \
                and band["lo"] > band["hi"]:
            problems.append("band.lo must not exceed band.hi")
    noise = record.get("noise")
    if not (isinstance(noise, dict) and noise.get("grade") in GRADES):
        problems.append(
            "noise.grade must be one of " + "/".join(GRADES)
        )
    prov = record.get("provenance")
    if not isinstance(prov, dict):
        problems.append("provenance must be an object")
    else:
        for key in ("git_rev", "platform", "env"):
            if key not in prov:
                problems.append(f"provenance.{key} missing")
    return problems


def records_from_full(doc: dict) -> list[dict]:
    """The judge's view of one bench full record: the primary-metric
    record plus every section in ``extra_metrics`` that carries a
    ``section`` name (error entries and pre-protocol records without
    one are skipped — nothing to band a verdict on)."""
    out = []
    for record in [doc] + list(doc.get("extra_metrics") or []):
        if record.get("metric") == "bench_extra_error":
            continue
        if record.get("section") and record.get("value") is not None:
            out.append(record)
    return out


# ---------------------------------------------------------------------------
# anchor registry
# ---------------------------------------------------------------------------


def _atomic_write_json(path: str, doc: dict) -> None:
    """tmp + ``os.replace`` — the PR-4 write discipline: the rename is
    the commit point, a crash mid-write never tears the artifact."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)


def load_anchors(path: str = DEFAULT_ANCHORS_PATH) -> dict:
    """The anchor registry document ({schema, round, anchors:{section:
    {value, unit, band_rel, noise_grade, pinned_round, provenance}}});
    an absent file is an empty registry."""
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except FileNotFoundError:
        return {"schema": ANCHORS_SCHEMA, "round": None, "anchors": {}}
    if not isinstance(doc, dict) or not isinstance(
            doc.get("anchors"), dict):
        raise ValueError(
            f"anchor registry {path} is not a valid document"
        )
    return doc


def pin_anchors(records: list[dict], round_id: str, *,
                path: str = DEFAULT_ANCHORS_PATH,
                sections: list[str] | None = None) -> dict:
    """Re-pin anchors from measured records (all of them, or only the
    named ``sections``): value, band, noise grade and provenance land
    in the registry under ``pinned_round``; untouched sections keep
    their existing pins. Atomic write; returns the new document."""
    doc = load_anchors(path)
    doc["schema"] = ANCHORS_SCHEMA
    doc["round"] = round_id
    wanted = set(sections) if sections is not None else None
    pinned = 0
    for record in records:
        section = record.get("section")
        if not section or (wanted is not None and section not in wanted):
            continue
        band = record.get("band") or {}
        doc["anchors"][section] = {
            "value": record.get("value"),
            "unit": record.get("unit"),
            "band_rel": band.get("rel", 0.0),
            "noise_grade": (record.get("noise") or {}).get("grade"),
            "pinned_round": round_id,
            "provenance": record.get("provenance"),
        }
        pinned += 1
    if wanted is not None and pinned < len(wanted):
        missing = sorted(
            wanted - {r.get("section") for r in records}
        )
        raise ValueError(
            f"sections not present in the record: {', '.join(missing)}"
        )
    _atomic_write_json(path, doc)
    return doc


# ---------------------------------------------------------------------------
# verdict engine
# ---------------------------------------------------------------------------

IMPROVED = "improved"
REGRESSED = "regressed"
WITHIN_NOISE = "within-noise"
INCOMPARABLE = "incomparable"
NEW_SECTION = "new-section"
MISSING_SECTION = "missing-section"


@dataclasses.dataclass
class Verdict:
    section: str
    status: str
    value: float | None = None
    anchor: float | None = None
    ratio: float | None = None
    tolerance: float | None = None
    notes: str = ""

    def render(self) -> str:
        parts = [f"{self.section}: {self.status}"]
        if self.ratio is not None and self.tolerance is not None:
            parts.append(
                f"(x{self.ratio:.4f} vs anchor {self.anchor}, "
                f"tolerance ±{100 * self.tolerance:.1f}%)"
            )
        if self.notes:
            parts.append(f"— {self.notes}")
        return " ".join(parts)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def classify(record: dict, anchor: dict | None) -> Verdict:
    """One section against its banded anchor. The tolerance is the sum
    of the anchor's band, the measurement's band, and the noise-grade
    floor of the LOUDER of the two rounds — two honest bands plus a
    floor neither round can undercut. Provenance mismatch short-
    circuits to ``incomparable``: re-pin (legitimately) instead of
    arguing with a different experiment."""
    section = str(record.get("section") or record.get("metric") or "?")
    value = record.get("value")
    if anchor is None or anchor.get("value") in (None, 0):
        return Verdict(section, NEW_SECTION, value=value,
                       notes="no anchor pinned for this section")
    mismatched = provenance_mismatches(
        record.get("provenance"), anchor.get("provenance")
    )
    if mismatched:
        return Verdict(
            section, INCOMPARABLE, value=value,
            anchor=anchor.get("value"),
            notes="provenance mismatch on " + ", ".join(mismatched),
        )
    anchor_value = float(anchor["value"])
    measured_band = float((record.get("band") or {}).get("rel") or 0.0)
    anchor_band = float(anchor.get("band_rel") or 0.0)
    floor = max(
        band_floor_for((record.get("noise") or {}).get("grade")),
        band_floor_for(anchor.get("noise_grade")),
    )
    tolerance = anchor_band + measured_band + floor
    ratio = float(value) / anchor_value
    if ratio >= 1.0 + tolerance:
        status = IMPROVED
    elif ratio <= 1.0 - tolerance:
        status = REGRESSED
    else:
        status = WITHIN_NOISE
    return Verdict(section, status, value=value, anchor=anchor_value,
                   ratio=round(ratio, 6), tolerance=round(tolerance, 6))


def judge_records(records: list[dict], anchors_doc: dict,
                  sections: list[str] | None = None) -> list[Verdict]:
    """Every record against the registry, plus a ``missing-section``
    verdict for each anchored section the round failed to measure — a
    silently vanished section must not read as a green round."""
    anchors = anchors_doc.get("anchors") or {}
    wanted = set(sections) if sections is not None else None
    verdicts = []
    seen = set()
    for record in records:
        section = record.get("section")
        if not section or (wanted is not None and section not in wanted):
            continue
        seen.add(section)
        verdicts.append(classify(record, anchors.get(section)))
    for section in sorted(anchors):
        if section in seen or (wanted is not None
                               and section not in wanted):
            continue
        verdicts.append(Verdict(
            section, MISSING_SECTION,
            anchor=(anchors[section] or {}).get("value"),
            notes="anchored section absent from this round",
        ))
    return verdicts


def verdict_exit_code(verdicts: list[Verdict]) -> int:
    """Nonzero exactly when a section regressed — the gate contract.
    ``incomparable``/``missing-section`` inform loudly but do not
    gate (they have their own remedies: re-pin, or fix the section)."""
    return 1 if any(v.status == REGRESSED for v in verdicts) else 0


# ---------------------------------------------------------------------------
# trajectory ledger
# ---------------------------------------------------------------------------


def ledger_entry(round_id: str, section: str, value: float, *,
                 unit: str | None = None, vs: float | None = None,
                 band_rel: float | None = None,
                 noise_grade: str | None = None,
                 source: str | None = None) -> dict:
    entry: dict = {"round": round_id, "section": section,
                   "value": value}
    if unit is not None:
        entry["unit"] = unit
    if vs is not None:
        entry["vs"] = vs
    if band_rel is not None:
        entry["band_rel"] = band_rel
    if noise_grade is not None:
        entry["noise_grade"] = noise_grade
    if source is not None:
        entry["source"] = source
    return entry


def read_ledger(path: str = DEFAULT_TRAJECTORY_PATH) -> list[dict]:
    """Every well-formed line of the ledger, in file order (a torn or
    hand-mangled line is skipped, not fatal — the ledger is evidence,
    and partial evidence beats none)."""
    entries: list[dict] = []
    try:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    entry = json.loads(line)
                except ValueError:
                    continue
                if isinstance(entry, dict):
                    entries.append(entry)
    except FileNotFoundError:
        pass
    return entries


def append_ledger(path: str, entries: list[dict]) -> int:
    """Append entries not already present (identity: round + section +
    source), atomically: the whole new file is written to a tmp name
    and ``os.replace``d over the old — the PR-4 discipline, so a
    crash mid-append can never leave a half-written line for
    ``read_ledger`` to skip silently forever. Returns how many
    entries were actually appended."""
    existing = read_ledger(path)
    present = {
        (e.get("round"), e.get("section"), e.get("source"))
        for e in existing
    }
    fresh = [
        e for e in entries
        if (e.get("round"), e.get("section"), e.get("source"))
        not in present
    ]
    if not fresh:
        return 0
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        for entry in existing + fresh:
            fh.write(json.dumps(entry, sort_keys=True) + "\n")
    os.replace(tmp, path)
    return len(fresh)


def _short_section(metric_name: str) -> str:
    """The compact section key bench.py's compact_record uses
    ("lm_decode_tokens_per_sec_per_chip[b1-p8k]" -> "decode[b1-p8k]");
    kept in lockstep so ledger rows join across round formats."""
    return (metric_name.replace("lm_", "", 1)
            .replace("_tokens_per_sec_per_chip", ""))


def entries_from_driver_round(doc: dict, round_id: str,
                              source: str | None = None) -> list[dict]:
    """Ledger entries from a committed BENCH_rNN.json driver capture
    (the ``parsed`` compact line: headline + per-section {v, vs})."""
    parsed = doc.get("parsed") or {}
    entries: list[dict] = []
    if parsed.get("value") is not None:
        entries.append(ledger_entry(
            round_id, "resnet", parsed["value"],
            unit=parsed.get("unit"), vs=parsed.get("vs_baseline"),
            source=source,
        ))
    for section, row in (parsed.get("sections") or {}).items():
        if not isinstance(row, dict) or row.get("v") is None:
            continue
        entries.append(ledger_entry(
            round_id, section, row["v"], vs=row.get("vs"),
            source=source,
        ))
    return entries


def entries_from_full_record(doc: dict, round_id: str,
                             source: str | None = None) -> list[dict]:
    """Ledger entries from a protocol-era full bench record — these
    carry bands and the round's noise grade alongside value/vs."""
    entries: list[dict] = []
    for record in records_from_full(doc):
        section = record["section"]
        if section != "resnet":
            section = _short_section(section) \
                if section.startswith("lm_") else section
        entries.append(ledger_entry(
            round_id, section, record["value"],
            unit=record.get("unit"), vs=record.get("vs_baseline"),
            band_rel=(record.get("band") or {}).get("rel"),
            noise_grade=(record.get("noise") or {}).get("grade"),
            source=source,
        ))
    return entries


def render_trend(entries: list[dict]) -> str:
    """The trajectory as one table: rows = sections (first-seen
    order), columns = rounds (sorted), cell = value with the
    vs-baseline ratio when recorded. BENCH_r01…rNN as one readable
    time series instead of N disconnected files."""
    if not entries:
        return "(empty trajectory ledger)"
    rounds: list[str] = []
    sections: list[str] = []
    cells: dict[tuple[str, str], str] = {}
    for entry in entries:
        round_id = str(entry.get("round"))
        section = str(entry.get("section"))
        if round_id not in rounds:
            rounds.append(round_id)
        if section not in sections:
            sections.append(section)
        value = entry.get("value")
        cell = f"{value:g}" if isinstance(value, (int, float)) else "?"
        if entry.get("vs") is not None:
            cell += f" ({entry['vs']:.2f}x)"
        cells[(section, round_id)] = cell
    rounds.sort()
    width = max(len(s) for s in sections) + 2
    col_widths = {
        r: max(len(r), *(len(cells.get((s, r), "-")) for s in sections))
        + 2
        for r in rounds
    }
    lines = ["".join(["section".ljust(width)]
                     + [r.rjust(col_widths[r]) for r in rounds])]
    for section in sections:
        lines.append("".join(
            [section.ljust(width)]
            + [cells.get((section, r), "-").rjust(col_widths[r])
               for r in rounds]
        ))
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def _load_json(path: str) -> dict:
    with open(path) as fh:
        return json.load(fh)


def _round_id_for(path: str) -> str:
    """BENCH_r04.json -> r04 (the backfill default)."""
    base = os.path.basename(path)
    stem = base.split(".")[0]
    tail = stem.rsplit("_", 1)[-1]
    return tail if tail.startswith("r") else stem


def main(argv: list[str] | None = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m kubeflow_tpu.obs.perfwatch",
        description=__doc__.splitlines()[0],
    )
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("noise", help="measure + print the host-noise "
                                     "sentinel document")

    p = sub.add_parser("verdict", help="judge a bench record against "
                                       "the anchor registry; exit 1 "
                                       "on any regression")
    p.add_argument("--record", required=True,
                   help="full bench record (testing/bench_full.json)")
    p.add_argument("--anchors", default=DEFAULT_ANCHORS_PATH)
    p.add_argument("--sections", nargs="*", default=None)
    p.add_argument("--json", action="store_true",
                   help="machine-readable verdicts")

    p = sub.add_parser("pin", help="re-pin anchors from a measured "
                                   "record (value + band + provenance)")
    p.add_argument("--record", required=True)
    p.add_argument("--round", required=True, dest="round_id")
    p.add_argument("--anchors", default=DEFAULT_ANCHORS_PATH)
    p.add_argument("--sections", nargs="*", default=None)

    p = sub.add_parser("ingest", help="append a protocol-era full "
                                      "record to the trajectory ledger")
    p.add_argument("--record", required=True)
    p.add_argument("--round", required=True, dest="round_id")
    p.add_argument("--ledger", default=DEFAULT_TRAJECTORY_PATH)
    p.add_argument("--source", default=None)

    p = sub.add_parser("backfill", help="rebuild ledger entries from "
                                        "committed BENCH_rNN.json "
                                        "driver captures")
    p.add_argument("rounds", nargs="+",
                   help="BENCH_rNN.json files (round id from the name)")
    p.add_argument("--ledger", default=DEFAULT_TRAJECTORY_PATH)

    p = sub.add_parser("report", help="render the trajectory ledger "
                                      "as one trend table")
    p.add_argument("--ledger", default=DEFAULT_TRAJECTORY_PATH)

    args = parser.parse_args(argv)

    if args.command == "noise":
        print(json.dumps(host_noise_sentinel(), indent=1))
        return 0

    if args.command == "verdict":
        records = records_from_full(_load_json(args.record))
        verdicts = judge_records(records, load_anchors(args.anchors),
                                 sections=args.sections)
        if args.json:
            print(json.dumps([v.to_dict() for v in verdicts], indent=1))
        else:
            for verdict in verdicts:
                print(verdict.render())
            counts: dict[str, int] = {}
            for verdict in verdicts:
                counts[verdict.status] = counts.get(verdict.status, 0) + 1
            print("summary: " + ", ".join(
                f"{counts[s]} {s}" for s in sorted(counts)
            ))
        return verdict_exit_code(verdicts)

    if args.command == "pin":
        records = records_from_full(_load_json(args.record))
        doc = pin_anchors(records, args.round_id, path=args.anchors,
                          sections=args.sections)
        print(f"pinned {len(doc['anchors'])} anchor(s) "
              f"(round {args.round_id}) -> {args.anchors}")
        return 0

    if args.command == "ingest":
        entries = entries_from_full_record(
            _load_json(args.record), args.round_id, source=args.source
        )
        added = append_ledger(args.ledger, entries)
        print(f"appended {added} entr(ies) -> {args.ledger}")
        return 0

    if args.command == "backfill":
        added = 0
        for path in args.rounds:
            doc = _load_json(path)
            added += append_ledger(args.ledger, entries_from_driver_round(
                doc, _round_id_for(path), source=os.path.basename(path)
            ))
        print(f"appended {added} entr(ies) -> {args.ledger}")
        return 0

    if args.command == "report":
        print(render_trend(read_ledger(args.ledger)))
        return 0

    parser.error(f"unknown command {args.command!r}")
    return 2


if __name__ == "__main__":
    try:
        rc = main()
    except BrokenPipeError:
        # `perfwatch report | head` closing the pipe is not an error;
        # point stdout at devnull so the interpreter's exit flush
        # doesn't raise a second time.
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        rc = 0
    raise SystemExit(rc)
