"""Alert state machine over burn-rate evaluations (stdlib only).

:class:`AlertManager` consumes the rows :meth:`BurnRateEvaluator.tick`
produces and tracks one alert per (objective, window pair):

    inactive --condition--> pending --held for_s--> firing
    pending --clear--> inactive
    firing --clear for clear_s--> resolved (-> inactive)

Hysteresis on both edges is deliberate: ``for_s`` keeps a single bad
scrape from paging, ``clear_s`` keeps a flapping recovery from
resolve/refire spam. Every transition is appended to a bounded history,
emitted as a structured log record, and stamped as a zero-duration span
on the obs tracer so an alert shows up in the same trace timeline as
the reconciles and apiserver calls that caused it.

:class:`SloEngine` is the composition the manager and the serving
gateway embed: evaluator + alert manager + a self-rate-limited ``tick``
safe to call from hot paths (controller tick hooks, scrape handlers).

Actuation (PR 11): :meth:`AlertManager.subscribe` registers callbacks
invoked once per transition, OUTSIDE the manager lock (the same
discipline the flight-recorder dump follows) — the autopilot's
actuators ride the exact pending→firing edges that trigger black-box
dumps. A failing subscriber is logged and isolated: it can never block
alert evaluation or the other subscribers.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Callable

from kubeflow_tpu.obs.slo import BurnRateEvaluator

log = logging.getLogger(__name__)

INACTIVE = "inactive"
PENDING = "pending"
FIRING = "firing"

STATE_VALUE = {INACTIVE: 0, PENDING: 1, FIRING: 2}


class AlertManager:
    """Pending/firing/resolved tracking for every (slo, speed) pair."""

    def __init__(
        self,
        clock: Callable[[], float] = time.monotonic,
        tracer=None,
        history_limit: int = 256,
    ):
        self.clock = clock
        self._tracer = tracer
        # (slo, speed) -> alert record (mutated in place).
        self._alerts: dict[tuple[str, str], dict] = {}
        # Transition subscribers: one entry per registered actuator,
        # fixed at wiring time.  # analysis: allow[py-unbounded-deque]
        self._subscribers: list[Callable[[dict], None]] = []
        self.history: deque = deque(maxlen=max(1, int(history_limit)))
        # update() runs on controller tick / scrape threads while
        # /fleet and /debug/alerts read on HTTP handler threads;
        # iterating _alerts/history during an insert/append raises
        # RuntimeError, so writes and read snapshots share this lock.
        self._lock = threading.Lock()

    # ---- subscriptions ---------------------------------------------------
    def subscribe(self, callback: Callable[[dict], None]):
        """Register ``callback(transition_event)`` for every state
        transition this manager records. Callbacks run on whatever
        thread called :meth:`update` (controller tick hooks, scrape
        handlers), OUTSIDE the manager lock — a callback may read the
        alert state back (``state_of``/``active``) without deadlock,
        and a slow actuator never stalls evaluation. Exceptions are
        logged and isolated per callback. Returns ``callback`` so the
        method composes as a decorator."""
        with self._lock:
            self._subscribers.append(callback)
        return callback

    # ---- updates ---------------------------------------------------------
    def update(self, rows: list[dict], now: float | None = None,
               notify: bool = True) -> list[dict]:
        """Advance every alert against one evaluation; returns the
        transitions that happened (also recorded in ``history``).
        With ``notify`` (the default) subscribers are dispatched here,
        outside this manager's lock; a caller holding its OWN lock
        around ``update`` (``SloEngine.tick``) passes ``notify=False``
        and calls :meth:`notify` after releasing it — subscriber code
        must never run under ANY evaluation lock."""
        now = self.clock() if now is None else now
        transitions: list[dict] = []
        with self._lock:
            self._update_locked(rows, now, transitions)
        if notify:
            self.notify(transitions)
        return transitions

    def notify(self, transitions: list[dict]) -> None:
        """Dispatch ``transitions`` to every subscriber (the dump
        discipline: no lock held — actuators routinely read alert
        state back, tick the owning engine, and perform their own
        locked bookkeeping). Exceptions are logged and isolated per
        callback."""
        if not transitions:
            return
        with self._lock:
            subscribers = list(self._subscribers)
        for transition in transitions:
            for callback in subscribers:
                try:
                    callback(transition)
                except Exception:
                    # One failing actuator must never block alerting or
                    # the other actuators.
                    log.exception(
                        "alert subscriber %r failed on %s/%s -> %s",
                        callback, transition["slo"],
                        transition["speed"], transition["to"],
                    )

    def _update_locked(self, rows: list[dict], now: float,
                       transitions: list[dict]) -> None:
        for row in rows:
            for speed, win in row.get("windows", {}).items():
                key = (row["slo"], speed)
                alert = self._alerts.get(key)
                if alert is None:
                    alert = self._alerts[key] = {
                        "slo": row["slo"],
                        "speed": speed,
                        "severity": win.get("severity", "warning"),
                        "namespace": row.get("namespace"),
                        "state": INACTIVE,
                        "since": now,
                        "pending_since": None,
                        "clear_since": None,
                        "burn": 0.0,
                    }
                alert["burn"] = win.get("burn", 0.0)
                alert["factor"] = win.get("factor")
                alert["namespace"] = row.get("namespace")
                if win.get("violated"):
                    alert["clear_since"] = None
                    if alert["state"] == INACTIVE:
                        alert["pending_since"] = now
                        self._move(alert, PENDING, now, transitions)
                    if (
                        alert["state"] == PENDING
                        and now - alert["pending_since"]
                        >= win.get("for_s", 0.0)
                    ):
                        self._move(alert, FIRING, now, transitions)
                else:
                    if alert["state"] == PENDING:
                        alert["pending_since"] = None
                        self._move(alert, INACTIVE, now, transitions)
                    elif alert["state"] == FIRING:
                        if alert["clear_since"] is None:
                            alert["clear_since"] = now
                        if (
                            now - alert["clear_since"]
                            >= win.get("clear_s", 0.0)
                        ):
                            self._move(alert, INACTIVE, now, transitions,
                                       resolved=True)

    def _move(self, alert: dict, state: str, now: float,
              transitions: list[dict], resolved: bool = False) -> None:
        previous = alert["state"]
        alert["state"] = state
        alert["since"] = now
        event = {
            "kind": "slo_alert",
            "slo": alert["slo"],
            "speed": alert["speed"],
            "severity": alert["severity"],
            "namespace": alert.get("namespace"),
            "from": previous,
            "to": "resolved" if resolved else state,
            "burn": round(float(alert.get("burn", 0.0)), 3),
            "at": now,
        }
        self.history.append(event)
        transitions.append(event)
        level = logging.WARNING if state == FIRING else logging.INFO
        log.log(
            level,
            "slo alert %s: %s/%s (severity=%s burn=%.1fx namespace=%s)",
            event["to"], alert["slo"], alert["speed"], alert["severity"],
            event["burn"], alert.get("namespace") or "-",
        )
        self._emit_span(event)

    def _emit_span(self, event: dict) -> None:
        from kubeflow_tpu import obs

        tracer = self._tracer if self._tracer is not None else obs.get_tracer()
        try:
            # A zero-duration root span: alert transitions land in the
            # same ring/JSONL stream as the work that caused them.
            span = tracer.start_span(
                "slo alert", parent=None,
                attributes={
                    "name": event["slo"],
                    "mode": event["speed"],
                    "severity": event["severity"],
                    "result": event["to"],
                },
            )
            span.end()
        except Exception:
            log.debug("slo alert span emit failed", exc_info=True)

    # ---- reads (snapshots under the writer lock) -------------------------
    def all(self) -> list[dict]:
        with self._lock:
            return [dict(a) for a in self._alerts.values()]

    def active(self) -> list[dict]:
        """Alerts currently pending or firing."""
        with self._lock:
            return [dict(a) for a in self._alerts.values()
                    if a["state"] != INACTIVE]

    def firing(self) -> list[dict]:
        with self._lock:
            return [dict(a) for a in self._alerts.values()
                    if a["state"] == FIRING]

    def state_of(self, slo: str, speed: str) -> str:
        with self._lock:
            alert = self._alerts.get((slo, speed))
            return alert["state"] if alert else INACTIVE

    def to_dict(self) -> dict:
        """The ``/debug/alerts`` document."""
        with self._lock:
            alerts = [dict(a) for a in self._alerts.values()]
            history = list(self.history)
        return {
            "alerts": sorted(alerts, key=lambda a: (a["slo"], a["speed"])),
            "history": history,
        }


class SloEngine:
    """Evaluator + alerts behind one self-rate-limited ``tick``.

    ``tick`` is wired into controller tick hooks and scrape handlers —
    call sites that fire tens of times per second — so it samples at
    most every ``min_interval_s`` unless forced (tests force with an
    explicit ``now``)."""

    def __init__(
        self,
        evaluator: BurnRateEvaluator | None = None,
        alerts: AlertManager | None = None,
        min_interval_s: float = 5.0,
        clock: Callable[[], float] | None = None,
        recorder=None,
    ):
        self.evaluator = evaluator or BurnRateEvaluator()
        if clock is None:
            clock = self.evaluator.clock
        self.clock = clock
        self.alerts = alerts or AlertManager(clock=clock)
        self.min_interval_s = float(min_interval_s)
        # Black-box capture (obs.recorder.FlightRecorder): a pending→
        # firing transition dumps the recorder's ring as a JSONL
        # artifact — the window leading up to the alert, captured
        # before anyone asks. The recorder rate-limits itself; a
        # failed/suppressed dump never fails the tick.
        self.recorder = recorder
        # tick() is called from HTTP handler threads (/fleet, /metrics)
        # and controller tick hooks concurrently; one lock serializes
        # the sample→evaluate→alert pipeline and the last_rows publish.
        self._lock = threading.Lock()
        self._last_tick: float | None = None
        self.last_rows: list[dict] = []

    def register(self, objective):
        return self.evaluator.register(objective)

    def tick(self, now: float | None = None) -> list[dict]:
        """Sample, evaluate, advance alerts. An explicit ``now`` always
        runs (deterministic tests drive the clock themselves); without
        one the call is rate-limited to ``min_interval_s``."""
        forced = now is not None
        now = self.clock() if now is None else now
        with self._lock:
            if (
                not forced
                and self._last_tick is not None
                and now - self._last_tick < self.min_interval_s
            ):
                return self.last_rows
            self._last_tick = now
            self.last_rows = self.evaluator.tick(now)
            # notify=False: subscriber dispatch must not run under
            # THIS engine's lock either — an actuator reading
            # signal()/status() back would deadlock, and a slow one
            # would stall every concurrent /v1/status, /fleet and
            # scrape tick. Dispatched below, after release.
            transitions = self.alerts.update(self.last_rows, now,
                                             notify=False)
            rows = self.last_rows
        # Subscribers first (their actions land in the flight ring),
        # then the dump — a black box captured for this very edge
        # carries the actuations it triggered. Both run OUTSIDE the
        # engine lock: a slow disk or actuator during an incident must
        # not stall every concurrent status read behind it.
        self.alerts.notify(transitions)
        if self.recorder is not None:
            fired = [t for t in transitions if t["to"] == FIRING]
            if fired:
                t = fired[0]
                self.recorder.dump(
                    f"slo {t['slo']}/{t['speed']} firing "
                    f"(burn {t['burn']}x)"
                )
        return rows

    def signal(self) -> dict:
        """ONE coherent snapshot of the judging layer as a plain dict:
        per-objective burn rates + alert states, read once (one locked
        rows read + one alerts snapshot) instead of re-derived per
        caller. Actuators, ``/v1/status`` and ``/fleet`` all consume
        this view — an actuator and the status page can never disagree
        about which alerts were firing at the same instant."""
        with self._lock:
            rows = list(self.last_rows)
        alerts = {(a["slo"], a["speed"]): a for a in self.alerts.all()}
        objectives = {}
        for row in rows:
            objectives[row["slo"]] = {
                "target": row["target"],
                "threshold_s": row["threshold_s"],
                "burn": {
                    speed: round(win["burn"], 3)
                    for speed, win in row["windows"].items()
                },
                "states": {
                    speed: alerts.get(
                        (row["slo"], speed), {}
                    ).get("state", INACTIVE)
                    for speed in row["windows"]
                },
            }
        active = [a for a in alerts.values() if a["state"] != INACTIVE]
        return {
            "objectives": objectives,
            "alerts": active,
            "firing": sum(1 for a in active if a["state"] == FIRING),
        }

    def status(self) -> dict:
        """The JSON block ``/fleet`` and the gateway's ``/v1/status``
        embed — a thin view of :meth:`signal` (same coherent read)."""
        sig = self.signal()
        return {
            "objectives": sig["objectives"],
            "alerts": sig["alerts"],
        }
