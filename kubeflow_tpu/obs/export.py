"""Span exporters + trace-tree assembly (stdlib only).

Exporters receive one ``span.to_dict()`` per finished sampled span:

- :class:`RingExporter` — bounded in-memory deque; the data source for
  ``/debug/traces`` and ``/debug/timeline`` on the manager's health
  server, and for test assertions.
- :class:`JsonlExporter` — one JSON line per span, append-only; the
  durable form the chaos harness and bench consume.
- :class:`MultiExporter` — fan-out.

The assembly helpers turn a flat span list back into the tree an
operator reads: group by trace id, parent by span id, order by start
time.
"""

from __future__ import annotations

import collections
import json
import os
import threading


class RingExporter:
    """Last-N finished spans, thread-safe, oldest evicted first."""

    def __init__(self, capacity: int = 512):
        self._spans: collections.deque = collections.deque(
            maxlen=max(1, int(capacity))
        )
        self._lock = threading.Lock()

    @property
    def capacity(self) -> int:
        return self._spans.maxlen or 0

    def export(self, span: dict) -> None:
        with self._lock:
            self._spans.append(span)

    def spans(self) -> list[dict]:
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()


class JsonlExporter:
    """Append spans to a JSONL file (one line each). Parent directories
    are created; writes are serialized so concurrent span ends cannot
    interleave half-lines. The append handle is opened once and flushed
    per line — spans end on every reconcile and training step, and an
    open/close syscall pair per record would dominate the export cost.

    Size-capped rotation: with ``max_bytes`` (or ``OBS_JSONL_MAX_BYTES``
    in the environment) set, a write that would push the file past the
    cap first atomically rotates it to ``<path>.1`` (``os.replace`` —
    the previous ``.1`` is dropped), so a long soak or a forever-cycling
    gateway holds at most ~2x the cap on disk instead of filling it.
    Unset means unbounded — the pre-existing default, unchanged."""

    def __init__(self, path: str, max_bytes: int | None = None):
        self.path = path
        if max_bytes is None:
            raw = os.environ.get("OBS_JSONL_MAX_BYTES")
            if raw:
                try:
                    max_bytes = int(raw)
                except ValueError:
                    max_bytes = None
        self.max_bytes = (
            int(max_bytes) if max_bytes and int(max_bytes) > 0 else None
        )
        self._written = 0  # bytes in the current file (tracked, not statted)
        self._lock = threading.Lock()
        self._fh = None
        try:
            os.makedirs(os.path.dirname(os.path.abspath(path)),
                        exist_ok=True)
        except OSError:
            # Telemetry must never take down the traced path: an
            # unwritable OBS_JSONL_PATH means exports fail later and
            # are dropped by Tracer._export, not a crashed constructor
            # inside the first traced request.
            pass

    def export(self, span: dict) -> None:
        line = json.dumps(span, default=str)
        encoded = line.encode("utf-8") + b"\n"
        with self._lock:
            if self._fh is None:
                self._fh = open(self.path, "a", encoding="utf-8")
                try:
                    self._written = os.fstat(self._fh.fileno()).st_size
                except OSError:
                    self._written = 0
            if (
                self.max_bytes is not None
                and self._written > 0
                and self._written + len(encoded) > self.max_bytes
            ):
                # Rotate-before-write: the record that would cross the
                # cap starts the fresh file, so no line is ever split
                # across generations.
                self._fh.close()
                self._fh = None
                try:
                    os.replace(self.path, self.path + ".1")
                except OSError:
                    # Rotation denied (e.g. read-only dir): keep
                    # appending — availability of the trace stream
                    # beats the size cap.
                    pass
                self._fh = open(self.path, "a", encoding="utf-8")
                self._written = 0
            self._fh.write(line + "\n")
            self._fh.flush()
            self._written += len(encoded)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                finally:
                    self._fh = None


class MultiExporter:
    def __init__(self, *exporters):
        self.exporters = list(exporters)

    def export(self, span: dict) -> None:
        for exporter in self.exporters:
            exporter.export(span)


def load_jsonl(path: str) -> list[dict]:
    """Read a JSONL span file back; skips any torn final line."""
    out: list[dict] = []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return out


# ---- trace assembly ------------------------------------------------------
def _by_trace(spans: list[dict]) -> dict[str, list[dict]]:
    traces: dict[str, list[dict]] = {}
    for span in spans:
        traces.setdefault(span.get("trace_id", ""), []).append(span)
    return traces


def span_tree(spans: list[dict]) -> list[dict]:
    """Spans of ONE trace → forest of ``{**span, "children": [...]}``
    ordered by start time. A span whose parent is missing (evicted
    from the ring, or the root) becomes a top-level node — a truncated
    trace still renders instead of vanishing."""
    nodes = {
        s["span_id"]: {**s, "children": []}
        for s in sorted(spans, key=lambda s: s.get("start", 0.0))
    }
    roots: list[dict] = []
    for node in nodes.values():
        parent = nodes.get(node.get("parent_id") or "")
        if parent is not None and parent is not node:
            parent["children"].append(node)
        else:
            roots.append(node)
    return roots


def trace_summaries(spans: list[dict], limit: int = 50) -> list[dict]:
    """One row per trace, newest first — the ``/debug/traces`` index."""
    out = []
    for trace_id, group in _by_trace(spans).items():
        start = min(s.get("start", 0.0) for s in group)
        end = max(s.get("end", 0.0) for s in group)
        root = next(
            (s for s in group if not s.get("parent_id")),
            min(group, key=lambda s: s.get("start", 0.0)),
        )
        out.append({
            "trace_id": trace_id,
            "root": root.get("name", ""),
            "spans": len(group),
            "errors": sum(1 for s in group if s.get("status") == "error"),
            "start": start,
            "duration_ms": round((end - start) * 1000, 3),
        })
    out.sort(key=lambda row: row["start"], reverse=True)
    return out[:limit]


def timeline(spans: list[dict], namespace: str, name: str) -> dict | None:
    """The most recent trace that touched object (namespace, name) —
    matched on span attributes — as a span tree. None when no trace
    knows the object."""
    touching = [
        s for s in spans
        if s.get("attributes", {}).get("namespace") == namespace
        and s.get("attributes", {}).get("name") == name
    ]
    if not touching:
        return None
    latest = max(touching, key=lambda s: s.get("start", 0.0))
    trace_id = latest.get("trace_id", "")
    group = [s for s in spans if s.get("trace_id") == trace_id]
    return {
        "trace_id": trace_id,
        "namespace": namespace,
        "name": name,
        "spans": len(group),
        "tree": span_tree(group),
    }
