"""Shared numeric env-knob parsing for the obs package.

Every obs env knob follows one contract: missing, malformed, or (where
a floor applies) out-of-range values fall back to the default — a bad
knob must never crash an import or a hot loop. One implementation,
imported by the leaf modules (this module imports nothing from obs, so
it is cycle-safe under ``obs/__init__``'s re-export graph).
"""

from __future__ import annotations

import os
from typing import Callable


def env_bool(name: str, default: bool = False) -> bool:
    """Read env var ``name`` as a truthy flag (``1``/``true``/``yes``,
    case-insensitive); ``default`` when unset."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.lower() in ("1", "true", "yes")


def env_number(
    name: str,
    default: float | int,
    cast: Callable = float,
    minimum: float | int | None = None,
):
    """Read env var ``name`` through ``cast`` (``float``/``int``),
    returning ``default`` when unset, unparsable, or below
    ``minimum``."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        value = cast(raw)
    except ValueError:
        return default
    if minimum is not None and value < minimum:
        return default
    return value
