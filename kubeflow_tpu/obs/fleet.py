"""Fleet health rollup: per-namespace cards over the live CR objects.

The manager's ``/fleet`` endpoint, the serving gateway's status block
and the dashboard's fleet gauges all read the same computation: list
Notebooks and InferenceServices through any duck-typed api handle,
fold their phases, recovery counters and goodput annotations into one
card per namespace, and overlay the SLO alert state so a firing
burn-rate alert turns the card red instead of hiding in ``/metrics``.

Stdlib-only and duck-typed on the api (FakeApiServer, ApiClient or the
chaos proxy), like everything else in ``obs`` — the dashboard and the
manager import *this*, not each other.
"""

from __future__ import annotations

import logging
import time
from typing import Callable

log = logging.getLogger(__name__)

# CR coordinates, mirrored from the controllers (obs must stay
# importable without them; the values are API contract, not code).
NOTEBOOK_API = "kubeflow.org/v1beta1"
INFERENCE_API = "serving.kubeflow.org/v1alpha1"

# Annotation the data plane publishes its goodput ratio through
# (GoodputAnnotationPublisher below; models/train.py's goodput_publish
# hook feeds it) — the async hop that carries train_goodput_ratio from
# the training pod to the fleet cards.
GOODPUT_ANNOTATION = "obs.kubeflow-tpu.org/goodput-ratio"

# Per-CRD preemption-restart annotation namespaces (slice_recovery).
_PREEMPTION_KEYS = (
    "notebooks.kubeflow-tpu.org/preemption-restarts",
    "inference.kubeflow-tpu.org/preemption-restarts",
)

# Phases that degrade a card even without an alert: the platform is
# mid-recovery or failed outright.
_UNHEALTHY_PHASES = frozenset({"Restarting", "Resharding", "Failed"})


def _phase_of(obj: dict) -> str:
    status = obj.get("status") or {}
    phase = status.get("phase")
    if phase:
        return str(phase)
    container = status.get("containerState") or {}
    if "running" in container:
        return "Running"
    if "waiting" in container:
        return "Waiting"
    if "terminated" in container:
        return "Stopped"
    return "Pending"


def _annotations(obj: dict) -> dict:
    return (obj.get("metadata") or {}).get("annotations") or {}


def _safe_list(api, api_version: str, kind: str) -> list[dict]:
    try:
        return api.list(api_version, kind) or []
    except Exception as exc:
        # The rollup is a read-only health surface: during an outage it
        # must render what it can, not 500 — same posture as the
        # last-known-good metric collectors.
        log.warning("fleet rollup: list %s failed (%s)", kind, exc)
        return []


def fleet_cards(
    api,
    alerts=None,
    counters: dict | None = None,
    clock: Callable[[], float] = time.time,
    scheduler=None,
) -> dict:
    """Per-namespace fleet cards.

    ``alerts`` is an :class:`~kubeflow_tpu.obs.alerts.AlertManager` (or
    anything with ``active()``); a namespace-scoped alert lands on its
    namespace's card, a cluster-scoped one (namespace None) on every
    card. ``counters`` optionally carries manager-side per-namespace
    counter readings, e.g. ``{"reshards": {ns: n}}`` folded from the
    Prometheus registry — the dashboard process omits them.
    ``scheduler`` (a duck-typed ``pool_snapshot()`` holder — the
    slice-pool scheduler) adds the top-level ``pool`` utilisation
    block; the per-card ``queued``/``suspended`` counts come from the
    CR phases themselves, so the rollup reflects the scheduler's
    states instead of lumping them into NotReady.
    """
    cards: dict[str, dict] = {}

    def card(ns: str) -> dict:
        return cards.setdefault(ns, {
            "notebooks": {},
            "inferenceservices": {},
            "preemption_restarts": 0,
            "reshards": 0,
            "queued": 0,
            "suspended": 0,
            "goodput_ratio": None,
            "alerts": [],
            "health": "ok",
        })

    for kind_key, api_version, kind in (
        ("notebooks", NOTEBOOK_API, "Notebook"),
        ("inferenceservices", INFERENCE_API, "InferenceService"),
    ):
        for obj in _safe_list(api, api_version, kind):
            ns = (obj.get("metadata") or {}).get("namespace", "")
            entry = card(ns)
            phase = _phase_of(obj)
            entry[kind_key][phase] = entry[kind_key].get(phase, 0) + 1
            anns = _annotations(obj)
            for key in _PREEMPTION_KEYS:
                try:
                    entry["preemption_restarts"] += int(anns.get(key, 0))
                except (TypeError, ValueError):
                    pass
            if phase == "Resharding":
                entry["reshards"] += 1
            elif phase == "Queued":
                entry["queued"] += 1
            elif phase == "Suspended":
                entry["suspended"] += 1
            raw = anns.get(GOODPUT_ANNOTATION)
            if raw is not None:
                try:
                    ratio = float(raw)
                except (TypeError, ValueError):
                    pass
                else:
                    # The card shows the worst job in the namespace —
                    # the one an operator should look at first.
                    cur = entry["goodput_ratio"]
                    entry["goodput_ratio"] = (
                        ratio if cur is None else min(cur, ratio)
                    )

    for counter_name, by_ns in (counters or {}).items():
        for ns, value in (by_ns or {}).items():
            card(ns)[counter_name] = card(ns).get(counter_name, 0) + value

    active = list(alerts.active()) if alerts is not None else []
    for alert in active:
        targets = (
            [alert["namespace"]] if alert.get("namespace")
            else list(cards)
        )
        for ns in targets:
            entry = card(ns)
            entry["alerts"].append({
                "slo": alert["slo"],
                "speed": alert["speed"],
                "severity": alert["severity"],
                "state": alert["state"],
            })

    for entry in cards.values():
        states = {a["state"] for a in entry["alerts"]}
        phases = set(entry["notebooks"]) | set(entry["inferenceservices"])
        if "firing" in states:
            entry["health"] = "critical"
        elif "pending" in states or phases & _UNHEALTHY_PHASES:
            entry["health"] = "degraded"

    doc = {
        "namespaces": {ns: cards[ns] for ns in sorted(cards)},
        "alerts": active,
        "generated_at": clock(),
    }
    if scheduler is not None:
        try:
            doc["pool"] = scheduler.pool_snapshot()
        except Exception as exc:
            # Same read-only posture as the LISTs above: a broken
            # capacity source degrades the pool block, never the cards.
            log.warning("fleet rollup: pool snapshot failed (%s)", exc)
    return doc


class GoodputAnnotationPublisher:
    """Publishes a GoodputMeter summary onto the owning CR as the
    :data:`GOODPUT_ANNOTATION` — the data-plane half of the goodput
    fleet card. Rate-limited and strictly best-effort: telemetry must
    never fail (or stall) the training loop it describes.

    Shaped for ``run_with_checkpointing(goodput_publish=...)``: called
    with ``meter.summary()`` at each save cadence and once at exit."""

    def __init__(
        self,
        api,
        namespace: str,
        name: str,
        kind: str = "Notebook",
        api_version: str = NOTEBOOK_API,
        min_interval_s: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.api = api
        self.namespace = namespace
        self.name = name
        self.kind = kind
        self.api_version = api_version
        self.min_interval_s = float(min_interval_s)
        self._clock = clock
        self._last_publish: float | None = None
        self.publishes = 0

    def __call__(self, summary: dict) -> None:
        now = self._clock()
        if (
            self._last_publish is not None
            and now - self._last_publish < self.min_interval_s
        ):
            return
        self.flush(summary)

    def flush(self, summary: dict) -> None:
        """Publish regardless of the rate limit — the once-at-exit
        path, so a run that just published on cadence still lands its
        FINAL ratio on the CR instead of leaving the mid-run one."""
        ratio = summary.get("goodput_ratio")
        if ratio is None:
            return
        now = self._clock()
        try:
            self.api.patch_merge(
                self.api_version, self.kind, self.name,
                {"metadata": {"annotations": {
                    GOODPUT_ANNOTATION: f"{float(ratio):.4f}",
                }}},
                self.namespace,
            )
        except Exception as exc:
            log.debug("goodput publish failed for %s/%s: %s",
                      self.namespace, self.name, exc)
            return
        self._last_publish = now
        self.publishes += 1
