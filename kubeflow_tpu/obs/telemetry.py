"""StepTelemetry: per-step training telemetry (wall time, examples/sec,
MFU) from the same pipeline operators scrape.

BENCH numbers and dashboards previously came from disjoint code paths;
this hook is the single meter: the training loop (models/train.py) or
the bench harness (bench.py) calls :meth:`observe` once per step, and
the same record fans out to

- an in-memory list (``records``) the caller aggregates,
- JSONL (``OBS_JSONL_PATH`` or an explicit path) for offline analysis,
- Prometheus gauges (lazily imported; absent prometheus_client
  degrades to the first two sinks).

MFU uses the per-topology peak-FLOPs tables in
:mod:`kubeflow_tpu.topology` — per-chip peak by default, the
whole-slice peak when the caller passes ``chips``. Off-TPU (CPU smoke
runs) the nominal host peak keeps MFU finite; the value is only
meaningful on the real accelerator.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from collections import deque
from typing import Callable

from kubeflow_tpu.obs.envknob import env_number
from kubeflow_tpu.obs.profile import active_digest

# In-memory record retention: a forever-running trainer must not grow
# the list without bound (the py-unbounded-deque discipline); 4096
# steps is far past what any aggregation here reads.
_RECORDS_MAX = 4096


def _records_max_from_env() -> int:
    """OBS_STEP_RECORDS_MAX, defaulting (not crashing) on malformed
    or non-positive values — the shared obs env-parser contract."""
    return env_number("OBS_STEP_RECORDS_MAX", _RECORDS_MAX,
                      cast=int, minimum=1)


class StepTelemetry:
    def __init__(
        self,
        flops_per_example: float,
        peak_flops: float | None = None,
        device_kind: str = "",
        chips: int = 1,
        jsonl_path: str | None = None,
        registry=None,
        clock: Callable[[], float] = time.time,
    ):
        from kubeflow_tpu import topology

        self.flops_per_example = float(flops_per_example)
        if peak_flops is None:
            peak_flops = topology.peak_flops_for_device_kind(device_kind)
        self.peak_flops = float(peak_flops) * max(1, int(chips))
        self.device_kind = device_kind
        self.jsonl_path = (
            jsonl_path if jsonl_path is not None
            else os.environ.get("OBS_JSONL_PATH")
        )
        self._clock = clock
        self._lock = threading.Lock()
        self._step = 0
        self.observed = 0
        self.records: deque = deque(
            maxlen=_records_max_from_env()
        )
        self._gauges = self._make_gauges(registry)
        # One JSONL discipline for the whole obs package: the sink IS
        # a JsonlExporter (guarded makedirs, locked appends); only the
        # disable-on-OSError posture is telemetry's own.
        self._jsonl = None
        if self.jsonl_path:
            from kubeflow_tpu.obs.export import JsonlExporter

            self._jsonl = JsonlExporter(self.jsonl_path)

    def _make_gauges(self, registry):
        try:
            from prometheus_client import CollectorRegistry, Counter, Gauge
        except ImportError:  # minimal worker images: JSONL-only
            self.registry = None
            return None
        self.registry = registry or CollectorRegistry()
        return {
            "step_time": Gauge(
                "training_step_time_seconds",
                "Wall time of the most recent training step",
                registry=self.registry,
            ),
            "examples": Gauge(
                "training_examples_per_sec",
                "Throughput of the most recent training step",
                registry=self.registry,
            ),
            "mfu": Gauge(
                "training_mfu",
                "Model FLOPs utilization of the most recent step "
                "(achieved / peak bf16 FLOPs)",
                registry=self.registry,
            ),
            "steps": Counter(
                "training_steps",
                "Training steps observed by this process",
                registry=self.registry,
            ),
        }

    # ---- recording -------------------------------------------------------
    def observe(
        self,
        batch_size: int,
        step_time_s: float,
        step: int | None = None,
        **extra,
    ) -> dict:
        """Record one completed step (host-synced wall time). Returns
        the record that was emitted."""
        step_time_s = max(float(step_time_s), 1e-12)
        examples_per_sec = batch_size / step_time_s
        mfu = examples_per_sec * self.flops_per_example / self.peak_flops
        with self._lock:
            if step is None:
                step = self._step
            self._step = step + 1
        record = {
            "kind": "step_telemetry",
            "ts": self._clock(),
            "step": step,
            "batch_size": batch_size,
            "step_time_s": round(step_time_s, 6),
            "examples_per_sec": round(examples_per_sec, 3),
            "mfu": round(mfu, 6),
            "flops_per_example": self.flops_per_example,
            "peak_flops": self.peak_flops,
            "device": self.device_kind,
            **extra,
        }
        if "phases" not in record:
            # Zero-flag phase attribution: when this observe runs
            # inside a PhaseProfiler activation (run_with_checkpointing
            # with a profiler plugged in), the live per-phase digest
            # rides the same per-step JSONL record bench already reads.
            digest = active_digest()
            if digest is not None:
                record["phases"] = digest
        with self._lock:
            self.observed += 1
            self.records.append(record)
        if self._gauges is not None:
            self._gauges["step_time"].set(step_time_s)
            self._gauges["examples"].set(examples_per_sec)
            self._gauges["mfu"].set(mfu)
            self._gauges["steps"].inc()
        if self._jsonl is not None:
            try:
                self._jsonl.export(record)
            except OSError:
                # Telemetry must never fail the step it measures
                # (read-only checkout, full disk): in-memory and
                # gauge sinks already carry the record.
                self._jsonl = None
                self.jsonl_path = None
        return record

    @contextlib.contextmanager
    def timed(self, batch_size: int, **extra):
        """``with telemetry.timed(batch):`` around one host-synced step."""
        t0 = time.perf_counter()
        yield
        self.observe(batch_size, time.perf_counter() - t0, **extra)

    # ---- aggregation -----------------------------------------------------
    def summary(self) -> dict:
        """Median-of-steps aggregate (first step excluded when there is
        more than one — it carries compile/dispatch warmup). ``steps``
        counts every observed step; the percentile window is the
        retained ring (bounded, OBS_STEP_RECORDS_MAX)."""
        with self._lock:
            records = list(self.records)
            observed = self.observed
        if not records:
            return {"steps": 0}
        steady = records[1:] if len(records) > 1 else records
        times = sorted(r["step_time_s"] for r in steady)
        mid = times[len(times) // 2]
        batch = steady[-1]["batch_size"]
        examples = batch / mid
        return {
            "steps": observed,
            "median_step_time_s": round(mid, 6),
            "examples_per_sec": round(examples, 3),
            "mfu": round(
                examples * self.flops_per_example / self.peak_flops, 6
            ),
            "device": self.device_kind,
        }


class _DowntimeSpan:
    """Handle a :meth:`GoodputMeter.downtime` block mutates: set
    ``.kind`` before the block exits to re-label the span (a restore
    that turns out to be cross-topology becomes a ``reshard``)."""

    def __init__(self, kind: str):
        self.kind = kind


class GoodputMeter:
    """Useful-step seconds vs wall clock across preempt/restore cycles.

    MFU says how well a *step* used the chips; goodput says how much of
    the job's *lifetime* was steps at all — the number preemption,
    restore and resharding downtime actually move. The meter accumulates

    - ``useful_s``  — host-synced seconds spent in completed train steps
      (:meth:`observe_step`, fed by ``run_with_checkpointing``),
    - ``downtime_s`` per kind — measured spans of known non-work
      (``restore``, ``reshard``, caller-defined kinds) via
      :meth:`downtime`, each also emitted as an obs tracer span,

    against a wall clock running since construction (or since the
    lineage started, when resumed from a :meth:`snapshot`). The ratio
    lands on the ``train_goodput_ratio`` gauge; downtime totals on
    ``train_downtime_seconds{kind}``.

    Cross-incarnation accounting: a preempted pod's successor calls
    :meth:`from_snapshot` with the predecessor's snapshot — the gap
    between the snapshot's ``saved_at`` and now (the slice restart,
    invisible to both processes) is charged as ``downtime["gap"]`` and
    added to the carried wall clock, so goodput stays honest across
    restarts instead of resetting with each incarnation.
    """

    def __init__(
        self,
        clock: Callable[[], float] = time.monotonic,
        epoch_clock: Callable[[], float] = time.time,
        registry=None,
        tracer=None,
    ):
        self._clock = clock
        self._epoch_clock = epoch_clock
        self._tracer = tracer
        self._lock = threading.Lock()
        self._started = clock()
        self._carried_wall_s = 0.0
        self.useful_s = 0.0
        self.steps = 0
        self.downtime_s: dict[str, float] = {}
        self._gauges = self._make_gauges(registry)

    def _make_gauges(self, registry):
        try:
            from prometheus_client import CollectorRegistry, Gauge
        except ImportError:  # minimal worker images: in-process only
            self.registry = None
            return None
        self.registry = registry or CollectorRegistry()
        return {
            "ratio": Gauge(
                "train_goodput_ratio",
                "Useful-step seconds / wall-clock seconds across "
                "preempt, restore and reshard cycles",
                registry=self.registry,
            ),
            "useful": Gauge(
                "train_useful_step_seconds",
                "Cumulative host-synced seconds spent in completed "
                "training steps",
                registry=self.registry,
            ),
            "downtime": Gauge(
                "train_downtime_seconds",
                "Cumulative measured non-work seconds by kind",
                ["kind"],
                registry=self.registry,
            ),
        }

    # ---- recording -------------------------------------------------------
    def observe_step(self, seconds: float) -> None:
        """One completed, host-synced training step."""
        with self._lock:
            self.useful_s += max(float(seconds), 0.0)
            self.steps += 1
        self._export()

    def record_downtime(self, kind: str, seconds: float) -> None:
        with self._lock:
            self.downtime_s[kind] = (
                self.downtime_s.get(kind, 0.0) + max(float(seconds), 0.0)
            )
        self._export()

    @contextlib.contextmanager
    def downtime(self, kind: str):
        """``with meter.downtime("restore") as span:`` around a known
        non-work interval. The block may re-label via ``span.kind``
        (e.g. "reshard" once the restore proves cross-topology). Also
        emitted as a ``train downtime`` span on the obs tracer, so the
        interval shows up in trace timelines next to the checkpoint
        restore spans it contains."""
        from kubeflow_tpu import obs

        handle = _DowntimeSpan(kind)
        tracer = self._tracer if self._tracer is not None \
            else obs.get_tracer()
        t0 = self._clock()
        with tracer.span("train downtime") as span:
            try:
                yield handle
            finally:
                span.set_attribute("kind", handle.kind)
                self.record_downtime(handle.kind, self._clock() - t0)

    # ---- reading ---------------------------------------------------------
    def wall_s(self) -> float:
        with self._lock:
            return self._carried_wall_s + (self._clock() - self._started)

    def goodput_ratio(self) -> float:
        """useful/wall in [0, 1]; 0.0 before any wall time elapsed."""
        wall = self.wall_s()
        if wall <= 0:
            return 0.0
        with self._lock:
            return min(self.useful_s / wall, 1.0)

    def summary(self) -> dict:
        with self._lock:
            downtime = dict(self.downtime_s)
            useful = self.useful_s
            steps = self.steps
        return {
            "kind": "goodput",
            "wall_s": round(self.wall_s(), 6),
            "useful_step_s": round(useful, 6),
            "steps": steps,
            "downtime_s": {k: round(v, 6)
                           for k, v in sorted(downtime.items())},
            "goodput_ratio": round(self.goodput_ratio(), 6),
        }

    def _export(self) -> None:
        if self._gauges is None:
            return
        self._gauges["ratio"].set(self.goodput_ratio())
        with self._lock:
            self._gauges["useful"].set(self.useful_s)
            for kind, total in self.downtime_s.items():
                self._gauges["downtime"].labels(kind).set(total)

    # ---- lineage (cross-incarnation) -------------------------------------
    def snapshot(self) -> dict:
        """Carryable state: wall/useful/downtime so far + the epoch
        instant it was taken (``from_snapshot`` charges the gap)."""
        with self._lock:
            return {
                "wall_s": self._carried_wall_s
                + (self._clock() - self._started),
                "useful_s": self.useful_s,
                "steps": self.steps,
                "downtime_s": dict(self.downtime_s),
                "saved_at": self._epoch_clock(),
            }

    @classmethod
    def from_snapshot(cls, snap: dict, **kwargs) -> "GoodputMeter":
        meter = cls(**kwargs)
        with meter._lock:
            meter._carried_wall_s = float(snap.get("wall_s", 0.0))
            meter.useful_s = float(snap.get("useful_s", 0.0))
            meter.steps = int(snap.get("steps", 0))
            meter.downtime_s = {
                str(k): float(v)
                for k, v in (snap.get("downtime_s") or {}).items()
            }
            saved_at = snap.get("saved_at")
            if saved_at is not None:
                gap = max(
                    float(meter._epoch_clock()) - float(saved_at), 0.0
                )
                if gap > 0:
                    # The restart interval neither process could
                    # measure: wall time between incarnations.
                    meter._carried_wall_s += gap
                    meter.downtime_s["gap"] = (
                        meter.downtime_s.get("gap", 0.0) + gap
                    )
        meter._export()
        return meter
