"""StepTelemetry: per-step training telemetry (wall time, examples/sec,
MFU) from the same pipeline operators scrape.

BENCH numbers and dashboards previously came from disjoint code paths;
this hook is the single meter: the training loop (models/train.py) or
the bench harness (bench.py) calls :meth:`observe` once per step, and
the same record fans out to

- an in-memory list (``records``) the caller aggregates,
- JSONL (``OBS_JSONL_PATH`` or an explicit path) for offline analysis,
- Prometheus gauges (lazily imported; absent prometheus_client
  degrades to the first two sinks).

MFU uses the per-topology peak-FLOPs tables in
:mod:`kubeflow_tpu.topology` — per-chip peak by default, the
whole-slice peak when the caller passes ``chips``. Off-TPU (CPU smoke
runs) the nominal host peak keeps MFU finite; the value is only
meaningful on the real accelerator.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Callable


class StepTelemetry:
    def __init__(
        self,
        flops_per_example: float,
        peak_flops: float | None = None,
        device_kind: str = "",
        chips: int = 1,
        jsonl_path: str | None = None,
        registry=None,
        clock: Callable[[], float] = time.time,
    ):
        from kubeflow_tpu import topology

        self.flops_per_example = float(flops_per_example)
        if peak_flops is None:
            peak_flops = topology.peak_flops_for_device_kind(device_kind)
        self.peak_flops = float(peak_flops) * max(1, int(chips))
        self.device_kind = device_kind
        self.jsonl_path = (
            jsonl_path if jsonl_path is not None
            else os.environ.get("OBS_JSONL_PATH")
        )
        self._clock = clock
        self._lock = threading.Lock()
        self._step = 0
        self.records: list[dict] = []
        self._gauges = self._make_gauges(registry)
        # One JSONL discipline for the whole obs package: the sink IS
        # a JsonlExporter (guarded makedirs, locked appends); only the
        # disable-on-OSError posture is telemetry's own.
        self._jsonl = None
        if self.jsonl_path:
            from kubeflow_tpu.obs.export import JsonlExporter

            self._jsonl = JsonlExporter(self.jsonl_path)

    def _make_gauges(self, registry):
        try:
            from prometheus_client import CollectorRegistry, Counter, Gauge
        except ImportError:  # minimal worker images: JSONL-only
            self.registry = None
            return None
        self.registry = registry or CollectorRegistry()
        return {
            "step_time": Gauge(
                "training_step_time_seconds",
                "Wall time of the most recent training step",
                registry=self.registry,
            ),
            "examples": Gauge(
                "training_examples_per_sec",
                "Throughput of the most recent training step",
                registry=self.registry,
            ),
            "mfu": Gauge(
                "training_mfu",
                "Model FLOPs utilization of the most recent step "
                "(achieved / peak bf16 FLOPs)",
                registry=self.registry,
            ),
            "steps": Counter(
                "training_steps",
                "Training steps observed by this process",
                registry=self.registry,
            ),
        }

    # ---- recording -------------------------------------------------------
    def observe(
        self,
        batch_size: int,
        step_time_s: float,
        step: int | None = None,
        **extra,
    ) -> dict:
        """Record one completed step (host-synced wall time). Returns
        the record that was emitted."""
        step_time_s = max(float(step_time_s), 1e-12)
        examples_per_sec = batch_size / step_time_s
        mfu = examples_per_sec * self.flops_per_example / self.peak_flops
        with self._lock:
            if step is None:
                step = self._step
            self._step = step + 1
        record = {
            "kind": "step_telemetry",
            "ts": self._clock(),
            "step": step,
            "batch_size": batch_size,
            "step_time_s": round(step_time_s, 6),
            "examples_per_sec": round(examples_per_sec, 3),
            "mfu": round(mfu, 6),
            "flops_per_example": self.flops_per_example,
            "peak_flops": self.peak_flops,
            "device": self.device_kind,
            **extra,
        }
        with self._lock:
            self.records.append(record)
        if self._gauges is not None:
            self._gauges["step_time"].set(step_time_s)
            self._gauges["examples"].set(examples_per_sec)
            self._gauges["mfu"].set(mfu)
            self._gauges["steps"].inc()
        if self._jsonl is not None:
            try:
                self._jsonl.export(record)
            except OSError:
                # Telemetry must never fail the step it measures
                # (read-only checkout, full disk): in-memory and
                # gauge sinks already carry the record.
                self._jsonl = None
                self.jsonl_path = None
        return record

    @contextlib.contextmanager
    def timed(self, batch_size: int, **extra):
        """``with telemetry.timed(batch):`` around one host-synced step."""
        t0 = time.perf_counter()
        yield
        self.observe(batch_size, time.perf_counter() - t0, **extra)

    # ---- aggregation -----------------------------------------------------
    def summary(self) -> dict:
        """Median-of-steps aggregate (first step excluded when there is
        more than one — it carries compile/dispatch warmup)."""
        with self._lock:
            records = list(self.records)
        if not records:
            return {"steps": 0}
        steady = records[1:] if len(records) > 1 else records
        times = sorted(r["step_time_s"] for r in steady)
        mid = times[len(times) // 2]
        batch = steady[-1]["batch_size"]
        examples = batch / mid
        return {
            "steps": len(records),
            "median_step_time_s": round(mid, 6),
            "examples_per_sec": round(examples, 3),
            "mfu": round(
                examples * self.flops_per_example / self.peak_flops, 6
            ),
            "device": self.device_kind,
        }
