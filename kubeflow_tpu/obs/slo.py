"""Declarative SLOs + multi-window burn-rate evaluation (stdlib only).

PRs 3-8 made the platform *measurable* — reconcile p99, queue wait,
TTFT, ``train_goodput_ratio``, checkpoint durations — but nothing
*judged* those measurements. This module is the judging layer: an
:class:`Objective` promises a fraction of good events (a latency
histogram staying under a threshold, an availability ratio, a goodput
floor), and :class:`BurnRateEvaluator` turns cumulative counters into
windowed error rates and Google-SRE multi-window burn rates.

The vocabulary (SRE workbook ch. 5): an objective with ``target`` T has
an error budget ``1 - T``. The *burn rate* over a window is the error
rate in that window divided by the budget — burn 1.0 spends exactly
the budget over the SLO period, burn 14.4 exhausts a 30-day budget in
2 days. An alert condition pairs a short and a long window (the short
one makes the alert resolve quickly, the long one de-flakes it) and
requires the burn to exceed the pair's factor on BOTH:

- **fast** pair: 5m + 1h windows at 14.4x — the page.
- **slow** pair: 30m + 6h windows at 6x — the ticket.

Everything takes an injectable clock; nothing here sleeps or threads,
so every burn-rate number in a test is a pure function of the scripted
(sample, clock) sequence. State transitions live in
:mod:`kubeflow_tpu.obs.alerts`.

Sources are zero-arg callables returning cumulative ``(good, total)``
floats — adapters below cover the platform's three meter shapes:
:class:`~kubeflow_tpu.obs.metrics.BucketHistogram` snapshots,
prometheus_client histograms (summed across label sets), and plain
counter pairs (availability, goodput seconds).
"""

from __future__ import annotations

import dataclasses
import logging
import time
from collections import deque
from typing import Callable

from kubeflow_tpu.obs.envknob import env_number

log = logging.getLogger(__name__)

# Window pairs, Google-SRE style. ``for_s`` is how long the condition
# must hold before pending becomes firing; ``clear_s`` how long it must
# stay clear before a firing alert resolves (hysteresis both ways).
@dataclasses.dataclass(frozen=True)
class BurnPair:
    speed: str       # "fast" | "slow"
    short_s: float
    long_s: float
    factor: float
    for_s: float
    clear_s: float
    severity: str    # "critical" (page) | "warning" (ticket)


DEFAULT_PAIRS: tuple[BurnPair, ...] = (
    BurnPair("fast", 300.0, 3600.0, 14.4,
             for_s=60.0, clear_s=300.0, severity="critical"),
    BurnPair("slow", 1800.0, 21600.0, 6.0,
             for_s=900.0, clear_s=1800.0, severity="warning"),
)


@dataclasses.dataclass
class Objective:
    """One SLO: ``source()`` returns cumulative ``(good, total)`` event
    counts; the promise is good/total >= target over the SLO period.
    ``namespace`` scopes the objective for the fleet rollup (None =
    cluster-wide); ``threshold_s`` is informational for latency
    objectives (the "good" cut-off the source already encodes)."""

    name: str
    source: Callable[[], tuple[float, float]]
    target: float = 0.99
    description: str = ""
    namespace: str | None = None
    threshold_s: float | None = None

    @property
    def budget(self) -> float:
        return max(1.0 - float(self.target), 1e-9)


def tunable(slug: str, knob: str, default: float) -> float:
    """Env override for a default objective's knob:
    ``KFT_SLO_<SLUG>_<KNOB>`` (slug upper-cased, ``-`` -> ``_``) —
    e.g. ``KFT_SLO_RECONCILE_DURATION_TARGET=0.999``."""
    env = f"KFT_SLO_{slug.upper().replace('-', '_')}_{knob.upper()}"
    return env_number(env, default)


# ---------------------------------------------------------------------------
# sources: cumulative (good, total) adapters
# ---------------------------------------------------------------------------


def histogram_good_total(snapshot: dict, threshold_s: float) -> tuple[float, float]:
    """(good, total) from a BucketHistogram snapshot: good = cumulative
    count of the largest bucket bound <= threshold (the usual
    histogram-resolution cut)."""
    good = 0.0
    for le, cum in snapshot.get("buckets", []):
        if le == "+Inf":
            continue
        if float(le) <= threshold_s + 1e-12:
            good = float(cum)
        else:
            break
    return good, float(snapshot.get("count", 0))


def bucket_histogram_source(hist, threshold_s: float):
    """Source over a :class:`BucketHistogram` (or a zero-arg callable
    returning one — the client's per-verb histograms appear lazily)."""

    def read() -> tuple[float, float]:
        h = hist() if callable(hist) else hist
        if h is None:
            return 0.0, 0.0
        return histogram_good_total(h.snapshot(), threshold_s)

    return read


def prom_histogram_source(metric, threshold_s: float):
    """Source over a prometheus_client Histogram (labelled or not):
    per label set, good = the cumulative bucket count at the largest
    ``le`` <= threshold; summed across label sets."""

    def read() -> tuple[float, float]:
        good_by_key: dict[tuple, float] = {}
        total = 0.0
        for family in metric.collect():
            for s in family.samples:
                if s.name.endswith("_count"):
                    total += s.value
                elif s.name.endswith("_bucket"):
                    try:
                        le = float(s.labels.get("le", "+Inf"))
                    except ValueError:
                        continue
                    if le <= threshold_s + 1e-12:
                        key = tuple(sorted(
                            (k, v) for k, v in s.labels.items()
                            if k != "le"
                        ))
                        # Buckets are cumulative in le: the largest
                        # bound under the threshold carries the count.
                        good_by_key[key] = max(
                            good_by_key.get(key, 0.0), s.value
                        )
        return sum(good_by_key.values()), total

    return read


def counter_source(good_fn: Callable[[], float],
                   total_fn: Callable[[], float]):
    def read() -> tuple[float, float]:
        return float(good_fn()), float(total_fn())

    return read


def availability_source(client_like):
    """Source over anything exposing ``availability_counts() ->
    (good, total)`` — the real ApiClient and the chaos proxy both do."""

    def read() -> tuple[float, float]:
        good, total = client_like.availability_counts()
        return float(good), float(total)

    return read


def goodput_source(meter):
    """Source over a :class:`~kubeflow_tpu.obs.GoodputMeter`: good =
    useful-step seconds, total = wall seconds — the windowed delta IS
    the goodput ratio over that window."""

    def read() -> tuple[float, float]:
        return float(meter.useful_s), float(meter.wall_s())

    return read


# ---------------------------------------------------------------------------
# default objectives (the fleet ships with these)
# ---------------------------------------------------------------------------


def reconcile_duration_objective(prom, namespace: str | None = None) -> Objective:
    thr = tunable("reconcile-duration", "threshold_s", 1.0)
    return Objective(
        name="reconcile-duration",
        description=f"reconciles complete within {thr:g}s",
        target=tunable("reconcile-duration", "target", 0.99),
        threshold_s=thr,
        namespace=namespace,
        source=prom_histogram_source(prom.reconcile_duration, thr),
    )


def queue_wait_objective(prom, namespace: str | None = None) -> Objective:
    thr = tunable("queue-wait", "threshold_s", 1.0)
    return Objective(
        name="queue-wait",
        description=f"reconcile requests dequeue within {thr:g}s of due",
        target=tunable("queue-wait", "target", 0.99),
        threshold_s=thr,
        namespace=namespace,
        source=prom_histogram_source(prom.queue_duration, thr),
    )


def apiserver_availability_objective(client_like,
                                     namespace: str | None = None) -> Objective:
    return Objective(
        name="apiserver-availability",
        description="apiserver round-trips complete without a 5xx/429",
        target=tunable("apiserver-availability", "target", 0.999),
        namespace=namespace,
        source=availability_source(client_like),
    )


def ttft_objective(metric, namespace: str | None = None) -> Objective:
    thr = tunable("inference-ttft", "threshold_s", 2.5)
    return Objective(
        name="inference-ttft",
        description=f"first token streamed within {thr:g}s",
        target=tunable("inference-ttft", "target", 0.99),
        threshold_s=thr,
        namespace=namespace,
        source=prom_histogram_source(metric, thr),
    )


def itl_objective(metric, namespace: str | None = None) -> Objective:
    thr = tunable("inference-itl", "threshold_s", 0.25)
    return Objective(
        name="inference-itl",
        description=f"inter-token gaps stay under {thr:g}s",
        target=tunable("inference-itl", "target", 0.99),
        threshold_s=thr,
        namespace=namespace,
        source=prom_histogram_source(metric, thr),
    )


def goodput_objective(meter, namespace: str | None = None) -> Objective:
    return Objective(
        name="train-goodput",
        description="useful-step seconds vs wall clock stays above target",
        target=tunable("train-goodput", "target", 0.80),
        namespace=namespace,
        source=goodput_source(meter),
    )


def checkpoint_save_objective(ckpt_metrics,
                              namespace: str | None = None) -> Objective:
    thr = tunable("checkpoint-save", "threshold_s", 60.0)
    return Objective(
        name="checkpoint-save",
        description=f"checkpoint saves commit within {thr:g}s",
        target=tunable("checkpoint-save", "target", 0.95),
        threshold_s=thr,
        namespace=namespace,
        source=bucket_histogram_source(
            lambda: getattr(ckpt_metrics, "save_duration", None), thr
        ),
    )


# ---------------------------------------------------------------------------
# evaluator
# ---------------------------------------------------------------------------


class BurnRateEvaluator:
    """Samples cumulative (good, total) per objective on an injectable
    clock and computes windowed error/burn rates.

    A window's reference point is the newest sample at or before
    ``now - window``; before enough history exists, the oldest sample
    stands in (a *partial* window — deliberately conservative: a
    blackout 10 minutes into a fresh process must still trip the 1h
    window, not hide behind missing history). Counter resets (a source
    whose total went backwards — process restart) drop that
    objective's history rather than producing negative rates."""

    def __init__(
        self,
        pairs: tuple[BurnPair, ...] = DEFAULT_PAIRS,
        clock: Callable[[], float] = time.monotonic,
        max_samples: int = 8192,
    ):
        # max_samples must span the longest window at the caller's tick
        # cadence or the deque's own maxlen evicts the window reference
        # and the long window silently shrinks: the default 6h window
        # at SloEngine's 5s min-interval needs 4320 samples — 8192
        # leaves margin (the horizon trim keeps the deque near
        # window/interval + 1 anyway; the cap is a backstop).
        self.pairs = tuple(pairs)
        self.clock = clock
        self._max_samples = max(16, int(max_samples))
        self._objectives: dict[str, Objective] = {}
        self._samples: dict[str, deque] = {}

    # ---- registry --------------------------------------------------------
    def register(self, objective: Objective) -> Objective:
        if objective.name in self._objectives:
            raise ValueError(f"duplicate objective {objective.name!r}")
        self._objectives[objective.name] = objective
        self._samples[objective.name] = deque(maxlen=self._max_samples)
        return objective

    def objectives(self) -> list[Objective]:
        return list(self._objectives.values())

    # ---- sampling --------------------------------------------------------
    def sample(self, now: float | None = None) -> None:
        now = self.clock() if now is None else now
        horizon = max(p.long_s for p in self.pairs) if self.pairs else 0.0
        for name, obj in self._objectives.items():
            try:
                good, total = obj.source()
            except Exception:
                # A broken source must not take down evaluation of the
                # others; the objective just stops accruing samples
                # (and its windows read as empty = healthy).
                log.debug("slo %s: source read failed", name,
                          exc_info=True)
                continue
            samples = self._samples[name]
            if samples and total < samples[-1][2]:
                samples.clear()  # counter reset (process restart)
            samples.append((now, float(good), float(total)))
            # Trim history beyond the longest window, keeping one
            # sample older than the horizon as the window reference.
            while (
                len(samples) > 2
                and samples[1][0] <= now - horizon
            ):
                samples.popleft()

    def _window(self, name: str, now: float, window_s: float) -> dict:
        samples = self._samples.get(name)
        if not samples:
            return {"events": 0.0, "error_rate": 0.0}
        cutoff = now - window_s
        ref = samples[0]
        for s in samples:
            if s[0] <= cutoff:
                ref = s
            else:
                break
        cur = samples[-1]
        d_total = cur[2] - ref[2]
        if d_total <= 0:
            return {"events": 0.0, "error_rate": 0.0}
        d_bad = max(d_total - (cur[1] - ref[1]), 0.0)
        return {
            "events": d_total,
            "error_rate": min(d_bad / d_total, 1.0),
        }

    def evaluate(self, now: float | None = None) -> list[dict]:
        """One row per objective: windowed error/burn rates and the
        per-pair violation verdict (burn >= factor on BOTH windows)."""
        now = self.clock() if now is None else now
        rows = []
        for name, obj in self._objectives.items():
            windows = {}
            for pair in self.pairs:
                short = self._window(name, now, pair.short_s)
                long_ = self._window(name, now, pair.long_s)
                short_burn = short["error_rate"] / obj.budget
                long_burn = long_["error_rate"] / obj.budget
                windows[pair.speed] = {
                    "short_s": pair.short_s,
                    "long_s": pair.long_s,
                    "factor": pair.factor,
                    "severity": pair.severity,
                    "for_s": pair.for_s,
                    "clear_s": pair.clear_s,
                    "short_rate": short["error_rate"],
                    "long_rate": long_["error_rate"],
                    "short_burn": short_burn,
                    "long_burn": long_burn,
                    "burn": min(short_burn, long_burn),
                    "violated": (
                        short_burn >= pair.factor
                        and long_burn >= pair.factor
                        and short["events"] > 0
                    ),
                }
            rows.append({
                "slo": name,
                "description": obj.description,
                "target": obj.target,
                "threshold_s": obj.threshold_s,
                "namespace": obj.namespace,
                "windows": windows,
            })
        return rows

    def tick(self, now: float | None = None) -> list[dict]:
        now = self.clock() if now is None else now
        self.sample(now)
        return self.evaluate(now)
