"""Flight recorder: a bounded black-box ring of hot-loop snapshots.

The aviation pattern the SRE books keep borrowing: always-on, bounded
recording of the last N units of work (train steps, batcher cycles,
reconciles) with enough structure — per-phase durations, queue depth,
batch occupancy, memory watermark, the active trace id — that when an
alert fires the window *leading up to it* is already captured and can
be dumped for offline forensics, instead of asking an operator to
reproduce a p99 regression hours later.

Recording is cheap (a dict build + a lock-guarded deque append per
unit); the ring bounds memory by construction (``maxlen`` — the
py-unbounded-deque analysis rule exists so this never regresses).
Dumps are JSONL artifacts written atomically (tmp + ``os.replace``,
the platform-wide torn-write discipline) and rate-limited so an alert
storm produces one artifact per interval, not one per transition.
:class:`~kubeflow_tpu.obs.alerts.SloEngine` triggers a dump on every
pending→firing transition when given a recorder; ``/debug/flightrecord``
serves the live ring on the manager and the serving gateway.

Environment:

- ``OBS_FLIGHT_CAPACITY``       — ring size (default 256 snapshots)
- ``OBS_FLIGHT_DIR``            — where dump artifacts land (default
  the working directory)
- ``OBS_FLIGHT_MIN_INTERVAL_S`` — minimum seconds between dumps
  (default 60; ``force=True`` bypasses)
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Callable

from kubeflow_tpu.obs.envknob import env_number

log = logging.getLogger(__name__)


class FlightRecorder:
    """Bounded ring of structured snapshots + rate-limited atomic dumps.

    ``record()`` runs on hot loops (scheduler thread, training loop,
    reconcile workers) while ``snapshots()``/``to_dict()`` run on HTTP
    handler threads and ``dump()`` on whatever thread ticks the SLO
    engine — one lock serializes the ring; the artifact write happens
    OUTSIDE it (file I/O under a hot-loop lock would be its own
    latency bug)."""

    def __init__(
        self,
        capacity: int | None = None,
        dump_dir: str | None = None,
        min_dump_interval_s: float | None = None,
        clock: Callable[[], float] = time.time,
        name: str = "flightrecord",
    ):
        if capacity is None:
            capacity = env_number("OBS_FLIGHT_CAPACITY", 256, cast=int)
        self.capacity = max(1, int(capacity))
        self.dump_dir = (dump_dir if dump_dir is not None
                         else os.environ.get("OBS_FLIGHT_DIR", "."))
        if min_dump_interval_s is None:
            min_dump_interval_s = env_number(
                "OBS_FLIGHT_MIN_INTERVAL_S", 60.0
            )
        self.min_dump_interval_s = float(min_dump_interval_s)
        self.name = name
        self._clock = clock
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)
        self._seq = 0
        self._last_dump_at: float | None = None
        self._dump_seq = 0
        self.dumps_total = 0
        self.dumps_suppressed = 0
        self.last_dump_path: str | None = None

    # ---- recording -------------------------------------------------------
    def record(self, kind: str, **fields) -> dict:
        """Append one snapshot. Stamps a monotonic sequence number, the
        recorder clock, and — unless the caller provided one — the
        trace id of the current sampled span, so a snapshot links back
        to the exact trace that produced it."""
        snap = {"kind": kind, **fields}
        if "trace_id" not in snap:
            from kubeflow_tpu.obs.trace import current_span

            span = current_span()
            snap["trace_id"] = (
                span.context.trace_id
                if span is not None and span.context.sampled else None
            )
        with self._lock:
            self._seq += 1
            snap["seq"] = self._seq
            snap["ts"] = self._clock()
            self._ring.append(snap)
        return snap

    # ---- reading ---------------------------------------------------------
    def snapshots(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def to_dict(self) -> dict:
        """The ``/debug/flightrecord`` document."""
        with self._lock:
            snapshots = list(self._ring)
            return {
                "capacity": self.capacity,
                "recorded": self._seq,
                "dumps": self.dumps_total,
                "dumps_suppressed": self.dumps_suppressed,
                "last_dump_path": self.last_dump_path,
                "snapshots": snapshots,
            }

    # ---- dumping ---------------------------------------------------------
    def dump(self, reason: str, force: bool = False) -> str | None:
        """Write the current ring as one JSONL artifact (header line
        with the trigger reason, then one line per snapshot), atomically
        via tmp + ``os.replace``. Rate-limited: within
        ``min_dump_interval_s`` of the previous dump the call is
        counted and skipped (an alert storm must not turn the recorder
        into a disk-filling amplifier) unless ``force``. Returns the
        artifact path, or None when suppressed or the write failed —
        a dump must never take down the tick that triggered it."""
        now = self._clock()
        with self._lock:
            if (
                not force
                and self._last_dump_at is not None
                and now - self._last_dump_at < self.min_dump_interval_s
            ):
                self.dumps_suppressed += 1
                return None
            # Reserve the slot under the lock so two concurrent firing
            # ticks cannot both pass the rate check and double-write.
            prev_dump_at = self._last_dump_at
            self._last_dump_at = now
            seq = self._dump_seq
            self._dump_seq += 1
            snapshots = list(self._ring)
        header = {
            "kind": "flight_dump",
            "reason": reason,
            "at": now,
            "snapshots": len(snapshots),
            "capacity": self.capacity,
        }
        path = os.path.join(self.dump_dir, f"{self.name}-{seq:04d}.jsonl")
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            os.makedirs(self.dump_dir, exist_ok=True)
            with open(tmp, "w", encoding="utf-8") as fh:
                fh.write(json.dumps(header, default=str) + "\n")
                for snap in snapshots:
                    fh.write(json.dumps(snap, default=str) + "\n")
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, path)  # the rename IS the commit
        except OSError as exc:
            log.warning("flight-recorder dump to %s failed: %s", path, exc)
            try:
                os.unlink(tmp)
            except OSError:
                pass
            with self._lock:
                # Release the rate-limit slot (unless a later dump
                # re-reserved it meanwhile): the artifact was lost, so
                # the next firing transition must retry, not sit out
                # the interval behind a write that never landed.
                if self._last_dump_at == now:
                    self._last_dump_at = prev_dump_at
            return None
        with self._lock:
            self.dumps_total += 1
            self.last_dump_path = path
        log.info("flight recorder dumped %d snapshot(s) to %s (%s)",
                 len(snapshots), path, reason)
        return path
