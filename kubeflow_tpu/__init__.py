"""kubeflow_tpu — a TPU-native notebooks platform.

A brand-new implementation of the capability surface of the Kubeflow
Notebooks platform (reference: kubeflow/kubeflow), redesigned TPU-first.
Current layout (grows as components land; see SURVEY.md §7 build plan):

- ``parallel/`` / ``models/`` — the JAX compute stack shipped in the
  ``jupyter-jax-tpu`` notebook images: named-mesh sharding,
  ``jax.distributed`` wiring from platform-injected env, and the
  ResNet-50 reference model with a sharded train step.
- ``topology.py`` — TPU accelerator/topology model (v4/v5e/v5p/v6e):
  chips-per-host math, GKE node selectors, ``google.com/tpu`` resources.
- ``native.py`` — ctypes bridge to the C++ core (``native/``) holding the
  reconcilers' desired-state generation, the PodDefault merge engine,
  the culling decision engine, and drift-repair helpers.
- ``controllers/`` — controller-side Python (watch loops and helpers
  driving the native core).
"""

__version__ = "0.1.0"
