"""kubeflow_tpu — a TPU-native notebooks platform.

A brand-new implementation of the capability surface of the Kubeflow
Notebooks platform (reference: kubeflow/kubeflow), redesigned TPU-first:

- ``controllers/`` — Kubernetes reconcilers (Notebook, Tensorboard,
  PVCViewer, Profile) whose desired-state generation, work queues, and
  merge engines live in the native C++ core (``native/``), driven here.
- ``webhook/`` — the PodDefault admission webhook that injects
  ``TPU_WORKER_ID`` / coordinator env into pods on TPU pod slices.
- ``crud_backend/`` + ``apps/`` — Flask REST backends for the Jupyter
  spawner, Volumes, and Tensorboards web apps.
- ``parallel/`` / ``models/`` / ``ops/`` — the JAX compute stack shipped
  in the ``jupyter-jax-tpu`` notebook images: device-mesh sharding,
  ``jax.distributed`` wiring from platform-injected env, ResNet-50 and
  long-context transformer reference models, and Pallas kernels.
- ``topology.py`` — TPU accelerator/topology model (v4/v5e/v5p/v6e):
  chips-per-host math, GKE node selectors, ``google.com/tpu`` resources.
- ``k8s/`` — a typed Kubernetes API client plus an in-memory fake API
  server used by the test ladder (the envtest equivalent).
"""

__version__ = "0.1.0"
