"""ctypes bridge to the native C++ core (native/libkft_native.so).

The reconcilers' desired-state generation, the PodDefault merge engine,
the culling decision engine, and the drift-repair helpers are native code
(the role Go plays in the reference — see SURVEY.md §2.2); Python layers
(controllers' watch loops, web apps, tests) call through here. Protocol:
one C function ``kft_invoke(fn, json) -> json`` — see native/src/api.cpp.
"""

from __future__ import annotations

import ctypes
import json
import os
import subprocess
import threading

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
# Containers ship a prebuilt library (docker/base.Dockerfile sets
# KFT_NATIVE_LIB) and carry no toolchain; the dev tree builds on demand.
_PREBUILT = os.environ.get("KFT_NATIVE_LIB")
_LIB_PATH = _PREBUILT or os.path.join(_NATIVE_DIR, "build", "libkft_native.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None


class NativeError(RuntimeError):
    """Error raised inside the native core (carries its message)."""


def ensure_built(force: bool = False) -> str:
    """Build the native library if missing or stale; returns its path.
    With KFT_NATIVE_LIB set, the prebuilt library is used as-is."""
    with _lock:
        if _PREBUILT:
            if not os.path.exists(_LIB_PATH):
                raise NativeError(
                    f"KFT_NATIVE_LIB={_LIB_PATH} does not exist"
                )
            return _LIB_PATH
        kft_bin = os.path.join(_NATIVE_DIR, "build", "kft")
        stale = (
            force
            or not os.path.exists(_LIB_PATH)
            or not os.path.exists(kft_bin)
        )
        if not stale:
            # Oldest artifact decides: an edit to main.cpp (CLI-only)
            # bumps only build/kft, and comparing against the .so alone
            # would re-run make on every call forever.
            lib_mtime = min(
                os.path.getmtime(_LIB_PATH), os.path.getmtime(kft_bin)
            )
            src_dir = os.path.join(_NATIVE_DIR, "src")
            # src_dir itself covers deletions (dir mtime bumps on unlink);
            # the Makefile covers flag changes.
            candidates = [src_dir, os.path.join(_NATIVE_DIR, "Makefile")] + [
                os.path.join(src_dir, fname) for fname in os.listdir(src_dir)
            ]
            stale = any(os.path.getmtime(p) > lib_mtime for p in candidates)
        if stale:
            proc = subprocess.run(
                ["make", "-C", _NATIVE_DIR],
                capture_output=True,
                text=True,
            )
            if proc.returncode != 0:
                raise NativeError(
                    f"native build failed:\n{proc.stdout}\n{proc.stderr}"
                )
        return _LIB_PATH


def _load() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        path = ensure_built()
        lib = ctypes.CDLL(path)
        lib.kft_invoke.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
        lib.kft_invoke.restype = ctypes.c_void_p  # manual free
        lib.kft_free.argtypes = [ctypes.c_void_p]
        lib.kft_free.restype = None
        _lib = lib
    return _lib


def invoke(fn: str, payload: dict | None = None) -> dict | list | str | int:
    """Call a native function; raises NativeError on native-side failure."""
    lib = _load()
    raw = lib.kft_invoke(
        fn.encode(), json.dumps(payload or {}).encode()
    )
    try:
        # errors="replace": a native-side encoding bug must surface as a
        # parseable error, never a UnicodeDecodeError crash in the bridge.
        reply = json.loads(ctypes.string_at(raw).decode(errors="replace"))
    finally:
        lib.kft_free(raw)
    if not reply.get("ok"):
        raise NativeError(reply.get("error", "unknown native error"))
    return reply["result"]
