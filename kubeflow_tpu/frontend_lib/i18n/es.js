/* Spanish catalog — the second locale, proving the i18n machinery is
 * not shaped around one language (reference ships full per-app
 * catalogs; same model here: English source strings are the keys,
 * missing keys fall through to English). Coverage is enforced by the
 * same guards as fr (tests/test_frontend_assets.py parameterises over
 * every shipped catalog). */
(function () {
  'use strict';
  window.KF.i18n.register('es', {
    // ---- lib chrome (frontend_lib/common.js) ----
    'Filter': 'Filtrar',
    'Refresh': 'Actualizar',
    'Download': 'Descargar',
    'Follow': 'Seguir',
    'Nothing here yet.': 'Todavía no hay nada aquí.',
    'No rows match the filter.': 'Ninguna fila coincide con el filtro.',
    '(no log output yet)': '(todavía sin registros)',
    'No conditions reported.': 'No se han registrado condiciones.',
    'No events for this resource.': 'No hay eventos para este recurso.',
    // ---- shared table / details columns ----
    'Name': 'Nombre',
    'Status': 'Estado',
    'Type': 'Tipo',
    'Reason': 'Motivo',
    'Message': 'Mensaje',
    'Last transition': 'Última transición',
    'Object': 'Objeto',
    'Count': 'Recuento',
    'Last seen': 'Visto por última vez',
    'Age': 'Antigüedad',
    'Image': 'Imagen',
    'CPU': 'CPU',
    'Memory': 'Memoria',
    'TPU': 'TPU',
    'TPU slice': 'Segmento TPU',
    'Overview': 'Resumen',
    'Conditions': 'Condiciones',
    'Events': 'Eventos',
    'Logs': 'Registros',
    'Logs path': 'Ruta de registros',
    'Size': 'Tamaño',
    'Mode': 'Modo',
    'Class': 'Clase',
    'Used by': 'Usado por',
    // ---- app chrome ----
    'Notebooks': 'Notebooks',
    'Volumes': 'Volúmenes',
    'TensorBoards': 'TensorBoards',
    '+ New Notebook': '+ Nuevo notebook',
    '+ New Volume': '+ Nuevo volumen',
    '+ New TensorBoard': '+ Nuevo TensorBoard',
    'Connect': 'Conectar',
    'Start': 'Iniciar',
    'Stop': 'Detener',
    'Delete': 'Eliminar',
    'Create': 'Crear',
    'Cancel': 'Cancelar',
    'New Notebook': 'Nuevo notebook',
    '← Back': '← Volver',
    'Pod': 'Pod',
    'Configurations': 'Configuraciones',
    'None (CPU only)': 'Ninguno (solo CPU)',
    'None': 'Ninguno',
    'Custom image': 'Imagen personalizada',
    'Create workspace volume': 'Crear volumen de trabajo',
    'Shared memory (/dev/shm)': 'Memoria compartida (/dev/shm)',
    'Namespace': 'Espacio de nombres',
    'Created': 'Creado',
    'Ready': 'Listo',
    'Access mode': 'Modo de acceso',
    'Storage class': 'Clase de almacenamiento',
    'Viewer': 'Visor',
    'Affinity': 'Afinidad',
    'Tolerations': 'Tolerancias',
    'No notebooks in this namespace. Create one to get started.':
      'No hay notebooks en este espacio de nombres. Cree uno para empezar.',
    'No volumes in this namespace.':
      'No hay volúmenes en este espacio de nombres.',
    'No TensorBoards in this namespace.':
      'No hay TensorBoards en este espacio de nombres.',
    'Delete notebook "{name}"? Attached PVCs are kept.':
      '¿Eliminar el notebook «{name}»? Los PVC adjuntos se conservan.',
    'Delete TensorBoard "{name}"?':
      '¿Eliminar el TensorBoard «{name}»?',
    'Delete volume "{name}" and its data?':
      '¿Eliminar el volumen «{name}» y sus datos?',
    'No PodDefaults in this namespace.':
      'No hay PodDefaults en este espacio de nombres.',
    'No pods yet — the StatefulSet has not started any.':
      'Todavía no hay pods: el StatefulSet no ha iniciado ninguno.',
    // ---- date-time humanization fallback (no-Intl browsers) ----
    '{age} ago': 'hace {age}',
    // ---- dashboard shell (centraldashboard static chrome) ----
    'TPU Notebooks': 'Notebooks TPU',
    'Home': 'Inicio',
    'TPU fleet': 'Flota TPU',
    'Quick links': 'Enlaces rápidos',
    'Recent activity': 'Actividad reciente',
    'Contributors': 'Colaboradores',
    'People who can use the selected namespace (reference manage-users view).':
      'Personas que pueden usar el espacio de nombres seleccionado (vista manage-users de referencia).',
    'Add contributor': 'Añadir colaborador',
    'Welcome': 'Bienvenido',
    'You don\'t have a namespace yet. Create one to start spawning TPU notebooks.':
      'Todavía no tiene un espacio de nombres. Cree uno para empezar a lanzar notebooks TPU.',
    'Create namespace': 'Crear espacio de nombres',
    // ---- widgets (spinner + help popover) ----
    'Loading…': 'Cargando…',
    'Help': 'Ayuda',
    'Accelerator and topology for the notebook. Multi-host slices spawn one pod per host with gang semantics: if any rank crashes, the whole slice restarts together.':
      'Acelerador y topología del notebook. Los segmentos multi-host lanzan un pod por host con semántica de pandilla: si un rango falla, todo el segmento se reinicia junto.',
    'PodDefaults applied by the admission webhook at pod creation (environment, volumes, tolerations).':
      'PodDefaults aplicados por el webhook de admisión al crear el pod (entorno, volúmenes, tolerancias).',
    // ---- editor widget + form controls (round 5) ----
    'YAML': 'YAML',
    'Dry-run & apply': 'Simular y aplicar',
    'Reset': 'Restablecer',
    'Applied': 'Aplicado',
    'document must be a mapping': 'el documento debe ser un mapeo',
    'Required': 'Obligatorio',
    'At most 63 characters': 'Como máximo 63 caracteres',
    'Lowercase letters, digits and "-"; must start and end alphanumeric':
      'Letras minúsculas, dígitos y «-»; debe empezar y terminar con un alfanumérico',
    'Not a quantity (examples: 0.5, 500m, 1.5Gi)':
      'No es una cantidad (ejemplos: 0.5, 500m, 1.5Gi)',
    'Not a valid image reference':
      'Referencia de imagen no válida',
  });
})();
