/* French catalog — the proof-of-concept locale (the reference ships
 * crud-web-apps/jupyter/frontend/i18n/fr). English source strings are
 * the keys (common.js KF.t); missing keys fall through to English, so
 * the catalog can grow incrementally. */
(function () {
  'use strict';
  window.KF.i18n.register('fr', {
    // ---- lib chrome (frontend_lib/common.js) ----
    'Filter': 'Filtrer',
    'Refresh': 'Actualiser',
    'Download': 'Télécharger',
    'Follow': 'Suivre',
    'Nothing here yet.': 'Rien ici pour le moment.',
    'No rows match the filter.': 'Aucune ligne ne correspond au filtre.',
    '(no log output yet)': '(pas encore de journal)',
    'No conditions reported.': 'Aucune condition signalée.',
    'No events for this resource.': 'Aucun événement pour cette ressource.',
    // ---- shared table / details columns ----
    'Name': 'Nom',
    'Status': 'État',
    'Type': 'Type',
    'Reason': 'Motif',
    'Message': 'Message',
    'Last transition': 'Dernière transition',
    'Object': 'Objet',
    'Count': 'Nombre',
    'Last seen': 'Vu pour la dernière fois',
    'Age': 'Âge',
    'Image': 'Image',
    'CPU': 'CPU',
    'Memory': 'Mémoire',
    'TPU': 'TPU',
    'TPU slice': 'Tranche TPU',
    'Overview': 'Aperçu',
    'Conditions': 'Conditions',
    'Events': 'Événements',
    'Logs': 'Journaux',
    'Logs path': 'Chemin des journaux',
    'Size': 'Taille',
    'Mode': 'Mode',
    'Class': 'Classe',
    'Used by': 'Utilisé par',
    // ---- toolbar shells (data-i18n) ----
    'Notebooks': 'Notebooks',
    'Volumes': 'Volumes',
    'TensorBoards': 'TensorBoards',
    '+ New Notebook': '+ Nouveau notebook',
    '+ New Volume': '+ Nouveau volume',
    '+ New TensorBoard': '+ Nouveau TensorBoard',
    // ---- actions ----
    'Connect': 'Se connecter',
    'Start': 'Démarrer',
    'Stop': 'Arrêter',
    'Delete': 'Supprimer',
    'Create': 'Créer',
    'Cancel': 'Annuler',
    'New Notebook': 'Nouveau notebook',
    '← Back': '← Retour',
    'Pod': 'Pod',
    'Configurations': 'Configurations',
    'None (CPU only)': 'Aucune (CPU uniquement)',
    'None': 'Aucun',
    'Custom image': 'Image personnalisée',
    'Create workspace volume': 'Créer un volume de travail',
    'Shared memory (/dev/shm)': 'Mémoire partagée (/dev/shm)',
    'Namespace': 'Espace de noms',
    'Created': 'Créé',
    'Ready': 'Prêt',
    'Access mode': 'Mode d\'accès',
    'Storage class': 'Classe de stockage',
    'Viewer': 'Visionneuse',
    'Affinity': 'Affinité',
    'Tolerations': 'Tolérances',
    'No notebooks in this namespace. Create one to get started.':
      'Aucun notebook dans cet espace de noms. Créez-en un pour commencer.',
    'No volumes in this namespace.':
      'Aucun volume dans cet espace de noms.',
    'No TensorBoards in this namespace.':
      'Aucun TensorBoard dans cet espace de noms.',
    'Delete notebook "{name}"? Attached PVCs are kept.':
      'Supprimer le notebook « {name} » ? Les PVC attachés sont conservés.',
    'Delete TensorBoard "{name}"?':
      'Supprimer le TensorBoard « {name} » ?',
    'Delete volume "{name}" and its data?':
      'Supprimer le volume « {name} » et ses données ?',
    'No PodDefaults in this namespace.':
      'Aucun PodDefault dans cet espace de noms.',
    'No pods yet — the StatefulSet has not started any.':
      'Pas encore de pods — le StatefulSet n\'en a démarré aucun.',
    // ---- date-time humanization fallback (no-Intl browsers) ----
    '{age} ago': 'il y a {age}',
    // ---- dashboard shell (centraldashboard static chrome) ----
    'TPU Notebooks': 'Notebooks TPU',
    'Namespace': 'Espace de noms',
    'Home': 'Accueil',
    'TPU fleet': 'Flotte TPU',
    'Quick links': 'Liens rapides',
    'Recent activity': 'Activité récente',
    'Contributors': 'Contributeurs',
    'People who can use the selected namespace (reference manage-users view).':
      'Personnes pouvant utiliser l\'espace de noms sélectionné (vue manage-users de référence).',
    'Add contributor': 'Ajouter un contributeur',
    'Welcome': 'Bienvenue',
    'You don\'t have a namespace yet. Create one to start spawning TPU notebooks.':
      'Vous n\'avez pas encore d\'espace de noms. Créez-en un pour lancer des notebooks TPU.',
    'Create namespace': 'Créer un espace de noms',
    // ---- widgets (round 4: spinner + help popover) ----
    'Loading…': 'Chargement…',
    'Help': 'Aide',
    'Accelerator and topology for the notebook. Multi-host slices spawn one pod per host with gang semantics: if any rank crashes, the whole slice restarts together.':
      'Accélérateur et topologie du notebook. Les tranches multi-hôtes lancent un pod par hôte avec une sémantique de gang : si un rang plante, toute la tranche redémarre ensemble.',
    'PodDefaults applied by the admission webhook at pod creation (environment, volumes, tolerations).':
      'PodDefaults appliqués par le webhook d\'admission à la création du pod (environnement, volumes, tolérances).',
    // ---- editor widget + form controls (round 5) ----
    'YAML': 'YAML',
    'Dry-run & apply': 'Simuler & appliquer',
    'Reset': 'Réinitialiser',
    'Applied': 'Appliqué',
    'document must be a mapping': 'le document doit être un mapping',
    'Required': 'Obligatoire',
    'At most 63 characters': 'Au plus 63 caractères',
    'Lowercase letters, digits and "-"; must start and end alphanumeric':
      'Lettres minuscules, chiffres et « - » ; doit commencer et finir par un alphanumérique',
    'Not a quantity (examples: 0.5, 500m, 1.5Gi)':
      'Pas une quantité (exemples : 0.5, 500m, 1.5Gi)',
    'Not a valid image reference':
      'Référence d\'image non valide',
  });
})();
